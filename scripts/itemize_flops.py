"""Itemize the analytic-vs-XLA FLOP gap on a bench step (r3 VERDICT item 4).

Compiles the exact ``bench.py`` executable and reconciles THREE counters:

* **model-analytic (nominal)** — the ``bench.py`` layer-formula count
  (2*M*N*K per layer, bwd = 2x fwd): the work an eager executor (the torch
  reference) performs for this model.
* **HLO-instruction sum (executed)** — every ``convolution``/``dot`` in the
  optimized module, counted with XLA's own convention
  (``utils.hlo_flops``): what the MXU actually runs after folding.
* **cost_analysis()** — XLA's total, which additionally counts VPU
  elementwise/reduce FLOPs.

and prints a per-instruction table with source-layer attribution
(HLO ``op_name`` metadata), grouping by pass (fwd / dgrad / wgrad).

r4 finding (VGG16/32x32, batch 4096): nominal 10.64 TF, executed 7.42 TF,
cost_analysis 9.02 TF. The fwd/dgrad/wgrad conv FLOPs reconcile
per-instruction; the whole nominal-vs-executed gap is the degenerate
classifier — at 32x32 the 1x1 feature map is replicated to 7x7 by the
adaptive pool, and XLA folds the replication out of the FC GEMMs (25088-wide
-> effective 512-wide). The r2/r3 "XLA undercounts conv backward" hypothesis
is retired.

Scope: the HLO recount is trustworthy for conv-stack models (vgg16,
resnet50, convnext_l) where convolutions appear in canonical form. XLA:TPU
lowers transformer dot_generals to *windowed* convolutions whose taps are
mostly padding — there the kernel-spatial formula overcounts (measured 6.7x
on ViT-B) and ``utils.hlo_flops.executed_matmul_flops`` returns None via its
cost_analysis reconciliation guard.

Usage: BENCH_MODEL=vgg16 python scripts/itemize_flops.py
"""

import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from distributed_training_pytorch_tpu.utils.hlo_flops import (
    itemize_hlo_matmul_flops,
    xla_cost_analysis,
)
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng


def classify(row: dict) -> str:
    """Pass attribution from the op_name metadata (authoritative) with a
    dim_labels fallback."""
    op = row["op_name"]
    if "transpose(jvp" in op:
        # wgrad convs contract over the batch dim (batch rides a feature
        # label); dgrad convs keep the batch layout of the fwd.
        labels = row["dim_labels"]
        if row["kind"] == "dot":
            return "bwd-dot"
        lhs = labels.split("_")[0]
        return "wgrad" if not lhs.startswith("b") else "dgrad"
    if "jvp" in op or not op:
        return "fwd"
    return "other"


def main():
    enable_fast_rng()
    setup = bench.build_bench_setup(os.environ.get("BENCH_MODEL", "vgg16"))
    cfg, model = setup["cfg"], setup["model"]
    batch, image_size = setup["batch"], setup["image_size"]
    engine, state, gbatch = setup["engine"], setup["state"], setup["gbatch"]
    compiled = engine.compile_train_step(
        state, gbatch, compiler_options=setup["compiler_options"]
    )
    cost = xla_cost_analysis(compiled)
    xla_total = float(cost.get("flops", 0.0))
    model_total = cfg["flops"](model, image_size) * batch * cfg["items_per_row"](image_size)

    rows = itemize_hlo_matmul_flops(compiled.as_text())
    hlo_total = sum(r["flops"] for r in rows)

    print(f"# FLOP itemization: {setup['model_name']} batch={batch} size={image_size}")
    print(f"model-analytic (nominal) : {model_total:>18,.0f}  (bench.py 2MNK, bwd=2x fwd)")
    print(f"HLO conv/dot (executed)  : {hlo_total:>18,.0f}  ({len(rows)} instructions)")
    print(f"cost_analysis() flops    : {xla_total:>18,.0f}  (+VPU elementwise)")
    print(f"executed/nominal = {hlo_total/model_total:.4f}   "
          f"xla/nominal = {xla_total/model_total:.4f}")

    by_pass: dict[str, float] = defaultdict(float)
    for r in rows:
        by_pass[classify(r)] += r["flops"]
    print("\n## per-pass executed totals")
    for k, v in sorted(by_pass.items(), key=lambda kv: -kv[1]):
        print(f"  {k:8s} {v/1e9:>10.1f} GF")

    groups = defaultdict(lambda: [0, 0.0, ""])
    for r in rows:
        key = (r["kind"], classify(r), r["out_elems"], r["reduction"])
        groups[key][0] += 1
        groups[key][1] += r["flops"]
        # Shorten op_name to the layer path (after the model name).
        op = r["op_name"]
        groups[key][2] = op.split(")/")[-1][:60] or r["name"][:40]
    print("\n## instruction groups (by pass x output x reduction)")
    print(f"{'kind':5s} {'pass':6s} {'n':>3s} {'out_elems':>13s} {'reduction':>10s} "
          f"{'GFLOP':>9s}  source layer")
    for (kind, pss, oe, red), (cnt, fl, ex) in sorted(
        groups.items(), key=lambda kv: -kv[1][1]
    ):
        print(f"{kind:5s} {pss:6s} {cnt:>3d} {oe:>13,d} {red:>10,d} {fl/1e9:>9.1f}  {ex}")


if __name__ == "__main__":
    main()
