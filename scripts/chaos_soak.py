#!/usr/bin/env python
"""Kill/resume chaos soak — the resilience subsystem's proof (ISSUE 5).

Runs a short REAL training job (sklearn digits, the offline stand-in every
accuracy clause uses: async checkpointing on, chained windows on, telemetry
on) and kills it with SIGTERM/SIGKILL at randomized — but **seeded** —
step offsets, N times, resuming from ``snapshot_path="latest_valid"`` after
every kill. The kill schedule deliberately includes:

* a **graceful SIGTERM** (the cloud-scheduler preemption path: flag ->
  collective vote -> emergency save -> clean exit);
* a **SIGKILL mid-background-commit** (the async saver's worker is inside
  the committing state — widened deterministically via the saver's
  ``commit_delay_s`` chaos seam — when the process dies);
* **SIGKILL at a random mid-epoch step** (with ``chain_steps=2`` this lands
  mid-chained-window: the device program dies between window boundaries).

Assertions (the acceptance criteria, checked by ``main``):

1. every kill leaves **>= 1 valid restorable checkpoint** on disk (validated
   against the SHA-256 manifest with a stdlib re-implementation of
   ``CheckpointManager.validate`` — the parent never imports jax, so the
   check cannot share a bug with the code under test);
2. every resume **succeeds** and the soaked run reaches completion;
3. the soaked run's final params are **bit-exact** with an uninterrupted
   reference run's (numpy array equality, every leaf);
4. the async save's hot-loop stall is **< 25 % of the synchronous save wall
   time** (measured on the same digits state by the reference child).

**Elastic mode** (``--elastic``; ISSUE 12) is the elasticity proof on top:
the child runs on N *forced host devices* (``compat.force_host_devices`` —
the ``xla_force_host_platform_device_count`` rig) under an fsdp mesh, is
killed mid-run, and resumes on M ≠ N devices with ``mesh=None`` — the
Trainer's elastic re-plan must solve the new mesh + grad-accum factor from
the checkpoint's sharding record without user intervention. Both directions
run (8→4 shrink and 4→8 grow), asserting:

1. every kill leaves >= 1 valid checkpoint whose meta records the sharded
   mesh; every elastic resume **succeeds** and reaches completion;
2. the resumed run's event log carries an ``elastic_restore`` record with
   the re-planned axes + accumulation factor;
3. **bit-exact re-plan**: the elastic resume (``mesh=None``, auto accum) is
   bit-for-bit identical to a *twin* resume of the same post-kill state with
   the hand-written explicit mesh/accum — pure extent re-grouping by the
   planner, zero numeric perturbation added (the 4→8 grow leg re-plans with
   *no* accum change, so the issue's "pure extent re-grouping, no accum
   change" case is asserted bit-exact);
4. final params are **equivalent to an uninterrupted same-global-batch run**
   on the starting topology at documented tolerance (ELASTIC_TOL, see
   docs/fault_tolerance.md — changing the batch-shard extent legally
   re-associates float reductions at ~1 ULP/step; measured max|Δ| ≈ 1e-7
   after 40 steps on this model, asserted at 100x headroom).

Usage::

    python scripts/chaos_soak.py --quick      # ~3 kills, CI stage (verify.sh)
    python scripts/chaos_soak.py              # full soak: 5 kills
    python scripts/chaos_soak.py --elastic --quick  # 8→4 + 4→8 kill/resume
    CHAOS_SEED=7 python scripts/chaos_soak.py # reproduce a failing schedule

``CHAOS_SEED`` (or ``--seed``) seeds the kill schedule, so a failure
reproduces deterministically.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ckpt_validate import valid_checkpoints  # noqa: E402  (shared stdlib helper)

STALL_MARKER = "CHAOS_STALL_JSON="
CHILD_TIMEOUT_S = 300.0  # hard bound per child attempt (compile + epochs)
TRIGGER_TIMEOUT_S = 120.0  # bound on waiting for a kill trigger
# Child exit codes the parent understands.
EXIT_OK = 0
EXIT_PREEMPTED = 3  # clean SIGTERM shutdown with a resumable save


# ---------------------------------------------------------------------------
# Child: the real training job (imports jax; run as a subprocess).


def child_main(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

    if args.devices:
        # Elastic mode: an N-device virtual CPU platform (must run before
        # anything initializes the jax backend).
        from distributed_training_pytorch_tpu import compat

        compat.force_host_devices(args.devices)

    import numpy as np
    import optax
    from flax import linen as nn

    import jax

    from distributed_training_pytorch_tpu.data import ArrayDataSource
    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec
    from distributed_training_pytorch_tpu.trainer import Trainer

    class DigitsNet(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    class SoakTrainer(Trainer):
        def build_train_dataset(self):
            from sklearn.datasets import load_digits

            digits = load_digits()
            images = (digits.images / 16.0).astype(np.float32)[..., None]
            labels = digits.target.astype(np.int32)
            # Tile the corpus: ~42 steps/epoch at batch 128, so epochs last
            # long enough for the parent to land kills mid-epoch instead of
            # racing a sub-second training run.
            images = np.concatenate([images] * 3)
            labels = np.concatenate([labels] * 3)
            return ArrayDataSource(image=images, label=labels)

        def build_model(self):
            return DigitsNet()

        def build_criterion(self):
            def criterion(logits, batch):
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, {"loss": loss}

            return criterion

        def build_optimizer(self, schedule):
            return optax.sgd(schedule, momentum=0.9)

        def build_scheduler(self):
            return 0.1

    # Elastic mode: --mesh SPEC pins an explicit sharded mesh (the killed
    # run, and the "twin" resume that hand-writes what the re-plan should
    # solve); an empty spec is mesh=None — the elastic-restore path, which
    # must re-plan the recorded mesh for THIS process's device count.
    mesh = mesh_config_from_spec(args.mesh).build() if args.mesh else None
    trainer = SoakTrainer(
        max_epoch=args.max_epoch,
        batch_size=128,
        save_folder=args.run_dir,
        snapshot_path="latest_valid",  # idempotent: cold start on first launch
        have_validate=False,
        save_period=1,  # periodic checkpoint every epoch (async commit)
        async_checkpoint=True,
        chain_steps=2,  # kills land mid-chained-window
        log_every=4,  # window events = the parent's step-progress signal
        preemption_check_every=2,
        telemetry="on",
        num_workers=0,
        progress=False,
        seed=0,
        mesh=mesh,
        accum_steps=args.accum,
        # DigitsNet's kernels are tiny; a small cutoff makes the fsdp mesh
        # genuinely shard them so checkpoints carry a sharding record.
        fsdp_min_size=256,
    )
    if args.commit_delay > 0:
        # Chaos seam: hold each background commit in the `committing` state
        # for this long so the parent can SIGKILL inside the window.
        trainer.saver.commit_delay_s = args.commit_delay
    trainer.train()
    if trainer._preempted:
        return EXIT_PREEMPTED

    # Completed: dump final params for the bit-exactness check.
    leaves = jax.device_get(jax.tree.leaves(trainer.state.params))
    np.savez(args.final, **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    digest = hashlib.sha256()
    for leaf in leaves:
        digest.update(np.ascontiguousarray(leaf).tobytes())
    print(f"CHAOS_PARAMS_SHA={digest.hexdigest()}", flush=True)

    if args.measure_stall:
        _measure_stall(trainer)
    return EXIT_OK


def _measure_stall(trainer) -> None:
    """Sync-save wall vs async-save hot-loop stall, on the trained state —
    the ISSUE 5 acceptance measurement, printed as one parseable line.
    Best-of-3 via the SAME helper bench.py's save_stall fields use
    (``resilience.measure_save_stall``), so the acceptance ratio and the
    benchmark metric cannot drift apart."""
    from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
    from distributed_training_pytorch_tpu.resilience import measure_save_stall

    measure_dir = os.path.join(trainer.save_folder, "stall_measure")
    with CheckpointManager(measure_dir, async_save=False) as mgr:
        stall = measure_save_stall(mgr, trainer.state, repeats=3)
    best = {
        "sync_ms": stall["sync_ms"],
        "async_ms": stall["stall_ms"],
        "commit_ms": stall["commit_ms"],
    }
    print(STALL_MARKER + json.dumps(best), flush=True)


# ---------------------------------------------------------------------------
# Parent: orchestration, kill schedule, validation (stdlib only — no jax).


class EventTail:
    """Incremental reader of the child's JSONL event log (lenient: a torn
    last line from a hard kill parses later or never — expected)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0

    def poll(self) -> list[dict]:
        records = []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                data = f.read()
        except OSError:
            return records
        # Only consume complete lines; a partial tail stays for next poll.
        end = data.rfind(b"\n")
        if end < 0:
            return records
        self.offset += end + 1
        for line in data[: end + 1].splitlines():
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return records


def spawn_child(script, run_dir, final, max_epoch, commit_delay, measure_stall, log,
                *, devices=0, mesh="", accum=1):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # NO persistent XLA compilation cache here, deliberately: a SIGKILL'd
    # writer can leave a cache entry that segfaults the next process at
    # deserialization (observed on this jax version) — the one crash the
    # checkpoint machinery cannot save us from. Each attempt pays its own
    # compile; the soak measures recovery, not wall time.
    cmd = [
        sys.executable, script, "--child",
        "--run-dir", run_dir,
        "--final", final,
        "--max-epoch", str(max_epoch),
        "--commit-delay", str(commit_delay),
        "--devices", str(devices),
        "--mesh", mesh,
        "--accum", str(accum),
    ]
    if measure_stall:
        cmd.append("--measure-stall")
    return subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT, env=env)


def wait_child(proc, timeout=CHILD_TIMEOUT_S) -> int:
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        raise SystemExit("chaos_soak: child exceeded its wall-time bound (hung?)")


def run_soak(args) -> int:
    script = os.path.abspath(__file__)
    seed = int(os.environ.get("CHAOS_SEED", args.seed))
    import random

    rng = random.Random(seed)
    workdir = tempfile.mkdtemp(prefix="chaos_soak_")
    max_epoch = 3 if args.quick else 4
    n_kills = 3 if args.quick else args.kills
    # Schedule: rotate through the three kill shapes, guaranteeing >= 1
    # graceful SIGTERM and >= 1 SIGKILL mid-background-commit.
    shapes = [("SIGTERM", "step"), ("SIGKILL", "commit"), ("SIGKILL", "step")]
    schedule = [shapes[i % len(shapes)] for i in range(n_kills)]
    commit_delay = 1.0
    print(
        f"chaos_soak: seed={seed} kills={n_kills} max_epoch={max_epoch} "
        f"workdir={workdir}\n  schedule: {schedule}"
    )

    failures: list[str] = []
    kill_log: list[str] = []
    try:
        # -- reference: uninterrupted run (also measures save stall) -------
        ref_dir = os.path.join(workdir, "ref")
        ref_final = os.path.join(workdir, "ref_final.npz")
        ref_log_path = os.path.join(workdir, "ref.log")
        with open(ref_log_path, "w") as log:
            rc = wait_child(
                spawn_child(script, ref_dir, ref_final, max_epoch, 0.0, True, log)
            )
        if rc != EXIT_OK or not os.path.isfile(ref_final):
            print(open(ref_log_path).read()[-4000:], file=sys.stderr)
            raise SystemExit(f"chaos_soak: reference run failed (exit {rc})")
        stall = None
        for line in open(ref_log_path):
            if line.startswith(STALL_MARKER):
                stall = json.loads(line[len(STALL_MARKER):])

        # -- soaked run: kill / verify / resume ----------------------------
        soak_dir = os.path.join(workdir, "soak")
        soak_final = os.path.join(workdir, "soak_final.npz")
        weights = os.path.join(soak_dir, "weights")
        events = EventTail(os.path.join(soak_dir, "telemetry", "events.jsonl"))
        soak_log_path = os.path.join(workdir, "soak.log")
        log = open(soak_log_path, "w")

        for i, (sig_name, trigger) in enumerate(schedule):
            # Drain events left over from the previous attempt's final
            # moments: stale window/save records must not satisfy THIS
            # attempt's trigger and kill the child during startup.
            events.poll()
            proc = spawn_child(
                script, soak_dir, soak_final, max_epoch, commit_delay, False, log
            )
            died = _wait_and_kill(proc, events, weights, sig_name, trigger, rng)
            rc = wait_child(proc, timeout=60.0)
            survivors = valid_checkpoints(weights)
            kill_log.append(
                f"kill {i + 1}/{n_kills}: {sig_name}@{trigger} ({died}) -> "
                f"exit {rc}, {len(survivors)} valid checkpoint(s): {survivors}"
            )
            print("  " + kill_log[-1])
            if died == "child exited before kill":
                # The schedule demands N REAL kills; a child that finished
                # before its kill landed means the harness lost the race.
                failures.append(
                    f"kill {i + 1} ({sig_name}@{trigger}) never landed — "
                    "child completed first"
                )
                continue
            if sig_name == "SIGTERM" and rc != EXIT_PREEMPTED:
                failures.append(
                    f"kill {i + 1}: SIGTERM child exited {rc}, expected clean "
                    f"preemption exit {EXIT_PREEMPTED}"
                )
            if not survivors:
                failures.append(
                    f"kill {i + 1} ({sig_name}@{trigger}) left ZERO valid checkpoints"
                )

        # -- final resume to completion ------------------------------------
        proc = spawn_child(script, soak_dir, soak_final, max_epoch, 0.0, False, log)
        rc = wait_child(proc)
        log.close()
        if rc != EXIT_OK or not os.path.isfile(soak_final):
            print(open(soak_log_path).read()[-4000:], file=sys.stderr)
            failures.append(f"final resume did not complete (exit {rc})")

        # -- bit-exactness -------------------------------------------------
        if os.path.isfile(soak_final):
            import numpy as np

            ref = np.load(ref_final)
            soak = np.load(soak_final)
            if sorted(ref.files) != sorted(soak.files):
                failures.append("final param trees differ in structure")
            else:
                for key in ref.files:
                    if not np.array_equal(ref[key], soak[key]):
                        failures.append(
                            f"final params NOT bit-exact (leaf {key} differs)"
                        )
                        break
                else:
                    print(
                        f"  final params bit-exact across {n_kills} kills "
                        f"({len(ref.files)} leaves)"
                    )

        # -- async stall acceptance ----------------------------------------
        if stall is None:
            failures.append("reference run produced no save-stall measurement")
        else:
            ratio = stall["async_ms"] / max(stall["sync_ms"], 1e-9)
            print(
                f"  save stall: sync {stall['sync_ms']:.1f} ms, async snapshot "
                f"{stall['async_ms']:.2f} ms (ratio {ratio:.3f}), background "
                f"commit {stall['commit_ms']:.1f} ms"
            )
            if stall["sync_ms"] < 5.0:
                print("  (sync save < 5 ms — ratio check skipped as noise)")
            elif ratio >= 0.25:
                failures.append(
                    f"async hot-loop stall is {ratio:.0%} of the sync save "
                    "wall time (acceptance: < 25%)"
                )
    finally:
        if args.keep:
            print(f"chaos_soak: artifacts kept at {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)

    if failures:
        print("CHAOS SOAK FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        print(f"  reproduce with CHAOS_SEED={seed}", file=sys.stderr)
        return 1
    print(
        f"chaos soak OK: {n_kills} kills (seed {seed}), every kill left a valid "
        "checkpoint, every resume succeeded, final params bit-exact"
    )
    return 0


# ---------------------------------------------------------------------------
# Elastic mode (ISSUE 12): kill on N forced-host devices, resume on M.

# Final-params equivalence tolerance vs the uninterrupted reference run.
# Rationale (docs/fault_tolerance.md): changing the batch-shard extent
# re-groups the gradient reductions' participant sets, which legally
# re-associates float32 sums at ~1 ULP per step — measured max|Δ| ≈ 1e-7
# after 40 steps on this DigitsNet (8-dev fsdp8 vs 4-dev fsdp4, identical
# global batches); asserted with ~100x headroom. BIT-exactness is asserted
# where it is the truth: the elastic resume vs the hand-configured twin on
# the same topology.
ELASTIC_TOL = 1e-4


def run_elastic_soak(args) -> int:
    script = os.path.abspath(__file__)
    seed = int(os.environ.get("CHAOS_SEED", args.seed))
    import random

    rng = random.Random(seed)
    workdir = tempfile.mkdtemp(prefix="chaos_elastic_")
    max_epoch = 2 if args.quick else 3
    # (tag, N, start mesh spec, kill, M, expected re-planned axes,
    #  expected re-planned accum, the explicit twin's spec)
    phases = [
        ("8to4", 8, "fsdp8", "SIGTERM", 4, {"data": 1, "fsdp": 4}, 2, "fsdp4x1"),
        ("4to8", 4, "fsdp4x1", "SIGKILL", 8, {"data": 2, "fsdp": 4}, 1, "fsdp4x2"),
    ]
    print(
        f"chaos_soak --elastic: seed={seed} max_epoch={max_epoch} "
        f"workdir={workdir}\n  phases: "
        + ", ".join(f"{t} ({s} {sig})" for t, _, s, sig, *_ in phases)
    )
    failures: list[str] = []
    try:
        for phase in phases:
            _elastic_phase(script, workdir, max_epoch, rng, failures, *phase)
    finally:
        if args.keep:
            print(f"chaos_soak: artifacts kept at {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print("ELASTIC CHAOS SOAK FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        print(f"  reproduce with CHAOS_SEED={seed}", file=sys.stderr)
        return 1
    print(
        "elastic chaos soak OK: 8->4 and 4->8 kill/resume both re-planned, "
        "bit-exact with their explicit twins, and equivalent to the "
        f"uninterrupted runs within {ELASTIC_TOL}"
    )
    return 0


def _elastic_phase(script, workdir, max_epoch, rng, failures,
                   tag, n, spec, sig_name, m, want_axes, want_accum, twin_spec):
    import numpy as np

    base = os.path.join(workdir, tag)
    os.makedirs(base, exist_ok=True)

    # 1. Uninterrupted reference on the START topology (the "same global
    # batch" comparison run of the acceptance criteria).
    ref_final = os.path.join(base, "ref_final.npz")
    ref_log = os.path.join(base, "ref.log")
    with open(ref_log, "w") as log:
        rc = wait_child(spawn_child(
            script, os.path.join(base, "ref"), ref_final, max_epoch, 0.0,
            False, log, devices=n, mesh=spec,
        ))
    if rc != EXIT_OK or not os.path.isfile(ref_final):
        print(open(ref_log).read()[-3000:], file=sys.stderr)
        failures.append(f"{tag}: reference run on {n} devices failed (exit {rc})")
        return

    # 2. The killed run: N devices, sharded mesh, seeded kill point.
    soak_dir = os.path.join(base, "soak")
    weights = os.path.join(soak_dir, "weights")
    events = EventTail(os.path.join(soak_dir, "telemetry", "events.jsonl"))
    soak_final = os.path.join(base, "soak_final.npz")
    soak_log = os.path.join(base, "soak.log")
    log = open(soak_log, "w")
    try:
        proc = spawn_child(
            script, soak_dir, soak_final, max_epoch, 0.0, False, log,
            devices=n, mesh=spec,
        )
        died = _wait_and_kill(proc, events, weights, sig_name, "step", rng)
        rc = wait_child(proc, timeout=60.0)
        survivors = valid_checkpoints(weights)
        print(
            f"  {tag}: {sig_name} on {n} devices ({died}) -> exit {rc}, "
            f"{len(survivors)} valid checkpoint(s): {survivors}"
        )
        if died == "child exited before kill":
            failures.append(f"{tag}: kill never landed — child completed first")
            return
        if not survivors:
            failures.append(f"{tag}: {sig_name} kill left ZERO valid checkpoints")
            return
        if sig_name == "SIGTERM" and rc != EXIT_PREEMPTED:
            failures.append(
                f"{tag}: SIGTERM child exited {rc}, expected clean preemption "
                f"exit {EXIT_PREEMPTED}"
            )

        # 3. Twin copy of the post-kill state BEFORE the resume mutates it.
        twin_dir = os.path.join(base, "twin")
        shutil.copytree(soak_dir, twin_dir)

        # 4. Elastic resume: M devices, mesh=None — the Trainer must re-plan
        # from the checkpoint's sharding record without user intervention.
        rc = wait_child(spawn_child(
            script, soak_dir, soak_final, max_epoch, 0.0, False, log,
            devices=m, mesh="", accum=1,
        ))
        if rc != EXIT_OK or not os.path.isfile(soak_final):
            print(open(soak_log).read()[-3000:], file=sys.stderr)
            failures.append(
                f"{tag}: elastic resume on {m} devices did not complete (exit {rc})"
            )
            return

        # 5. The resume's flight record must carry the re-plan.
        recs = [r for r in events.poll() if r.get("event") == "elastic_restore"]
        if not recs:
            failures.append(f"{tag}: no elastic_restore event in the resumed run's log")
        else:
            rec = recs[-1]
            if rec.get("to_mesh") != want_axes or not rec.get("replanned"):
                failures.append(
                    f"{tag}: elastic_restore re-planned {rec.get('from_mesh')} -> "
                    f"{rec.get('to_mesh')} (replanned={rec.get('replanned')}); "
                    f"expected {want_axes}"
                )
            if rec.get("accum_steps") != want_accum:
                failures.append(
                    f"{tag}: elastic_restore accum_steps={rec.get('accum_steps')}, "
                    f"expected {want_accum}"
                )

        # 6. Explicit twin: the same post-kill state resumed with the
        # hand-written mesh/accum the re-plan should have solved.
        twin_final = os.path.join(base, "twin_final.npz")
        rc = wait_child(spawn_child(
            script, twin_dir, twin_final, max_epoch, 0.0, False, log,
            devices=m, mesh=twin_spec, accum=want_accum,
        ))
        if rc != EXIT_OK or not os.path.isfile(twin_final):
            failures.append(f"{tag}: explicit twin resume did not complete (exit {rc})")
            return
    finally:
        log.close()

    # 7. Bit-exactness: the elastic re-plan adds zero numeric perturbation
    # over the hand-configured program (the 4->8 leg re-plans with NO accum
    # change — the pure-extent-re-grouping case, asserted bit-exact).
    elastic, twin = np.load(soak_final), np.load(twin_final)
    if sorted(elastic.files) != sorted(twin.files) or not all(
        np.array_equal(elastic[k], twin[k]) for k in elastic.files
    ):
        failures.append(
            f"{tag}: elastic resume NOT bit-exact with the explicit "
            f"{twin_spec}/accum={want_accum} twin"
        )
    else:
        change = "no accum change" if want_accum == 1 else f"accum -> {want_accum}"
        print(f"  {tag}: elastic resume bit-exact with the explicit twin ({change})")

    # 8. Equivalence with the uninterrupted reference at documented tolerance.
    ref = np.load(ref_final)
    worst = max(float(np.max(np.abs(ref[k] - elastic[k]))) for k in ref.files)
    print(
        f"  {tag}: final params vs uninterrupted {n}-device run: "
        f"max|d| = {worst:.2e} (tolerance {ELASTIC_TOL})"
    )
    if not (worst <= ELASTIC_TOL):
        failures.append(
            f"{tag}: final params diverged from the uninterrupted run "
            f"(max|d| {worst:.2e} > {ELASTIC_TOL})"
        )


def _wait_and_kill(proc, events, weights_dir, sig_name, trigger, rng) -> str:
    """Block until the seeded trigger condition holds, then deliver the
    signal. Returns a short description of the actual kill point."""
    sig = signal.SIGTERM if sig_name == "SIGTERM" else signal.SIGKILL
    deadline = time.monotonic() + TRIGGER_TIMEOUT_S
    # Randomized (seeded) step offset: fire after the k-th window event of
    # THIS attempt (window events land every log_every=4 steps), plus a
    # sub-step jitter sleep so the kill lands anywhere inside a window.
    target_windows = rng.randint(1, 3)
    jitter = rng.uniform(0.0, 0.25)
    windows_seen = 0
    commit_armed = False
    desc = "trigger timeout"
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return "child exited before kill"
        for rec in events.poll():
            kind = rec.get("event")
            if kind == "window":
                windows_seen += 1
            elif kind == "checkpoint_save" and rec.get("mode") == "async":
                commit_armed = True
        if trigger == "commit" and commit_armed:
            # The async commit worker is inside its commit_delay_s window
            # right now: sleep partway into it, then SIGKILL mid-commit.
            time.sleep(0.5)
            desc = "mid-background-commit"
            break
        if trigger == "step" and windows_seen >= target_windows:
            # SIGKILL must find something restorable on disk already; the
            # SIGTERM path saves its own emergency checkpoint on the way out.
            if sig == signal.SIGKILL and not valid_checkpoints(weights_dir):
                time.sleep(0.02)
                continue
            time.sleep(jitter)
            desc = f"after window {windows_seen} (+{jitter:.2f}s)"
            break
        time.sleep(0.02)
    if proc.poll() is None:
        try:
            os.kill(proc.pid, sig)
        except ProcessLookupError:
            return "child exited before kill"
    return desc


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true", help="CI mode: 3 kills, 3 epochs")
    parser.add_argument("--kills", type=int, default=5, help="kill count (full mode)")
    parser.add_argument("--seed", type=int, default=0, help="kill-schedule seed (CHAOS_SEED wins)")
    parser.add_argument("--keep", action="store_true", help="keep the work dir")
    parser.add_argument(
        "--elastic", action="store_true",
        help="elastic mode: kill on N forced-host devices, resume on M "
        "(8->4 and 4->8; ISSUE 12)",
    )
    # child-mode flags
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--run-dir", dest="run_dir", help=argparse.SUPPRESS)
    parser.add_argument("--final", help=argparse.SUPPRESS)
    parser.add_argument("--max-epoch", dest="max_epoch", type=int, default=3, help=argparse.SUPPRESS)
    parser.add_argument("--commit-delay", dest="commit_delay", type=float, default=0.0, help=argparse.SUPPRESS)
    parser.add_argument("--measure-stall", dest="measure_stall", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--devices", type=int, default=0, help=argparse.SUPPRESS)
    parser.add_argument("--mesh", default="", help=argparse.SUPPRESS)
    parser.add_argument("--accum", type=int, default=1, help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.child:
        return child_main(args)
    if args.elastic:
        return run_elastic_soak(args)
    return run_soak(args)


if __name__ == "__main__":
    sys.exit(main())
