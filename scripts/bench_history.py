#!/usr/bin/env python
"""Bench-history ledger CLI (ISSUE 14) — read the committed rounds.

Ingests the repo's committed ``BENCH_r*.json`` / ``MULTICHIP_r*.json``
rounds into per-metric trajectories (``telemetry.history``) and prints the
ledger with flat-streak and regression detections — the across-rounds
instrument the per-run stack (goodput, StepProfile, doctor) never had:
BENCH r02→r05 sat flat for four rounds and nothing noticed.

Usage::

    python scripts/bench_history.py                 # ledger + detections
    python scripts/bench_history.py --json          # machine-readable
    python scripts/bench_history.py --events E      # + a `bench_history`
                                                    #   JSONL record
    python scripts/bench_history.py --self-test     # CI gate (verify.sh)

``--self-test`` asserts the detector's acceptance case on the committed
files themselves, in BOTH directions (re-anchored for ISSUE 17):

* the historical r02→r05 plateau (step_ms ~76 ms, value ~54k img/s/chip,
  spread 1.4%) MUST still be reported as a >= 4-round flat streak on both
  the ``step_ms`` and ``value`` series — ended streaks stay in the ledger;
* that streak MUST have *ended*: BENCH_r06 (the first autotuned round,
  ``TUNED.json``) sits outside the flat band, so no flat streak on the
  headline series may extend to the newest committed round. A future
  round sequence that re-flattens the line will fail this gate — by
  design: the detector must never again sit quiet on a live plateau.

The detector boundary cases stay covered in ``tests/test_run_compare.py``.

Exit codes: 0 ok, 1 self-test failure (expected streak not detected, or a
live flat streak at HEAD), 2 no round files found under ``--root``.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_training_pytorch_tpu.telemetry import history as history_lib  # noqa: E402

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def self_test(report) -> int:
    """The committed-rounds acceptance check: r02->r05 must read as a flat
    streak that has ENDED — detected in the ledger, but not extending to
    the newest committed round of the headline series (BENCH_r06, the
    autotuned round, must sit outside the band)."""
    failures = []
    for field in ("step_ms", "value"):
        hits = [
            s for s in report.streaks
            if s.series.endswith(f":: {field}")
            and len(s.rounds) >= 4
            and s.rounds[0] <= 2
            and s.rounds[-1] >= 5
        ]
        if not hits:
            failures.append(
                f"{field}: no >=4-round flat streak covering r02->r05 "
                f"(streaks: {[s.describe() for s in report.streaks]})"
            )
            continue
        streak = hits[0]
        last_round = max(r for r, _ in report.series[streak.series])
        live = [
            s for s in report.streaks
            if s.series == streak.series and s.rounds[-1] >= last_round
        ]
        if last_round <= streak.rounds[-1]:
            failures.append(
                f"{field}: the plateau is the newest data — no round after "
                f"r{streak.rounds[-1]:02d} on {streak.series} (the flat "
                "streak was never ended)"
            )
        elif live:
            failures.append(
                f"{field}: a flat streak extends to the newest round "
                f"r{last_round:02d} — the bench line is STILL flat at HEAD "
                f"({live[0].describe()})"
            )
        else:
            print(f"bench_history self-test [{field}]: {streak.describe()} — "
                  f"detected, ended (r{last_round:02d} is outside the band)")
    if failures:
        print("BENCH HISTORY SELF-TEST FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("bench_history self-test OK: the r02->r05 plateau is detected on "
          "both trajectories and ends before the newest committed round")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=REPO_ROOT,
                        help="directory holding the BENCH_r*/MULTICHIP_r* files "
                             "(default: the repo root)")
    parser.add_argument("--flat-tol", type=float, default=history_lib.FLAT_REL_TOL,
                        help="flat-streak relative band (default %(default)s)")
    parser.add_argument("--flat-rounds", type=int, default=history_lib.FLAT_MIN_ROUNDS,
                        help="rounds needed for a flat streak to fire "
                             "(default %(default)s; one fewer stays quiet)")
    parser.add_argument("--regression-tol", type=float,
                        default=history_lib.REGRESSION_REL_TOL,
                        help="round-over-round bad-direction tolerance "
                             "(default %(default)s)")
    parser.add_argument("--json", action="store_true",
                        help="print the full ledger as one JSON object")
    parser.add_argument("--events", default=None,
                        help="append a bench_history record to this JSONL event log")
    parser.add_argument("--self-test", action="store_true",
                        help="CI gate: the committed r02->r05 plateau must be "
                             "detected (verify.sh)")
    args = parser.parse_args()

    report = history_lib.analyze_history(
        args.root,
        flat_tol=args.flat_tol,
        flat_min_rounds=args.flat_rounds,
        regression_tol=args.regression_tol,
    )
    if not report.entries:
        print(f"bench_history: no BENCH_r*/MULTICHIP_r* round files under "
              f"{args.root}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.describe())

    if args.events:
        from distributed_training_pytorch_tpu.telemetry import EventLog

        EventLog(args.events, process_index=0).emit(
            "bench_history",
            root=os.path.abspath(args.root),
            entries=len(report.entries),
            series=len(report.series),
            streaks=[s.to_dict() for s in report.streaks],
            regressions=[r.to_dict() for r in report.regressions],
        )
    if args.self_test:
        return self_test(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
