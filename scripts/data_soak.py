#!/usr/bin/env python
"""Streaming-data soak — the streaming subsystem's proof (ISSUE 19).

Runs short REAL streaming training jobs (sklearn digits packed into DTPR1
record shards, decoded by the ``StreamingLoader``'s bounded worker pool,
async checkpointing on, chained windows on, telemetry on) and proves the
subsystem's four contracts end to end:

1. **Deterministic resume**: a run killed with SIGTERM *and* SIGKILL and
   resumed from ``snapshot_path="latest_valid"`` consumes a record-id
   sequence **byte-identical** to an uninterrupted twin's (the loader's
   ``record_log_path`` audit trail, compared with a stdlib JSONL parse —
   the parent never imports jax, so the check cannot share a bug with the
   code under test), and its final params are **bit-exact** with the twin's.
   The resumed attempt's first consumed batch must equal the checkpoint's
   ``data/`` cursor (read straight from the item's JSON file on disk).
2. **Elastic composition** (8→4): the same kill/resume on 8 forced host
   devices (``fsdp8``) resuming on 4 with ``mesh=None`` — the elastic
   re-plan re-splits the per-host shard assignment, but the *global*
   consumed sequence stays byte-identical to the uninterrupted 8-device
   reference (and to the 1-device twin: the sequence is a pure function of
   ``(seed, epoch, shard structure)``, independent of topology). Final
   params match the reference within ELASTIC_TOL, and the resumed attempt's
   ``shard_assignment`` event records the re-split (``elastic: true``).
3. **Decode-worker death**: a run whose decode worker is killed mid-epoch
   (the ``crash_on_batch`` seam) completes within its wall-time bound
   (never a hang), reports ``respawns >= 1``, and consumes the SAME
   sequence as the twin — a crashed worker's batch is re-enqueued, not
   dropped.
4. **Corrupt-record degradation**: a corpus with a structurally-corrupt
   payload under ``skip_corrupt=True`` completes with
   ``corrupt_skipped >= 1`` (deterministic skip-and-substitute, counted).

Finally the uninterrupted twin's run directory is handed to
``scripts/run_doctor.py``: the clean streaming run must read ``healthy`` —
never ``data_bound`` (the worker pool keeps the step loop fed).

Usage::

    python scripts/data_soak.py --quick    # CI stage (verify.sh)
    python scripts/data_soak.py            # longer soak (4 kills, 4 epochs)
    DATA_SOAK_SEED=7 python scripts/data_soak.py   # reproduce a schedule
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ckpt_validate import valid_checkpoints  # noqa: E402  (shared stdlib helper)

STATS_MARKER = "DATA_SOAK_STATS="
CHILD_TIMEOUT_S = 300.0  # hard bound per child attempt — the never-hang bound
TRIGGER_TIMEOUT_S = 120.0
EXIT_OK = 0
EXIT_PREEMPTED = 3  # clean SIGTERM shutdown with a resumable save
ELASTIC_TOL = 1e-4  # same rationale as chaos_soak / docs/fault_tolerance.md

NUM_SHARDS = 8  # the record corpus's on-disk shard structure
CORRUPT_INDEX = 7  # which record the corrupt-corpus leg damages


# ---------------------------------------------------------------------------
# Child: the real streaming training job (imports jax; run as a subprocess).


def _ensure_shards(shards_dir: str, *, corrupt: bool = False) -> None:
    """Pack sklearn digits (tiled x3, ~42 batches/epoch at batch 128) into
    DTPR1 shards once, atomically (write to a temp dir, rename into place) —
    a killed child can never leave a half-written corpus for the next
    attempt. Payload = raw float32 image bytes; label = the digit."""
    if os.path.isdir(shards_dir):
        return
    import numpy as np
    from sklearn.datasets import load_digits

    from distributed_training_pytorch_tpu.data.records import write_shards

    digits = load_digits()
    images = (digits.images / 16.0).astype(np.float32)[..., None]
    labels = digits.target.astype(np.int64)
    images = np.concatenate([images] * 3)
    labels = np.concatenate([labels] * 3)
    tmp = shards_dir + f".tmp-{os.getpid()}"

    def records():
        for i in range(len(labels)):
            payload = np.ascontiguousarray(images[i]).tobytes()
            if corrupt and i == CORRUPT_INDEX:
                # Structurally undecodable: 7 bytes is not a multiple of
                # float32 itemsize, so decode raises -> CorruptRecordError.
                payload = b"CORRUPT"
            yield payload, int(labels[i])

    write_shards(os.path.join(tmp, "digits"), records(), num_shards=NUM_SHARDS)
    try:
        os.rename(tmp, shards_dir)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # lost a race; keep the winner


def child_main(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

    if args.devices:
        from distributed_training_pytorch_tpu import compat

        compat.force_host_devices(args.devices)

    import numpy as np
    import optax
    from flax import linen as nn

    import jax

    from distributed_training_pytorch_tpu.data import StreamingLoader
    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec
    from distributed_training_pytorch_tpu.trainer import Trainer

    _ensure_shards(args.shards, corrupt=bool(args.corrupt))

    def decode(payload: bytes) -> np.ndarray:
        return np.frombuffer(payload, dtype=np.float32).reshape(8, 8, 1)

    class DigitsNet(nn.Module):
        # A small conv, not a Dense toy — run_doctor's reasoning: its
        # per-step wall is large against per-batch decode, so the clean
        # twin's steady fractions look like a real run's and the pool's
        # prefetch genuinely hides decode (the doctor-healthy criterion).
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = nn.relu(nn.Conv(16, (3, 3))(x))
            x = nn.relu(nn.Conv(32, (3, 3))(x))
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10)(x)

    run_dir = args.run_dir
    os.makedirs(run_dir, exist_ok=True)  # the loader's records.jsonl home

    class StreamSoakTrainer(Trainer):
        def build_train_dataset(self):
            # Only build_example_input reads this (record [0] for shape
            # inference); the train loader owns the shards directly.
            from distributed_training_pytorch_tpu.data.records import (
                RecordFileSource,
            )

            return RecordFileSource(args.shards, decode=decode)

        def build_dataloader(self, dataset, phase="train"):
            return StreamingLoader.from_records(
                args.shards,
                self.batch_size,
                decode=decode,
                skip_corrupt=bool(args.corrupt),
                shuffle=True,
                seed=self.seed,
                num_workers=2,
                prefetch_batches=self.prefetch_batches,
                drop_last=True,
                record_log_path=os.path.join(run_dir, "records.jsonl"),
            )

        def build_model(self):
            return DigitsNet()

        def build_criterion(self):
            def criterion(logits, batch):
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, {"loss": loss}

            return criterion

        def build_optimizer(self, schedule):
            return optax.sgd(schedule, momentum=0.9)

        def build_scheduler(self):
            return 0.1

    mesh = mesh_config_from_spec(args.mesh).build() if args.mesh else None
    trainer = StreamSoakTrainer(
        max_epoch=args.max_epoch,
        batch_size=128,
        save_folder=run_dir,
        snapshot_path="latest_valid",
        have_validate=False,
        save_period=1,
        async_checkpoint=True,
        chain_steps=2,
        log_every=4,
        preemption_check_every=2,
        telemetry="on",
        num_workers=2,
        progress=False,
        seed=0,
        mesh=mesh,
        fsdp_min_size=256,
    )
    if args.crash_batch >= 0:
        # Chaos seam: the decode worker servicing this batch dies; the pool
        # must respawn it and re-enqueue the batch (never a hang).
        trainer.train_dataloader.crash_on_batch = args.crash_batch
    trainer.train()
    loader = trainer.train_dataloader
    print(
        STATS_MARKER
        + json.dumps(
            {
                "respawns": int(loader.respawns),
                "crashes": int(loader.crashes),
                "corrupt_skipped": int(loader.corrupt_skipped),
                **loader.decode_stats(),
            }
        ),
        flush=True,
    )
    if trainer._preempted:
        return EXIT_PREEMPTED

    leaves = jax.device_get(jax.tree.leaves(trainer.state.params))
    np.savez(args.final, **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    return EXIT_OK


# ---------------------------------------------------------------------------
# Parent: orchestration, kill schedule, sequence auditing (stdlib only).


class EventTail:
    """Incremental reader of a child's JSONL log (lenient: a torn last line
    from a hard kill parses later or never — expected)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0

    def poll(self) -> list[dict]:
        records = []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                data = f.read()
        except OSError:
            return records
        end = data.rfind(b"\n")
        if end < 0:
            return records
        self.offset += end + 1
        for line in data[: end + 1].splitlines():
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return records


def consumed_map(log_path: str) -> dict[tuple[int, int], list[int]]:
    """The EFFECTIVE consumed sequence: ``{(epoch, batch): ids}`` with later
    attempts winning — a killed attempt's re-consumed batches are overwritten
    by the resume, exactly as the optimizer state sees them."""
    out: dict[tuple[int, int], list[int]] = {}
    try:
        with open(log_path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail from a hard kill
                out[(int(rec["epoch"]), int(rec["batch"]))] = list(rec["ids"])
    except OSError:
        pass
    return out


def first_consumed_after(log_path: str, offset: int) -> tuple[int, int] | None:
    """(epoch, batch) of the first complete record-log line past ``offset``."""
    try:
        with open(log_path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return None
    for line in data.splitlines():
        try:
            rec = json.loads(line)
            return int(rec["epoch"]), int(rec["batch"])
        except (json.JSONDecodeError, KeyError, ValueError):
            continue
    return None


def read_data_item(weights_dir: str, name: str) -> dict | None:
    """The checkpoint's ``data/`` reader-state item, straight off disk with
    stdlib json — the parent-side mirror of ``read_data_state``."""
    path = os.path.join(weights_dir, name, "data", "metadata")
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def spawn_child(script, run_dir, shards, final, max_epoch, log,
                *, devices=0, mesh="", crash_batch=-1, corrupt=False):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # No persistent XLA compilation cache (see chaos_soak: a SIGKILL'd cache
    # writer can poison the next attempt's deserialization).
    cmd = [
        sys.executable, script, "--child",
        "--run-dir", run_dir,
        "--shards", shards,
        "--final", final,
        "--max-epoch", str(max_epoch),
        "--devices", str(devices),
        "--mesh", mesh,
        "--crash-batch", str(crash_batch),
    ]
    if corrupt:
        cmd.append("--corrupt")
    return subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT, env=env)


def wait_child(proc, timeout=CHILD_TIMEOUT_S) -> int:
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        raise SystemExit("data_soak: child exceeded its wall-time bound (hung?)")


def child_stats(log_path: str) -> dict | None:
    stats = None
    try:
        for line in open(log_path, encoding="utf-8"):
            if line.startswith(STATS_MARKER):
                stats = json.loads(line[len(STATS_MARKER):])
    except OSError:
        pass
    return stats


def _wait_and_kill(proc, events, weights_dir, sig_name, rng) -> str:
    """Block until a seeded number of window events from THIS attempt have
    landed (plus, for SIGKILL, a valid checkpoint on disk), then signal."""
    sig = signal.SIGTERM if sig_name == "SIGTERM" else signal.SIGKILL
    deadline = time.monotonic() + TRIGGER_TIMEOUT_S
    target_windows = rng.randint(1, 3)
    jitter = rng.uniform(0.0, 0.25)
    windows_seen = 0
    desc = "trigger timeout"
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return "child exited before kill"
        for rec in events.poll():
            if rec.get("event") == "window":
                windows_seen += 1
        if windows_seen >= target_windows:
            if sig == signal.SIGKILL and not valid_checkpoints(weights_dir):
                time.sleep(0.02)
                continue
            time.sleep(jitter)
            desc = f"after window {windows_seen} (+{jitter:.2f}s)"
            break
        time.sleep(0.02)
    if proc.poll() is None:
        try:
            os.kill(proc.pid, sig)
        except ProcessLookupError:
            return "child exited before kill"
    return desc


def compare_sequences(tag, ref_map, got_map, failures) -> bool:
    if ref_map == got_map:
        n = len(ref_map)
        print(f"  {tag}: consumed sequence byte-identical ({n} batches)")
        return True
    missing = sorted(set(ref_map) - set(got_map))[:3]
    extra = sorted(set(got_map) - set(ref_map))[:3]
    diff = sorted(
        k for k in set(ref_map) & set(got_map) if ref_map[k] != got_map[k]
    )[:3]
    failures.append(
        f"{tag}: consumed sequence DIVERGED "
        f"(missing {missing}, extra {extra}, first id diffs at {diff})"
    )
    return False


def compare_params(tag, ref_path, got_path, failures, *, tol=None) -> None:
    import numpy as np  # parent-side compare only (chaos_soak precedent)

    ref, got = np.load(ref_path), np.load(got_path)
    if sorted(ref.files) != sorted(got.files):
        failures.append(f"{tag}: final param trees differ in structure")
        return
    if tol is None:
        for key in ref.files:
            if not np.array_equal(ref[key], got[key]):
                failures.append(
                    f"{tag}: final params NOT bit-exact (leaf {key} differs)"
                )
                return
        print(f"  {tag}: final params bit-exact ({len(ref.files)} leaves)")
    else:
        worst = max(
            float(np.max(np.abs(ref[k] - got[k]))) for k in ref.files
        )
        print(f"  {tag}: final params max|d| = {worst:.2e} (tolerance {tol})")
        if not (worst <= tol):
            failures.append(
                f"{tag}: final params diverged (max|d| {worst:.2e} > {tol})"
            )


def run_soak(args) -> int:
    script = os.path.abspath(__file__)
    seed = int(os.environ.get("DATA_SOAK_SEED", args.seed))
    import random

    rng = random.Random(seed)
    workdir = tempfile.mkdtemp(prefix="data_soak_")
    max_epoch = 3 if args.quick else 4
    shards = os.path.join(workdir, "shards")
    kill_schedule = (
        ["SIGTERM", "SIGKILL"] if args.quick
        else ["SIGTERM", "SIGKILL", "SIGTERM", "SIGKILL"]
    )
    print(
        f"data_soak: seed={seed} kills={len(kill_schedule)} "
        f"max_epoch={max_epoch} workdir={workdir}"
    )
    failures: list[str] = []
    try:
        # -- 1. uninterrupted twin (1 device): the ground truth ------------
        twin_dir = os.path.join(workdir, "twin")
        twin_final = os.path.join(workdir, "twin_final.npz")
        twin_log = os.path.join(workdir, "twin.log")
        with open(twin_log, "w") as log:
            rc = wait_child(
                spawn_child(script, twin_dir, shards, twin_final, max_epoch, log)
            )
        if rc != EXIT_OK or not os.path.isfile(twin_final):
            print(open(twin_log).read()[-4000:], file=sys.stderr)
            raise SystemExit(f"data_soak: twin run failed (exit {rc})")
        twin_map = consumed_map(os.path.join(twin_dir, "records.jsonl"))
        if not twin_map:
            raise SystemExit("data_soak: twin run logged no consumed records")
        print(f"  twin: {len(twin_map)} batches consumed over {max_epoch} epochs")

        # -- 2. kill lineage: SIGTERM + SIGKILL, resume, audit -------------
        soak_dir = os.path.join(workdir, "soak")
        soak_final = os.path.join(workdir, "soak_final.npz")
        weights = os.path.join(soak_dir, "weights")
        records_path = os.path.join(soak_dir, "records.jsonl")
        events = EventTail(os.path.join(soak_dir, "telemetry", "events.jsonl"))
        soak_log = os.path.join(workdir, "soak.log")
        log = open(soak_log, "w")
        try:
            for i, sig_name in enumerate(kill_schedule):
                events.poll()  # drain the previous attempt's leftovers
                proc = spawn_child(
                    script, soak_dir, shards, soak_final, max_epoch, log
                )
                died = _wait_and_kill(proc, events, weights, sig_name, rng)
                rc = wait_child(proc, timeout=60.0)
                survivors = valid_checkpoints(weights)
                print(
                    f"  kill {i + 1}/{len(kill_schedule)}: {sig_name} ({died}) "
                    f"-> exit {rc}, {len(survivors)} valid checkpoint(s)"
                )
                if died == "child exited before kill":
                    failures.append(
                        f"kill {i + 1} ({sig_name}) never landed — child "
                        "completed first"
                    )
                    continue
                if sig_name == "SIGTERM" and rc != EXIT_PREEMPTED:
                    failures.append(
                        f"kill {i + 1}: SIGTERM child exited {rc}, expected "
                        f"{EXIT_PREEMPTED}"
                    )
                if not survivors:
                    failures.append(
                        f"kill {i + 1} ({sig_name}) left ZERO valid checkpoints"
                    )

            # Final resume to completion; audit that its first consumed
            # batch equals the restored checkpoint's data/ cursor.
            events.poll()
            log_offset = (
                os.path.getsize(records_path)
                if os.path.isfile(records_path) else 0
            )
            proc = spawn_child(script, soak_dir, shards, soak_final, max_epoch, log)
            rc = wait_child(proc)
            if rc != EXIT_OK or not os.path.isfile(soak_final):
                print(open(soak_log).read()[-4000:], file=sys.stderr)
                failures.append(f"final resume did not complete (exit {rc})")
            restores = [
                r for r in events.poll() if r.get("event") == "checkpoint_restore"
            ]
            if not restores:
                failures.append("final resume logged no checkpoint_restore event")
            else:
                restored = restores[0]
                item = read_data_item(weights, str(restored.get("name")))
                first = first_consumed_after(records_path, log_offset)
                if item is None:
                    failures.append(
                        f"restored checkpoint {restored.get('name')!r} has no "
                        "readable data/ item"
                    )
                elif first is None:
                    failures.append("final resume logged no consumed records")
                else:
                    want = (int(item["epoch"]), int(item["cursor"]) // 128)
                    if first != want:
                        failures.append(
                            f"resume consumed first batch {first}, but the "
                            f"checkpoint's data/ cursor says {want}"
                        )
                    else:
                        print(
                            f"  resume: first consumed batch {first} == "
                            f"data/ cursor of {restored.get('name')!r} (O(1) "
                            "positioning, no replay)"
                        )
        finally:
            log.close()
        compare_sequences(
            "kill-lineage", twin_map, consumed_map(records_path), failures
        )
        if os.path.isfile(soak_final):
            compare_params("kill-lineage", twin_final, soak_final, failures)

        # -- 3. elastic leg: kill on 8 devices, resume on 4 ----------------
        if not args.no_elastic:
            _elastic_leg(script, workdir, shards, max_epoch, rng,
                         twin_map, failures)

        # -- 4. decode-worker crash: respawn, same sequence, no hang -------
        crash_dir = os.path.join(workdir, "crash")
        crash_final = os.path.join(workdir, "crash_final.npz")
        crash_log = os.path.join(workdir, "crash.log")
        with open(crash_log, "w") as log:
            rc = wait_child(spawn_child(
                script, crash_dir, shards, crash_final, 1, log, crash_batch=2,
            ))
        stats = child_stats(crash_log)
        if rc != EXIT_OK:
            print(open(crash_log).read()[-4000:], file=sys.stderr)
            failures.append(f"worker-crash run did not complete (exit {rc})")
        elif stats is None:
            failures.append("worker-crash run printed no stats line")
        elif stats.get("respawns", 0) < 1:
            failures.append(
                f"worker-crash run reported respawns={stats.get('respawns')}, "
                "expected >= 1"
            )
        else:
            print(
                f"  worker-crash: completed with respawns={stats['respawns']} "
                f"crashes={stats['crashes']} (bounded wait — never hung)"
            )
        crash_map = consumed_map(os.path.join(crash_dir, "records.jsonl"))
        twin_epoch0 = {k: v for k, v in twin_map.items() if k[0] == 0}
        compare_sequences("worker-crash (epoch 0)", twin_epoch0, crash_map,
                          failures)

        # -- 5. corrupt corpus under skip_corrupt --------------------------
        corrupt_dir = os.path.join(workdir, "corrupt")
        corrupt_shards = os.path.join(workdir, "shards_corrupt")
        corrupt_final = os.path.join(workdir, "corrupt_final.npz")
        corrupt_log = os.path.join(workdir, "corrupt.log")
        with open(corrupt_log, "w") as log:
            rc = wait_child(spawn_child(
                script, corrupt_dir, corrupt_shards, corrupt_final, 1, log,
                corrupt=True,
            ))
        stats = child_stats(corrupt_log)
        if rc != EXIT_OK:
            print(open(corrupt_log).read()[-4000:], file=sys.stderr)
            failures.append(f"corrupt-corpus run did not complete (exit {rc})")
        elif stats is None:
            failures.append("corrupt-corpus run printed no stats line")
        elif stats.get("corrupt_skipped", 0) < 1:
            failures.append(
                "corrupt-corpus run reported corrupt_skipped="
                f"{stats.get('corrupt_skipped')}, expected >= 1"
            )
        else:
            print(
                "  corrupt-corpus: completed with corrupt_skipped="
                f"{stats['corrupt_skipped']} (skip-and-substitute, counted)"
            )

        # -- 6. the clean streaming run must read healthy ------------------
        # A 'data_bound' verdict is the regression this leg guards (the pool
        # failing to hide decode) and fails IMMEDIATELY. Other verdicts on a
        # clean run are host-timing noise (a CI neighbor's cache pressure
        # reads as a straggler window) — retry ONCE on a fresh clean run; a
        # real bottleneck reproduces, noise does not.
        verdicts = []
        for attempt, run_dir in enumerate(
            (twin_dir, os.path.join(workdir, "doctor_retry"))
        ):
            if attempt:
                retry_log = os.path.join(workdir, "doctor_retry.log")
                with open(retry_log, "w") as log:
                    rc = wait_child(spawn_child(
                        script, run_dir, shards,
                        os.path.join(workdir, "doctor_retry.npz"),
                        max_epoch, log,
                    ))
                if rc != EXIT_OK:
                    break
            doctor = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(script), "run_doctor.py"),
                 run_dir, "--json"],
                capture_output=True, text=True, timeout=120,
            )
            verdict = None
            if doctor.returncode == 0:
                try:
                    verdict = json.loads(doctor.stdout).get("verdict")
                except json.JSONDecodeError:
                    pass
            verdicts.append(verdict)
            if verdict == "healthy" or verdict == "data_bound":
                break
        if verdicts and verdicts[-1] == "healthy":
            note = f" (after retry; first read {verdicts[0]!r})" \
                if len(verdicts) > 1 else ""
            print(f"  doctor: clean streaming run reads 'healthy'{note}")
        else:
            failures.append(
                f"run_doctor read the clean streaming run as {verdicts!r} "
                "(must be 'healthy', never 'data_bound')"
            )
    finally:
        if args.keep:
            print(f"data_soak: artifacts kept at {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)

    if failures:
        print("DATA SOAK FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        print(f"  reproduce with DATA_SOAK_SEED={seed}", file=sys.stderr)
        return 1
    print(
        f"data soak OK: {len(kill_schedule)} kills resumed byte-identical "
        "(params bit-exact), elastic 8->4 re-split kept the global sequence, "
        "worker crash respawned, corrupt record skipped, doctor healthy"
    )
    return 0


def _elastic_leg(script, workdir, shards, max_epoch, rng, twin_map, failures):
    """Kill a streaming run on 8 forced host devices (fsdp8), resume on 4
    with mesh=None: the re-plan re-splits per-host shard assignments, the
    GLOBAL sequence must not move."""
    base = os.path.join(workdir, "elastic")
    os.makedirs(base, exist_ok=True)

    ref_final = os.path.join(base, "ref_final.npz")
    ref_log = os.path.join(base, "ref.log")
    ref_dir = os.path.join(base, "ref")
    with open(ref_log, "w") as log:
        rc = wait_child(spawn_child(
            script, ref_dir, shards, ref_final, max_epoch, log,
            devices=8, mesh="fsdp8",
        ))
    if rc != EXIT_OK or not os.path.isfile(ref_final):
        print(open(ref_log).read()[-3000:], file=sys.stderr)
        failures.append(f"elastic: 8-device reference failed (exit {rc})")
        return
    ref_map = consumed_map(os.path.join(ref_dir, "records.jsonl"))
    # Topology independence: the 8-device reference consumed the SAME global
    # sequence as the 1-device twin (pure function of seed/epoch/shards).
    compare_sequences("elastic ref (8-dev vs 1-dev twin)", twin_map, ref_map,
                      failures)

    soak_dir = os.path.join(base, "soak")
    weights = os.path.join(soak_dir, "weights")
    events = EventTail(os.path.join(soak_dir, "telemetry", "events.jsonl"))
    soak_final = os.path.join(base, "soak_final.npz")
    soak_log = os.path.join(base, "soak.log")
    log = open(soak_log, "w")
    try:
        proc = spawn_child(
            script, soak_dir, shards, soak_final, max_epoch, log,
            devices=8, mesh="fsdp8",
        )
        died = _wait_and_kill(proc, events, weights, "SIGTERM", rng)
        rc = wait_child(proc, timeout=60.0)
        survivors = valid_checkpoints(weights)
        print(
            f"  elastic: SIGTERM on 8 devices ({died}) -> exit {rc}, "
            f"{len(survivors)} valid checkpoint(s)"
        )
        if died == "child exited before kill":
            failures.append("elastic: kill never landed — child completed first")
            return
        if not survivors:
            failures.append("elastic: kill left ZERO valid checkpoints")
            return

        rc = wait_child(spawn_child(
            script, soak_dir, shards, soak_final, max_epoch, log,
            devices=4, mesh="",
        ))
        if rc != EXIT_OK or not os.path.isfile(soak_final):
            print(open(soak_log).read()[-3000:], file=sys.stderr)
            failures.append(
                f"elastic: resume on 4 devices did not complete (exit {rc})"
            )
            return
        assigns = [
            r for r in events.poll() if r.get("event") == "shard_assignment"
        ]
        resumed = [r for r in assigns if r.get("elastic")]
        if not resumed:
            failures.append(
                "elastic: resumed attempt emitted no shard_assignment with "
                "elastic=true"
            )
        else:
            rec = resumed[-1]
            if rec.get("batch_extent") != 4:
                failures.append(
                    "elastic: re-split shard_assignment has batch_extent="
                    f"{rec.get('batch_extent')}, expected 4"
                )
            else:
                print(
                    "  elastic: shard_assignment re-split recorded "
                    f"(batch_extent 8 -> {rec['batch_extent']}, version "
                    f"{rec.get('version')})"
                )
    finally:
        log.close()

    compare_sequences(
        "elastic 8->4", ref_map,
        consumed_map(os.path.join(soak_dir, "records.jsonl")), failures,
    )
    compare_params("elastic 8->4 (vs 8-dev ref)", ref_final, soak_final,
                   failures, tol=ELASTIC_TOL)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--quick", action="store_true",
                        help="CI mode: 2 kills, 3 epochs (verify.sh stage)")
    parser.add_argument("--seed", type=int, default=0,
                        help="kill-schedule seed (DATA_SOAK_SEED wins)")
    parser.add_argument("--keep", action="store_true", help="keep the work dir")
    parser.add_argument("--no-elastic", action="store_true",
                        help="skip the 8->4 elastic leg")
    # child-mode flags
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--run-dir", dest="run_dir", help=argparse.SUPPRESS)
    parser.add_argument("--shards", help=argparse.SUPPRESS)
    parser.add_argument("--final", help=argparse.SUPPRESS)
    parser.add_argument("--max-epoch", dest="max_epoch", type=int, default=3,
                        help=argparse.SUPPRESS)
    parser.add_argument("--devices", type=int, default=0, help=argparse.SUPPRESS)
    parser.add_argument("--mesh", default="", help=argparse.SUPPRESS)
    parser.add_argument("--crash-batch", dest="crash_batch", type=int,
                        default=-1, help=argparse.SUPPRESS)
    parser.add_argument("--corrupt", action="store_true", help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.child:
        return child_main(args)
    return run_soak(args)


if __name__ == "__main__":
    sys.exit(main())
