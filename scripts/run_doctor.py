#!/usr/bin/env python
"""Run doctor — automated bottleneck diagnosis for a training run (ISSUE 13).

Reads a run directory's telemetry (``<run_dir>/telemetry/events.jsonl``,
the Trainer's flight log) and prints a **ranked, machine-checkable
diagnosis** — one of ``compile_bound`` / ``data_bound`` /
``checkpoint_stall`` / ``straggler`` / ``comm_heavy`` / ``healthy`` — each
verdict carrying the evidence rows (steady-state goodput fractions,
event-log line numbers, timeline track refs) that justify it. The rules
live in ``telemetry/doctor.py`` and are the SAME rules the trainer
projects live into the epoch-end ``doctor/*`` TensorBoard scalars.

Usage::

    python scripts/run_doctor.py <run_dir>            # diagnose
    python scripts/run_doctor.py <run_dir> --json     # machine-readable
    python scripts/run_doctor.py <run_dir> --timeline # + export the
                                                      #   Perfetto trace
    python scripts/run_doctor.py <run_dir> --events E # append a
                                                      #   `run_doctor` JSONL record
    python scripts/run_doctor.py --self-test          # CI gate (below)

``--self-test`` (the verify.sh stage; the perf-gate injected-regression
pattern): trains four short real sklearn-digits runs — a clean twin plus
three with a KNOWN bottleneck injected through existing seams — and
asserts the doctor names each culprit:

* **clean**            -> ``healthy`` (also: its exported timeline must be
  valid trace-event JSON whose goodput spans re-derive the meter's
  fractions within epsilon);
* **data-bound**       -> the ``ShardedLoader.load_delay_s`` seam starves
  the step loop (the perf gate's ``--inject-data-wait`` seam);
* **checkpoint-stall** -> the async saver's ``commit_delay_s`` chaos seam
  backs up the committer until the run stalls on its own saves;
* **hung/straggler**   -> ``FaultPlan("hang")`` injects host-side step
  hangs; the step-time detector fires and the doctor attributes it.

Exit codes: 0 diagnosis produced / self-test passed, 1 self-test
misdiagnosis, 2 no event log at the given path.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry import timeline as timeline_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry.events import load_run_events  # noqa: E402


def diagnose_run(run_dir: str):
    # The ONE shared reader (telemetry.events.EventFollower) — the same
    # parse the streaming monitor tails with (ISSUE 15).
    events = load_run_events(run_dir)
    return doctor_lib.diagnose(events)


def _self_test_trainer(tmp: str, **kw):
    """A small real-digits trainer with injection knobs: ``load_delay_s``
    (loader seam), ``commit_delay_s`` (async committer seam), plus any
    Trainer kwargs. Shared with ``scripts/perf_gate.py --data-wait`` — the
    gate's ceiling and the doctor's verdicts measure the SAME workload
    through the same steady-fraction definition, so they cannot drift.

    The net is a small conv (not a Dense toy) ON PURPOSE: its per-step
    wall (~15ms CPU) is large against the fixed per-batch fetch and
    per-save costs, so the healthy twin's steady-state fractions look
    like a real run's (productive-dominated) instead of being swamped by
    micro-run overhead that would read as a bottleneck."""
    import numpy as np
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.data import ArrayDataSource
    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.trainer import Trainer

    load_delay_s = kw.pop("load_delay_s", 0.0)
    commit_delay_s = kw.pop("commit_delay_s", 0.0)
    streaming = kw.pop("streaming", False)

    class DoctorNet(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = nn.relu(nn.Conv(16, (3, 3))(x))
            x = nn.relu(nn.Conv(32, (3, 3))(x))
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10)(x)

    class DoctorTrainer(Trainer):
        def build_train_dataset(self):
            from sklearn.datasets import load_digits

            digits = load_digits()
            return ArrayDataSource(
                image=(digits.images / 16.0).astype(np.float32)[..., None],
                label=digits.target.astype(np.int32),
            )

        def build_model(self):
            return DoctorNet()

        def build_criterion(self):
            def criterion(logits, batch):
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, {"loss": loss}

            return criterion

        def build_optimizer(self, schedule):
            return optax.sgd(schedule, momentum=0.9)

        def build_scheduler(self):
            return 0.1

        def build_dataloader(self, dataset, phase="train"):
            if streaming and phase == "train":
                # The streaming reader (ISSUE 19) honours the SAME
                # load_delay_s seam, so the data-bound case and the perf
                # gate's --inject-data-wait keep working unchanged.
                from distributed_training_pytorch_tpu.data import (
                    StreamingLoader,
                    shard_array_source,
                )

                loader = StreamingLoader(
                    shard_array_source(dataset, 4),
                    self.batch_size,
                    shuffle=True,
                    seed=self.seed,
                    num_workers=self.num_workers,
                    prefetch_batches=self.prefetch_batches,
                    drop_last=True,
                )
            else:
                loader = super().build_dataloader(dataset, phase)
            if load_delay_s:
                loader.load_delay_s = load_delay_s
            return loader

    defaults = dict(
        max_epoch=2,
        batch_size=128,
        save_folder=tmp,
        telemetry="on",
        chain_steps=2,
        log_every=4,
        num_workers=0,
        progress=False,
        have_validate=False,
        save_period=1,
        logger=type("Q", (), {"log": staticmethod(lambda *a, **k: None)})(),
    )
    defaults.update(kw)
    trainer = DoctorTrainer(**defaults)
    if commit_delay_s:
        trainer.saver.commit_delay_s = commit_delay_s
    return trainer


def self_test() -> int:
    import math
    import shutil
    import tempfile

    from distributed_training_pytorch_tpu.fault import FaultPlan
    from distributed_training_pytorch_tpu.telemetry import AnomalyDetector, Telemetry

    # (name, expected top verdict, injection kwargs). Injected runs turn
    # the anomaly detector off where it would double-report the injected
    # disease through a second signal (a starved loader also inflates
    # sync-to-sync window wall) — each run isolates ONE culprit.
    cases = [
        # clean: ONE async save with two epochs of overlap room after it
        # (save_period=3 on a 3-epoch run saves at epoch 0 only). A micro
        # run saving every tiny epoch honestly spends >20% of its steady
        # wall waiting on its own commits — that is checkpoint-stall, not
        # a misdiagnosis; the healthy twin keeps save cost in proportion.
        ("clean", "healthy", dict(max_epoch=3, save_period=3)),
        ("data-bound", "data_bound",
         dict(load_delay_s=0.05, telemetry=Telemetry(anomaly=None))),
        ("checkpoint-stall", "checkpoint_stall",
         dict(commit_delay_s=0.6, max_epoch=3, telemetry=Telemetry(anomaly=None))),
        # hang: chain_steps=1 — a chained run's fault windows fall back to
        # single-step executables never compiled in epoch 0, and that
        # late compile is a LEGITIMATE compile_bound signal that would
        # outrank the straggler verdict this case isolates.
        # hangs land in epoch 1's THIRD window (steps 8-11): the first two
        # clean windows finish the detector's warmup (epoch 0's windows
        # paid compile, so their step times are withheld from the EWMA —
        # the trainer's compile-window rule), and the hung window then
        # trips the step-time detector against a true steady baseline.
        ("hung-straggler", "straggler",
         dict(fault_plan=FaultPlan()
              .add("hang", epoch=1, step=8, payload=0.4)
              .add("hang", epoch=1, step=9, payload=0.4)
              .add("hang", epoch=1, step=10, payload=0.4)
              .add("hang", epoch=1, step=11, payload=0.4),
              chain_steps=1,
              telemetry=Telemetry(anomaly=AnomalyDetector(warmup=2)))),
    ]
    failures = []
    for name, expected, kw in cases:
        tmp = tempfile.mkdtemp(prefix=f"run_doctor_{name}_")
        try:
            trainer = _self_test_trainer(tmp, **kw)
            trainer.train()
            diagnosis = diagnose_run(tmp)
            verdict = diagnosis.verdict
            status = "ok" if verdict == expected else "MISDIAGNOSIS"
            print(f"run_doctor self-test [{name}]: expected {expected!r}, "
                  f"got {verdict!r} — {status}")
            print(diagnosis.describe())
            if verdict != expected:
                failures.append(f"{name}: expected {expected!r}, got {verdict!r}")
            if name == "clean":
                # The timeline acceptance ride-along: export, re-parse with
                # stdlib json, and check the goodput spans re-derive the
                # meter's fractions (the spans ARE the partition).
                trace, path = timeline_lib.export_timeline(tmp)
                with open(path, encoding="utf-8") as f:
                    reparsed = json.load(f)
                derived = timeline_lib.span_bucket_seconds(reparsed)
                want = trainer.goodput.to_state()
                total_d, total_w = sum(derived.values()), sum(want.values())
                for bucket, w in want.items():
                    d = derived.get(bucket, 0.0)
                    if abs(d / max(total_d, 1e-12) - w / max(total_w, 1e-12)) > 1e-6:
                        failures.append(
                            f"timeline: {bucket} span fraction "
                            f"{d / max(total_d, 1e-12):.6f} != goodput fraction "
                            f"{w / max(total_w, 1e-12):.6f}")
                commits = [e for e in reparsed["traceEvents"]
                           if e.get("tid") == timeline_lib.TRACKS["committer"]
                           and e.get("ph") == "X"]
                if not commits:
                    failures.append("timeline: no committer-track spans for the "
                                    "async-checkpointing clean run")
                if not math.isclose(
                    sum(trainer.goodput.fractions().values()), 1.0, abs_tol=1e-6
                ):
                    failures.append("goodput fractions do not sum to 1")
                print(f"run_doctor self-test [clean]: timeline OK ({path})")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print("RUN DOCTOR SELF-TEST FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("run_doctor self-test OK: healthy twin + 3 injected bottlenecks "
          "each correctly named")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("run_dir", nargs="?", default=None,
                        help="run directory (the Trainer save_folder) or a "
                             "direct events.jsonl path")
    parser.add_argument("--json", action="store_true",
                        help="print the diagnosis as one JSON object")
    parser.add_argument("--timeline", action="store_true",
                        help="also export <run_dir>/telemetry/timeline.json "
                             "(Perfetto / chrome://tracing)")
    parser.add_argument("--events", default=None,
                        help="append a run_doctor record to this JSONL event log")
    parser.add_argument("--self-test", action="store_true",
                        help="CI gate: diagnose injected bottlenecks (verify.sh)")
    args = parser.parse_args()
    if args.self_test:
        return self_test()
    if args.run_dir is None:
        parser.error("run_dir is required (or use --self-test)")
    try:
        diagnosis = diagnose_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"run_doctor: {e}", file=sys.stderr)
        return 2
    if args.timeline:
        _, path = timeline_lib.export_timeline(args.run_dir)
        print(f"run_doctor: timeline exported to {path} "
              "(open in ui.perfetto.dev or chrome://tracing)")
    if args.json:
        print(json.dumps(diagnosis.to_dict(), indent=2, sort_keys=True))
    else:
        print(f"run_doctor: {args.run_dir}")
        print(diagnosis.describe())
        print(f"verdict: {diagnosis.verdict}")
    if args.events:
        from distributed_training_pytorch_tpu.telemetry import EventLog
        from distributed_training_pytorch_tpu.telemetry.doctor import scalar_fields

        EventLog(args.events, process_index=0).emit(
            "run_doctor",
            run_dir=str(args.run_dir),
            verdict=diagnosis.verdict,
            healthy=diagnosis.healthy,
            scores=scalar_fields(diagnosis.signals),
            steady_fractions=doctor_lib.steady_fractions(
                diagnosis.signals.goodput_seconds or {}
            ),
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
