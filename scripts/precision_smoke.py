#!/usr/bin/env python
"""Precision smoke — mixed-precision CI gate (ISSUE 3 satellite).

Trains a small classifier on the REAL sklearn digits corpus (the offline
stand-in every accuracy clause uses) for a few epochs in ``precision="bf16"``
with the non-finite guard armed (``nan_policy="skip"``), and asserts:

* the loss actually decreases — a policy regression that silently zeroes
  grads (e.g. a cast detaching the params from the graph) fails here in
  seconds, not as a flat curve on real hardware;
* zero steps were skipped — bf16 training needs no loss scaling, so any
  ``nonfinite`` count means the precision path manufactured an overflow;
* the compute really ran in bf16 (logit dtype probed at trace time) while
  the master weights stayed fp32 — the policy's core contract.

Fails fast (nonzero exit) so ``scripts/verify.sh`` catches precision
regressions the way the retrace guard catches dispatch regressions.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import numpy as np
import optax
from flax import linen as nn

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.trainer import Trainer

SEEN_LOGIT_DTYPES: set = set()


class DigitsNet(nn.Module):
    """Dtype-inferring MLP (no forced casts): nn.Dense with dtype=None runs
    in whatever dtype the policy hands it — exactly the model class the
    boundary-cast design serves."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


class SmokeTrainer(Trainer):
    def build_train_dataset(self):
        from sklearn.datasets import load_digits

        digits = load_digits()
        images = (digits.images / 16.0).astype(np.float32)[..., None]
        return ArrayDataSource(
            image=images, label=digits.target.astype(np.int32)
        )

    def build_model(self):
        return DigitsNet()

    def build_criterion(self):
        def criterion(logits, batch):
            SEEN_LOGIT_DTYPES.add(str(logits.dtype))  # trace-time probe
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return 0.1


class _Recorder(SmokeTrainer):
    epoch_losses: list

    def train_epoch(self, epoch):
        metrics = super().train_epoch(epoch)
        self.epoch_losses.append(metrics["loss"])
        return metrics


def main() -> int:
    import shutil

    tmp = tempfile.mkdtemp(prefix="precision_smoke_")
    try:
        trainer = _Recorder(
            max_epoch=3,
            batch_size=128,
            save_folder=tmp,
            precision="bf16",
            nan_policy="skip",  # arm the guard so a skip would be COUNTED
            num_workers=0,
            log_every=0,
            async_checkpoint=False,
            progress=False,
            logger=type("Q", (), {"log": staticmethod(lambda *a, **k: None)})(),
        )
        trainer.epoch_losses = []
        trainer.train()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    errors = []
    first, last = trainer.epoch_losses[0], trainer.epoch_losses[-1]
    if not last < first * 0.7:
        errors.append(f"loss did not decrease under bf16: {trainer.epoch_losses}")
    if trainer.nonfinite_steps:
        errors.append(
            f"{trainer.nonfinite_steps} steps skipped — bf16 must not overflow"
        )
    if "bfloat16" not in SEEN_LOGIT_DTYPES:
        errors.append(f"compute did not run in bf16 (logit dtypes: {SEEN_LOGIT_DTYPES})")
    bad = [
        str(p.dtype)
        for p in jax.tree.leaves(trainer.state.params)
        if str(p.dtype) != "float32"
    ]
    if bad:
        errors.append(f"master weights not fp32: {bad}")
    if errors:
        print("PRECISION SMOKE FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print(
        f"precision smoke OK: bf16 digits loss {first:.3f} -> {last:.3f}, "
        f"0 skipped steps, fp32 master weights"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
