#!/usr/bin/env python
"""Telemetry smoke — observability CI gate (ISSUE 4 satellite).

Trains a small classifier on the REAL sklearn digits corpus (the offline
stand-in every accuracy clause uses) for a couple of epochs with
``telemetry="on"`` and ``chain_steps=2`` (windows + the health stats riding
scan outputs), then asserts the subsystem's core contracts:

* the event log is **well-formed JSONL**: every line parses, every record
  carries the schema fields (event, t_wall, t_mono, process, host), the
  ``t_mono`` stream is nondecreasing, and the run's narrative events
  (run_start, epoch_end, checkpoint_save, run_end) are all present;
* **goodput bucket fractions sum to 1 ± ε** and the run actually spent time
  compiling and stepping (a partition that silently lost a bucket would
  fail here in seconds, not as a nonsense dashboard on real hardware);
* the on-device **train-health stats** came back through the epoch metrics
  (grad_norm / param_norm / update_ratio finite, nonfinite == 0) without
  disturbing the retrace contract (chained executable traced exactly once);
* the run is traced with ``profile=ProfileConfig(steps=2)`` (ISSUE 6): the
  capture completes on a real digits run, its ``StepProfile`` **category
  fractions sum to 1 ± ε**, and the ``profile_capture`` event lands in the
  log with the attribution summary;
* the exported **timeline** (ISSUE 13, ``telemetry.timeline``) is valid
  trace-event JSON (stdlib re-parse of the written file), every lane's
  spans are monotone and non-overlapping, and summing the goodput lanes'
  span durations **re-derives the meter's bucket fractions within ε** —
  the trace is the partition, not a picture of it.

Fails fast (nonzero exit) so ``scripts/verify.sh`` catches observability
regressions the way the retrace/precision gates catch theirs.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import optax
from flax import linen as nn

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.profiling import ProfileConfig
from distributed_training_pytorch_tpu.telemetry import read_events
from distributed_training_pytorch_tpu.trainer import Trainer


class DigitsNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


class SmokeTrainer(Trainer):
    def build_train_dataset(self):
        from sklearn.datasets import load_digits

        digits = load_digits()
        images = (digits.images / 16.0).astype(np.float32)[..., None]
        return ArrayDataSource(image=images, label=digits.target.astype(np.int32))

    def build_model(self):
        return DigitsNet()

    def build_criterion(self):
        def criterion(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return 0.1


REQUIRED_FIELDS = ("event", "t_wall", "t_mono", "process", "host")
REQUIRED_EVENTS = ("run_start", "window", "epoch_end", "checkpoint_save", "run_end")
STAT_KEYS = ("grad_norm", "param_norm", "update_ratio", "nonfinite")


def main() -> int:
    import shutil

    tmp = tempfile.mkdtemp(prefix="telemetry_smoke_")
    epoch_metrics = []

    class Recorder(SmokeTrainer):
        def train_epoch(self, epoch):
            m = super().train_epoch(epoch)
            epoch_metrics.append(m)
            return m

    try:
        trainer = Recorder(
            max_epoch=2,
            batch_size=128,
            save_folder=tmp,
            telemetry="on",
            profile=ProfileConfig(steps=2),
            chain_steps=2,
            log_every=4,
            num_workers=0,
            async_checkpoint=False,
            progress=False,
            # no validation -> the periodic checkpoint branch saves
            have_validate=False,
            save_period=1,
            logger=type("Q", (), {"log": staticmethod(lambda *a, **k: None)})(),
        )
        trainer.train()

        errors = []

        # -- event log: well-formed JSONL with the full narrative ----------
        # read via the shipped consumer (telemetry.read_events) so the gate
        # exercises the same parse path tests and tooling use
        path = os.path.join(tmp, "telemetry", "events.jsonl")
        events = []
        if not os.path.isfile(path):
            errors.append(f"no event log at {path}")
        else:
            try:
                events = list(read_events(path))
            except ValueError as e:
                errors.append(str(e))
        for rec in events:
            missing = [k for k in REQUIRED_FIELDS if k not in rec]
            if missing:
                errors.append(f"event {rec.get('event')!r} missing fields {missing}")
                break
        mono = [rec["t_mono"] for rec in events if "t_mono" in rec]
        if mono != sorted(mono):
            errors.append("t_mono stream is not nondecreasing")
        kinds = {rec.get("event") for rec in events}
        for required in REQUIRED_EVENTS:
            if required not in kinds:
                errors.append(f"missing {required!r} event (saw {sorted(kinds)})")

        # -- goodput: exhaustive partition, real compile + step time -------
        fractions = trainer.goodput.fractions()
        total = sum(fractions.values())
        if abs(total - 1.0) > 1e-6:
            errors.append(f"goodput fractions sum to {total!r}, not 1: {fractions}")
        if not trainer.goodput.buckets["compile"] > 0:
            errors.append(f"no compile time accounted: {trainer.goodput.buckets}")
        if not trainer.goodput.buckets["productive_step"] > 0:
            errors.append(f"no productive time accounted: {trainer.goodput.buckets}")

        # -- on-device health stats rode the chained windows ---------------
        for key in STAT_KEYS:
            if key not in epoch_metrics[-1]:
                errors.append(f"epoch metrics missing stat {key!r}: {epoch_metrics[-1]}")
            elif not np.isfinite(epoch_metrics[-1][key]):
                errors.append(f"stat {key!r} not finite: {epoch_metrics[-1][key]}")
        if epoch_metrics[-1].get("nonfinite"):
            errors.append(f"clean run reported nonfinite steps: {epoch_metrics[-1]}")
        if trainer.engine.trace_counts["chained_2"] != 1:
            errors.append(
                f"chained executable retraced with telemetry on: "
                f"{dict(trainer.engine.trace_counts)}"
            )

        # -- profile capture on the real digits run (ISSUE 6) ---------------
        cap = trainer._profile_capture
        if cap is None or cap.state != "done" or cap.steps_traced < 2:
            errors.append(f"profile capture did not complete: {cap and cap.state}")
        if cap is not None and cap.report is None:
            errors.append("profile capture produced no StepProfile report")
        elif cap is not None:
            cat_sum = sum(cap.report.categories.values())
            if abs(cat_sum - 1.0) > 1e-6:
                errors.append(
                    f"StepProfile category fractions sum to {cat_sum!r}, not 1: "
                    f"{cap.report.categories}"
                )
        captures = [rec for rec in events if rec.get("event") == "profile_capture"]
        if len(captures) != 1:
            errors.append(f"expected exactly 1 profile_capture event, got {len(captures)}")
        elif "categories" not in captures[0]:
            errors.append(f"profile_capture event carries no attribution: {captures[0]}")

        # -- timeline export: strict JSON + goodput re-derivation (ISSUE 13)
        import json

        from distributed_training_pytorch_tpu.telemetry import timeline as timeline_lib

        try:
            _, tl_path = timeline_lib.export_timeline(tmp)
            with open(tl_path, encoding="utf-8") as f:
                trace = json.load(f)  # stdlib re-parse: strict-JSON contract
        except ValueError as e:
            trace = None
            errors.append(f"timeline export is not valid JSON: {e}")
        if trace is not None:
            spans = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
            if not spans:
                errors.append("timeline has no spans")
            lanes = {}
            for ev in spans:
                lanes.setdefault((ev["pid"], ev["tid"]), []).append(ev)
            for key, lane in lanes.items():
                lane.sort(key=lambda e: e["ts"])
                for a, b in zip(lane, lane[1:], strict=False):
                    if b["ts"] < a["ts"] + a["dur"] - 1e-3:
                        errors.append(
                            f"timeline lane {key} spans overlap: {a} then {b}"
                        )
                        break
            derived = timeline_lib.span_bucket_seconds(trace)
            total_d = sum(derived.values())
            for bucket, frac in fractions.items():
                got = derived.get(bucket, 0.0) / max(total_d, 1e-12)
                if abs(got - frac) > 1e-6:
                    errors.append(
                        f"timeline {bucket} span fraction {got:.6f} != goodput "
                        f"fraction {frac:.6f}"
                    )

        if errors:
            print("TELEMETRY SMOKE FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  - {e}", file=sys.stderr)
            return 1
        print(
            f"telemetry smoke OK: {len(events)} events, goodput "
            f"{trainer.goodput.goodput:.2f} productive "
            f"(compile {fractions['compile']:.2f}), "
            f"grad_norm {epoch_metrics[-1]['grad_norm']:.3f}"
        )
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
