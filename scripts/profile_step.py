"""Profile a compiled train step: headless per-op device-time table.

Thin CLI over ``distributed_training_pytorch_tpu.profiling`` (ISSUE 6): builds
the exact executable ``bench.py`` times (same model registry, batch, compiler
options), runs a traced window, and prints ``report.analyze_trace``'s
attribution — busy/idle split, category rollup (conv / matmul / fusions /
copies / collectives / reduce / idle), and the top-op table joined with
per-op FLOPs + bytes + arithmetic intensity (roofline position). The
categorizer and the report are the package's — one source of truth shared
with ``Trainer(profile=...)`` captures and bench's ``BENCH_PROFILE`` fields.

Usage:  BENCH_MODEL=resnet50 python scripts/profile_step.py
Env:    PROFILE_STEPS (default 3 traced steps), PROFILE_LIMIT (table rows),
        plus every BENCH_* knob bench.py honors.
"""

import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from distributed_training_pytorch_tpu.profiling import (
    IDLE,
    analyze_trace,
    flops_index,
    top_ops,
    trace,
)
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng


def main():
    enable_fast_rng()
    steps = int(os.environ.get("PROFILE_STEPS", "3"))
    limit = int(os.environ.get("PROFILE_LIMIT", "40"))

    # Exactly the executable bench.py times (shared builder, same env knobs).
    setup = bench.build_bench_setup(os.environ.get("BENCH_MODEL", "resnet50"))
    model_name, batch, image_size = (
        setup["model_name"], setup["batch"], setup["image_size"]
    )
    engine, state, gbatch = setup["engine"], setup["state"], setup["gbatch"]
    compiled = engine.compile_train_step(
        state, gbatch, compiler_options=setup["compiler_options"]
    )

    # Warm (first call on the relay pays dispatch setup), then trace.
    state, m = compiled(state, gbatch)
    _ = float(m["loss"])
    log_dir = os.environ.get("PROFILE_DIR") or tempfile.mkdtemp(prefix=f"prof_{model_name}_")
    with trace(log_dir):
        for _ in range(steps):
            state, m = compiled(state, gbatch)
        _ = float(m["loss"])

    report = analyze_trace(
        log_dir, steps=steps, top_k=limit, flops_by_op=flops_index(compiled)
    )
    # The device "Async XLA Ops" line holds overlapped DMA windows — outside
    # the report's critical-path attribution (summing it in would
    # double-count overlap) but worth a line: it is the H2D/prefetch story.
    async_total = sum(t for _, t, _ in top_ops(log_dir, limit=2000, line="Async XLA Ops"))

    print(f"# profile: {model_name} batch={batch} size={image_size} "
          f"steps={steps} (trace {report.trace_path})")
    print(f"# {report.summary()}")
    print(f"# source: {report.source}; busy {report.busy_us/1e3:.2f} ms + idle "
          f"{report.idle_us/1e3:.2f} ms over {report.span_us/1e3:.2f} ms span"
          + (f" = {report.step_us/1e3:.2f} ms/step" if report.step_us else "")
          + (f"  |  async DMA windows (overlapped): {async_total/1e3:.2f} ms"
             if async_total else ""))
    print("\n## category attribution (fractions of span, sum = 1)")
    for cat, frac in sorted(report.categories.items(), key=lambda kv: -kv[1]):
        us = report.category_us.get(cat, report.idle_us if cat == IDLE else 0.0)
        print(f"  {cat:20s} {us/1e3:9.2f} ms  {100*frac:5.1f}%")
    print(f"\n## top {limit} ops (self-time; flops/bytes/intensity where the "
          "HLO walk itemizes them)")
    for row in report.top_ops:
        short = re.sub(r"\s+", " ", row.name)[:120]
        roofline = (
            f"  [{row.flops:.3g} flop / {row.bytes:.3g} B = {row.arith_intensity:.1f} F/B]"
            if row.arith_intensity is not None
            else ""
        )
        print(f"  {row.total_us/1e3:8.2f} ms  x{row.count:<4d} "
              f"{100*row.frac_busy:5.1f}%  {short}{roofline}")


if __name__ == "__main__":
    main()
