"""Profile a compiled train step: headless per-op device-time table.

Builds the exact executable ``bench.py`` times (same model registry, batch,
compiler options), runs a traced window, and prints the top device ops by
self-time plus a category rollup (conv fwd / dgrad / wgrad, fusions, copies,
BN-ish elementwise, all-else). This is the profile-first tool the zoo-config
perf work runs before touching any model (VERDICT r3 items 1/3/6).

Usage:  BENCH_MODEL=resnet50 python scripts/profile_step.py
Env:    PROFILE_STEPS (default 3 traced steps), PROFILE_LIMIT (table rows),
        plus every BENCH_* knob bench.py honors.
"""

import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from distributed_training_pytorch_tpu.utils.profiling import top_ops, trace
from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng


def categorize(name: str) -> str:
    """Bucket an HLO op name from the critical-path trace line."""
    head = name.split(" = ")[0]
    if "convolution" in name:
        return "convolution"
    if "select_and_scatter" in name or "select-and-scatter" in name:
        return "pool-backward"
    if "reduce_window" in name or "reduce-window" in name:
        return "pool-forward"
    if "all-reduce" in name or "all-gather" in name or "reduce-scatter" in name:
        return "collective"
    if "copy" in head or "transpose" in head or "bitcast" in head:
        return "copy/transpose"
    if "reduce" in head:  # BN batch statistics, loss reductions
        return "reduce(stats)"
    if "fusion" in head:
        return "fusion(elementwise)"
    if "dot" in head or "custom-call" in head:
        return "matmul"
    return "other"


def main():
    enable_fast_rng()
    steps = int(os.environ.get("PROFILE_STEPS", "3"))
    limit = int(os.environ.get("PROFILE_LIMIT", "40"))

    # Exactly the executable bench.py times (shared builder, same env knobs).
    setup = bench.build_bench_setup(os.environ.get("BENCH_MODEL", "resnet50"))
    model_name, batch, image_size = (
        setup["model_name"], setup["batch"], setup["image_size"]
    )
    engine, state, gbatch = setup["engine"], setup["state"], setup["gbatch"]
    compiled = engine.compile_train_step(
        state, gbatch, compiler_options=setup["compiler_options"]
    )

    # Warm (first call on the relay pays dispatch setup), then trace.
    state, m = compiled(state, gbatch)
    _ = float(m["loss"])
    log_dir = os.environ.get("PROFILE_DIR") or tempfile.mkdtemp(prefix=f"prof_{model_name}_")
    with trace(log_dir):
        for _ in range(steps):
            state, m = compiled(state, gbatch)
        _ = float(m["loss"])

    # "XLA Ops" is the synchronous critical path: its events sum to wall step
    # time. (The "Async XLA Ops" line holds overlapped DMA windows — summing
    # it in would double-count; see utils/profiling.top_ops docstring.)
    op_rows = top_ops(log_dir, limit=2000, line="XLA Ops")
    op_total = sum(t for _, t, _ in op_rows)
    async_rows = top_ops(log_dir, limit=2000, line="Async XLA Ops")
    async_total = sum(t for _, t, _ in async_rows)

    print(f"# profile: {model_name} batch={batch} size={image_size} "
          f"steps={steps} (trace {log_dir})")
    print(f"# critical path (XLA Ops line): {op_total/1e3:.2f} ms over {steps} steps "
          f"= {op_total/1e3/steps:.2f} ms/step  |  async DMA windows "
          f"(overlapped): {async_total/1e3:.2f} ms")
    cats: dict[str, float] = {}
    for name, t, _ in op_rows:
        cats[categorize(name)] = cats.get(categorize(name), 0.0) + t
    print("\n## category rollup (self-time)")
    for cat, t in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:12s} {t/1e3:9.2f} ms  {100*t/op_total:5.1f}%")
    print(f"\n## top {limit} ops")
    for name, t, n in op_rows[:limit]:
        short = re.sub(r"\s+", " ", name)[:160]
        print(f"  {t/1e3:8.2f} ms  x{n:<4d} {100*t/op_total:5.1f}%  {short}")


if __name__ == "__main__":
    main()
