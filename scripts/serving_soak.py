#!/usr/bin/env python
"""Serving soak (ISSUE 18 tentpole d): continuous-batching inference under
open-loop traffic, on the SAME machinery the trainer uses (mesh, shardings,
checkpoint manifests, flight recorder).

Four legs, all required, each with its own printed verdict line:

1. **SLO** — seeded Poisson + bursty-tenant open-loop traffic against a
   TP-sharded ``TransformerLM`` (LMTiny on a ``tp2`` mesh) and a replicated
   vision model (ViTTiny on ``dp8``). Asserts: every request answered 200,
   outputs match a direct un-served ``apply`` call, the trailing-window p99
   meets the SLO, and steady-state traffic re-traces nothing (the
   TrainEngine retrace-guard contract, applied to serving).
2. **Hot-swap bit-identity** — a real :class:`CheckpointManager` commits
   checkpoints while traffic runs. Re-committing identical params must
   produce byte-identical ``/predict`` bodies across the swap boundary; a
   new checkpoint at a higher epoch must change them. No request may fail
   during any swap (the atomic reference flip never stalls the queue).
3. **Failover** — a serving replica runs as a subprocess supervised by the
   fleet controller (``RunSpec(kind="serve")``). SIGKILL it mid-service:
   the controller's dead-process rule must respawn it, and the respawned
   replica (same seed, same params) must answer byte-identically.
4. **Zero capacity** — a server whose batcher admits nothing must REFUSE
   (typed 429 within a bounded wall) — never hang the client.

Open-loop means arrivals do not wait for completions: a slow server meets
a growing queue, exactly like production. ``--quick`` shortens the traffic
windows for CI; the assertions are identical.

``--actuate`` (ISSUE 20) runs the actuated-offer legs instead — the
self-healing drain/re-plan path end to end, against REAL subprocess
replicas driven by the REAL fleet controller:

* **actuate** — a chip freed by a trainer's ``restart_excluding`` is
  offered to a dp1 replica over ``/admin/offer``; the accept drains,
  re-plans onto dp2, and the A/B judge keeps the absorb. Asserted: ZERO
  failed requests across the drain window (RetryClient riding the
  Retry-After headers), response bytes bit-identical across the re-plan,
  the ``offer_chip -> offer_accept -> drain_start -> replan_done`` audit
  chain in wall-clock order, keep evidenced by QPS-per-chip, and a
  monitor polling throughout that NEVER reads the draining replica as
  dead.
* **actuate_decline** — a replica under SLO pressure declines; nothing
  is drained, nothing is re-planned, the decline is audited.
* **actuate_timeout** — a handshake that cannot reach its replica
  reverts cleanly and re-arms the offer (a second offer still fires).

Exit 0 = every leg passed. Any failure prints ``serving_soak: FAIL`` lines
and exits 1.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_training_pytorch_tpu import compat  # noqa: E402


class SoakFailure(AssertionError):
    """One leg's assertion, carrying the leg name for the verdict line."""


def _check(cond: bool, leg: str, msg: str) -> None:
    if not cond:
        raise SoakFailure(f"[{leg}] {msg}")


# ---------------------------------------------------------------------------
# HTTP helpers (stdlib only — the soak must not dress up the client side)
# ---------------------------------------------------------------------------


def _post(port: int, payload: dict, timeout: float = 30.0) -> "tuple[int, bytes]":
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _get_json(port: int, route: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{route}", timeout=timeout
    ) as resp:
        return json.loads(resp.read())


def _wait_serving(port: int, row, *, timeout: float = 90.0) -> bytes:
    """Poll /predict until the replica answers 200, return the body."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            code, body = _post(port, {"tenant": "probe", "inputs": [row]}, timeout=10.0)
            if code == 200:
                return body
            last = (code, body[:200])
        except (OSError, urllib.error.URLError) as e:
            last = repr(e)
        time.sleep(0.25)
    raise SoakFailure(f"[failover] replica on :{port} never served (last: {last})")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Open-loop traffic: seeded Poisson arrivals + a bursty tenant
# ---------------------------------------------------------------------------


def open_loop_traffic(
    port: int,
    make_row,
    *,
    seed: int,
    duration_s: float,
    rate_hz: float,
    burst_every_s: float,
    burst_n: int,
):
    """Fire requests open-loop: exponential inter-arrival gaps for tenant
    ``web`` (Poisson process) plus tenant ``burst`` dumping ``burst_n``
    requests at once every ``burst_every_s`` — the fairness stressor. Each
    request runs on its own thread (arrivals never wait for completions).
    Returns (results, errors): results are (tenant, code, body, latency_ms).
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    results: list = []
    errors: list = []
    threads: list = []

    def fire(tenant: str, row) -> None:
        t0 = time.monotonic()
        try:
            code, body = _post(port, {"tenant": tenant, "inputs": [row]})
            results.append((tenant, code, body, (time.monotonic() - t0) * 1e3))
        except Exception as e:  # noqa: BLE001 — client-side transport failure
            errors.append((tenant, repr(e)))

    t_end = time.monotonic() + duration_s
    next_burst = time.monotonic() + burst_every_s
    while time.monotonic() < t_end:
        gap = float(rng.exponential(1.0 / rate_hz))
        time.sleep(min(gap, max(0.0, t_end - time.monotonic())))
        th = threading.Thread(target=fire, args=("web", make_row(rng)), daemon=True)
        th.start()
        threads.append(th)
        if time.monotonic() >= next_burst:
            next_burst += burst_every_s
            for _ in range(burst_n):
                th = threading.Thread(
                    target=fire, args=("burst", make_row(rng)), daemon=True
                )
                th.start()
                threads.append(th)
    for th in threads:
        th.join(timeout=60.0)
    return results, errors


# ---------------------------------------------------------------------------
# Leg 1: SLO under Poisson + burst, LM on tp2 and vision on dp8
# ---------------------------------------------------------------------------

SEQ_LEN = 16
LM_VOCAB = 64


def _lm_engine(mesh, seed: int, buckets=(1, 2, 4, 8)):
    import jax
    import jax.numpy as jnp

    from distributed_training_pytorch_tpu.models import LMTiny
    from distributed_training_pytorch_tpu.serving import InferEngine

    model = LMTiny(vocab_size=LM_VOCAB)
    params = model.init(
        jax.random.key(seed), jnp.zeros((1, SEQ_LEN), jnp.int32)
    )["params"]

    def apply_fn(p, tokens):
        return model.apply({"params": p}, tokens)

    engine = InferEngine(apply_fn, mesh, buckets=tuple(buckets))
    return engine, params, apply_fn


def leg_slo(run_root: str, args) -> None:
    import jax
    import numpy as np

    from distributed_training_pytorch_tpu.parallel.mesh import mesh_config_from_spec
    from distributed_training_pytorch_tpu.serving import InferenceServer, MicroBatcher

    leg = "slo"
    mesh = mesh_config_from_spec("tp2").build(jax.devices()[:2])
    engine, params, apply_fn = _lm_engine(mesh, seed=args.seed)
    engine.swap_params(params, version="init")
    engine.warmup(np.zeros((SEQ_LEN,), np.int32))
    traces_after_warmup = engine.trace_counts["infer_step"]

    run_dir = os.path.join(run_root, "slo")
    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_delay_s=0.004),
        run_dir=run_dir,
        slo_p99_ms=args.slo_p99_ms,
        pulse_every_s=0.25,
        input_dtype="int32",
    ).start()
    try:
        def make_row(rng):
            return rng.integers(0, LM_VOCAB, size=(SEQ_LEN,)).tolist()

        results, errors = open_loop_traffic(
            server.port,
            make_row,
            seed=args.seed,
            duration_s=args.traffic_s,
            rate_hz=args.rate_hz,
            burst_every_s=max(0.5, args.traffic_s / 4),
            burst_n=6,
        )
        _check(not errors, leg, f"transport errors: {errors[:3]}")
        bad = [r for r in results if r[1] != 200]
        _check(not bad, leg, f"{len(bad)} non-200 responses, first: {bad[:1]}")
        _check(len(results) >= 10, leg, f"only {len(results)} requests completed")

        # Correctness spot-check: the served answer for a fixed row equals
        # a direct (un-served, un-batched at bucket 1) forward pass.
        row = np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB
        code, body = _post(server.port, {"tenant": "check", "inputs": [row.tolist()]})
        _check(code == 200, leg, f"spot-check returned {code}")
        served = np.asarray(json.loads(body)["outputs"][0])
        direct = np.asarray(apply_fn(params, row[None, :]))[0]
        _check(
            np.allclose(served, direct, rtol=1e-5, atol=1e-5),
            leg,
            "served output diverges from direct apply",
        )

        status = _get_json(server.port, "/status")
        p99 = status["p99_ms"]
        _check(p99 is not None, leg, "no p99 in the window after traffic")
        _check(
            p99 <= args.slo_p99_ms,
            leg,
            f"p99 {p99:.1f} ms breaches the {args.slo_p99_ms:.0f} ms SLO",
        )
        _check(status["slo_ok"] is True, leg, f"slo_ok is {status['slo_ok']}")
        _check(status["qps"] > 0, leg, "window qps is 0 after traffic")
        # Retrace guard: warmup compiled every bucket; traffic adds nothing.
        _check(
            engine.trace_counts["infer_step"] == traces_after_warmup,
            leg,
            f"steady-state serving re-traced: {traces_after_warmup} -> "
            f"{engine.trace_counts['infer_step']}",
        )
        print(
            f"serving_soak: slo OK — {len(results)} requests, "
            f"p50 {status['p50_ms']:.1f} ms, p99 {p99:.1f} ms "
            f"(SLO {args.slo_p99_ms:.0f} ms), {status['qps']:.1f} qps, "
            f"pad_frac {status['pad_frac']:.2f}, 0 retraces"
        )
    finally:
        server.close()


def leg_vision(run_root: str, args) -> None:
    """The replicated leg: a vision model on a pure-data ``dp8`` mesh —
    params replicate, the batch shards 8-wide, so the smallest legal bucket
    is 8 and every 1-row request exercises the pad-to-extent path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_training_pytorch_tpu.models import ViTTiny
    from distributed_training_pytorch_tpu.parallel.mesh import mesh_config_from_spec
    from distributed_training_pytorch_tpu.serving import InferenceServer, MicroBatcher

    leg = "vision"
    mesh = mesh_config_from_spec("dp8").build(jax.devices())
    model = ViTTiny(num_classes=4)
    params = model.init(jax.random.key(args.seed), jnp.zeros((1, 8, 8, 3)))["params"]

    def apply_fn(p, x):
        return model.apply({"params": p}, x)

    from distributed_training_pytorch_tpu.serving import InferEngine

    engine = InferEngine(apply_fn, mesh, buckets=(8, 16))
    engine.swap_params(params, version="init")
    engine.warmup(np.zeros((8, 8, 3), np.float32))

    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_delay_s=0.004),
        run_dir=os.path.join(run_root, "vision"),
        slo_p99_ms=args.slo_p99_ms,
        pulse_every_s=0.25,
    ).start()
    try:
        def make_row(rng):
            return rng.standard_normal((8, 8, 3)).astype(np.float32).tolist()

        results, errors = open_loop_traffic(
            server.port,
            make_row,
            seed=args.seed + 1,
            duration_s=max(2.0, args.traffic_s / 2),
            rate_hz=args.rate_hz / 2,
            burst_every_s=1.0,
            burst_n=4,
        )
        _check(not errors, leg, f"transport errors: {errors[:3]}")
        bad = [r for r in results if r[1] != 200]
        _check(not bad, leg, f"{len(bad)} non-200 responses, first: {bad[:1]}")

        rng = np.random.default_rng(args.seed + 2)
        row = rng.standard_normal((8, 8, 3)).astype(np.float32)
        code, body = _post(server.port, {"tenant": "check", "inputs": [row.tolist()]})
        _check(code == 200, leg, f"spot-check returned {code}")
        served = np.asarray(json.loads(body)["outputs"][0])
        direct = np.asarray(apply_fn(params, row[None])).astype(np.float64)[0]
        _check(
            np.allclose(served, direct, rtol=1e-4, atol=1e-5),
            leg,
            "served vision output diverges from direct apply",
        )
        status = _get_json(server.port, "/status")
        _check(
            status["pad_frac"] > 0.0,
            leg,
            "dp8 with 1-row requests must pad (pad_frac 0 is impossible)",
        )
        print(
            f"serving_soak: vision OK — {len(results)} requests on a "
            f"replicated dp8 mesh, pad_frac {status['pad_frac']:.2f}, "
            f"p99 {status['p99_ms']:.1f} ms"
        )
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Leg 2: hot-swap bit-identity against a REAL CheckpointManager
# ---------------------------------------------------------------------------


def leg_hot_swap(run_root: str, args) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
    from distributed_training_pytorch_tpu.parallel.mesh import mesh_config_from_spec
    from distributed_training_pytorch_tpu.serving import InferenceServer, MicroBatcher
    from distributed_training_pytorch_tpu.train.state import TrainState

    leg = "hot_swap"
    run_dir = os.path.join(run_root, "hot_swap")
    mesh = mesh_config_from_spec("tp2").build(jax.devices()[:2])
    engine, params_a, _ = _lm_engine(mesh, seed=args.seed)

    def _state(params):
        # Minimal real TrainState: serving needs no optimizer, but orbax
        # refuses an EMPTY composite item, so opt_state carries one scalar.
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=(jnp.zeros((), jnp.float32),),
            model_state={},
            rng=jax.random.key(0),
        )

    mgr = CheckpointManager(os.path.join(run_dir, "weights"), async_save=False)
    mgr.save("best", _state(params_a), 1)

    target = _state(jax.tree.map(jnp.zeros_like, params_a))
    engine.restore_params(mgr, target, name="best")
    engine.warmup(np.zeros((SEQ_LEN,), np.int32))
    _check(engine.params_version == "best@e1", leg, f"initial restore gave {engine.params_version}")

    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_delay_s=0.004),
        run_dir=run_dir,
        manager=mgr,
        target_state=target,
        serve_name="best",
        swap_poll_s=0.1,
        slo_p99_ms=args.slo_p99_ms,
        pulse_every_s=0.25,
        input_dtype="int32",
    ).start()
    try:
        row = (np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB).tolist()
        stop = threading.Event()
        failures: list = []

        def hammer() -> None:
            # Background load across every swap: any non-200 is a stall or
            # a torn swap, and fails the leg.
            rng = np.random.default_rng(args.seed + 3)
            while not stop.is_set():
                r = rng.integers(0, LM_VOCAB, size=(SEQ_LEN,)).tolist()
                try:
                    code, body = _post(server.port, {"tenant": "load", "inputs": [r]})
                    if code != 200:
                        failures.append((code, body[:200]))
                except Exception as e:  # noqa: BLE001
                    failures.append((None, repr(e)))
                time.sleep(0.005)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()

        code, body_a = _post(server.port, {"tenant": "check", "inputs": [row]})
        _check(code == 200, leg, f"pre-swap predict returned {code}")

        def _wait(pred, what: str, timeout: float = 30.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                time.sleep(0.05)
            raise SoakFailure(f"[{leg}] timed out waiting for {what}")

        # Re-commit IDENTICAL params: the manifest mtime changes, the swap
        # fires, and the response bytes must not.
        swaps_before = engine.swap_count
        mgr.save("best", _state(params_a), 1)
        _wait(lambda: engine.swap_count > swaps_before, "re-commit swap")
        code, body_same = _post(server.port, {"tenant": "check", "inputs": [row]})
        _check(code == 200, leg, f"post-swap predict returned {code}")
        _check(
            body_same == body_a,
            leg,
            "re-committing identical params changed the response bytes",
        )

        # Commit NEW params at a higher epoch: version moves, bytes change.
        _eng, params_b, _fn = _lm_engine(mesh, seed=args.seed + 17)
        del _eng, _fn
        mgr.save("best", _state(params_b), 2)
        _wait(lambda: engine.params_version == "best@e2", "best@e2 swap")
        code, body_b = _post(server.port, {"tenant": "check", "inputs": [row]})
        _check(code == 200, leg, f"post-update predict returned {code}")
        _check(body_b != body_a, leg, "new params produced identical bytes")
        _check(
            json.loads(body_b)["params_version"] == "best@e2",
            leg,
            f"served version is {json.loads(body_b)['params_version']}",
        )

        stop.set()
        th.join(timeout=30.0)
        _check(
            not failures,
            leg,
            f"{len(failures)} requests failed across swaps, first: {failures[:1]}",
        )

        from distributed_training_pytorch_tpu.telemetry.events import (
            read_events,
            resolve_events_path,
        )

        swaps = [
            r for r in read_events(resolve_events_path(run_dir))
            if r.get("event") == "hot_swap"
        ]
        _check(len(swaps) >= 2, leg, f"only {len(swaps)} hot_swap events recorded")
        _check(
            swaps[-1]["to_version"] == "best@e2",
            leg,
            f"last hot_swap went to {swaps[-1]['to_version']}",
        )
        print(
            f"serving_soak: hot_swap OK — {engine.swap_count} swaps under "
            f"load, re-commit bit-identical, best@e2 changed the bytes, "
            f"0 failed requests"
        )
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Leg 3: SIGKILL failover under the fleet controller
# ---------------------------------------------------------------------------


def serve_worker(args) -> int:
    """Child mode: one serving replica on a FIXED port, deterministic
    params from ``--seed`` (so a respawn is bit-identical), supervised via
    its run_dir flight recorder. Runs until SIGTERM.

    ``--mesh-spec``/``--device-ids``/``--device-count``/``--buckets``
    (ISSUE 20) let the actuate legs start a replica on a SUBSET of the
    host's virtual devices (e.g. dp1 on chip 0 of 2) so the actuated
    offer has a real spare chip to grow onto."""
    compat.force_host_devices(args.device_count)
    import jax
    import numpy as np

    from distributed_training_pytorch_tpu.parallel.mesh import mesh_config_from_spec
    from distributed_training_pytorch_tpu.serving import InferenceServer, MicroBatcher

    if args.device_ids:
        want = {int(x) for x in args.device_ids.split(",")}
        devs = [d for d in jax.devices() if d.id in want]
    else:
        devs = jax.devices()[:2]
    mesh = mesh_config_from_spec(args.mesh_spec).build(devs)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    engine, params, _ = _lm_engine(mesh, seed=args.seed, buckets=buckets)
    engine.swap_params(params, version=f"seed{args.seed}")
    engine.warmup(np.zeros((SEQ_LEN,), np.int32))
    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_delay_s=0.004),
        port=args.port,
        run_dir=args.run_dir,
        slo_p99_ms=args.slo_p99_ms,
        window_s=args.window_s,
        pulse_every_s=0.5,
        input_dtype="int32",
    ).start()
    if not server.enabled:
        return 1
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        while not stop.wait(0.2):
            pass
    finally:
        server.close()
    return 0


def leg_failover(run_root: str, args) -> None:
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fleet_controller import FleetController, RunSpec

    from distributed_training_pytorch_tpu.telemetry.controller import ControllerConfig
    from distributed_training_pytorch_tpu.telemetry.events import (
        EventLog,
        read_events,
        resolve_events_path,
    )
    from distributed_training_pytorch_tpu.telemetry.monitor import AlertConfig

    leg = "failover"
    run_dir = os.path.join(run_root, "replica0")
    os.makedirs(run_dir, exist_ok=True)
    port = _free_port()
    spec = RunSpec(
        name="replica0",
        run_dir=run_dir,
        kind="serve",
        cmd=[
            sys.executable,
            os.path.abspath(__file__),
            "--serve-worker",
            "--run-dir", run_dir,
            "--port", str(port),
            "--seed", str(args.seed),
            "--slo-p99-ms", str(args.slo_p99_ms),
        ],
    )
    ctl_events = EventLog(
        os.path.join(run_root, "controller_events.jsonl"), process_index=0
    )
    ctl = FleetController(
        [spec],
        config=ControllerConfig(max_restarts=2, backoff_s=0.1, confirm_polls=1),
        monitor_config=AlertConfig(stale_after_s=60.0, dead_after_s=120.0),
        event_log=ctl_events,
        interval=0.2,
    )
    ctl.start()
    run = ctl.runs["replica0"]
    try:
        row = (np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB).tolist()
        body_before = _wait_serving(port, row)

        # SIGKILL the replica mid-service — no cleanup, no goodbye.
        run.proc.kill()
        run.proc.wait(timeout=30)

        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            ctl.poll_once()
            if any(a.kind == "restart" for a in run.actions):
                break
            time.sleep(0.2)
        restarts = [a for a in run.actions if a.kind == "restart"]
        _check(bool(restarts), leg, "controller never issued a restart")
        _check(restarts[0].reason == "dead", leg, f"restart reason {restarts[0].reason}")

        body_after = _wait_serving(port, row)
        _check(
            body_after == body_before,
            leg,
            "respawned replica's response differs from the killed one",
        )
        recs = read_events(resolve_events_path(run_dir))
        starts = [r for r in recs if r.get("event") == "serve_start"]
        _check(
            len(starts) >= 2 and starts[-1]["attempt"] >= 2,
            leg,
            f"expected a second serve_start attempt, got {len(starts)}",
        )
        acts = [
            r
            for r in read_events(os.path.join(run_root, "controller_events.jsonl"))
            if r.get("event") == "controller_action" and r.get("action") == "restart"
        ]
        _check(bool(acts), leg, "no controller_action restart in the audit log")
        print(
            f"serving_soak: failover OK — SIGKILL'd replica respawned by the "
            f"fleet controller (attempt {starts[-1]['attempt']}), response "
            f"bit-identical across the failover"
        )
    finally:
        ctl.shutdown()
        ctl_events.close()


# ---------------------------------------------------------------------------
# Leg 4: zero capacity refuses, never hangs
# ---------------------------------------------------------------------------


def leg_zero_capacity(run_root: str, args) -> None:
    import jax
    import numpy as np

    from distributed_training_pytorch_tpu.parallel.mesh import mesh_config_from_spec
    from distributed_training_pytorch_tpu.serving import InferenceServer, MicroBatcher
    from distributed_training_pytorch_tpu.telemetry.events import (
        read_events,
        resolve_events_path,
    )

    leg = "zero_capacity"
    run_dir = os.path.join(run_root, "zero")
    mesh = mesh_config_from_spec("tp2").build(jax.devices()[:2])
    engine, params, _ = _lm_engine(mesh, seed=args.seed)
    engine.swap_params(params, version="init")
    server = InferenceServer(
        engine,
        batcher=MicroBatcher(buckets=engine.buckets, max_queue_depth=0),
        run_dir=run_dir,
        pulse_every_s=0.25,
        input_dtype="int32",
    ).start()
    try:
        row = (np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB).tolist()
        t0 = time.monotonic()
        code, body = _post(server.port, {"tenant": "t", "inputs": [row]})
        wall = time.monotonic() - t0
        _check(code == 429, leg, f"expected 429, got {code}: {body[:200]}")
        _check(wall < 2.0, leg, f"refusal took {wall:.2f}s — that is a hang, not a refusal")
        parsed = json.loads(body)
        _check(
            parsed == {"error": "overload", "tenant": "t", "depth": 0, "bound": 0},
            leg,
            f"untyped overload body: {parsed}",
        )
        rejects = [
            r for r in read_events(resolve_events_path(run_dir))
            if r.get("event") == "admission_reject"
        ]
        _check(len(rejects) == 1, leg, f"{len(rejects)} admission_reject events")
        print(
            f"serving_soak: zero_capacity OK — typed 429 in {wall * 1e3:.0f} ms, "
            f"1 admission_reject event"
        )
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Leg 5: import neutrality — serving imported but unused is bit-exact
# ---------------------------------------------------------------------------


def neutrality_worker(args) -> int:
    """Child mode: a small deterministic TrainEngine run, optionally with
    the ENTIRE serving stack imported first (package + engine + server —
    stronger than the package-only import the unit test pins). Prints one
    JSON line: sha256 of the final params bytes + the engine's trace
    counts. Two children must print identical lines."""
    if args.with_serving:
        import distributed_training_pytorch_tpu.serving  # noqa: F401
        import distributed_training_pytorch_tpu.serving.engine  # noqa: F401
        import distributed_training_pytorch_tpu.serving.server  # noqa: F401
    compat.force_host_devices(2)
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(3)(x)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"ce_loss": loss}

    mesh = mesh_lib.create_mesh()
    model = Net()
    engine = TrainEngine(
        make_supervised_loss(model, criterion), optax.sgd(0.05, momentum=0.9), mesh
    )
    state = engine.init_state(
        jax.random.key(args.seed), lambda rng: model.init(rng, jnp.zeros((1, 4, 4, 3)))
    )
    rng = np.random.RandomState(args.seed)
    labels = rng.randint(0, 3, size=(16,)).astype(np.int32)
    images = rng.randn(16, 4, 4, 3).astype(np.float32) + labels[:, None, None, None]
    batch = engine.shard_batch({"image": images, "label": labels})
    for _ in range(10):
        state, _ = engine.train_step(state, batch)
    h = hashlib.sha256()
    for leaf in jax.device_get(jax.tree.leaves(state.params)):
        h.update(np.asarray(leaf).tobytes())
    print(
        json.dumps(
            {
                "params_sha256": h.hexdigest(),
                "trace_counts": sorted(dict(engine.trace_counts).items()),
            }
        )
    )
    return 0


def leg_neutrality(run_root: str, args) -> None:
    import subprocess

    leg = "neutrality"
    outs = []
    for flag in ((), ("--with-serving",)):
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--neutrality-worker", "--seed", str(args.seed), *flag,
        ]
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        _check(
            proc.returncode == 0, leg,
            f"worker {cmd[3:]} failed rc={proc.returncode}: {proc.stderr[-400:]}",
        )
        outs.append(proc.stdout.strip().splitlines()[-1])
    _check(
        outs[0] == outs[1],
        leg,
        f"serving import changed the trainer: {outs[0]} != {outs[1]}",
    )
    digest = json.loads(outs[0])
    print(
        f"serving_soak: neutrality OK — trainer with the full serving stack "
        f"imported is bit-exact with one that never imported it "
        f"(params {digest['params_sha256'][:12]}…, traces {digest['trace_counts']})"
    )


# ---------------------------------------------------------------------------
# --actuate legs (ISSUE 20): the actuated chip offer, end to end
# ---------------------------------------------------------------------------


def _serve_spec(fc, run_dir: str, port: int, args, *, slo_p99_ms: float,
                mesh_spec: str = "dp1", device_ids: str = "0",
                buckets: str = "2,4,8", window_s: float = 3.0):
    """A supervised serving-replica RunSpec whose admin ``port`` is known
    to the controller — the thing that turns offer_chip from an advisory
    record into the actuated handshake. The short latency window matches
    the judge's settle: by judge time the drain gap has rolled out and
    the after-probe reads steady post-absorb traffic, not the gap."""
    return fc.RunSpec(
        name="server0",
        run_dir=run_dir,
        kind="serve",
        port=port,
        cmd=[
            sys.executable,
            os.path.abspath(__file__),
            "--serve-worker",
            "--run-dir", run_dir,
            "--port", str(port),
            "--seed", str(args.seed),
            "--slo-p99-ms", str(slo_p99_ms),
            "--mesh-spec", mesh_spec,
            "--device-ids", device_ids,
            "--device-count", "2",
            "--buckets", buckets,
            "--window-s", str(window_s),
        ],
    )


def _freed_chip_action(chip: int):
    """The trainer-side trigger: what restart_excluding leaves behind."""
    from distributed_training_pytorch_tpu.telemetry.controller import Action

    return Action(
        kind="restart_excluding",
        reason="straggler",
        params={"exclude_chip": int(chip)},
        evidence=[{"metric": "straggler_ratio", "value": 3.2}],
    )


def _start_fleet(fc, specs, run_root: str, args, *, settle_s: float = 1.0):
    from distributed_training_pytorch_tpu.telemetry.controller import ControllerConfig
    from distributed_training_pytorch_tpu.telemetry.events import EventLog
    from distributed_training_pytorch_tpu.telemetry.monitor import AlertConfig

    ctl_log = os.path.join(run_root, "controller_events.jsonl")
    ctl = fc.FleetController(
        specs,
        # A generous noise floor: the soak judges the MECHANISM (the
        # chip-scaled floor, the evidence chain), not CPU-emulation perf —
        # a drain pause inside the QPS window must not flake the verdict.
        config=ControllerConfig(
            max_restarts=2, backoff_s=0.1, confirm_polls=1,
            ab_noise_floor=0.5, offer_timeout_s=120.0,
            offer_settle_s=settle_s,
        ),
        monitor_config=AlertConfig(stale_after_s=60.0, dead_after_s=120.0),
        event_log=EventLog(ctl_log, process_index=0),
        interval=0.2,
    )
    ctl.start()
    return ctl, ctl_log


def leg_actuate(run_root: str, args) -> None:
    """The tentpole end to end: offer -> accept -> drain -> re-plan dp1->dp2
    -> settle -> A/B keep, with RetryClient traffic riding the 503s and a
    monitor that must never read the draining replica as dead."""
    import types

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fleet_controller as fc

    from distributed_training_pytorch_tpu.serving.client import (
        RetriesExhausted,
        RetryClient,
    )
    from distributed_training_pytorch_tpu.telemetry.events import (
        read_events,
        resolve_events_path,
    )
    from distributed_training_pytorch_tpu.telemetry.monitor import (
        AlertConfig,
        RunMonitor,
    )

    leg = "actuate"
    run_dir = os.path.join(run_root, "server0")
    os.makedirs(run_dir, exist_ok=True)
    port = _free_port()
    trainer = fc.RunSpec(
        name="trainer0", run_dir=os.path.join(run_root, "trainer0"),
        adopt=True, device_ids=(0, 1), mesh="fsdp2",
    )
    os.makedirs(trainer.run_dir, exist_ok=True)
    server = _serve_spec(fc, run_dir, port, args, slo_p99_ms=args.slo_p99_ms)
    # Settle past the replica's 3 s QPS window: the judge's after-probe
    # must read post-absorb steady state, not the drain gap.
    ctl, ctl_log = _start_fleet(fc, [trainer, server], run_root, args,
                                settle_s=4.0)
    try:
        row = (np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB).tolist()
        body_before = _wait_serving(port, row)

        stop = threading.Event()
        failures: list = []
        ok_count = [0]
        dead_sightings: list = []
        cli = RetryClient(max_attempts=8, base_delay_s=0.05,
                          max_delay_s=2.0, timeout_s=30.0)
        req_threads: list = []

        def one_request(r) -> None:
            # Through the retry helper: a 503 during the drain window is
            # the CONTRACT (Retry-After + backoff), not a failure. Only
            # an exhausted retry budget or a non-200 terminal answer
            # counts as failed.
            try:
                code, _body = cli.post_json(
                    f"http://127.0.0.1:{port}/predict",
                    {"tenant": "load", "inputs": [r]},
                )
                if code != 200:
                    failures.append(("status", code))
                else:
                    ok_count[0] += 1
            except RetriesExhausted as e:
                failures.append(("exhausted", e.attempts[-3:]))
            except Exception as e:  # noqa: BLE001
                failures.append(("transport", repr(e)))

        def hammer() -> None:
            # OPEN-LOOP arrivals: a new request every 20 ms regardless of
            # completions. A caller stuck honoring a long drain
            # Retry-After must not starve the after-window — fresh
            # arrivals keep probing, exactly like independent clients.
            rng = np.random.default_rng(args.seed + 5)
            while not stop.is_set():
                r = rng.integers(0, LM_VOCAB, size=(SEQ_LEN,)).tolist()
                th = threading.Thread(target=one_request, args=(r,),
                                      daemon=True)
                th.start()
                req_threads.append(th)
                time.sleep(0.02)

        def watch_monitor() -> None:
            # The tentpole's monitor clause: a draining replica is
            # DRAINING, never dead — polled live across the whole
            # handshake, not reconstructed afterwards.
            mon = RunMonitor(run_dir, AlertConfig(stale_after_s=60.0,
                                                  dead_after_s=120.0))
            while not stop.is_set():
                st = mon.poll()
                if st.status == "dead":
                    dead_sightings.append(st.verdict)
                time.sleep(0.1)

        threads = [threading.Thread(target=hammer, daemon=True),
                   threading.Thread(target=watch_monitor, daemon=True)]
        for th in threads:
            th.start()
        time.sleep(3.5)  # fill the replica's QPS window at steady rate

        status = types.SimpleNamespace(attempt=1, status="training",
                                       verdict="straggler")
        ctl._offer_freed_chip(
            ctl.runs["trainer0"], _freed_chip_action(1), status
        )
        time.sleep(0.5)  # post-verdict traffic across the grown mesh
        stop.set()
        for th in threads:
            th.join(timeout=30.0)
        for th in req_threads:  # every in-flight retry must resolve
            th.join(timeout=30.0)
        _check(not any(th.is_alive() for th in req_threads), leg,
               "a retrying request never resolved (hang)")

        _check(not failures, leg,
               f"{len(failures)} failed requests, first: {failures[:1]}")
        _check(ok_count[0] >= 10, leg,
               f"only {ok_count[0]} requests completed")
        _check(not dead_sightings, leg,
               f"monitor read the replica as dead: {dead_sightings[:1]}")

        # The absorb happened and was KEPT: dp1 -> dp2, same params.
        st = _get_json(port, "/status")
        _check(st["state"] == "serving", leg, f"end state {st['state']}")
        _check(st["chips"] == 2 and st["device_ids"] == [0, 1], leg,
               f"mesh did not grow: {st['chips']} chips {st['device_ids']}")
        _check(st["replans"] == 1 and st["drains"] == 1, leg,
               f"replans={st['replans']} drains={st['drains']}")
        body_after = _post(port, {"tenant": "probe", "inputs": [row]})[1]
        _check(body_after == body_before, leg,
               "response bytes changed across the re-plan (same params!)")

        acts = [a for a in ctl.runs["server0"].actions]
        kinds = [a.kind for a in acts]
        _check(kinds == ["offer_chip", "keep"], leg,
               f"controller actions {kinds} (wanted offer_chip, keep)")
        _check(acts[0].params.get("actuated") is True, leg,
               "offer_chip was advisory, not actuated")
        qpc = [e for e in acts[1].evidence
               if e.get("metric") == "qps_per_chip"]
        _check(bool(qpc) and qpc[0]["after"] >= qpc[0]["expected_floor"],
               leg, f"keep not evidenced by qps_per_chip: {acts[1].evidence}")

        # The audit chain, in wall-clock order across BOTH logs:
        # controller's offer_chip precedes the replica's accept -> drain
        # -> replan_done.
        replica = [r for r in read_events(resolve_events_path(run_dir))
                   if r.get("event") in ("offer_accept", "offer_decline",
                                         "drain_start", "replan_done")]
        _check([r["event"] for r in replica]
               == ["offer_accept", "drain_start", "replan_done"],
               leg, f"replica audit chain {[r['event'] for r in replica]}")
        offer_t = [r["t_wall"] for r in read_events(ctl_log)
                   if r.get("action") == "offer_chip"]
        _check(bool(offer_t) and offer_t[0] <= replica[0]["t_wall"], leg,
               "offer_chip not audited before the replica's accept")
        rp = replica[-1]
        _check(rp["from_mesh"] == {"data": 1}
               and rp["to_mesh"] == {"data": 2}, leg,
               f"replan_done meshes {rp['from_mesh']} -> {rp['to_mesh']}")
        print(
            f"serving_soak: actuate OK — chip 1 absorbed (dp1 -> dp2), "
            f"kept on qps/chip {qpc[0]['after']:.1f} >= floor "
            f"{qpc[0]['expected_floor']:.1f}, {ok_count[0]} requests with "
            f"0 failures across the drain, bytes bit-identical, "
            f"monitor never saw dead"
        )
    finally:
        ctl.shutdown()
        ctl.events.close()


def leg_actuate_decline(run_root: str, args) -> None:
    """A replica under SLO pressure must DECLINE: no drain, no re-plan,
    the decline audited with its SLO evidence."""
    import types

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fleet_controller as fc

    from distributed_training_pytorch_tpu.telemetry.events import (
        read_events,
        resolve_events_path,
    )

    leg = "actuate_decline"
    root = os.path.join(run_root, "decline")
    run_dir = os.path.join(root, "server0")
    os.makedirs(run_dir, exist_ok=True)
    port = _free_port()
    trainer = fc.RunSpec(
        name="trainer0", run_dir=os.path.join(root, "trainer0"),
        adopt=True, device_ids=(0, 1), mesh="fsdp2",
    )
    os.makedirs(trainer.run_dir, exist_ok=True)
    # An SLO no CPU can meet: the first window breaches, slo_ok -> False.
    server = _serve_spec(fc, run_dir, port, args, slo_p99_ms=0.001)
    ctl, _ = _start_fleet(fc, [trainer, server], root, args)
    try:
        row = (np.arange(SEQ_LEN, dtype=np.int32) % LM_VOCAB).tolist()
        _wait_serving(port, row)
        for _ in range(10):  # populate the latency window past the SLO
            _post(port, {"tenant": "load", "inputs": [row]})
        _check(_get_json(port, "/status")["slo_ok"] is False, leg,
               "replica not under SLO pressure — decline leg is vacuous")

        status = types.SimpleNamespace(attempt=1, status="training",
                                       verdict="straggler")
        ctl._offer_freed_chip(
            ctl.runs["trainer0"], _freed_chip_action(1), status
        )

        st = _get_json(port, "/status")
        _check(st["chips"] == 1 and st["replans"] == 0 and st["drains"] == 0,
               leg, f"decline actuated anyway: {st['chips']} chips, "
                    f"{st['replans']} replans")
        kinds = [a.kind for a in ctl.runs["server0"].actions]
        _check(kinds == ["offer_chip"], leg,
               f"controller actions {kinds} (decline must not keep/revert)")
        declines = [r for r in read_events(resolve_events_path(run_dir))
                    if r.get("event") == "offer_decline"]
        _check(len(declines) == 1 and "SLO" in declines[0]["reason"], leg,
               f"decline not audited with SLO evidence: {declines}")
        print(
            f"serving_soak: actuate_decline OK — replica under SLO "
            f"pressure declined chip 1 ({declines[0]['reason']!r}), "
            f"nothing drained, nothing re-planned"
        )
    finally:
        ctl.shutdown()
        ctl.events.close()


def leg_actuate_timeout(run_root: str, args) -> None:
    """A handshake that cannot reach its replica reverts cleanly and
    re-arms: the freed chip stays offerable. No child process — the
    port points at nothing, which IS the failure under test."""
    import types

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fleet_controller as fc

    leg = "actuate_timeout"
    root = os.path.join(run_root, "timeout")
    trainer = fc.RunSpec(
        name="trainer0", run_dir=os.path.join(root, "trainer0"),
        adopt=True, device_ids=(0, 1), mesh="fsdp2",
    )
    dead_port = _free_port()  # nothing listens here
    server = fc.RunSpec(
        name="server0", run_dir=os.path.join(root, "server0"),
        kind="serve", adopt=True, port=dead_port,
    )
    for spec in (trainer, server):
        os.makedirs(spec.run_dir, exist_ok=True)
    ctl, _ = _start_fleet(fc, [trainer, server], root, args)
    try:
        status = types.SimpleNamespace(attempt=1, status="training",
                                       verdict="straggler")
        for _ in range(2):  # re-armed: the SECOND offer must still fire
            ctl._offer_freed_chip(
                ctl.runs["trainer0"], _freed_chip_action(1), status
            )
        acts = ctl.runs["server0"].actions
        kinds = [a.kind for a in acts]
        _check(kinds == ["offer_chip", "revert"] * 2, leg,
               f"controller actions {kinds}")
        rev = acts[1]
        _check(rev.reason == "offer_timeout", leg,
               f"revert reason {rev.reason}")
        _check(rev.params["rearmed"] is True, leg, "revert did not re-arm")
        _check(rev.params["handshake_state"] == "offered", leg,
               f"handshake died in state {rev.params['handshake_state']}")
        print(
            f"serving_soak: actuate_timeout OK — unreachable replica on "
            f":{dead_port} reverted ({rev.reason}), offer re-armed and "
            f"fired again"
        )
    finally:
        ctl.shutdown()
        ctl.events.close()


def run_actuate(args) -> int:
    compat.force_host_devices(8)
    import tempfile

    failures = []
    with tempfile.TemporaryDirectory(prefix="serving_actuate_") as run_root:
        for leg_fn in (leg_actuate, leg_actuate_decline, leg_actuate_timeout):
            try:
                leg_fn(run_root, args)
            except SoakFailure as e:
                failures.append(str(e))
                print(f"serving_soak: FAIL {e}", file=sys.stderr)
    if failures:
        print(f"serving_soak: {len(failures)} actuate leg(s) FAILED",
              file=sys.stderr)
        return 1
    print("serving_soak: PASS — all actuate legs green")
    return 0


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def run_soak(args) -> int:
    compat.force_host_devices(8)
    import tempfile

    failures = []
    with tempfile.TemporaryDirectory(prefix="serving_soak_") as run_root:
        for leg_fn in (leg_slo, leg_vision, leg_hot_swap, leg_failover,
                       leg_zero_capacity, leg_neutrality):
            try:
                leg_fn(run_root, args)
            except SoakFailure as e:
                failures.append(str(e))
                print(f"serving_soak: FAIL {e}", file=sys.stderr)
    if failures:
        print(f"serving_soak: {len(failures)} leg(s) FAILED", file=sys.stderr)
        return 1
    print("serving_soak: PASS — all legs green")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--quick", action="store_true", help="short CI windows")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--traffic-s", type=float, default=None,
                        help="open-loop traffic window per leg (default 10, 3 with --quick)")
    parser.add_argument("--rate-hz", type=float, default=None,
                        help="Poisson arrival rate (default 60, 30 with --quick)")
    parser.add_argument("--slo-p99-ms", type=float, default=500.0,
                        help="p99 SLO asserted by the slo leg and exported by every server")
    parser.add_argument("--actuate", action="store_true",
                        help="run the actuated-offer legs instead (ISSUE 20)")
    parser.add_argument("--serve-worker", action="store_true",
                        help="child mode: one supervised replica (failover leg)")
    parser.add_argument("--run-dir", default=None)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--mesh-spec", default="tp2",
                        help="serve-worker mesh spec (actuate legs use dp1)")
    parser.add_argument("--device-ids", default="",
                        help="serve-worker: comma-separated device ids to serve on")
    parser.add_argument("--device-count", type=int, default=2,
                        help="serve-worker: forced host device count")
    parser.add_argument("--buckets", default="1,2,4,8",
                        help="serve-worker: comma-separated batch buckets")
    parser.add_argument("--window-s", type=float, default=30.0,
                        help="serve-worker: trailing latency/QPS window")
    parser.add_argument("--neutrality-worker", action="store_true",
                        help="child mode: short deterministic trainer run (neutrality leg)")
    parser.add_argument("--with-serving", action="store_true",
                        help="neutrality child: import the full serving stack first")
    args = parser.parse_args()
    if args.traffic_s is None:
        args.traffic_s = 3.0 if args.quick else 10.0
    if args.rate_hz is None:
        args.rate_hz = 30.0 if args.quick else 60.0
    if args.neutrality_worker:
        return neutrality_worker(args)
    if args.serve_worker:
        return serve_worker(args)
    if args.actuate:
        return run_actuate(args)
    return run_soak(args)


if __name__ == "__main__":
    sys.exit(main())
