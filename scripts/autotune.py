#!/usr/bin/env python
"""XLA-flag / schedule autotuner CLI (ISSUE 17) — sweep a declared candidate
space on the bench workload and commit the winner as ``TUNED.json``.

The flat r02->r05 bench streak showed the stack could *measure* but nothing
*searched*: every knob with a measured win somewhere (latency-hiding
scheduler, scoped VMEM, chain length, Pallas hot paths) sat behind manual
env flags. This CLI closes the loop:

* **Candidate space** — declared up front (``CANDIDATES`` below, or
  ``--candidates FILE.json``): XLA latency-hiding/async-collective flags
  (applied per-compile via ``train.engine.xla_flag_options`` — never by
  mutating global XLA_FLAGS), ``chain_steps``, microbatch shape, and the
  unified ``pallas`` knob. The grammar is ``train.autotune.Candidate``;
  docs/performance.md "Autotuning" documents it.
* **Measurement** — every candidate runs through
  ``train.autotune.measure_chained_step``: two-length differencing on the
  REAL ``TrainEngine.compile_chained_train_steps`` executable of the
  ``BENCH_MODEL`` workload (``bench.build_bench_setup`` — the program that
  ships), plus a perf_gate-style traced window for category fractions.
* **Ranking + refusal** — ``train.autotune.rank_candidates``: lowest
  step_ms wins; every delta is attributed per-category through
  ``profiling.diff`` (the run_compare implementation); a candidate whose
  provenance differs from the baseline on an UNdeclared key is refused
  (PR 14 rule). A win inside the flat-streak noise band is reverted.
* **Evidence** — ``--emit`` writes the full report (baseline, ranked
  candidates with attribution, refusals, verdict) as TUNED.json; entries
  opt in with ``TUNED=1`` (``train.autotune.tuned_defaults``).

``--self-test`` (the scripts/verify.sh stage; CPU, ~seconds) runs a real
tiny sweep with two teeth checks: a deliberately 3x de-tuned chain_steps=1
baseline (``--inject-slowdown``, perf_gate's seam pattern — the injection is
printed and applied AFTER measurement) that every real candidate must beat
with per-category attribution attached, and a provenance-mismatched
candidate (undeclared dtype drift) that MUST land in the refused list.
Exit 0 pass, 1 fail.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_training_pytorch_tpu.telemetry.provenance import provenance_fields
from distributed_training_pytorch_tpu.train import autotune as autotune_lib
from distributed_training_pytorch_tpu.train import xla_flag_options
from distributed_training_pytorch_tpu.train.autotune import Candidate

# The declared bench-host candidate space (docs/performance.md "Autotuning").
# Every knob here has a measured win SOMEWHERE in this repo's history
# (BASELINE.md r3-r5, utils/tpu.py) — the sweep's job is to find which
# combination wins on the CURRENT program, with evidence.
CANDIDATES = [
    Candidate("latency-hiding",
              {"xla_flags": "--xla_tpu_enable_latency_hiding_scheduler=true"},
              "overlap DMA/collectives with compute"),
    Candidate("async-collectives",
              {"xla_flags": "--xla_tpu_enable_async_collective_fusion=true"
                            " --xla_tpu_enable_async_collective_fusion_fuse_all_gather=true"},
              "async all-reduce/all-gather fusion"),
    Candidate("lhs+scoped-vmem",
              {"xla_flags": "--xla_tpu_enable_latency_hiding_scheduler=true"
                            " --xla_tpu_scoped_vmem_limit_kib=98304"},
              "latency hiding + wider scoped VMEM (ConvNeXt-L's +6% value)"),
    Candidate("chain-20", {"chain_steps": 20},
              "longer on-device window amortizes dispatch further"),
    Candidate("chain-40", {"chain_steps": 40}, ""),
    Candidate("pallas-on", {"pallas": True},
              "force the Pallas hot paths (ops/dispatch.py)"),
]


def _load_candidates(path: str | None) -> list[Candidate]:
    if not path:
        return CANDIDATES
    with open(path, encoding="utf-8") as f:
        rows = json.load(f)
    return [Candidate(r["name"], r.get("knobs", {}), r.get("note", "")) for r in rows]


def _result(name, knobs, measurement, note="") -> dict:
    return {"name": name, "knobs": dict(knobs), "note": note,
            "measurement": measurement}


def _print_report(report: dict) -> None:
    base = report["baseline"]
    print(f"autotune: baseline {base['name']}: "
          f"{base['measurement']['step_ms']} ms/step")
    for entry in report["ranked"]:
        line = (f"autotune:   {entry['name']:<18s} "
                f"{entry['measurement']['step_ms']:>9.3f} ms "
                f"({entry['delta_ms']:+.3f} ms)")
        if entry["attribution_text"]:
            line += f"  [{entry['attribution_text']}]"
        print(line)
    for ref in report["refused"]:
        print(f"autotune:   {ref['name']:<18s} REFUSED — provenance differs "
              f"on undeclared keys {ref['differing_keys']}")
    if report["kept"]:
        w = report["winner"]
        print(f"autotune: WINNER {w['name']} ({w['delta_ms']:+.3f} ms, "
              f"knobs {w['knobs']}) — kept (beats baseline past the "
              f"{report['rel_margin']:.0%} flat-streak band)")
    else:
        print("autotune: no candidate beat the baseline past the "
              f"{report['rel_margin']:.0%} band — baseline config stands "
              "(a sub-noise win is reverted, not shipped)")


# ---------------------------------------------------------------- self-test


def _tiny_engine(batch: int = 32):
    """The perf_gate GateNet shape, shrunk: a real conv+dense TrainEngine
    workload that compiles in ~a second on CPU — the sweep measures the
    same executable family the real mode does, just small."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.train import (
        TrainEngine,
        make_supervised_loss,
    )

    class TuneNet(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = nn.relu(nn.Conv(8, (3, 3))(x))
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10)(x)

    def criterion(logits, b):
        loss = cross_entropy_loss(logits, b["label"])
        return loss, {"loss": loss}

    model = TuneNet()
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh_lib.create_mesh(),
    )
    rng = np.random.RandomState(0)
    gbatch = engine.shard_batch({
        "image": rng.randn(batch, 12, 12, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(batch,)).astype(np.int32),
    })
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 12, 12, 3)))
    )
    return engine, state, gbatch, batch


def self_test(inject_slowdown: float) -> int:
    batch = 32
    engine, state, gbatch, batch = _tiny_engine(batch)

    def prov(chain_steps, dtype="float32"):
        return provenance_fields(
            mesh="dp1", dtype=dtype, chain_steps=chain_steps, batch=batch
        )

    # Baseline: a DELIBERATELY de-tuned config — chain_steps=1 (maximum
    # per-dispatch overhead share) with the measured time multiplied by
    # --inject-slowdown AFTER measurement (the measurement itself is
    # untouched; perf_gate's "gate has teeth" seam). Every real candidate
    # below must rank ahead of it, or the ranking has no teeth.
    meas, state = autotune_lib.measure_chained_step(
        engine, state, gbatch, chain_steps=1, windows=2
    )
    meas["provenance"] = prov(1)
    meas["step_ms"] = round(meas["step_ms"] * inject_slowdown, 4)
    meas["injected_slowdown"] = inject_slowdown
    print(f"autotune: SELF-TEST — injected x{inject_slowdown} slowdown into "
          "the de-tuned chain_steps=1 baseline (every real candidate must "
          "out-rank it)")
    baseline = _result("baseline-chain1-detuned", {"chain_steps": 1}, meas)

    results = []
    for cs in (2, 4, 8):
        meas, state = autotune_lib.measure_chained_step(
            engine, state, gbatch, chain_steps=cs, windows=2
        )
        meas["provenance"] = prov(cs)
        results.append(_result(f"chain-{cs}", {"chain_steps": cs}, meas))

    # The refusal leg: same numbers as chain-2, but the provenance says the
    # measurement ran a different compute dtype — and "dtype" is NOT in the
    # candidate's declared knobs. PR 14 rule: refused, never ranked.
    drift = dict(results[0]["measurement"], provenance=prov(2, dtype="bfloat16"))
    results.append(_result("dtype-drift", {"chain_steps": 2}, drift))

    report = autotune_lib.rank_candidates(baseline, results)
    _print_report(report)

    failures = []
    refused_names = {r["name"] for r in report["refused"]}
    if refused_names != {"dtype-drift"}:
        failures.append(f"expected exactly dtype-drift refused, got {refused_names}")
    elif report["refused"][0]["differing_keys"] != ["dtype"]:
        failures.append("refusal must name the undeclared key 'dtype', got "
                        f"{report['refused'][0]['differing_keys']}")
    if any(e["name"] == "dtype-drift" for e in report["ranked"]):
        failures.append("refused candidate leaked into the ranking")
    if not report["kept"]:
        failures.append("no winner kept — the x3-de-tuned baseline was not beaten")
    else:
        if report["winner"]["delta_ms"] >= 0:
            failures.append("winner does not improve on the baseline")
        if not report["winner"]["attribution"]:
            failures.append("winner carries no per-category attribution "
                            "(category capture failed on both sides?)")
    if len(report["ranked"]) < 3:
        failures.append(f"expected >= 3 ranked candidates, got {len(report['ranked'])}")

    # TUNED.json round-trip: emit -> reload -> the entry-side opt-in returns
    # the winner's knobs under TUNED=1 and NOTHING otherwise.
    with tempfile.TemporaryDirectory(prefix="autotune_selftest_") as tmp:
        path = os.path.join(tmp, "TUNED.json")
        autotune_lib.emit_tuned(path, report)
        knobs_on = autotune_lib.tuned_defaults(path, env={"TUNED": "1"})
        knobs_off = autotune_lib.tuned_defaults(path, env={})
        if report["kept"] and knobs_on != report["winner"]["knobs"]:
            failures.append(f"tuned_defaults round-trip mismatch: {knobs_on}")
        if knobs_off != {}:
            failures.append("tuned_defaults must be empty with TUNED unset "
                            f"(autotuner off = no behavior change), got {knobs_off}")

    # The XLA_FLAGS bridge: parse + reject, both directions.
    opts = xla_flag_options("--xla_a=2 --xla_b")
    if opts != {"xla_a": "2", "xla_b": "true"}:
        failures.append(f"xla_flag_options parse mismatch: {opts}")
    try:
        xla_flag_options("--not_an_xla_flag=1")
        failures.append("xla_flag_options accepted a non-xla flag")
    except ValueError:
        pass

    if failures:
        for f in failures:
            print(f"autotune: SELF-TEST FAIL — {f}")
        return 1
    print("autotune: self-test OK (ranking teeth, provenance refusal, "
          "TUNED round-trip, XLA-flag bridge)")
    return 0


# --------------------------------------------------------------- real sweep


def run_sweep(args) -> int:
    import bench

    from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng

    enable_fast_rng()
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    windows = int(os.environ.get("BENCH_WINDOWS", "3"))
    setup = bench.build_bench_setup()
    base_opts = setup["compiler_options"]
    dtype = setup["dtype_name"] or "bf16"

    def prov(chain_steps, batch, extra_flags=None):
        p = provenance_fields(
            mesh=setup["mesh_spec"], dtype=dtype,
            chain_steps=chain_steps, batch=batch,
        )
        if extra_flags:
            # Stamp the EFFECTIVE flags: the sweep applies them per-compile
            # (compiler_options), but the provenance must say what the
            # executable actually ran under.
            p["xla_flags"] = (p["xla_flags"] + " " + extra_flags).strip()
        return p

    print(f"autotune: baseline {setup['model_name']} batch={setup['batch']} "
          f"chain_steps={steps} (BENCH_* env)")
    meas, _ = autotune_lib.measure_chained_step(
        setup["engine"], setup["state"], setup["gbatch"],
        chain_steps=steps, windows=windows, compiler_options=base_opts,
    )
    meas["provenance"] = prov(steps, setup["batch"])
    baseline = _result("baseline", {"chain_steps": steps}, meas)

    results = []
    for cand in _load_candidates(args.candidates):
        cs = int(cand.knobs.get("chain_steps", steps))
        flags = cand.knobs.get("xla_flags")
        opts = dict(base_opts or {})
        if flags:
            opts.update(xla_flag_options(flags))
        cand_setup = setup
        if cand.knobs.get("pallas") is not None:
            # The pallas knob changes the MODEL, not the compile: rebuild
            # the whole setup with BENCH_PALLAS so the candidate measures
            # the program a PALLAS=1 entry would run.
            saved = os.environ.get("BENCH_PALLAS")
            os.environ["BENCH_PALLAS"] = "1" if cand.knobs["pallas"] else "0"
            try:
                cand_setup = bench.build_bench_setup()
            finally:
                if saved is None:
                    os.environ.pop("BENCH_PALLAS", None)
                else:
                    os.environ["BENCH_PALLAS"] = saved
        print(f"autotune: measuring {cand.name} {cand.knobs}")
        try:
            meas, _ = autotune_lib.measure_chained_step(
                cand_setup["engine"], cand_setup["state"], cand_setup["gbatch"],
                chain_steps=cs, windows=windows, compiler_options=opts or None,
            )
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # compile/run is reported and skipped; the sweep continues.
            print(f"autotune: {cand.name} failed ({e}) — skipped", file=sys.stderr)
            continue
        meas["provenance"] = prov(cs, cand_setup["batch"], extra_flags=flags)
        results.append(_result(cand.name, cand.knobs, meas, cand.note))

    report = autotune_lib.rank_candidates(baseline, results)
    report["workload"] = {
        "model": setup["model_name"], "batch": setup["batch"],
        "image_size": setup["image_size"], "dtype": dtype,
        "steps": steps, "windows": windows,
    }
    _print_report(report)
    if args.emit:
        autotune_lib.emit_tuned(args.emit, report)
        print(f"autotune: report written to {args.emit} — commit it with the "
              "bench round it justifies (docs/performance.md)")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--self-test", action="store_true",
                        help="tiny CPU sweep with teeth + refusal checks "
                             "(the verify.sh stage)")
    parser.add_argument("--inject-slowdown", type=float, default=3.0,
                        metavar="F",
                        help="self-test seam: de-tune the baseline by F after "
                             "measurement (default 3.0)")
    parser.add_argument("--candidates", default=None, metavar="FILE",
                        help="JSON candidate list overriding the built-in "
                             "space ([{name, knobs, note}, ...])")
    parser.add_argument("--emit", default=None, metavar="PATH",
                        help="write the full report (TUNED.json) here")
    args = parser.parse_args()
    if args.self_test:
        return self_test(args.inject_slowdown)
    return run_sweep(args)


if __name__ == "__main__":
    sys.exit(main())
