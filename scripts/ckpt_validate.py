"""Stdlib-only checkpoint-validity re-check, shared by the acceptance soaks.

``valid_checkpoints`` is a deliberate re-implementation of
``CheckpointManager.validate`` (per-file size + SHA-256 against the commit
manifest) using nothing outside the standard library, so the soak parents'
"is there something restorable on disk?" check cannot share a bug with the
checkpoint code under test.  Both ``chaos_soak.py`` and
``fleet_controller.py`` import this one copy (ISSUE 16 satellite), so the
two acceptance checks cannot drift apart.
"""

from __future__ import annotations

import hashlib
import json
import os

MANIFEST_NAME = "manifest.dtp.json"


def valid_checkpoints(weights_dir: str) -> list[str]:
    """Committed checkpoint names passing manifest validation. A stdlib
    re-implementation of ``CheckpointManager.validate`` (size + SHA-256 per
    file), so the soak's 'is there something restorable?' check is
    independent of the code under test."""
    names = []
    if not os.path.isdir(weights_dir):
        return names
    for entry in sorted(os.listdir(weights_dir)):
        if entry.startswith(".") or entry.endswith(".old"):
            continue
        path = os.path.join(weights_dir, entry)
        manifest_path = os.path.join(path, MANIFEST_NAME)
        if not os.path.isdir(path) or not os.path.isfile(manifest_path):
            continue
        try:
            with open(manifest_path, encoding="utf-8") as f:
                manifest = json.load(f)
            ok = True
            for rel, want in manifest.get("files", {}).items():
                fp = os.path.join(path, rel)
                if not os.path.isfile(fp) or os.path.getsize(fp) != want["size"]:
                    ok = False
                    break
                digest = hashlib.sha256()
                with open(fp, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        digest.update(chunk)
                if digest.hexdigest() != want["sha256"]:
                    ok = False
                    break
        except (OSError, json.JSONDecodeError, KeyError, TypeError):
            ok = False
        if ok:
            names.append(entry)
    return names
