#!/usr/bin/env python
"""Retrace guard — chained-dispatch-path CI gate (ISSUE 2 satellite).

Runs a tiny CPU training job through the REAL ``Trainer.train_epoch`` hot
path with ``chain_steps=4`` (windows + epoch-tail singles, two epochs so
every executable is re-dispatched) and asserts, via the engine's compilation
counters (``TrainEngine.trace_counts``, bumped once per jit TRACE), that:

* the chained window executable compiled exactly ONCE for its (length,
  shapes) — a second trace means something in the dispatch path (sharding
  drift, shape drift, cache-key churn) silently retraces every window, which
  on a real model turns each window into a multi-minute compile;
* the single-step executable (epoch tails) also compiled exactly once;
* no unexpected chain lengths were compiled (a tail must fall back to the
  single step, not compile a fresh chain per tail length).

Fails fast (nonzero exit) so ``scripts/verify.sh`` catches dispatch-path
regressions before the full test suite runs.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import optax
from flax import linen as nn

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.trainer import Trainer


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(3)(x)


class GuardTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(48,)).astype(np.int32)
        images = (rng.randn(48, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return TinyNet()

    def build_criterion(self):
        def criterion(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


def main() -> int:
    import shutil

    tmp = tempfile.mkdtemp(prefix="retrace_guard_")
    try:
        trainer = GuardTrainer(
            max_epoch=2,  # epoch 2 re-dispatches every executable: cache must hit
            batch_size=8,  # 48 records -> 6 steps/epoch: one window + 2-step tail
            save_folder=tmp,
            chain_steps=4,
            num_workers=0,
            log_every=0,
            async_checkpoint=False,
            progress=False,
            logger=type("Q", (), {"log": staticmethod(lambda *a, **k: None)})(),
        )
        trainer.train()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    counts = dict(trainer.engine.trace_counts)
    expected = {"chained_4": 1, "train_step": 1}
    errors = []
    for key, want in expected.items():
        got = counts.get(key, 0)
        if got != want:
            errors.append(f"{key}: traced {got}x, expected {want}x")
    stray = [k for k in counts if k.startswith("chained_") and k not in expected]
    if stray:
        errors.append(
            f"unexpected chain lengths compiled: {stray} (epoch tails must "
            "reuse the single step, not compile per-tail chains)"
        )
    if errors:
        print(f"RETRACE GUARD FAILED — trace counts {counts}:", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print(f"retrace guard OK: {counts} (chained executable compiled once per shape)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
