#!/usr/bin/env bash
# Tier-1 verification with a fast import-health gate.
#
# Stage 1 runs `pytest --collect-only`: any module that fails to import
# (a moved JAX API, a broken compat shim, a missing dependency) fails here in
# seconds, instead of surfacing as a wall of per-module collection ERRORs
# buried in a multi-minute test run — exactly how the seed's 14 import
# breakages went unnoticed.
#
# Stage 2 is the static audit (docs/static_analysis.md): generic lint (ruff
# or the stdlib fallback), jaxlint's seven project rules (host syncs in
# compiled regions, un-rank-gated writes, unlocked cross-thread mutation,
# wall-clock in jitted code, bare excepts, undonated state jits, unstrict
# pytree-leaf zips — every waiver printed with its reason), the
# compiled-program HLO audit (100% param/opt-state donation on the real
# single-step AND chained programs, no fp32 dot/conv under bf16, no host
# callbacks in the chained window), and the SPMD communication audit
# (ISSUE 11): a collective inventory of the partitioned dp8/fsdp8/tp2x4/
# dp2fsdp2tp2 single-step and chained programs checked against the analytic
# expected-comm model (no accidental full-param gathers on the tensor axis,
# totals within the model's bound) and gated against COMM_BASELINE.json
# exactly like the perf gate. The audits run on 8 forced-host devices so
# donation + precision + collectives are all verified on genuinely sharded
# SPMD programs (ISSUE 10/11). The gate's teeth are tested on every run:
# an injected lint violation, an injected undonated lowering (sharded
# programs included), and an injected mis-ruled TP spec (whose optimizer
# update must all-gather the full parameter) must each make it FAIL.
#
# Stage 3 is a ~8s CPU run through the real chained Trainer hot path
# asserting (via the engine's compilation counters) that the chained
# executable compiles exactly once per shape — a dispatch-path regression
# that silently retraces every window fails here in seconds instead of as a
# mysterious multi-minute-per-window slowdown on real hardware.
#
# Stage 4 is a ~10s CPU digits run in precision="bf16" asserting the loss
# decreases, no steps are skipped, compute runs in bf16, and master weights
# stay fp32 — precision regressions fail fast like retrace regressions.
#
# Stage 5 is a short CPU digits run with telemetry="on" asserting the event
# log is well-formed JSONL, goodput bucket fractions sum to 1 +- eps, and the
# on-device health stats rode the chained windows without a retrace. The run
# is also traced with profile=ProfileConfig (ISSUE 6): the capture must
# complete, its StepProfile category fractions must sum to 1 +- eps, and the
# profile_capture event must land in the log.
#
# Stage 6 is the memory-accounting gate (docs/memory.md): the preflight's
# predicted peak must equal the number re-derived from
# compiled.memory_analysis() by independent stdlib arithmetic on the real
# digits single-step AND chained programs, with buffer-class fractions
# summing to 1 — and its --inject-oversize self-test: a deliberately
# unfittable capacity MUST fail preflight with a finite, actually-fitting
# batch recommendation (the perf-gate "gate has teeth" pattern).
#
# Stage 7 is the sharded-training smoke (docs/parallelism.md): on 8
# forced-host CPU devices, an fsdp=8 run must be BIT-EXACT with pure DP
# (losses + params), a data=2/fsdp=2/tensor=2 run must match DP to
# float32-ULP with bit-exact sharded init, the sharded chained trainer must
# compile once per shape, and a SIGTERM-killed fsdp=8 run must resume under
# a pure-DP mesh (the resharding restore path) and finish bit-exact with an
# uninterrupted run.
#
# Stage 8 is the chaos soak in --quick mode: a real digits training job killed
# 3 times (graceful SIGTERM, SIGKILL mid-background-commit, SIGKILL mid-
# chained-window) at seeded offsets, resumed after each kill, asserting every
# kill leaves >= 1 valid checkpoint, the final params are bit-exact with an
# uninterrupted run, and the async save's hot-loop stall is < 25% of the sync
# save wall time. CHAOS_SEED reproduces a failing schedule deterministically.
#
# Stage 9 is the elastic chaos soak (ISSUE 12): the same digits job run on
# 8 forced-host devices under an fsdp=8 mesh, killed (SIGTERM / SIGKILL) and
# resumed on 4 devices with mesh=None — the Trainer must re-plan the mesh +
# grad-accum factor from the checkpoint's sharding record — plus the mirror
# 4->8 grow leg. Asserts every kill leaves a valid sharded checkpoint, every
# elastic resume completes and logs an elastic_restore event with the
# expected axes/accum, the elastic resume is BIT-EXACT with an explicitly
# hand-configured twin resume (the 4->8 leg with no accum change), and final
# params match an uninterrupted same-global-batch run within the documented
# tolerance (docs/fault_tolerance.md).
#
# Stage 10 is the perf-regression gate (docs/profiling.md): a ~10s CPU
# measurement of the real chained-engine path, gated as a machine-portable
# calibrated ratio against the committed PERF_BASELINE.json — a step-time
# regression past tolerance (an accidental retrace, a lost chained dispatch
# path) fails here. The gate's own teeth are tested on every run: a
# deliberate 3x injected slowdown must make it FAIL.
#
# Stage 11 is the data-wait gate (ISSUE 13 / ROADMAP item 5): a short real
# digits Trainer run with telemetry on, gating the steady-state data_wait
# goodput fraction against the committed PERF_BASELINE.json ceiling — the
# input pipeline cannot quietly become the bottleneck. Teeth: an injected
# per-batch loader sleep (the ShardedLoader.load_delay_s seam) must FAIL.
#
# Stage 12 is the run-doctor self-test (ISSUE 13; docs/observability.md):
# four short digits runs — a clean twin plus three with a known bottleneck
# injected through existing seams (loader sleep, async commit_delay_s,
# FaultPlan hang) — and the doctor must name each culprit (data_bound /
# checkpoint_stall / straggler) and say healthy on the clean twin. The
# clean twin's exported timeline must be valid trace-event JSON whose
# goodput spans re-derive the meter's fractions within epsilon.
#
# Stage 13 is the live-monitor self-test (ISSUE 15; docs/observability.md
# "Live monitoring"): run_monitor.py --self-test drives the streaming
# monitor against real background digits runs through the existing fault
# seams — a clean run must read training/healthy live and match
# run_doctor.py's post-hoc steady fractions to 1e-6 (byte-identical
# diagnoses), an injected FaultPlan hang must flip the verdict to
# stale_heartbeat while the watchdog's patrol heartbeats keep the log
# breathing, SIGKILL mid-hang must flip it to dead, a loader-sleep run
# followed live must raise exactly ONE debounced data_bound alert, and
# the --once exit codes (0 clean / 1 degraded / 2 dead) are asserted.
#
# Stage 14 is the run-comparison gate (ISSUE 14; docs/profiling.md
# "before/after ritual"): run_compare.py --self-test — identical twin runs
# must diff clean (no goodput bucket over the noise floor), and three
# injected known-cause slowdowns (a synthetic 3x convolution, the loader
# load_delay_s seam, the async committer commit_delay_s seam) must each be
# attributed to the correct category/bucket with evidence refs — followed
# by bench_history.py --self-test: the committed BENCH_r02->r05 plateau
# (step_ms ~76 ms flat for four rounds) must be detected as a flat streak
# on the committed files themselves — AND must have ended: BENCH_r06 (the
# autotuned round, ISSUE 17) has to sit outside the flat band, so a future
# re-flattened line fails this gate instead of sitting quiet.
#
# Stage 15 is the autotuner gate (ISSUE 17; docs/performance.md
# "Autotuning"): autotune.py --self-test measures a deliberately 3x de-tuned
# baseline on a tiny CPU workload (the perf-gate inject-slowdown pattern —
# applied AFTER measurement so the seam cannot leak into candidates), sweeps
# >= 3 declared chain_steps candidates, and must rank the known-win seam
# first with per-category attribution through profiling.diff — while a
# candidate whose provenance drifted on an UNdeclared key (dtype) must be
# REFUSED, never ranked (the run_compare rule from ISSUE 14, applied
# per-candidate). The TUNED.json emit/load round-trip and the XLA-flag ->
# per-compile compiler_options bridge are asserted in the same run. A
# Pallas-parity smoke leg then re-checks kernel<->plain forward AND backward
# parity in interpret mode plus the one-time kernel_dispatch telemetry and
# the shared scan-chain timing core.
#
# Stage 16 is the fleet-controller soak (ISSUE 16; docs/fault_tolerance.md
# "Closed-loop recovery"): fleet_controller.py --soak --quick spawns a 3-run
# digits fleet and injects one disease per run (SIGKILL mid-run, a FaultPlan
# hang tripping the step watchdog, the slow_chip seam degrading one named
# chip under fsdp=2); the controller must restore ALL THREE to healthy
# autonomously — restart from latest_valid, restart excluding exactly the
# slow chip via the elastic re-plan, and an A/B-judged prefetch tune on the
# starved run — with every decision audited as a controller_action record
# carrying evidence, and final params within the elastic tolerance of
# uninterrupted twins. The --max-restarts 0 leg must REFUSE (record the
# decision, touch nothing) and exit non-zero: the controller never acts
# without budget.
#
# Stage 18 is the actuated-offer soak (ISSUE 20; docs/serving.md "Drain,
# re-plan, and degraded mode"): serving_soak.py --actuate drives the full
# self-healing handshake against real subprocess replicas — a chip freed by
# a trainer's restart_excluding is offered over /admin/offer, the accepting
# dp1 replica drains (bounded deadline, typed 503 + Retry-After) and
# re-plans live onto dp2, and the absorb is A/B-judged on QPS-per-chip with
# the chip-scaled expected floor and KEPT; RetryClient traffic rides the
# drain with ZERO failed requests and bit-identical response bytes across
# the re-plan; the offer_chip -> offer_accept -> drain_start -> replan_done
# audit chain is asserted in wall-clock order across both flight recorders;
# a monitor polling throughout must never read the draining replica as
# dead. A replica under SLO pressure must DECLINE (nothing drained), and a
# handshake against an unreachable replica must revert cleanly and re-arm.
#
# Stage 19 is the streaming-data soak (ISSUE 19; docs/data.md): a real
# digits run streaming DTPR1 record shards through the StreamingLoader's
# decode pool, killed (SIGTERM + SIGKILL) at seeded offsets and resumed
# from latest_valid — the consumed record-id sequence must be
# byte-identical to an uninterrupted twin's (the loader's record_log audit
# trail, compared with a stdlib JSONL parse) and final params bit-exact;
# the resumed attempt's first consumed batch must equal the checkpoint's
# data/ cursor (O(1) positioning, no replay). An elastic 8->4 leg asserts
# the re-planned shard assignment changes per-host splits but NOT the
# global sequence (params within the documented tolerance); a
# decode-worker-crash leg must respawn and complete (never a hang); a
# corrupt-record leg must skip-and-count under skip_corrupt; and the clean
# streaming run must read 'healthy' from run_doctor (never data_bound).
#
# Stage 20 is the ROADMAP.md tier-1 command verbatim.
set -o pipefail

cd "$(dirname "$0")/.."

echo "== stage 1/20: import health (pytest --collect-only) =="
if ! JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --collect-only \
    -p no:cacheprovider > /tmp/_collect.log 2>&1; then
  echo "COLLECTION FAILED — import breakage (full log: /tmp/_collect.log):"
  grep -aE "ERROR|ImportError|ModuleNotFoundError" /tmp/_collect.log | head -40
  exit 2
fi
tail -1 /tmp/_collect.log

echo "== stage 2/20: static audit (generic + jaxlint + HLO + comm) =="
if ! JAX_PLATFORMS=cpu python scripts/static_audit.py; then
  echo "STATIC AUDIT FAILED — fix the finding or waive it inline with a reason"
  echo "(# jaxlint: disable=<rule> -- <why>; catalog: docs/static_analysis.md;"
  echo " comm-baseline drift? re-record: scripts/static_audit.py --update-comm-baseline)"
  exit 3
fi
# Each injection run skips the passes it does not target (they already ran
# clean above) — the self-tests pay only for the pass under test.
if JAX_PLATFORMS=cpu python scripts/static_audit.py --inject-violation lint --skip-hlo --skip-comm \
    > /tmp/_audit_selftest.log 2>&1; then
  echo "STATIC AUDIT SELF-TEST FAILED — injected lint violations PASSED the gate"
  exit 3
fi
if JAX_PLATFORMS=cpu python scripts/static_audit.py --inject-violation hlo --skip-comm \
    > /tmp/_audit_selftest.log 2>&1; then
  echo "STATIC AUDIT SELF-TEST FAILED — an undonated program PASSED the HLO audit"
  exit 3
fi
if JAX_PLATFORMS=cpu python scripts/static_audit.py --inject-violation comm --skip-hlo \
    > /tmp/_audit_selftest.log 2>&1; then
  echo "STATIC AUDIT SELF-TEST FAILED — a mis-ruled TP spec (full-param all-gather) PASSED the comm audit"
  exit 3
fi
echo "static_audit self-tests OK: injected lint + donation + comm violations correctly failed"

echo "== stage 3/20: chained-dispatch retrace guard =="
if ! JAX_PLATFORMS=cpu python scripts/retrace_guard.py; then
  echo "RETRACE GUARD FAILED — the chained executable recompiles per window"
  exit 4
fi

echo "== stage 4/20: mixed-precision smoke (bf16 digits) =="
if ! JAX_PLATFORMS=cpu python scripts/precision_smoke.py; then
  echo "PRECISION SMOKE FAILED — bf16 training path regressed"
  exit 5
fi

echo "== stage 5/20: telemetry smoke (event log + goodput + stats) =="
if ! JAX_PLATFORMS=cpu python scripts/telemetry_smoke.py; then
  echo "TELEMETRY SMOKE FAILED — observability subsystem regressed"
  exit 6
fi

echo "== stage 6/20: memory-accounting gate (preflight parity + oversize self-test) =="
if ! JAX_PLATFORMS=cpu python scripts/memory_probe.py; then
  echo "MEMORY PROBE FAILED — preflight prediction drifted from compiled.memory_analysis()"
  exit 7
fi
if ! JAX_PLATFORMS=cpu python scripts/memory_probe.py --inject-oversize; then
  echo "MEMORY PROBE SELF-TEST FAILED — an unfittable config must fail preflight with a batch recommendation"
  exit 7
fi

echo "== stage 7/20: sharded-training smoke (FSDP/TP parity + resharding resume) =="
if ! JAX_PLATFORMS=cpu python scripts/sharding_smoke.py; then
  echo "SHARDING SMOKE FAILED — FSDP/TP parity, sharded retrace guard, or the resharding restore path regressed"
  exit 8
fi

echo "== stage 8/20: chaos soak (kill/resume, async checkpointing) =="
if ! JAX_PLATFORMS=cpu python scripts/chaos_soak.py --quick; then
  echo "CHAOS SOAK FAILED — recovery machinery regressed (reproduce: CHAOS_SEED)"
  exit 9
fi

echo "== stage 9/20: elastic chaos soak (kill on N devices, resume on M) =="
if ! JAX_PLATFORMS=cpu python scripts/chaos_soak.py --elastic --quick; then
  echo "ELASTIC CHAOS SOAK FAILED — the N->M mesh re-plan / batch-equivalent"
  echo "restore regressed (reproduce: CHAOS_SEED; docs/fault_tolerance.md)"
  exit 11
fi

echo "== stage 10/20: perf-regression gate (clean + injected-slowdown self-test) =="
if ! JAX_PLATFORMS=cpu python scripts/perf_gate.py --quick; then
  echo "PERF GATE FAILED — step time regressed past tolerance vs PERF_BASELINE.json"
  echo "(legitimate perf change? re-record: scripts/perf_gate.py --quick --update)"
  exit 10
fi
if JAX_PLATFORMS=cpu python scripts/perf_gate.py --quick --inject-slowdown 3; then
  echo "PERF GATE SELF-TEST FAILED — a 3x injected regression PASSED the gate"
  exit 10
fi
echo "perf_gate self-test OK: injected 3x regression correctly failed"

echo "== stage 11/20: data-wait gate (clean + injected-starvation self-test) =="
if ! JAX_PLATFORMS=cpu python scripts/perf_gate.py --data-wait; then
  echo "DATA-WAIT GATE FAILED — the input pipeline's steady-state data_wait"
  echo "fraction exceeds the PERF_BASELINE.json ceiling (ROADMAP item 5)"
  echo "(legitimate pipeline change? re-record: scripts/perf_gate.py --data-wait --update)"
  exit 12
fi
if JAX_PLATFORMS=cpu python scripts/perf_gate.py --data-wait --inject-data-wait 0.05 \
    > /tmp/_data_wait_selftest.log 2>&1; then
  echo "DATA-WAIT GATE SELF-TEST FAILED — an injected starved pipeline PASSED the gate"
  exit 12
fi
echo "data-wait gate self-test OK: injected loader sleep correctly failed"

echo "== stage 12/20: run-doctor self-test (injected-bottleneck diagnosis + timeline) =="
if ! JAX_PLATFORMS=cpu python scripts/run_doctor.py --self-test; then
  echo "RUN DOCTOR SELF-TEST FAILED — an injected bottleneck was misdiagnosed,"
  echo "the clean twin was not healthy, or the exported timeline broke the"
  echo "goodput span re-derivation (docs/observability.md)"
  exit 13
fi

echo "== stage 13/20: live-monitor self-test (heartbeat liveness + streaming doctor + alerts) =="
if ! JAX_PLATFORMS=cpu python scripts/run_monitor.py --self-test; then
  echo "RUN MONITOR SELF-TEST FAILED — the liveness contract broke: a hang did"
  echo "not read stale_heartbeat, a SIGKILL did not read dead, the healthy twin"
  echo "diverged from run_doctor's fractions, or the data_bound alert was not"
  echo "debounced to exactly one firing (docs/observability.md 'Live monitoring')"
  exit 15
fi

echo "== stage 14/20: run-comparison gate (twin-diff + injected attribution + bench history) =="
if ! JAX_PLATFORMS=cpu python scripts/run_compare.py --self-test; then
  echo "RUN COMPARE SELF-TEST FAILED — identical twins did not diff clean, or"
  echo "an injected known-cause slowdown (3x conv / loader sleep / commit"
  echo "delay) was attributed to the wrong category/bucket (docs/profiling.md)"
  exit 14
fi
if ! JAX_PLATFORMS=cpu python scripts/bench_history.py --self-test; then
  echo "BENCH HISTORY SELF-TEST FAILED — the committed r02->r05 flat streak"
  echo "was not detected on the committed BENCH_r files, or a flat streak is"
  echo "STILL live at the newest round (r06 must sit outside the band —"
  echo "docs/profiling.md)"
  exit 14
fi

echo "== stage 15/20: autotune gate (injected-win ranking + provenance refusal) + pallas parity =="
if ! JAX_PLATFORMS=cpu python scripts/autotune.py --self-test; then
  echo "AUTOTUNE SELF-TEST FAILED — the injected known-win (3x de-tuned"
  echo "baseline) was not ranked first with per-category attribution, a"
  echo "provenance-drifted candidate was not refused, or the TUNED.json"
  echo "round-trip broke (docs/performance.md 'Autotuning')"
  exit 17
fi
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_pallas.py tests/test_dispatch.py tests/test_autotune.py \
    -q -m 'not slow' -p no:cacheprovider > /tmp/_pallas_parity.log 2>&1; then
  echo "PALLAS PARITY SMOKE FAILED — kernel<->plain parity, dispatch telemetry,"
  echo "or the shared timing core regressed (log: /tmp/_pallas_parity.log)"
  tail -20 /tmp/_pallas_parity.log
  exit 17
fi
tail -1 /tmp/_pallas_parity.log

echo "== stage 16/20: fleet-controller soak (closed-loop recovery + zero-budget refusal) =="
if ! JAX_PLATFORMS=cpu python scripts/fleet_controller.py --soak --quick; then
  echo "FLEET SOAK FAILED — the closed-loop controller did not restore the"
  echo "diseased fleet to healthy (restart / restart_excluding / A/B tune),"
  echo "an action went unaudited, or final params diverged from the"
  echo "uninterrupted twins (docs/fault_tolerance.md 'Closed-loop recovery')"
  exit 16
fi
if JAX_PLATFORMS=cpu python scripts/fleet_controller.py --soak --quick --max-restarts 0 \
    > /tmp/_fleet_zero_budget.log 2>&1; then
  echo "FLEET SOAK SELF-TEST FAILED — with --max-restarts 0 the controller"
  echo "must REFUSE (record the decision, touch nothing) and exit non-zero"
  exit 16
fi
echo "fleet soak self-test OK: zero-budget controller refused without acting"

echo "== stage 17/20: serving soak (continuous-batching SLO + hot-swap + failover) =="
if ! JAX_PLATFORMS=cpu python scripts/serving_soak.py --quick; then
  echo "SERVING SOAK FAILED — the p99 SLO was breached, responses were not"
  echo "bit-identical across a checkpoint hot-swap, a SIGKILL'd replica was"
  echo "not failed over by the fleet controller, or a zero-capacity server"
  echo "hung instead of refusing (docs/serving.md)"
  exit 18
fi

echo "== stage 18/20: actuated-offer soak (drain + live re-plan + A/B keep) =="
if ! JAX_PLATFORMS=cpu python scripts/serving_soak.py --actuate --quick; then
  echo "ACTUATE SOAK FAILED — the actuated chip offer regressed: a request"
  echo "failed or hung across the drain window, response bytes changed across"
  echo "the live re-plan, the offer/accept/drain/replan audit chain broke,"
  echo "the A/B judge mis-called the absorb, an SLO-pressured replica did not"
  echo "decline, a dead-replica handshake did not revert-and-re-arm, or the"
  echo "monitor read a draining replica as dead (docs/serving.md)"
  exit 20
fi

echo "== stage 19/20: streaming-data soak (kill/resume determinism + elastic re-split) =="
if ! JAX_PLATFORMS=cpu python scripts/data_soak.py --quick; then
  echo "DATA SOAK FAILED — the streaming reader's deterministic-resume,"
  echo "elastic re-split, worker-respawn, or corrupt-skip contract regressed"
  echo "(reproduce: DATA_SOAK_SEED; docs/data.md)"
  exit 19
fi

echo "== stage 20/20: tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
