#!/usr/bin/env python
"""Memory-accounting CI gate (ISSUE 8 satellite).

Runs the memory preflight on the digits config (the offline stand-in every
accuracy clause uses) and asserts the subsystem's core contracts, the way
the retrace/precision/telemetry/perf gates assert theirs:

* **prediction parity**: the preflight's predicted peak equals the number
  re-derived here from ``compiled.memory_analysis()`` by stdlib arithmetic
  (argument + output - alias + temp + code) — on BOTH the real single-step
  and the real chained (window) programs. The re-derivation is independent
  of ``memory/analysis.py`` (the chaos-soak "independent re-validation"
  pattern), so a drift between the preflight's math and XLA's buffer
  assignment fails here, not as a wrong fit verdict on real hardware;
* **exhaustive attribution**: buffer-class fractions sum to 1 on both
  programs, every class non-negative, and the largest-buffer table is
  populated;
* **``--inject-oversize`` self-test** (the perf-gate/static-audit "gate
  has teeth" pattern): a deliberately unfittable capacity — midway between
  the smallest shard-aligned batch's peak and the configured batch's peak —
  MUST make the preflight FAIL with a finite batch recommendation whose
  predicted peak actually fits. A preflight that waves an oversized config
  through, or fails without a recommendation, exits nonzero.

CPU-viable end to end: every number comes from abstract lowerings — no
device execution, no allocator required.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import numpy as np
import optax
from flax import linen as nn

from distributed_training_pytorch_tpu.memory import (
    BUFFER_CLASSES,
    Preflight,
    PreflightOOMError,
    analyze_step_memory,
    run_preflight,
)
from distributed_training_pytorch_tpu.memory.analysis import stack_chain_batch
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

CHAIN = 2
BATCH = 128


class DigitsNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def build():
    """The digits engine + abstract batch (the telemetry-smoke config
    without the Trainer — sklearn-digits shapes, 8x8x1 images, 10 classes).
    Everything here is abstract lowering; no corpus needs loading."""
    model = DigitsNet()

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.1, momentum=0.9),
        mesh_lib.create_mesh(),
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda rng: model.init(rng, np.zeros((1, 8, 8, 1), np.float32)),
    )
    batch = {
        "image": jax.ShapeDtypeStruct((BATCH, 8, 8, 1), np.float32),
        "label": jax.ShapeDtypeStruct((BATCH,), np.int32),
    }
    return engine, state, batch


def independent_peak(engine, state, batch, chain_length=None) -> int:
    """Re-derive the predicted peak straight from the compiled probe's
    ``memory_analysis()`` with stdlib arithmetic — no memory/ code."""
    probe_batch = (
        stack_chain_batch(batch, chain_length) if chain_length else batch
    )
    stats = engine.compile_step_probe(
        state, probe_batch, donate=True, chain_length=chain_length
    ).memory_analysis()
    return int(
        stats.argument_size_in_bytes
        + stats.output_size_in_bytes
        - stats.alias_size_in_bytes
        + stats.temp_size_in_bytes
        + stats.generated_code_size_in_bytes
    )


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--inject-oversize",
        action="store_true",
        help="self-test: an unfittable capacity MUST fail preflight with a "
        "finite batch recommendation",
    )
    args = parser.parse_args()

    engine, state, batch = build()
    errors = []

    if args.inject_oversize:
        # The ONE batch-granularity rule (preflight's own): duplicating it
        # here would let the seam and the bisection floor silently diverge
        # on meshes with an fsdp extent.
        from distributed_training_pytorch_tpu.memory.preflight import _batch_shard

        shard = _batch_shard(engine.mesh)
        floor_peak = analyze_step_memory(
            engine,
            state,
            jax.tree.map(
                lambda l: jax.ShapeDtypeStruct((shard,) + l.shape[1:], l.dtype),
                batch,
            ),
            top_k=0,
        ).peak_bytes
        full_peak = analyze_step_memory(engine, state, batch, top_k=0).peak_bytes
        if not floor_peak < full_peak:
            print(
                f"MEMORY PROBE: cannot build oversize seam — peak at batch "
                f"{shard} ({floor_peak}) not below peak at {BATCH} ({full_peak})",
                file=sys.stderr,
            )
            return 1
        # headroom=0 so the capacity seam IS the usable boundary
        config = Preflight(
            capacity_bytes=(floor_peak + full_peak) // 2, headroom=0.0
        )
        try:
            run_preflight(engine, state, batch, config)
        except PreflightOOMError as e:
            report = e.report
            if report.recommended_batch is None:
                errors.append("oversize preflight failed WITHOUT a batch recommendation")
            else:
                rec_batch = jax.tree.map(
                    lambda l: jax.ShapeDtypeStruct(
                        (report.recommended_batch,) + l.shape[1:], l.dtype
                    ),
                    batch,
                )
                rec_peak = analyze_step_memory(engine, state, rec_batch, top_k=0).peak_bytes
                if rec_peak > report.usable_bytes:
                    errors.append(
                        f"recommended batch {report.recommended_batch} does NOT "
                        f"fit: peak {rec_peak} > usable {report.usable_bytes}"
                    )
                print(
                    f"memory probe self-test OK: oversized config failed "
                    f"preflight with recommended batch "
                    f"{report.recommended_batch} (peak {rec_peak} <= usable "
                    f"{report.usable_bytes}, {report.trials} trials)"
                )
        else:
            errors.append(
                "oversize preflight PASSED — the gate has no teeth "
                f"(capacity {config.capacity_bytes} < predicted {full_peak})"
            )
        if errors:
            print("MEMORY PROBE SELF-TEST FAILED:", file=sys.stderr)
            for err in errors:
                print(f"  - {err}", file=sys.stderr)
            return 1
        return 0

    # -- clean pass: prediction parity + exhaustive attribution ------------
    for chain_length in (None, CHAIN):
        label = "chained" if chain_length else "single-step"
        report = run_preflight(
            engine,
            state,
            batch,
            # capacity pinned huge: this is the parity check, not a fit test
            # (CPU reports no real capacity anyway)
            Preflight(capacity_bytes=1 << 62),
            chain_length=chain_length,
        )
        direct = independent_peak(engine, state, batch, chain_length)
        if report.predicted_peak_bytes != direct:
            errors.append(
                f"{label}: preflight predicted {report.predicted_peak_bytes} "
                f"!= memory_analysis-derived {direct}"
            )
        fractions = report.profile.fractions()
        total = sum(fractions.values())
        if abs(total - 1.0) > 1e-6:
            errors.append(f"{label}: class fractions sum to {total!r}: {fractions}")
        negative = {c: v for c, v in report.profile.bytes_by_class.items() if v < 0}
        if negative:
            errors.append(f"{label}: negative class bytes {negative}")
        if set(report.profile.bytes_by_class) != set(BUFFER_CLASSES):
            errors.append(f"{label}: class set drifted: {report.profile.bytes_by_class}")
        if not report.profile.top_buffers:
            errors.append(f"{label}: empty largest-buffers table")
        if report.fits is not True:
            errors.append(f"{label}: huge capacity did not fit?! {report.fits}")
        if not errors:
            biggest = report.profile.top_buffers[0]
            print(
                f"memory probe {label}: predicted peak "
                f"{report.predicted_peak_bytes} B == memory_analysis exactly; "
                f"fractions sum to 1; top buffer {biggest['dtype']}"
                f"{biggest['shape']} {biggest['bytes']} B ({biggest['op']})"
            )

    if errors:
        print("MEMORY PROBE FAILED:", file=sys.stderr)
        for err in errors:
            print(f"  - {err}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
