#!/usr/bin/env python
"""Autonomous fleet controller — doctor verdicts to remediation actions.

PRs 11-15 built the *diagnosis* half of operations: elastic N->M resume,
the streaming run doctor, per-chip straggler attribution, debounced
alerts, a fleet table. Every remediation was still a human. This script
closes the loop (ISSUE 16): it supervises N run directories — each a
trainer subprocess it spawned (or a run it merely adopted) — polls each
through :class:`telemetry.monitor.RunMonitor`, feeds the statuses to the
:class:`telemetry.controller.RunPolicy` state machine, and EXECUTES the
decided actions:

* ``dead`` (abnormal subprocess exit, or a silent/hung log) ->
  **restart**: kill what remains and respawn; the trainer resumes from
  ``snapshot_path="latest_valid"`` on its own.
* persistent ``straggler`` verdict naming a chip -> **restart_excluding**:
  re-plan the mesh onto the surviving devices via
  ``parallel.elastic.replan_excluding`` and respawn on M-1 chips.
* persistent ``data_bound`` / ``checkpoint_stall`` alert -> **tune**: ONE
  bounded knob change (prefetch depth up to a cap / ``commit_delay_s``
  down to a floor), then an A/B verdict through ``run_compare``'s
  steady-fraction diff — improved => **keep**, else **revert**.

Every decision is debounced, budgeted (``--max-restarts``, exponential
backoff, never two concurrent actions per run) and audited: one
``controller_action`` JSONL record per action in the controller's own
event log (``--events``; default ``<workdir>/controller/events.jsonl``
under ``--soak``), carrying the verdict/alert evidence rows that
justified it — the same timeline/doctor ritual as the trainer events it
reacted to (docs/fault_tolerance.md "Closed-loop recovery").

Usage::

    # supervise a fleet; {run_dir} in --cmd is substituted per run
    python scripts/fleet_controller.py RUN_DIR... \\
        --cmd 'python train.py --save-folder {run_dir}' --events ops.jsonl

    # adopt-only (no --cmd): decisions are recorded, not executed
    python scripts/fleet_controller.py RUN_DIR... --once

    # the closed-loop acceptance soak (verify.sh): a 3-run digits fleet is
    # SIGKILL'd, hung, chip-degraded and loader-starved; the controller
    # must restore every run to healthy completion with no human input,
    # final params within ELASTIC_TOL of uninterrupted twins
    python scripts/fleet_controller.py --soak --quick

    # the teeth: a zero-budget controller must refuse and exit non-zero
    python scripts/fleet_controller.py --soak --quick --max-restarts 0
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shlex
import shutil
import signal
import subprocess
import sys
import tempfile
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))
sys.path.insert(0, _HERE)
from ckpt_validate import valid_checkpoints  # noqa: E402  (shared stdlib helper)

EXIT_OK = 0
EXIT_PREEMPTED = 3  # clean SIGTERM shutdown with a resumable save
GRACE_S = 30.0  # SIGTERM -> wait -> SIGKILL when stopping a child
CHILD_TIMEOUT_S = 300.0  # hard bound per twin run
SOAK_TIMEOUT_S = 480.0  # hard bound on the whole supervised fleet
# Same-global-batch topology change legally re-associates float reductions
# (~1 ULP/step); the chaos_soak elastic tolerance, shared here verbatim.
ELASTIC_TOL = 1e-4

# Mesh-axis -> spec-grammar token (parallel.mesh.mesh_config_from_spec).
_SPEC_TOKEN = {"data": "dp", "fsdp": "fsdp", "tensor": "tp"}


def _http_get_json(url: str, timeout: float = 5.0):
    """``(status, parsed_body)`` from a GET — the handshake's probe
    transport (stdlib only, same as the server's own surface)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode() or "{}")


def _http_post_json(url: str, payload: dict, timeout: float = 30.0):
    """``(status, parsed_body)`` from a JSON POST. 4xx/5xx are *data*
    here (the replica's typed refusals carry bodies the handshake must
    read), not exceptions — only transport failures raise."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        try:
            return e.code, json.loads(body or "{}")
        except ValueError:
            return e.code, {"raw": body}


def axes_to_spec(axes: dict) -> str:
    """Render a re-planned axes dict back into the ``--mesh`` grammar the
    child parses (``{"data": 2, "fsdp": 2}`` -> ``"dp2fsdp2"``)."""
    parts = [
        f"{_SPEC_TOKEN[k]}{int(v)}"
        for k, v in axes.items()
        if k in _SPEC_TOKEN and int(v) > 1
    ]
    if not parts:
        return f"dp{int(axes.get('data', 1) or 1)}"
    return "".join(parts)


# ---------------------------------------------------------------------------
# Child: the real training job (imports jax; run as a subprocess).


def child_main(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

    if args.devices:
        # Forced N-device virtual CPU platform (must run before anything
        # initializes the jax backend) — the straggler/exclusion leg.
        from distributed_training_pytorch_tpu import compat

        compat.force_host_devices(args.devices)

    import numpy as np
    import optax
    from flax import linen as nn

    import jax

    from distributed_training_pytorch_tpu.data import ArrayDataSource
    from distributed_training_pytorch_tpu.fault import FaultPlan
    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec
    from distributed_training_pytorch_tpu.telemetry import Telemetry
    from distributed_training_pytorch_tpu.trainer import Trainer

    class DigitsNet(nn.Module):
        # Wider than chaos_soak's 32-unit twin ON PURPOSE: the data-bound
        # leg's cure (prefetch depth) only works when step compute sits
        # between the parallel and the serial per-batch production cost —
        # a sub-millisecond step is data-bound at ANY prefetch depth and
        # the A/B judge would (correctly) revert the tune.
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.Dense(2048)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    class BatchArraySource(ArrayDataSource):
        """ArrayDataSource plus ``load_batch``: whole-batch production in
        ONE worker call, so the loader's batch fast path carries the
        ``load_delay_s`` seam INSIDE the pool — prefetch depth then
        genuinely governs production concurrency (the per-record path
        pays the delay on the consumer thread, where no depth helps)."""

        def load_batch(self, rows, epoch):
            return {k: v[rows] for k, v in self.arrays.items()}

    load_delay_s = float(args.load_delay)

    class FleetTrainer(Trainer):
        def build_train_dataset(self):
            from sklearn.datasets import load_digits

            digits = load_digits()
            images = (digits.images / 16.0).astype(np.float32)[..., None]
            labels = digits.target.astype(np.int32)
            # Tile the corpus (~14*tile steps/epoch at batch 128): epochs
            # must be long enough that injections land mid-epoch AND that
            # per-epoch checkpoint/compile cost stays an honestly small
            # steady fraction — the doctor's healthy verdict is asserted.
            images = np.concatenate([images] * args.tile)
            labels = np.concatenate([labels] * args.tile)
            return BatchArraySource(image=images, label=labels)

        def build_model(self):
            return DigitsNet()

        def build_criterion(self):
            def criterion(logits, batch):
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, {"loss": loss}

            return criterion

        def build_optimizer(self, schedule):
            return optax.sgd(schedule, momentum=0.9)

        def build_scheduler(self):
            return 0.1

        def build_dataloader(self, dataset, phase="train"):
            loader = super().build_dataloader(dataset, phase)
            if load_delay_s:
                # The data-starvation seam (run_doctor/perf_gate's): every
                # batch's production path sleeps this long.
                loader.load_delay_s = load_delay_s
            return loader

    # Deterministic fault plan from argv — restarts rebuild it, so hangs
    # are pinned to an exact (epoch, step): the watchdog's emergency save
    # lands PAST the hang step and the resumed attempt never re-fires it.
    plan = FaultPlan()
    if args.hang_payload > 0:
        plan.add(
            "hang",
            epoch=args.hang_epoch,
            step=args.hang_step,
            payload=args.hang_payload,
        )
    if args.slow_chip:
        dev, _, ms = args.slow_chip.partition(":")
        plan.add(
            "slow_chip",
            count=args.slow_chip_count,
            payload={"device": int(dev), "delay_ms": float(ms or 0.0)},
        )

    mesh = mesh_config_from_spec(args.mesh).build() if args.mesh else None
    trainer = FleetTrainer(
        max_epoch=args.max_epoch,
        batch_size=128,
        save_folder=args.run_dir,
        snapshot_path="latest_valid",  # idempotent: cold start on first launch
        have_validate=False,
        save_period=1,
        async_checkpoint=True,
        chain_steps=2,
        log_every=4,
        preemption_check_every=2,
        telemetry=Telemetry(
            anomaly=None,  # each leg isolates ONE disease; no double-reports
            heartbeat_every_s=args.heartbeat_every,
        ),
        num_workers=args.num_workers,
        prefetch_batches=args.prefetch,
        step_timeout=args.step_timeout or None,
        fault_plan=plan if plan.events else None,
        progress=False,
        seed=args.seed,
        mesh=mesh,
        accum_steps=args.accum,
        # DigitsNet's kernels are tiny; a small cutoff makes the fsdp mesh
        # genuinely shard them so checkpoints carry a sharding record.
        fsdp_min_size=256,
    )
    if args.commit_delay > 0:
        # The checkpoint-stall seam: hold each background commit this long.
        trainer.saver.commit_delay_s = args.commit_delay
    trainer.train()
    if trainer._preempted:
        return EXIT_PREEMPTED

    if args.final:
        leaves = jax.device_get(jax.tree.leaves(trainer.state.params))
        np.savez(args.final, **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    return EXIT_OK


# ---------------------------------------------------------------------------
# Parent: the supervising controller (mechanism around telemetry.controller).


@dataclasses.dataclass
class RunSpec:
    """One supervised run: where it lives and how to (re)spawn it.

    ``cmd`` set = generic mode (a fixed argv; ``None`` with ``adopt`` =
    record-only). Unset = the soak's self-contained digits child, rebuilt
    from the mutable topology/knob fields on every respawn — tunes and
    exclusions edit THESE, so the next spawn carries the remediation.
    """

    name: str
    run_dir: str
    kind: str = "train"  # "train" | "serve" — a mixed fleet (ISSUE 18)
    # A serving replica's admin port (ISSUE 20): non-zero turns the chip
    # offer from an advisory record into the actuated offer -> accept ->
    # drain/re-plan -> A/B-judged handshake over /admin/offer + /admin/
    # replan. 0 keeps the ISSUE 18 advisory-record behavior.
    port: int = 0
    cmd: list | None = None
    adopt: bool = False  # no spawn at start; supervise whatever writes the log
    final: str = ""
    max_epoch: int = 4
    devices: int = 0  # 0 = the default backend (no forced platform)
    device_ids: tuple = ()
    mesh: str = ""
    accum: int = 1
    tile: int = 3  # dataset tiling factor (epoch length lever)
    batch_size: int = 128
    knobs: dict = dataclasses.field(default_factory=dict)
    extra: tuple = ()  # passthrough child argv (the injection seams)

    def child_cmd(self) -> list:
        if self.cmd is not None:
            return list(self.cmd)
        return [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            "--run-dir", self.run_dir,
            "--final", self.final,
            "--max-epoch", str(self.max_epoch),
            "--devices", str(self.devices),
            "--mesh", self.mesh,
            "--accum", str(self.accum),
            "--tile", str(self.tile),
            "--prefetch", str(self.knobs.get("prefetch_batches", 2)),
            "--commit-delay", str(self.knobs.get("commit_delay_s", 0.0)),
            *self.extra,
        ]


class SupervisedRun:
    """A spec plus its live supervision state (monitor, policy, process)."""

    def __init__(self, spec: RunSpec, monitor, policy, log_path: str):
        self.spec = spec
        self.monitor = monitor
        self.policy = policy
        self.log_path = log_path
        self.proc: subprocess.Popen | None = None
        self.last_status = None
        self.actions: list = []  # every executed Action, in order


class FleetController:
    """Supervise N runs: poll -> decide -> execute -> audit (see module
    doc). ``event_log`` is the controller's OWN EventLog — trainer children
    write their run logs; two writers on one JSONL file would interleave."""

    def __init__(
        self,
        specs,
        *,
        config,
        monitor_config,
        event_log,
        interval: float = 2.0,
        steady_diff=None,
        clock=time.monotonic,
    ):
        from distributed_training_pytorch_tpu.telemetry import monitor as monitor_lib
        from distributed_training_pytorch_tpu.telemetry.controller import RunPolicy

        self.config = config
        self.events = event_log
        self.interval = float(interval)
        self._clock = clock
        # The actuated-offer seams (ISSUE 20), attribute-injectable so the
        # handshake is testable without sockets or wall-clock sleeps.
        self._steady_diff = steady_diff
        self._sleep = time.sleep
        self._http_get = _http_get_json
        self._http_post = _http_post_json
        self.runs: dict[str, SupervisedRun] = {}
        for spec in specs:
            mon = monitor_lib.RunMonitor(
                spec.run_dir, monitor_config, alert_log=event_log
            )
            pol = RunPolicy(
                config, knobs=dict(spec.knobs), steady_diff=steady_diff
            )
            log_path = os.path.join(
                os.path.dirname(spec.run_dir) or ".", f"{spec.name}.log"
            )
            self.runs[spec.name] = SupervisedRun(spec, mon, pol, log_path)

    # -- process plumbing --------------------------------------------------

    def _spawn(self, run: SupervisedRun) -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # NO persistent XLA compilation cache, deliberately: a SIGKILL'd
        # child can leave a torn cache entry behind (chaos_soak's rule).
        with open(run.log_path, "a") as log:
            run.proc = subprocess.Popen(
                run.spec.child_cmd(),
                stdout=log,
                stderr=subprocess.STDOUT,
                env=env,
            )

    def _stop(self, run: SupervisedRun, *, graceful: bool) -> None:
        proc = run.proc
        if proc is None or proc.poll() is not None:
            return
        if graceful:
            # SIGTERM -> preemption vote -> emergency resumable save ->
            # clean exit: tunes/exclusions must not lose the epoch.
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=GRACE_S)
                return
            except subprocess.TimeoutExpired:
                pass
        proc.kill()
        proc.wait()

    def start(self) -> None:
        for run in self.runs.values():
            if not run.spec.adopt:
                self._spawn(run)

    # -- action execution --------------------------------------------------

    def _execute(self, run: SupervisedRun, action, status, now: float) -> None:
        spec = run.spec
        can_spawn = spec.cmd is not None or not spec.adopt
        if action.kind == "restart" and can_spawn:
            self._stop(run, graceful=False)
            # The restart safety ritual: what will the resume find? The
            # stdlib manifest check (shared with chaos_soak) — recorded on
            # the action so the audit shows the decision was restorable.
            weights = os.path.join(spec.run_dir, "weights")
            action.params["valid_checkpoints"] = valid_checkpoints(weights)
            self._spawn(run)
        elif action.kind == "restart_excluding" and can_spawn:
            self._replan_spec(spec, action)
            self._stop(run, graceful=True)
            self._spawn(run)
            self._offer_freed_chip(run, action, status)
        elif action.kind in ("tune", "revert") and can_spawn:
            spec.knobs[action.params["knob"]] = action.params["to"]
            self._stop(run, graceful=True)
            self._spawn(run)
        elif action.kind in ("keep", "give_up", "refuse"):
            pass  # record-only: state already says it all
        else:
            action.message += " [adopted run: recorded, not executed]"
        run.policy.note_applied(action, now=self._clock())
        run.actions.append(action)
        self.events.emit(
            "controller_action",
            run=spec.name,
            run_dir=spec.run_dir,
            attempt=status.attempt,
            status=status.status,
            verdict=status.verdict,
            restarts_used=run.policy.restarts_used,
            max_restarts=self.config.max_restarts,
            **action.event_fields(),
        )

    def _emit_serve_action(self, srv: SupervisedRun, act) -> None:
        """Record one serving-side handshake action: appended to the
        replica's action ledger and emitted as a ``controller_action``
        audit record. Deliberately NOT routed through the replica's
        RunPolicy — offer actuation never respawns the server process, so
        it must not consume restart budget or start a backoff window."""
        srv.actions.append(act)
        st = srv.last_status
        self.events.emit(
            "controller_action",
            run=srv.spec.name,
            run_dir=srv.spec.run_dir,
            attempt=st.attempt if st is not None else None,
            status=st.status if st is not None else "unknown",
            verdict=st.verdict if st is not None else "unknown",
            restarts_used=srv.policy.restarts_used,
            max_restarts=self.config.max_restarts,
            **act.event_fields(),
        )

    def _offer_freed_chip(self, run: SupervisedRun, action, status) -> None:
        """Mixed-fleet accounting: a chip a trainer's ``restart_excluding``
        just dropped from its mesh is not returned to the scheduler — it
        is OFFERED to a serving replica in the same fleet. A straggler
        chip too slow for a lockstep collective is often fine for
        latency-bound inference (no per-step barrier to hold hostage).

        Replicas with a known admin ``port`` get the ACTUATED handshake
        (ISSUE 20): offer -> accept/decline over ``/admin/offer``, an
        accepted offer drains/re-plans over ``/admin/replan``, and the
        absorb is A/B-judged on before/after QPS-per-chip + p99 — kept or
        reverted like the PR 16 bounded tunes. Port-less replicas keep
        the ISSUE 18 advisory record (recorded, audited, not executed).
        Either way the controller never respawns a healthy server."""
        from distributed_training_pytorch_tpu.telemetry.controller import Action

        chip = action.params.get("exclude_chip")
        if chip is None:
            return
        servers = [
            r for r in self.runs.values()
            if r.spec.kind == "serve" and r.spec.name != run.spec.name
        ]
        for srv in servers:
            if srv.spec.port:
                self._actuate_offer(run, srv, int(chip), action)
                continue
            offer = Action(
                kind="offer_chip",
                reason=action.reason,
                message=(
                    f"chip {chip} freed from {run.spec.name}'s mesh by "
                    f"restart_excluding; offered to serving replica "
                    f"{srv.spec.name}"
                ),
                params={
                    "chip": int(chip),
                    "from_run": run.spec.name,
                    "to_run": srv.spec.name,
                },
                evidence=list(action.evidence),
            )
            self._emit_serve_action(srv, offer)

    def _replan_back(self, base: str, old_ids: list) -> bool:
        """Best-effort restore of the pre-offer device set — the physical
        half of the handshake's revert. Failure here is tolerable by
        design: the replica pre-validates every re-plan before touching
        admission, so a replica we cannot reach is either dead (its own
        monitor surfaces that) or still serving *some* valid plan."""
        try:
            code, _ = self._http_post(
                base + "/admin/replan",
                {"device_ids": list(old_ids), "deadline_s": 10.0},
                timeout=60.0,
            )
            return code == 200
        except Exception:
            return False

    def _actuate_offer(self, run: SupervisedRun, srv: SupervisedRun,
                       chip: int, action) -> None:
        """Drive one actuated chip offer end to end (ISSUE 20 tentpole b):
        the mechanism around :class:`telemetry.controller.OfferHandshake`.
        Every terminal path leaves an audit record on the serving run —
        ``offer_chip`` then ``keep`` (absorbed, judged better-or-equal),
        or ``revert`` (judged against / replica refused / handshake timed
        out, the latter two re-armed for a future offer). The replica's
        own flight recorder carries the matching ``offer_accept`` /
        ``offer_decline`` / ``drain_start`` / ``replan_done`` records."""
        from distributed_training_pytorch_tpu.telemetry.controller import (
            Action,
            OfferHandshake,
        )

        base = f"http://127.0.0.1:{srv.spec.port}"
        cfg = self.config
        try:
            _, before = self._http_get(base + "/status", timeout=5.0)
        except Exception as e:
            before = {"probe_error": f"{type(e).__name__}: {e}"}
        hs = OfferHandshake(
            chip,
            before=before,
            now=self._clock(),
            timeout_s=float(getattr(cfg, "offer_timeout_s", 60.0)),
            settle_s=float(getattr(cfg, "offer_settle_s", 2.0)),
        )
        self._emit_serve_action(srv, Action(
            kind="offer_chip",
            reason=action.reason,
            message=(
                f"chip {chip} freed from {run.spec.name}'s mesh by "
                f"restart_excluding; offered to serving replica "
                f"{srv.spec.name} for actuation"
            ),
            params={
                "chip": int(chip),
                "from_run": run.spec.name,
                "to_run": srv.spec.name,
                "port": int(srv.spec.port),
                "actuated": True,
            },
            evidence=list(action.evidence),
        ))

        def fail(reason: str, detail: str, *, rearm: bool = True,
                 evidence: list = ()) -> None:
            self._emit_serve_action(srv, Action(
                kind="revert",
                reason=reason,
                message=(
                    f"offer of chip {chip} to {srv.spec.name}: {detail}"
                    + (" — re-armed" if rearm else "")
                ),
                params={
                    "chip": int(chip),
                    "to_run": srv.spec.name,
                    "rearmed": bool(rearm),
                    "handshake_state": hs.state,
                },
                evidence=list(evidence),
            ))

        # 1) offer -> the replica's accept/decline (its own SLO call).
        try:
            code, body = self._http_post(
                base + "/admin/offer", {"chip": int(chip)}, timeout=10.0
            )
        except Exception as e:
            fail("offer_timeout",
                 f"offer transport failed ({type(e).__name__}: {e})")
            return
        if code != 200 or body.get("decision") not in ("accept", "decline"):
            fail("offer_timeout", f"offer answered {code}: {body}")
            return
        hs.note_decision(body["decision"], body.get("reason", ""))
        if hs.state == "declined":
            # The decline is the replica's own flight-recorder record
            # (offer_decline, with its SLO evidence); nothing was
            # actuated, so there is nothing to revert or record here.
            return

        # 2) actuate: drain + re-plan onto the grown device set.
        old_ids = sorted(int(d) for d in (before.get("device_ids") or []))
        if not old_ids:
            fail("replan_failed",
                 "replica reported no device_ids to grow from")
            return
        new_ids = sorted(set(old_ids) | {int(chip)})
        wall_left = max(1.0, hs.deadline - self._clock())
        try:
            code, summary = self._http_post(
                base + "/admin/replan",
                {"device_ids": new_ids,
                 "deadline_s": min(10.0, wall_left)},
                timeout=wall_left,
            )
        except Exception as e:
            # Transport died mid-actuation: the replica may or may not
            # have re-planned — push it back to the known-good set.
            self._replan_back(base, old_ids)
            fail("offer_timeout",
                 f"replan transport failed ({type(e).__name__}: {e})")
            return
        if code != 200:
            # The replica pre-validates before touching admission: a
            # refused re-plan left it serving the OLD plan untouched.
            fail("replan_failed",
                 f"replica refused the re-plan ({code}: {summary})")
            return
        hs.note_actuated(summary, now=self._clock())

        # 3) settle, then judge on the after-side probe.
        while not hs.ready_to_judge(self._clock()):
            if hs.expired(self._clock()):
                self._replan_back(base, old_ids)
                fail("offer_timeout", hs.reason)
                return
            self._sleep(0.05)
        try:
            _, after = self._http_get(base + "/status", timeout=5.0)
        except Exception as e:
            self._replan_back(base, old_ids)
            fail("offer_timeout",
                 f"after-probe failed ({type(e).__name__}: {e})")
            return
        verdict, evidence = hs.judge(
            after,
            noise_floor=float(getattr(cfg, "ab_noise_floor", 0.10)),
            steady_diff=self._steady_diff,
        )
        if verdict == "keep":
            self._emit_serve_action(srv, Action(
                kind="keep",
                reason="offer_chip",
                message=(
                    f"chip {chip} absorbed by {srv.spec.name} and kept: "
                    f"{hs.reason}"
                ),
                params={
                    "chip": int(chip),
                    "to_run": srv.spec.name,
                    "device_ids": new_ids,
                },
                evidence=evidence,
            ))
            return
        self._replan_back(base, old_ids)
        fail(
            "offer_chip",
            f"A/B judged against the absorb: {hs.reason}",
            rearm=False,
            evidence=evidence,
        )

    def _replan_spec(self, spec: RunSpec, action) -> None:
        """Fold the policy's exclusion into the spawn spec through the
        elastic planner — the controller does not invent topologies."""
        from distributed_training_pytorch_tpu.parallel import elastic
        from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec

        chip = int(action.params["exclude_chip"])
        if not spec.device_ids:
            action.message += " [no known topology: plain restart]"
            return
        mc = mesh_config_from_spec(spec.mesh) if spec.mesh else None
        axes = {"data": len(spec.device_ids)}
        if mc is not None:
            axes = {
                "data": max(1, int(mc.data)),
                **{
                    name: int(getattr(mc, name))
                    for name in ("fsdp", "pipe", "expert", "seq", "tensor")
                    if int(getattr(mc, name)) != 1
                },
            }
        plan = elastic.replan_excluding(
            axes,
            spec.device_ids,
            [chip],
            batch_size=spec.batch_size,
            accum_steps=spec.accum,
        )
        survivors = tuple(d for d in spec.device_ids if int(d) != chip)
        spec.device_ids = survivors
        spec.devices = len(survivors)
        spec.mesh = axes_to_spec(plan.new_axes)
        spec.accum = int(plan.accum_steps)
        action.params.update(
            new_axes=dict(plan.new_axes),
            accum_steps=int(plan.accum_steps),
            devices=spec.devices,
            plan_reason=plan.reason,
        )

    # -- the loop ----------------------------------------------------------

    def poll_once(self) -> None:
        now = self._clock()
        for run in self.runs.values():
            status = run.monitor.poll()
            run.last_status = status
            rc = run.proc.poll() if run.proc is not None else None
            proc_running = run.proc is not None and rc is None
            action = run.policy.decide(
                status, proc_running=proc_running, exit_code=rc, now=now
            )
            if action is not None:
                self._execute(run, action, status, now)

    def _terminal(self, run: SupervisedRun) -> bool:
        rc = run.proc.poll() if run.proc is not None else None
        if run.proc is not None and rc is None:
            return False  # still running
        if run.policy.gave_up:
            return True  # surfaced to a human; nothing more will happen
        st = run.last_status
        return rc == 0 and st is not None and st.status == "finished"

    def run_loop(self, *, timeout: float, hook=None) -> bool:
        """Poll until every run is terminal (or ``timeout``). ``hook`` is
        the soak's chaos hand — called once per sweep with the controller.
        Returns True when all runs went terminal in time."""
        deadline = self._clock() + timeout
        while True:
            self.poll_once()
            if hook is not None:
                hook(self)
            if all(self._terminal(r) for r in self.runs.values()):
                return True
            if self._clock() >= deadline:
                return False
            time.sleep(self.interval)

    def shutdown(self) -> None:
        for run in self.runs.values():
            self._stop(run, graceful=False)

    def summary(self) -> dict:
        out = {}
        for name, run in self.runs.items():
            st = run.last_status
            rc = run.proc.poll() if run.proc is not None else None
            out[name] = {
                "status": st.status if st else "unknown",
                "verdict": st.verdict if st else "unknown",
                "attempt": st.attempt if st else None,
                "exit_code": rc,
                "gave_up": run.policy.gave_up,
                "restarts_used": run.policy.restarts_used,
                "actions": [a.kind for a in run.actions],
                "ok": (
                    rc == 0
                    and st is not None
                    and st.status == "finished"
                    and not run.policy.gave_up
                ),
            }
        return out


# ---------------------------------------------------------------------------
# The acceptance soak (verify.sh stage): 3 diseased runs + clean twins.


def _spawn_twin(spec: RunSpec, log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    with open(log_path, "a") as log:
        return subprocess.Popen(
            spec.child_cmd(), stdout=log, stderr=subprocess.STDOUT, env=env
        )


def _compare_finals(name: str, a_path: str, b_path: str, failures: list) -> None:
    import numpy as np

    if not (os.path.exists(a_path) and os.path.exists(b_path)):
        failures.append(f"{name}: missing final params ({a_path} / {b_path})")
        return
    a, b = np.load(a_path), np.load(b_path)
    worst = max(float(np.max(np.abs(a[k] - b[k]))) for k in a.files)
    print(
        f"  {name}: final params vs uninterrupted twin: max|d| = {worst:.2e} "
        f"(tolerance {ELASTIC_TOL})"
    )
    if not (worst <= ELASTIC_TOL):
        failures.append(
            f"{name}: final params diverged from the twin "
            f"(max|d| {worst:.2e} > {ELASTIC_TOL})"
        )


def run_soak(args) -> int:
    from distributed_training_pytorch_tpu.telemetry import monitor as monitor_lib
    from distributed_training_pytorch_tpu.telemetry.controller import ControllerConfig
    from distributed_training_pytorch_tpu.telemetry.events import (
        EventLog,
        peek_attempt,
        read_events,
    )

    import run_compare

    workdir = tempfile.mkdtemp(prefix="fleet_soak_")
    epochs = 5 if args.quick else 7
    tile = 8  # ~112 steps/epoch: verdict fractions get honest denominators
    hb = ("--heartbeat-every", "0.5")
    zero_budget = args.max_restarts <= 0

    def spec(name, **kw):
        return RunSpec(
            name=name,
            run_dir=os.path.join(workdir, name),
            final=os.path.join(workdir, f"{name}_final.npz"),
            **kw,
        )

    if zero_budget:
        # The teeth (the perf-gate injected-failure pattern): one clean run,
        # one SIGKILL — a zero-budget controller must refuse to remediate
        # and this process must exit non-zero.
        specs = [spec("killed", max_epoch=3, tile=tile, extra=hb)]
    else:
        specs = [
            # SIGKILL'd AND loader-starved: restart from latest_valid, then
            # the bounded prefetch tune, A/B-judged.
            spec(
                "killed",
                max_epoch=epochs,
                tile=tile,
                knobs={"prefetch_batches": 1, "commit_delay_s": 0.0},
                extra=("--load-delay", "0.02", "--num-workers", "8", *hb),
            ),
            # Hung mid-epoch: the step watchdog SIGTERMs a resumable save
            # out of the hang; the controller sees the abnormal exit and
            # respawns past the pinned hang step.
            spec(
                "hung",
                max_epoch=3,
                tile=tile,
                extra=(
                    "--step-timeout", "2",
                    "--hang-epoch", "1", "--hang-step", "4",
                    "--hang-payload", "6",
                    *hb,
                ),
            ),
            # Degraded chip on a forced 2-device fsdp mesh: the straggler
            # verdict names chip 1; the controller re-plans onto the
            # survivor and respawns (the slow-chip flag stays — the bad
            # chip is still bad, just no longer in the mesh).
            spec(
                "straggler",
                max_epoch=3,
                tile=tile,
                devices=2,
                device_ids=(0, 1),
                mesh="fsdp2",
                extra=("--slow-chip", "1:60", "--slow-chip-count", "1000", *hb),
            ),
        ]

    controller_events = os.path.join(workdir, "controller", "events.jsonl")
    os.makedirs(os.path.dirname(controller_events), exist_ok=True)
    config = ControllerConfig(
        max_restarts=args.max_restarts,
        backoff_s=2.0,
        backoff_factor=2.0,
        confirm_polls=2,
        # The A/B verdict waits for this much of the tuned attempt's steady
        # wall: the first post-warmup window's tiny denominator must not
        # decide a revert.
        ab_min_steady_s=1.5,
    )
    # Liveness ceilings sit HIGH on purpose: the subprocess exit code is
    # the controller's definitive death signal here; the log-silence rules
    # exist for adopted runs and must not misread an XLA compile as death.
    monitor_config = monitor_lib.AlertConfig(
        stale_after_s=60.0, dead_after_s=180.0, min_steady_s=1.0
    )
    fleet = FleetController(
        specs,
        config=config,
        monitor_config=monitor_config,
        event_log=EventLog(controller_events, process_index=0),
        interval=0.3,
        steady_diff=run_compare.steady_diff,
    )

    # Twins: the same math (global batch, epochs, seed, starting topology),
    # no injections, never touched by the controller.
    twins, twin_procs = {}, {}
    if not zero_budget:
        twins = {
            "killed": spec("killed_twin", max_epoch=epochs, tile=tile),
            "hung": spec("hung_twin", max_epoch=3, tile=tile),
            "straggler": spec(
                "straggler_twin", max_epoch=3, tile=tile, devices=2, mesh="fsdp2"
            ),
        }
        twin_procs = {
            name: _spawn_twin(t, os.path.join(workdir, f"{t.name}.log"))
            for name, t in twins.items()
        }

    # The chaos hand: one SIGKILL on the "killed" run, delivered only once
    # a valid restorable checkpoint exists (assertion 1 of chaos_soak —
    # SIGKILL must find something restorable on disk already).
    state = {"killed": False}

    def chaos_hook(ctl: FleetController) -> None:
        if state["killed"]:
            return
        run = ctl.runs["killed"]
        if run.proc is None or run.proc.poll() is not None:
            return
        weights = os.path.join(run.spec.run_dir, "weights")
        survivors = valid_checkpoints(weights)
        if survivors:
            print(
                f"  chaos: SIGKILL killed/ with {len(survivors)} valid "
                f"checkpoint(s) on disk"
            )
            run.proc.kill()
            state["killed"] = True

    print(f"fleet soak: workdir {workdir} (max_restarts={args.max_restarts})")
    fleet.start()
    try:
        converged = fleet.run_loop(timeout=SOAK_TIMEOUT_S, hook=chaos_hook)
    finally:
        fleet.shutdown()
    summary = fleet.summary()
    for name, row in summary.items():
        print(
            f"  {name}: {row['status']}/{row['verdict']} exit={row['exit_code']} "
            f"attempt={row['attempt']} restarts={row['restarts_used']} "
            f"actions={row['actions']}{' GAVE UP' if row['gave_up'] else ''}"
        )

    actions = [
        r for r in read_events(controller_events)
        if r.get("event") == "controller_action"
    ]

    if zero_budget:
        # Refusal contract: exactly zero respawns, a recorded `refuse`,
        # and a non-zero exit from this process.
        failures = []
        if not state["killed"]:
            failures.append("the SIGKILL was never delivered")
        if any(a["action"] in ("restart", "restart_excluding", "tune", "revert")
               for a in actions):
            failures.append("a zero-budget controller executed a respawn")
        if not any(a["action"] == "refuse" for a in actions):
            failures.append("no `refuse` controller_action was recorded")
        att = peek_attempt(specs[0].run_dir)
        if att != 1:
            failures.append(f"run respawned: attempt counter is {att}, not 1")
        for f in failures:
            print(f"FLEET SOAK BUG: {f}")
        if failures:
            return 2
        print(
            "fleet soak (zero budget): controller refused to act, run stays "
            "dead — exiting non-zero as designed"
        )
        if not args.keep:
            shutil.rmtree(workdir, ignore_errors=True)
        return 1

    failures: list[str] = []
    if not converged:
        failures.append(f"fleet did not converge within {SOAK_TIMEOUT_S:.0f}s")
    for name, row in summary.items():
        if not row["ok"]:
            failures.append(f"{name}: not restored to completion ({row})")
        elif row["verdict"] != "healthy":
            failures.append(f"{name}: final verdict {row['verdict']}, not healthy")

    # Action catalog: each disease produced its remediation, every action
    # carries evidence, and budgets were respected.
    by_run = {name: [a for a in actions if a.get("run") == name] for name in summary}
    if not any(a["action"] == "restart" for a in by_run.get("killed", ())):
        failures.append("killed: no `restart` action recorded")
    kinds_killed = {a["action"] for a in by_run.get("killed", ())}
    if not {"tune", "keep"} <= kinds_killed:
        failures.append(f"killed: expected tune+keep, got {sorted(kinds_killed)}")
    if not any(a["action"] == "restart" for a in by_run.get("hung", ())):
        failures.append("hung: no `restart` action recorded")
    strag_actions = [
        a for a in by_run.get("straggler", ()) if a["action"] == "restart_excluding"
    ]
    if not strag_actions:
        failures.append("straggler: no `restart_excluding` action recorded")
    elif strag_actions[0]["params"].get("exclude_chip") != 1:
        failures.append(
            f"straggler: excluded chip {strag_actions[0]['params']} != 1"
        )
    for a in actions:
        if not a.get("evidence"):
            failures.append(f"action without evidence: {a['action']} on {a['run']}")
        if a.get("max_restarts") != args.max_restarts:
            failures.append(f"action missing budget fields: {a}")

    # Attempt counters are monotonic and bounded by the respawn count. A
    # child the chaos hand kills during STARTUP (before train() claims)
    # legitimately leaves a gap, so the exact-equality check lives in the
    # unit tests; here attempts must have moved and never outrun respawns.
    for name, row in summary.items():
        att = peek_attempt(fleet.runs[name].spec.run_dir)
        lo = 2 if row["restarts_used"] else 1
        if not (lo <= att <= 1 + row["restarts_used"]):
            failures.append(
                f"{name}: attempt counter {att} outside [{lo}, "
                f"1 + {row['restarts_used']} respawns]"
            )

    # Final-params equivalence with the uninterrupted twins.
    for name, twin in twins.items():
        rc = twin_procs[name].wait(timeout=CHILD_TIMEOUT_S)
        if rc != 0:
            failures.append(f"{twin.name}: twin exited {rc}")
            continue
        _compare_finals(
            name, fleet.runs[name].spec.final, twin.final, failures
        )

    for f in failures:
        print(f"FLEET SOAK BUG: {f}")
    if failures:
        print(f"fleet soak FAILED ({len(failures)} finding(s)); kept {workdir}")
        return 1
    if not args.keep:
        shutil.rmtree(workdir, ignore_errors=True)
    print(
        "fleet soak OK: SIGKILL/hang/degraded-chip/loader-starve across a "
        "3-run fleet all remediated to healthy completion autonomously; "
        f"{len(actions)} controller_action record(s), every one with "
        "evidence; final params within tolerance of uninterrupted twins"
    )
    return 0


# ---------------------------------------------------------------------------
# Generic supervision mode.


def run_fleet(args) -> int:
    from distributed_training_pytorch_tpu.telemetry import monitor as monitor_lib
    from distributed_training_pytorch_tpu.telemetry.controller import ControllerConfig
    from distributed_training_pytorch_tpu.telemetry.events import EventLog

    import run_compare

    specs = []
    for d in args.run_dirs:
        name = os.path.basename(os.path.normpath(d)) or d
        cmd = shlex.split(args.cmd.format(run_dir=d)) if args.cmd else None
        specs.append(RunSpec(name=name, run_dir=d, cmd=cmd, adopt=cmd is None))
    config = ControllerConfig(max_restarts=args.max_restarts)
    fleet = FleetController(
        specs,
        config=config,
        monitor_config=monitor_lib.AlertConfig(),
        event_log=EventLog(args.events, process_index=0),
        interval=args.interval,
        steady_diff=run_compare.steady_diff,
    )
    fleet.start()
    try:
        if args.once:
            fleet.poll_once()
        else:
            fleet.run_loop(timeout=args.timeout)
    except KeyboardInterrupt:
        pass
    finally:
        if args.cmd:  # adopted runs are not ours to kill
            fleet.shutdown()
    summary = fleet.summary()
    print(json.dumps(summary, indent=2, default=str))
    return 0 if all(r["ok"] or args.once for r in summary.values()) else 1


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("run_dirs", nargs="*", help="run directories to supervise")
    parser.add_argument(
        "--cmd",
        help="respawn command template; {run_dir} is substituted per run. "
        "Without it runs are adopted: decisions are recorded, not executed",
    )
    parser.add_argument("--interval", type=float, default=2.0, help="poll cadence (s)")
    parser.add_argument(
        "--max-restarts", dest="max_restarts", type=int, default=3,
        help="respawn budget per run (0 = the controller must refuse to act)",
    )
    parser.add_argument(
        "--events", default="controller_events.jsonl",
        help="controller_action/monitor_alert JSONL audit log",
    )
    parser.add_argument("--once", action="store_true", help="single poll, then exit")
    parser.add_argument(
        "--timeout", type=float, default=SOAK_TIMEOUT_S,
        help="supervision wall-clock bound (s)",
    )
    parser.add_argument(
        "--soak", action="store_true",
        help="run the closed-loop acceptance soak (see module doc)",
    )
    parser.add_argument("--quick", action="store_true", help="CI-sized soak")
    parser.add_argument("--keep", action="store_true", help="keep the soak workdir")
    # child-mode flags (the soak's trainer subprocess)
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--run-dir", dest="run_dir", help=argparse.SUPPRESS)
    parser.add_argument("--final", default="", help=argparse.SUPPRESS)
    parser.add_argument("--max-epoch", dest="max_epoch", type=int, default=4,
                        help=argparse.SUPPRESS)
    parser.add_argument("--devices", type=int, default=0, help=argparse.SUPPRESS)
    parser.add_argument("--mesh", default="", help=argparse.SUPPRESS)
    parser.add_argument("--accum", type=int, default=1, help=argparse.SUPPRESS)
    parser.add_argument("--tile", type=int, default=3, help=argparse.SUPPRESS)
    parser.add_argument("--prefetch", type=int, default=2, help=argparse.SUPPRESS)
    parser.add_argument("--num-workers", dest="num_workers", type=int, default=0,
                        help=argparse.SUPPRESS)
    parser.add_argument("--load-delay", dest="load_delay", type=float, default=0.0,
                        help=argparse.SUPPRESS)
    parser.add_argument("--commit-delay", dest="commit_delay", type=float,
                        default=0.0, help=argparse.SUPPRESS)
    parser.add_argument("--heartbeat-every", dest="heartbeat_every", type=float,
                        default=2.0, help=argparse.SUPPRESS)
    parser.add_argument("--step-timeout", dest="step_timeout", type=float,
                        default=0.0, help=argparse.SUPPRESS)
    parser.add_argument("--hang-epoch", dest="hang_epoch", type=int, default=1,
                        help=argparse.SUPPRESS)
    parser.add_argument("--hang-step", dest="hang_step", type=int, default=4,
                        help=argparse.SUPPRESS)
    parser.add_argument("--hang-payload", dest="hang_payload", type=float,
                        default=0.0, help=argparse.SUPPRESS)
    parser.add_argument("--slow-chip", dest="slow_chip", default="",
                        help=argparse.SUPPRESS)
    parser.add_argument("--slow-chip-count", dest="slow_chip_count", type=int,
                        default=1000, help=argparse.SUPPRESS)
    parser.add_argument("--seed", type=int, default=0, help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.child:
        return child_main(args)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.soak:
        return run_soak(args)
    if not args.run_dirs:
        parser.error("run_dirs required (or --soak)")
    return run_fleet(args)


if __name__ == "__main__":
    sys.exit(main())
