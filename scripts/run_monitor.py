#!/usr/bin/env python
"""Live run monitor — the run doctor, streaming (ISSUE 15).

Tails one or more run directories' ``telemetry/events.jsonl`` while the
runs are alive and prints the doctor's diagnosis *online* plus the
liveness verdicts only a live observer can produce
(``telemetry/monitor.py``): ``training`` / ``stale_heartbeat`` (records
still arrive but no execution unit completes) / ``dead`` (the log itself
went silent) / ``finished``. One directory renders the detailed view;
several render a fleet table, refreshed every ``--interval`` seconds.

Inference servers (ISSUE 18, ``serving/server.py``) are first-class fleet
members: a run dir whose log opens with ``serve_start`` reads status
``serving``, its liveness keys off the server's ~1 Hz ``request_batch``
pulse, and its fleet row fills the ``qps``/``p99`` columns that trainer
rows blank (trainer-only columns blank in turn on server rows). A pulse
reporting ``slo_ok: false`` turns the verdict to ``slo_breach`` — which
``--once`` exits 1 on, the same CI contract as a degraded trainer.

Usage::

    python scripts/run_monitor.py RUN_DIR             # follow one run
    python scripts/run_monitor.py DIR1 DIR2 ...       # fleet table
    python scripts/run_monitor.py RUN_DIR --once      # one poll + exit code
    python scripts/run_monitor.py RUN_DIR --once --json
    python scripts/run_monitor.py RUN_DIR --events E  # append debounced
                                                      #   `monitor_alert` records
    python scripts/run_monitor.py --self-test         # CI gate (below)

Alert rules (``telemetry.monitor.AlertConfig`` — all debounced: a rule
fires once when its condition goes false->true and re-arms only after it
clears): ``--stale-after`` / ``--dead-after`` liveness ceilings,
``--data-wait-ceiling`` / ``--checkpoint-ceiling`` steady-state goodput
fraction ceilings, anomaly kinds, and verdict transitions
(compile_bound / straggler / comm_heavy crossing score 1.0).

Exit codes (``--once``, and follow mode with ``--exit-on-end``):
0 = alive-or-finished and clean, 1 = degraded (stale heartbeat, a
non-healthy verdict, or an alert rule over its line), 2 = dead,
3 = nothing to monitor (no event log yet).

``--self-test`` (the verify.sh stage; the perf-gate injected-regression
pattern) drives the monitor against REAL background digits runs through
the existing fault seams, sharing ``run_doctor._self_test_trainer`` so
the monitor watches the exact workload the doctor self-diagnoses:

* a clean run must read ``training``/``healthy`` live and ``finished``/
  ``healthy`` after, with steady-state goodput fractions matching
  ``run_doctor.py``'s post-hoc fractions to 1e-6 on the same log (and
  byte-identical diagnosis dicts — the shared-implementation proof);
* an injected ``FaultPlan("hang")`` must flip the verdict to
  ``stale_heartbeat`` while the watchdog's patrol heartbeats keep the
  log breathing (exit 1);
* SIGKILL mid-hang must flip it to ``dead`` once the log goes silent
  past the ceiling (exit 2);
* a loader-sleep run (the ``ShardedLoader.load_delay_s`` seam) followed
  live must raise exactly ONE debounced ``data_bound`` alert into the
  ``--events`` JSONL despite polling every 0.3s (exit 1).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry import monitor as monitor_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry.events import (  # noqa: E402
    EventLog,
    load_run_events,
)

SCRIPT = os.path.abspath(__file__)

# ---------------------------------------------------------------------------
# Rendering


_FLEET_COLUMNS = (
    "run", "status", "verdict", "att", "epoch", "step", "step_ms",
    "qps", "p99", "good%", "data%", "ckpt%", "age_s", "alerts",
)


def render_fleet(statuses) -> str:
    rows = [s.fleet_row() for s in statuses]
    widths = {
        c: max(len(c), *(len(str(r[c])) for r in rows)) for c in _FLEET_COLUMNS
    }
    lines = ["  ".join(c.ljust(widths[c]) for c in _FLEET_COLUMNS)]
    for r in rows:
        lines.append("  ".join(str(r[c]).ljust(widths[c]) for c in _FLEET_COLUMNS))
    return "\n".join(lines)


def render(statuses, as_json: bool) -> str:
    if as_json:
        payload = [s.to_dict() for s in statuses]
        return json.dumps(payload[0] if len(payload) == 1 else payload,
                          indent=2, sort_keys=True)
    if len(statuses) == 1:
        return statuses[0].describe()
    return render_fleet(statuses)


# ---------------------------------------------------------------------------
# The monitor loop


def run_monitor(args) -> int:
    config = monitor_lib.AlertConfig(
        stale_after_s=args.stale_after,
        dead_after_s=args.dead_after,
        data_wait_frac=args.data_wait_ceiling,
        checkpoint_frac=args.checkpoint_ceiling,
    )
    alert_log = (
        EventLog(args.events, process_index=0) if args.events else None
    )
    monitors = [
        monitor_lib.RunMonitor(d, config, alert_log=alert_log)
        for d in args.run_dir
    ]
    try:
        while True:
            statuses = [m.poll() for m in monitors]
            print(render(statuses, args.json))
            for s in statuses:
                for a in s.alerts:
                    print(
                        f"run_monitor ALERT [{a['rule']}] {s.run_dir}: "
                        f"value={a.get('value')} threshold={a.get('threshold')} "
                        f"— {a.get('message', '')}",
                        file=sys.stderr,
                    )
            code = monitor_lib.worst_exit_code(statuses)
            if args.once:
                return code
            if args.exit_on_end and all(
                s.status in ("finished", "dead") for s in statuses
            ):
                return code
            time.sleep(args.interval)
    finally:
        if alert_log is not None:
            alert_log.close()


# ---------------------------------------------------------------------------
# Self-test: real background digits runs through the existing fault seams.
# The training harness is run_doctor._self_test_trainer — the monitor
# watches the exact workload the doctor self-diagnoses, so the two gates
# cannot drift apart.

_HANG_S = 20.0
_HB_S = 0.2  # worker heartbeat cadence (the self-test's tightened clock)


def _worker_kwargs(case: str) -> dict:
    from distributed_training_pytorch_tpu.fault import FaultPlan
    from distributed_training_pytorch_tpu.telemetry import Telemetry

    if case == "healthy":
        # The doctor self-test's clean shape: one async save with overlap
        # room (a micro run saving every epoch honestly reads
        # checkpoint_stall).
        return dict(
            max_epoch=3, save_period=3,
            telemetry=Telemetry(heartbeat_every_s=_HB_S),
        )
    if case == "hang":
        # One long host-side hang in epoch 1 (epoch 0 arms the watchdog —
        # it pats per completed unit). step_timeout is far above the hang
        # so the watchdog never SIGTERMs: the point is the PATROL thread's
        # heartbeats flowing while the main thread sleeps. chain_steps=1
        # keeps the fault window on the plain single-step path.
        return dict(
            max_epoch=3, chain_steps=1, step_timeout=90.0,
            fault_plan=FaultPlan().add("hang", epoch=1, step=8, payload=_HANG_S),
            telemetry=Telemetry(anomaly=None, heartbeat_every_s=_HB_S),
        )
    if case == "data-wait":
        # The perf gate / doctor loader seam: every fetch sleeps, the
        # steady-state data_wait fraction crosses any honest ceiling and
        # STAYS crossed — the debounce proof.
        return dict(
            max_epoch=2, load_delay_s=0.05,
            telemetry=Telemetry(anomaly=None, heartbeat_every_s=_HB_S),
        )
    raise ValueError(f"unknown worker case {case!r}")


def train_worker(case: str, run_dir: str) -> int:
    sys.path.insert(0, os.path.dirname(SCRIPT))
    import run_doctor

    trainer = run_doctor._self_test_trainer(run_dir, **_worker_kwargs(case))
    trainer.train()
    return 0


def _spawn_worker(case: str, run_dir: str):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    log = open(os.path.join(run_dir, "worker.log"), "w")  # jaxlint: disable=file-write-without-rank-gate -- single-process CI harness, not a training-job writer
    proc = subprocess.Popen(
        [sys.executable, SCRIPT, "--train-worker", case, run_dir],
        stdout=log, stderr=subprocess.STDOUT, env=env,
    )
    proc._log_file = log  # closed by _reap
    return proc


def _reap(proc, timeout=180) -> int:
    try:
        code = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        code = proc.wait()
    if getattr(proc, "_log_file", None) is not None:
        proc._log_file.close()
    return code


def _cli_once(run_dirs, *extra_args, timeout=120):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, SCRIPT, *run_dirs, "--once", *extra_args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    return out


def _wait_for(predicate, timeout, interval=0.3):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def self_test() -> int:
    import shutil
    import tempfile

    failures: list[str] = []

    def check(cond, msg):
        print(f"run_monitor self-test: {'ok' if cond else 'FAIL'} — {msg}")
        if not cond:
            failures.append(msg)

    tight = monitor_lib.AlertConfig(stale_after_s=2.5, dead_after_s=3.0)

    # -- leg 1: healthy — live `training`, post-hoc parity with the doctor
    tmp_healthy = tempfile.mkdtemp(prefix="run_monitor_healthy_")
    proc = _spawn_worker("healthy", tmp_healthy)
    try:
        live = monitor_lib.RunMonitor(tmp_healthy)
        seen_training = _wait_for(
            lambda: live.poll().status == "training", timeout=90
        )
        check(seen_training, "healthy run observed live in status 'training'")

        def settled():
            # Let the steady state accumulate before asserting the CLI's
            # verdict: the first post-compile sync's tiny denominator is
            # honest noise, not a diagnosis.
            st = live.poll()
            return st.status == "finished" or (
                st.verdict == "healthy"
                and st.steady_fractions.get("productive_step", 0.0) > 0.3
            )

        _wait_for(settled, timeout=90)
        out = _cli_once([tmp_healthy])
        check(
            out.returncode == 0 and "healthy" in out.stdout,
            f"--once on the live healthy run exits 0 and prints healthy "
            f"(got rc={out.returncode})",
        )
        code = _reap(proc)
        check(code == 0, f"healthy worker exited 0 (got {code})")
    finally:
        _reap(proc, timeout=5)

    # Post-hoc: the monitor and the doctor read the SAME log through the
    # SAME reader + signal fold — fractions to 1e-6 (they are identical
    # floats) and byte-identical diagnosis dicts (ISSUE 15 acceptance).
    post = doctor_lib.diagnose(load_run_events(tmp_healthy))
    mon_status = monitor_lib.RunMonitor(tmp_healthy).poll()
    check(
        mon_status.status == "finished" and mon_status.verdict == "healthy",
        f"finished healthy run reads finished/healthy "
        f"(got {mon_status.status}/{mon_status.verdict})",
    )
    doctor_fr = doctor_lib.steady_fractions(post.signals.goodput_seconds or {})
    worst = max(
        abs(mon_status.steady_fractions.get(b, 0.0) - doctor_fr.get(b, 0.0))
        for b in doctor_fr
    )
    check(
        worst <= 1e-6,
        f"monitor steady fractions match run_doctor's to 1e-6 (worst {worst:g})",
    )
    check(
        json.dumps(mon_status.diagnosis.to_dict(), sort_keys=True)
        == json.dumps(post.to_dict(), sort_keys=True),
        "streaming and post-hoc diagnoses are byte-identical on the same log",
    )

    # -- legs 2+3: hang -> stale_heartbeat, SIGKILL -> dead
    tmp_hang = tempfile.mkdtemp(prefix="run_monitor_hang_")
    proc = _spawn_worker("hang", tmp_hang)
    try:
        live = monitor_lib.RunMonitor(tmp_hang, tight)

        def deep_in_hang():
            st = live.poll()
            return (
                st.status == "stale_heartbeat"
                and (st.progress_age_s or 0.0) >= 3.5
            )

        check(
            _wait_for(deep_in_hang, timeout=120),
            "injected hang read as stale_heartbeat (patrol heartbeats, no unit)",
        )
        events = load_run_events(tmp_hang)
        patrol = [
            r for r in events
            if r.get("event") == "heartbeat" and r.get("source") == "watchdog"
        ]
        check(
            bool(patrol) and any(
                float(r.get("since_progress_s") or 0.0) >= 2.0 for r in patrol
            ),
            "watchdog patrol heartbeats carry an honest since_progress_s",
        )
        out = _cli_once([tmp_hang], "--stale-after", "2.5", "--dead-after", "60")
        check(
            out.returncode == 1 and "stale_heartbeat" in out.stdout,
            f"--once mid-hang exits 1 with stale_heartbeat (got rc={out.returncode})",
        )
        proc.send_signal(signal.SIGKILL)
        _reap(proc, timeout=15)
        time.sleep(tight.resolved_dead_after() + 1.0)
        out = _cli_once([tmp_hang], "--stale-after", "2.5", "--dead-after", "3")
        check(
            out.returncode == 2 and "dead" in out.stdout,
            f"--once after SIGKILL exits 2 with dead (got rc={out.returncode})",
        )
    finally:
        _reap(proc, timeout=5)

    # -- leg 4: loader sleep -> exactly ONE debounced data_bound alert
    tmp_dw = tempfile.mkdtemp(prefix="run_monitor_datawait_")
    alerts_path = os.path.join(tmp_dw, "alerts.jsonl")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    mon_proc = subprocess.Popen(
        [
            sys.executable, SCRIPT, tmp_dw,
            "--interval", "0.3", "--events", alerts_path, "--exit-on-end",
            "--stale-after", "60", "--dead-after", "120",
        ],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )
    worker = _spawn_worker("data-wait", tmp_dw)
    wcode = _reap(worker)
    check(wcode == 0, f"data-wait worker exited 0 (got {wcode})")
    mcode = _reap(mon_proc, timeout=60)
    check(
        mcode == 1,
        f"follow-mode monitor exits 1 on the data-bound run (got {mcode})",
    )
    alert_recs = (
        load_run_events(alerts_path) if os.path.isfile(alerts_path) else []
    )
    data_alerts = [
        r for r in alert_recs
        if r.get("event") == "monitor_alert" and r.get("rule") == "data_bound"
    ]
    check(
        len(data_alerts) == 1,
        f"exactly one debounced data_bound monitor_alert "
        f"(got {len(data_alerts)} across {len(alert_recs)} records, "
        f"polled every 0.3s)",
    )

    # -- leg 5: fleet table over two runs
    out = _cli_once([tmp_healthy, tmp_dw])
    base_h = os.path.basename(tmp_healthy)
    base_d = os.path.basename(tmp_dw)
    check(
        base_h in out.stdout and base_d in out.stdout and out.returncode == 1,
        f"fleet --once renders both runs and exits 1 "
        f"(data-bound run degraded; got rc={out.returncode})",
    )

    for tmp in (tmp_healthy, tmp_hang, tmp_dw):
        shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print("RUN MONITOR SELF-TEST FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(
        "run_monitor self-test OK: live healthy + hang->stale_heartbeat + "
        "SIGKILL->dead + one debounced data_bound alert + fleet table"
    )
    return 0


# ---------------------------------------------------------------------------


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("run_dir", nargs="*", default=[],
                        help="run directory(ies) (the Trainer save_folder) or "
                             "direct events.jsonl path(s); several = fleet table")
    parser.add_argument("--once", action="store_true",
                        help="one poll, print, exit with the CI code")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="follow-mode poll cadence in seconds (default 2)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable status instead of the console view")
    parser.add_argument("--events", default=None,
                        help="append debounced monitor_alert records to this JSONL log")
    parser.add_argument("--stale-after", type=float, default=120.0,
                        help="no completed unit for this long => stale_heartbeat")
    parser.add_argument("--dead-after", type=float, default=None,
                        help="log silent for this long => dead (default 3x stale)")
    parser.add_argument("--data-wait-ceiling", type=float,
                        default=doctor_lib.THRESHOLDS["data_wait_frac"],
                        help="steady-state data_wait fraction alert ceiling")
    parser.add_argument("--checkpoint-ceiling", type=float,
                        default=doctor_lib.THRESHOLDS["checkpoint_frac"],
                        help="steady-state checkpoint fraction alert ceiling")
    parser.add_argument("--exit-on-end", action="store_true",
                        help="follow mode: exit (with the CI code) once every "
                             "monitored run is finished or dead")
    parser.add_argument("--self-test", action="store_true",
                        help="CI gate: drive the monitor against real runs with "
                             "injected hang/SIGKILL/loader-sleep (verify.sh)")
    parser.add_argument("--train-worker", default=None,
                        metavar="CASE", help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.train_worker is not None:
        if len(args.run_dir) != 1:
            parser.error("--train-worker takes exactly one run_dir")
        return train_worker(args.train_worker, args.run_dir[0])
    if args.self_test:
        return self_test()
    if not args.run_dir:
        parser.error("at least one run_dir is required (or use --self-test)")
    return run_monitor(args)


if __name__ == "__main__":
    sys.exit(main())
