#!/usr/bin/env python
"""Run comparison — ranked A/B attribution between two runs (ISSUE 14).

The per-run stack can explain one run exhaustively (goodput, StepProfile,
memory classes, comm inventory, doctor); this CLI answers the question the
ROADMAP actually asks: *why did step_ms change (or refuse to change)
between two runs?* It takes two artifacts, auto-detects their kind, and
prints a doctor-style ranked attribution report — every verdict row
carrying evidence refs (trace paths, event-log line numbers) — through the
ONE delta-attribution implementation (``profiling.diff``; perf_gate's FAIL
diagnosis uses the same code, test-enforced).

Inputs (both sides must be the same kind; ``--kind`` overrides detection)::

    python scripts/run_compare.py A.xplane.pb B.xplane.pb   # profile captures
    python scripts/run_compare.py tracedirA/ tracedirB/     #   (or trace dirs)
    python scripts/run_compare.py run_a/ run_b/             # Trainer run dirs
    python scripts/run_compare.py BENCH_r02.json BENCH_r05.json  # bench entries
    python scripts/run_compare.py --kind hlo a.hlo b.hlo    # optimized-HLO texts

* **profile vs profile** — ``profiling.diff.diff_profiles``: ranked
  per-category step-delta rows (fractions of delta sum to 1), matched
  top-op deltas with new/removed ops named, roofline shifts
  (memory->compute is the Pallas-win signature; ``--ridge`` arms it).
* **run dir vs run dir** — per-step goodput-bucket deltas (the same bucket
  wall the doctor reads), plus the profile-category diff when both runs
  carried a ``profile_capture``; evidence rows cite event-log lines.
* **bench vs bench** — headline metric deltas (step_ms, value, mfu family),
  with category attribution when both entries carry ``BENCH_PROFILE=1``
  fields.
* **hlo vs hlo** — ``analysis.diff``: op-category/fusion-count deltas and
  (with ``--mesh``) the per-axis collective-inventory byte delta with
  replica-group changes named.

Provenance (ISSUE 14 stamping): entries whose stamped *configuration*
differs (jax/jaxlib, XLA_FLAGS, mesh, dtype, chain_steps, batch — git SHA
is exempt: differing code is the point) are REFUSED with the differing keys
named; ``--force`` overrides. Unstamped (pre-ISSUE-14) artifacts compare
with a note.

``--events E`` appends a ``run_compare`` JSONL record.
``--self-test`` is the verify.sh gate: identical twins must diff clean (no
category/bucket over the noise floor), and three injected known-cause
slowdowns — a 3x synthetic conv slowdown, the loader-sleep seam, the
async-committer delay seam — must each be attributed to the correct
category/bucket with evidence refs.

Exit codes: 0 report produced / self-test passed, 1 self-test failure,
2 provenance refusal (re-run with --force), 3 unusable input.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_training_pytorch_tpu.profiling import diff as diff_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry import history as history_lib  # noqa: E402
from distributed_training_pytorch_tpu.telemetry import provenance as prov_lib  # noqa: E402

DEFAULT_NOISE_FLOOR = 0.10


# ---------------------------------------------------------------------------
# Input detection + loading
# ---------------------------------------------------------------------------


def detect_kind(path: str) -> str:
    """profile | run | bench — by what the path actually holds."""
    if path.endswith(".xplane.pb"):
        return "profile"
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "telemetry", "events.jsonl")):
            return "run"
        from distributed_training_pytorch_tpu.profiling import latest_trace_file

        if latest_trace_file(path) is not None:
            return "profile"
        raise ValueError(
            f"{path}: directory holds neither telemetry/events.jsonl (a run "
            "dir) nor a *.xplane.pb trace (a profile capture)"
        )
    if os.path.basename(path) == "events.jsonl":
        return "run"
    if path.endswith((".json", ".jsonl")):
        return "bench"
    raise ValueError(
        f"{path}: cannot detect artifact kind (expected a *.xplane.pb trace, "
        "a run dir, or a bench *.json) — pass --kind explicitly"
    )


def load_bench_entry(path: str) -> dict:
    """One bench measurement dict from a committed round file (first entry,
    noting sweeps), a raw bench JSON line, or a JSONL file of lines."""
    if history_lib._ROUND_RE.search(os.path.basename(path)):
        entries = history_lib.load_round_file(path)
        if not entries:
            raise ValueError(f"{path}: round file carries no bench entries")
        if len(entries) > 1:
            print(f"run_compare: {path} is a {len(entries)}-entry sweep — "
                  "comparing its FIRST entry", file=sys.stderr)
        return entries[0].fields
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec, dict) and ("metric" in rec or "step_ms" in rec):
                return rec
    raise ValueError(f"{path}: no bench JSON line found")


def load_run_summary(path: str) -> dict:
    """Distill a run dir's event log: cumulative goodput seconds (last
    snapshot), total steps, provenance (run_start), the last profile
    capture's categories, and the event-log lines the figures came from."""
    from distributed_training_pytorch_tpu.telemetry import timeline as timeline_lib

    run_dir = os.path.dirname(os.path.dirname(path)) if path.endswith(
        "events.jsonl") else path
    events = timeline_lib.load_run_events(run_dir)
    out = {
        "run_dir": os.path.abspath(run_dir),
        "goodput_seconds": None,
        "goodput_line": None,
        "steps": None,
        "provenance": None,
        "profile": None,
        "profile_line": None,
    }
    max_step = 0
    for rec in events:
        if rec.get("step") is not None:
            max_step = max(max_step, int(rec["step"]))
        if isinstance(rec.get("goodput_seconds"), dict):
            out["goodput_seconds"] = dict(rec["goodput_seconds"])
            out["goodput_line"] = rec.get("_line")
            # Pair the snapshot with the step count AT snapshot time (the
            # record's own counter, else the newest step seen so far) —
            # normalizing a mid-run snapshot by a LATER step counter (a
            # preempted run's windows past the last epoch_end) would
            # under-report every bucket's per-step wall.
            out["steps"] = (int(rec["step"]) if rec.get("step") is not None
                            else max_step)
        if rec.get("event") == "run_start" and isinstance(
            rec.get("provenance"), dict
        ):
            out["provenance"] = rec["provenance"]
        if rec.get("event") == "profile_capture" and isinstance(
            rec.get("categories"), dict
        ):
            out["profile"] = {
                "categories": rec["categories"],
                "step_us": rec.get("step_us"),
            }
            out["profile_line"] = rec.get("_line")
    if out["goodput_seconds"] is None:
        raise ValueError(
            f"{run_dir}: event log carries no goodput_seconds snapshot — "
            "was the run telemetry-on?"
        )
    if not out["steps"]:
        raise ValueError(
            f"{run_dir}: no goodput snapshot covering completed steps — "
            "nothing to normalize per-step"
        )
    return out


# ---------------------------------------------------------------------------
# The three comparisons (all through profiling.diff — the ONE attribution)
# ---------------------------------------------------------------------------


def check_provenance(before: "dict | None", after: "dict | None",
                     force: bool) -> "tuple[bool, list[str], str]":
    """(ok, differing_keys, note). Refusal is the ok=False case."""
    if not before or not after:
        return True, [], ("one or both sides carry no provenance stamp "
                          "(pre-ISSUE-14 artifact) — comparing unverified")
    keys = prov_lib.differing_keys(before, after)
    if not keys:
        sha = (before.get("git_sha"), after.get("git_sha"))
        return True, [], f"provenance OK (git {sha[0]} -> {sha[1]})"
    if force:
        return True, keys, (
            f"provenance DIFFERS on {', '.join(keys)} — compared anyway (--force)"
        )
    return False, keys, (
        f"provenance DIFFERS on {', '.join(keys)} — these entries measure "
        "different programs; re-run with --force to compare anyway"
    )


def compare_profiles(path_a: str, path_b: str, *, ridge=None, top=6,
                     noise_floor=DEFAULT_NOISE_FLOOR) -> dict:
    from distributed_training_pytorch_tpu.profiling import analyze_trace

    diff = diff_lib.diff_profiles(
        analyze_trace(path_a), analyze_trace(path_b), ridge_intensity=ridge,
    )
    clean = diff.max_category_delta_frac() <= noise_floor
    return {
        "kind": "profile",
        "clean": clean,
        "step_delta_ms": diff.step_delta_us / 1e3,
        "top_rows": [r.to_dict() for r in diff.categories[:top]],
        "new_ops": [o.name for o in diff.new_ops],
        "removed_ops": [o.name for o in diff.removed_ops],
        "roofline_shifts": [o.to_dict() for o in diff.roofline_shifts],
        "report": (
            ("CLEAN — no category exceeds the "
             f"{100 * noise_floor:.0f}% noise floor\n" if clean else "")
            + diff.describe(top=top)
        ),
        "provenance": (None, None),
    }


def steady_diff(seconds_a: dict, seconds_b: dict, *,
                noise_floor=DEFAULT_NOISE_FLOOR) -> dict:
    """Steady-state goodput-fraction diff between two bucket-seconds dicts —
    THE clean check of run-vs-run comparison, shared verbatim with the
    fleet controller's knob A/B (ISSUE 16: a tune is kept only when this
    diff says the targeted fraction actually moved, judged by the same
    code an operator's ``run_compare.py`` would run).

    Fractions are the doctor's steady-state ones (compile/restart/
    overlapped-commit excluded from the denominator), diffed through the
    ONE delta-attribution implementation (``profiling.diff``). Returns
    ``{"rows": [...], "max_delta": float, "clean": bool, "fractions":
    (a, b)}`` — rows ranked by |delta|, ``clean`` = nothing moved past the
    noise floor."""
    from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib

    steady_a = doctor_lib.steady_fractions(dict(seconds_a))
    steady_b = doctor_lib.steady_fractions(dict(seconds_b))
    rows = diff_lib.attribute_delta(steady_a, steady_b)
    max_delta = max((abs(r.delta) for r in rows), default=0.0)
    return {
        "rows": rows,
        "max_delta": max_delta,
        "clean": max_delta <= noise_floor,
        "fractions": (steady_a, steady_b),
    }


def compare_runs(path_a: str, path_b: str, *, top=6,
                 noise_floor=DEFAULT_NOISE_FLOOR) -> dict:
    a = load_run_summary(path_a)
    b = load_run_summary(path_b)
    # Per-step wall per goodput bucket (ms): the bucket seconds the doctor
    # reads, normalized by each run's own step count so runs of different
    # lengths compare. Deltas sum to the per-step total-wall delta exactly
    # (the one attribute_delta rule).
    per_step_a = {k: v / a["steps"] * 1e3 for k, v in a["goodput_seconds"].items()}
    per_step_b = {k: v / b["steps"] * 1e3 for k, v in b["goodput_seconds"].items()}
    rows = diff_lib.attribute_delta(per_step_a, per_step_b)
    # The clean check runs on STEADY-STATE fractions (compile/restart/
    # overlapped-commit excluded — the doctor's denominator), so a twin
    # pair differing only in XLA warmup wall still reads clean.
    sd = steady_diff(a["goodput_seconds"], b["goodput_seconds"],
                     noise_floor=noise_floor)
    steady_rows = sd["rows"]
    max_steady_delta = sd["max_delta"]
    clean = sd["clean"]

    total_delta = sum(r.delta for r in rows)
    lines = []
    if clean:
        lines.append(
            f"CLEAN — no steady-state bucket fraction moved more than the "
            f"{100 * noise_floor:.0f}% noise floor "
            f"(max |delta| {100 * max_steady_delta:.1f}%)"
        )
    lines.append(
        f"per-step wall {sum(per_step_a.values()):.2f} -> "
        f"{sum(per_step_b.values()):.2f} ms ({total_delta:+.2f} ms): "
        + diff_lib.describe_rows(rows, top=top)
    )
    lines.append(
        f"  evidence: goodput snapshots {a['run_dir']}/telemetry/"
        f"events.jsonl:{a['goodput_line']} vs {b['run_dir']}/telemetry/"
        f"events.jsonl:{b['goodput_line']} "
        f"({a['steps']} vs {b['steps']} steps)"
    )
    profile_rows = None
    if a["profile"] and b["profile"]:
        profile_rows = diff_lib.attribute_entry_delta(
            {"step_ms": (a["profile"]["step_us"] or 0) / 1e3,
             "categories": a["profile"]["categories"]},
            {"step_ms": (b["profile"]["step_us"] or 0) / 1e3,
             "categories": b["profile"]["categories"]},
        )
        if profile_rows:
            lines.append(
                "profile categories: " + diff_lib.describe_rows(profile_rows, top=top)
            )
            lines.append(
                f"  evidence: profile_capture events at lines "
                f"{a['profile_line']} vs {b['profile_line']}"
            )
    return {
        "kind": "run",
        "clean": clean,
        "step_delta_ms": total_delta,
        "top_rows": [r.to_dict() for r in rows[:top]],
        "steady_rows": [r.to_dict() for r in steady_rows[:top]],
        "profile_rows": [r.to_dict() for r in profile_rows[:top]] if profile_rows else None,
        "report": "\n".join(lines),
        "provenance": (a["provenance"], b["provenance"]),
    }


def compare_bench(path_a: str, path_b: str, *, top=6,
                  noise_floor=DEFAULT_NOISE_FLOOR) -> dict:
    a = load_bench_entry(path_a)
    b = load_bench_entry(path_b)
    lines = []
    headline = []
    for field in ("step_ms", "value", "mfu", "mfu_exec", "mfu_xla",
                  "comm_bytes_per_step"):
        va, vb = a.get(field), b.get(field)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            change = (vb / va - 1.0) if va else 0.0
            headline.append({"field": field, "before": va, "after": vb,
                             "change": change})
            lines.append(
                f"{field}: {va:.4g} -> {vb:.4g} ({100 * change:+.2f}%)"
            )
    if not headline:
        raise ValueError("the two bench entries share no comparable numeric field")
    step_fields = {h["field"]: h for h in headline}
    # Clean = EVERY shared headline figure within the floor — two entries
    # sharing only mfu_exec/comm_bytes must not read clean while one of
    # those halved (headline is non-empty here, so this is never vacuous).
    clean = all(abs(h["change"]) <= noise_floor for h in headline)
    rows = diff_lib.attribute_entry_delta(a, b)
    if rows:
        lines.append(
            "step_ms attribution (BENCH_PROFILE categories): "
            + diff_lib.describe_rows(rows, top=top)
        )
    elif "step_ms" in step_fields:
        lines.append(
            "  (no category attribution: one or both entries lack "
            "BENCH_PROFILE=1 `categories` — re-run the sweep with it to get "
            "pre-diagnosed deltas)"
        )
    return {
        "kind": "bench",
        "clean": clean,
        "step_delta_ms": (
            step_fields["step_ms"]["after"] - step_fields["step_ms"]["before"]
            if "step_ms" in step_fields else 0.0
        ),
        "headline": headline,
        "top_rows": [r.to_dict() for r in rows[:top]] if rows else None,
        "report": "\n".join(lines),
        "provenance": (a.get("provenance"), b.get("provenance")),
    }


def compare_hlo(path_a: str, path_b: str, *, mesh_spec=None, top=6) -> dict:
    from distributed_training_pytorch_tpu.analysis import diff as adiff

    with open(path_a, encoding="utf-8") as f:
        text_a = f.read()
    with open(path_b, encoding="utf-8") as f:
        text_b = f.read()
    struct = adiff.diff_hlo(text_a, text_b, label_before=path_a, label_after=path_b)
    lines = [struct.describe(top=top)]
    comm = None
    if mesh_spec:
        from distributed_training_pytorch_tpu import compat
        from distributed_training_pytorch_tpu.analysis import collective_inventory
        from distributed_training_pytorch_tpu.parallel.mesh import (
            mesh_config_from_spec,
        )

        cfg = mesh_config_from_spec(mesh_spec)
        # The comm diff is pure text analysis, but axis mapping needs a
        # device mesh of the spec's extent — force virtual host devices
        # (the PR 11 helper every comm-audit consumer uses) so `--mesh
        # fsdp8` works on a 1-device laptop. Safe here: nothing before the
        # hlo path initializes the backend.
        compat.force_host_devices(
            max(cfg.data, 1) * cfg.fsdp * cfg.pipe * cfg.expert * cfg.seq
            * cfg.tensor
        )
        mesh = cfg.build()
        comm = adiff.diff_comm(
            collective_inventory(text_a, mesh, label=path_a),
            collective_inventory(text_b, mesh, label=path_b),
        )
        lines.append(comm.describe(top=top))
    return {
        "kind": "hlo",
        "clean": struct.identical and (comm is None or comm.identical),
        "step_delta_ms": 0.0,
        "structural": struct.to_dict(),
        "comm": comm.to_dict() if comm else None,
        "report": "\n".join(lines),
        "provenance": (None, None),
    }


# ---------------------------------------------------------------------------
# Self-test (the verify.sh stage)
# ---------------------------------------------------------------------------


def _synthetic_trace(tmp: str, name: str, conv_us: float) -> str:
    """A one-plane device trace: conv + fusion + a dispatch gap, conv
    duration parameterized — the injected-3x seam of the self-test."""
    from distributed_training_pytorch_tpu.profiling import xplane

    us = 1_000_000  # ps per us
    events = [
        ("%convolution.1", 0, int(conv_us * us)),
        ("%fusion.2", int(conv_us * us), 200 * us),
        # 100 us dispatch gap, then the tail op.
        ("%copy.3", int(conv_us * us) + 300 * us, 100 * us),
    ]
    path = os.path.join(tmp, f"{name}.xplane.pb")
    with open(path, "wb") as f:  # jaxlint: disable=file-write-without-rank-gate -- offline self-test fixture synthesis, single process by contract
        f.write(xplane.encode_xspace([{
            "name": "/device:TPU:0",
            "lines": [{"name": "XLA Ops", "timestamp_ns": 0, "events": events}],
        }]))
    return path


def self_test() -> int:
    import shutil
    import tempfile

    failures: list[str] = []

    # [1] Identical synthetic twins must diff clean; a 3x-slower conv must
    # be attributed to `convolution` with the delta fraction dominating.
    tmp = tempfile.mkdtemp(prefix="run_compare_selftest_")
    try:
        twin_a = _synthetic_trace(tmp, "twin_a", conv_us=500)
        twin_b = _synthetic_trace(tmp, "twin_b", conv_us=500)
        slow = _synthetic_trace(tmp, "slow", conv_us=1500)
        res = compare_profiles(twin_a, twin_b)
        print(f"run_compare self-test [twin-profiles]: "
              f"{'clean' if res['clean'] else 'NOT CLEAN'}")
        if not res["clean"]:
            failures.append(f"identical twin traces did not diff clean: {res['report']}")
        res = compare_profiles(twin_a, slow)
        top = res["top_rows"][0]
        print(f"run_compare self-test [3x-conv]: top category "
              f"{top['key']!r} ({top['delta']:+.0f} us, "
              f"{100 * top['frac_of_delta']:.0f}% of delta)")
        if res["clean"] or top["key"] != "convolution" or top["frac_of_delta"] < 0.9:
            failures.append(
                f"injected 3x conv slowdown misattributed: {res['report']}"
            )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # [2] Real-trainer legs, through the SAME injection seams the perf gate
    # and doctor self-tests use (run_doctor._self_test_trainer): identical
    # twins clean, loader sleep -> data_wait, committer delay -> the
    # checkpoint/checkpoint_async backpressure buckets.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import run_doctor

    dirs: dict[str, str] = {}
    legs = [
        ("clean_a", {}),
        ("clean_b", {}),
        ("loader-sleep", {"load_delay_s": 0.05}),
        ("commit-delay", {"commit_delay_s": 0.6}),
    ]
    try:
        from distributed_training_pytorch_tpu.telemetry import Telemetry

        for name, kw in legs:
            d = tempfile.mkdtemp(prefix=f"run_compare_{name}_")
            dirs[name] = d
            trainer = run_doctor._self_test_trainer(
                d, telemetry=Telemetry(anomaly=None, mfu=False), **kw
            )
            trainer.train()
        res = compare_runs(dirs["clean_a"], dirs["clean_b"])
        print(f"run_compare self-test [twin-runs]: "
              f"{'clean' if res['clean'] else 'NOT CLEAN'}")
        print("  " + res["report"].replace("\n", "\n  "))
        if not res["clean"]:
            failures.append(
                f"identical twin runs did not diff clean: {res['report']}"
            )
        # The provenance stamp must have ridden run_start (ISSUE 14
        # satellite) and the twins' configurations must compare equal.
        prov_a, prov_b = res["provenance"]
        if not prov_a or not prov_b:
            failures.append("run_start carried no provenance stamp")
        elif prov_lib.differing_keys(prov_a, prov_b):
            failures.append(
                "twin runs' provenance configurations differ: "
                f"{prov_lib.differing_keys(prov_a, prov_b)}"
            )
        for name, want in (
            ("loader-sleep", ("data_wait",)),
            ("commit-delay", ("checkpoint", "checkpoint_async")),
        ):
            res = compare_runs(dirs["clean_a"], dirs[name])
            top = res["top_rows"][0]
            print(f"run_compare self-test [{name}]: top bucket {top['key']!r} "
                  f"({top['delta']:+.2f} ms/step)")
            if res["clean"] or top["key"] not in want or top["delta"] <= 0:
                failures.append(
                    f"injected {name} misattributed (wanted {want}, got "
                    f"{top['key']!r}): {res['report']}"
                )
    finally:
        for d in dirs.values():
            shutil.rmtree(d, ignore_errors=True)

    if failures:
        print("RUN COMPARE SELF-TEST FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("run_compare self-test OK: twins diff clean; 3x-conv, loader-sleep "
          "and commit-delay each attributed to the correct category/bucket")
    return 0


# ---------------------------------------------------------------------------


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("before", nargs="?", help="the A side (baseline)")
    parser.add_argument("after", nargs="?", help="the B side (candidate)")
    parser.add_argument("--kind", default="auto",
                        choices=("auto", "bench", "profile", "run", "hlo"),
                        help="artifact kind (default: auto-detect per side)")
    parser.add_argument("--force", action="store_true",
                        help="compare despite differing provenance configuration")
    parser.add_argument("--mesh", default=None,
                        help="mesh spec (e.g. fsdp4x2) for --kind hlo comm diffing")
    parser.add_argument("--ridge", type=float, default=None,
                        help="roofline ridge intensity (FLOPs/byte) to classify "
                             "memory<->compute bound shifts")
    parser.add_argument("--top", type=int, default=6,
                        help="rows per attribution section (default %(default)s)")
    parser.add_argument("--noise-floor", type=float, default=DEFAULT_NOISE_FLOOR,
                        help="clean-verdict floor: max category/bucket move, as "
                             "a fraction (default %(default)s)")
    parser.add_argument("--json", action="store_true",
                        help="print the comparison as one JSON object")
    parser.add_argument("--events", default=None,
                        help="append a run_compare record to this JSONL event log")
    parser.add_argument("--self-test", action="store_true",
                        help="CI gate: twins clean + injected slowdowns "
                             "attributed (verify.sh)")
    args = parser.parse_args()
    if args.self_test:
        return self_test()
    if not args.before or not args.after:
        parser.error("BEFORE and AFTER are required (or use --self-test)")

    try:
        if args.kind == "auto":
            kind_a, kind_b = detect_kind(args.before), detect_kind(args.after)
            if kind_a != kind_b:
                print(f"run_compare: {args.before} is a {kind_a} but "
                      f"{args.after} is a {kind_b} — same kind required",
                      file=sys.stderr)
                return 3
            kind = kind_a
        else:
            kind = args.kind
        if kind == "profile":
            result = compare_profiles(args.before, args.after, ridge=args.ridge,
                                      top=args.top, noise_floor=args.noise_floor)
        elif kind == "run":
            result = compare_runs(args.before, args.after, top=args.top,
                                  noise_floor=args.noise_floor)
        elif kind == "bench":
            result = compare_bench(args.before, args.after, top=args.top,
                                   noise_floor=args.noise_floor)
        else:
            result = compare_hlo(args.before, args.after, mesh_spec=args.mesh,
                                 top=args.top)
    except (FileNotFoundError, ValueError) as e:
        print(f"run_compare: {e}", file=sys.stderr)
        return 3

    ok, keys, note = check_provenance(*result["provenance"], args.force)
    print(f"run_compare [{result['kind']}]: {args.before} -> {args.after}")
    print(f"  {note}")
    if not ok:
        return 2
    if args.json:
        out = {k: v for k, v in result.items() if k not in ("report", "provenance")}
        out["provenance_differs"] = keys
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(result["report"])

    if args.events:
        from distributed_training_pytorch_tpu.telemetry import EventLog

        EventLog(args.events, process_index=0).emit(
            "run_compare",
            kind=result["kind"],
            before=str(args.before),
            after=str(args.after),
            clean=result["clean"],
            step_delta_ms=result["step_delta_ms"],
            top_rows=result.get("top_rows"),
            provenance_differs=keys,
            forced=bool(keys and args.force),
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
