"""Minimal repro of the upstream XLA SPMD-partitioner CHECK that blocks the
GSPMD-constraint formulation of the data x expert x pipe composition
(r4 VERDICT item 7; bisected on jax 0.9 / CPU).

An MoE stage whose expert parallelism is expressed as sharding CONSTRAINTS
(parallel.moe.MoEMlp weight constraints) inside pipeline_apply's pipe-manual
shard_map region dies with a process-fatal

    F spmd_partitioner_util.cc:495 Check failed:
      partition_group_list.num_replica_groups() *
      partition_group_list.num_devices_per_group() ==
      device_groups.num_devices_per_group()
    ... ExpandDeviceGroupsWithIota / AllReduceAlongShardingDims

This is why the supported triple path is MANUAL expert parallelism instead:
pipeline_apply(extra_manual_axes=("expert",), stage_param_specs=...) with
moe.manual_expert_ffn_local stage bodies (see tests/test_pipeline.py
test_pipeline_triple_data_expert_pipe). Nested shard_map is not an option
either: Shardy rejects both re-binding a parent's manual axis and an inner
mesh that differs from the context mesh (errors quoted in
moe.manual_expert_mlp).

Run me to confirm the upstream bug still exists (the process CRASHES when it
does — a clean exit 0 means a jax upgrade fixed it and the GSPMD formulation
can be re-evaluated):  python scripts/repro_triple_check.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_training_pytorch_tpu import compat  # noqa: E402

compat.force_host_devices(8)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel.moe import MoEMlp
from distributed_training_pytorch_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)

rng = np.random.RandomState(0)
mesh = mesh_lib.create_mesh(
    {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 2, mesh_lib.EXPERT_AXIS: 2}
)
d, hid, pipe = 8, 16, 2
moe = MoEMlp(num_experts=2, hidden_dim=hid, top_k=2, capacity_factor=4.0, num_groups=2)
x0 = jnp.asarray(rng.randn(4, 8, d), jnp.float32)
micro = jnp.asarray(rng.randn(4, 4, 8, d), jnp.float32)
stages = [
    {
        "w1": jnp.asarray(rng.randn(d, hid) * 0.2, jnp.float32),
        "w2": jnp.asarray(rng.randn(hid, d) * 0.2, jnp.float32),
        "moe": moe.init(jax.random.key(30 + i), x0)["params"],
    }
    for i in range(pipe)
]


def stage(p, x):
    x = x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]
    return x + moe.apply({"params": p["moe"]}, x)  # GSPMD expert constraints


stacked = stack_stage_params(stages)


def loss(stacked):
    fed = jax.lax.with_sharding_constraint(
        micro, PartitionSpec(None, mesh_lib.DATA_AXIS)
    )
    return jnp.sum(pipeline_apply(stacked, fed, stage, mesh) ** 2)


print("compiling the GSPMD-constraint triple (crashes while the bug exists)...")
with compat.set_mesh(mesh):
    l, _ = jax.jit(jax.value_and_grad(loss))(stacked)
print(f"NO CRASH (loss {float(l):.3f}) — the upstream CHECK is fixed; the "
      "GSPMD formulation of data x expert x pipe can be re-evaluated.")
