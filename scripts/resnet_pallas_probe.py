"""Measure the fused 1x1-conv+BN-apply+ReLU Pallas kernel against XLA's own
fusion on ResNet-50 stage-1 shapes (r4 VERDICT item 2).

The r4 profile left one assertion untested: "the ~21 ms residual is XLA
conv-kernel inefficiency ... not reachable from user-level JAX without
replacing XLA's conv kernels outright". Stage-1's 1x1 convs are the
tractable subset — pure GEMMs at ~28 FLOP/byte (bandwidth-bound on a
240 FLOP/byte v5e), so a hand-tiled Pallas GEMM+epilogue either moves more
bytes/s than XLA's conv fusion or it measurably cannot. This script produces
that measurement (BASELINE.md "ResNet-50" records the verdict).

Method: each candidate computes relu((x . w) * a + b) on NHWC stage-1
shapes; timing is a lax.scan chain of STEPS calls (one dispatch per window
— the relay's ~hundreds-of-ms per-call latency never lands inside the
window), best of WINDOWS windows, with the weight perturbed per trip by the
carried output statistic so no iteration is loop-invariant. The bandwidth
floor (read x + write y at 819 GB/s) anchors every number.

Usage: python scripts/resnet_pallas_probe.py   (env: STEPS, WINDOWS, BATCH)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act
from distributed_training_pytorch_tpu.train.autotune import time_chained

HBM_BYTES_PER_S = 819e9  # v5e
STEPS = int(os.environ.get("STEPS", "20"))
WINDOWS = int(os.environ.get("WINDOWS", "4"))
BATCH = int(os.environ.get("BATCH", "256"))


def xla_conv(x, w, a, b, relu=True):
    """The model's formulation: 1x1 conv_general_dilated + affine + relu —
    what XLA fuses in the real step (models/resnet.py BottleneckBlock)."""
    z = jax.lax.conv_general_dilated(
        x, w.reshape(1, 1, *w.shape), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    y = z * a + b
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def xla_dot(x, w, a, b, relu=True):
    """Same math as a flattened dot — rules out conv-vs-dot lowering as the
    variable."""
    lead = x.shape[:-1]
    z = jnp.dot(x.reshape(-1, x.shape[-1]), w, preferred_element_type=jnp.float32)
    y = z * a + b
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype).reshape(*lead, w.shape[1])


def pallas_fused(block_rows):
    def f(x, w, a, b, relu=True):
        return conv1x1_bn_act(x, w, a, b, relu=relu, block_rows=block_rows)

    return f


# Timing: train.autotune.time_chained — the ONE two-length-differencing
# scan-chain timer, now shared with the autotuner's candidate measurement
# (ISSUE 17 moved it there; tests/test_autotune.py AST-enforces that this
# probe keeps no private copy). Semantics unchanged: per-call seconds as
# (t_long - t_short) / extra_trips with the weight (arg 1) perturbed per
# trip by the carried output statistic.


def main():
    results = []
    shapes = [(64, 256, "stage1 expand 56x56x64->256"),
              (256, 64, "stage1 reduce 56x56x256->64")]
    only = os.environ.get("SHAPE")  # "expand" | "reduce" — rerun one shape
    if only:
        shapes = [sh for sh in shapes if only in sh[2]]
    for cin, cout, tag in shapes:
        # Generate ON DEVICE: shipping a 100-400 MB host array through the
        # relay's in-order H2D link costs minutes (memory: 2-35 MB/s).
        @jax.jit
        def gen(key):
            kx, kw, ka, kb = jax.random.split(key, 4)
            return (
                jax.random.normal(kx, (BATCH, 56, 56, cin), jnp.bfloat16),
                jax.random.normal(kw, (cin, cout), jnp.bfloat16) * 0.05,
                jax.random.uniform(ka, (cout,), jnp.float32) + 0.5,
                jax.random.normal(kb, (cout,), jnp.float32),
            )

        x, w, a, b = gen(jax.random.key(0))
        n = BATCH * 56 * 56
        bytes_moved = n * (cin + cout) * 2  # read x + write y, bf16
        floor_ms = bytes_moved / HBM_BYTES_PER_S * 1e3

        row = {"shape": tag, "floor_ms": round(floor_ms, 3)}
        cands = {"xla_conv": xla_conv, "xla_dot": xla_dot}
        for br in (1024, 2048):
            cands[f"pallas_b{br}"] = pallas_fused(br)
        err_of = jax.jit(
            lambda got, x, w, a, b: jnp.max(
                jnp.abs(got.astype(jnp.float32) - xla_conv(x, w, a, b).astype(jnp.float32))
            )
        )
        for name, f in cands.items():
            # error computed on device — a full-tensor D2H pull through the
            # relay costs ~1 min per candidate
            err = float(err_of(jax.jit(f)(x, w, a, b), x, w, a, b))
            dt = time_chained(f, x, w, a, b, steps=STEPS, windows=WINDOWS)
            row[name] = {
                "ms": round(dt * 1e3, 3),
                "pct_of_bw_floor": round(floor_ms / (dt * 1e3) * 100, 1),
                "max_abs_err_vs_conv": err,
            }
            print(f"{tag:36s} {name:12s} {dt*1e3:7.3f} ms "
                  f"({floor_ms/(dt*1e3)*100:5.1f}% of BW floor, err {err:.3g})",
                  flush=True)
        results.append(row)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
