#!/usr/bin/env python
"""Perf-regression gate — step-time CI contract (ISSUE 6).

Four flat bench rounds (BENCH_r02 -> r05) happened silently because nothing
*failed* when step time slipped. This gate measures a step time and compares
it against the committed ``PERF_BASELINE.json`` (``profiling.gate``); a
regression past the relative tolerance is a nonzero exit, wired as a
``scripts/verify.sh`` stage next to the retrace/precision/telemetry gates.

Three modes:

* ``--data-wait`` (the verify stage's input-pipeline gate; ISSUE 13 /
  ROADMAP item 5) — trains a few epochs of the real sklearn-digits Trainer
  with telemetry on and gates the **steady-state ``data_wait`` goodput
  fraction** (``telemetry.doctor.steady_fractions`` — the same figure the
  run doctor's ``data_bound`` verdict reads, so the gate and the doctor
  cannot disagree) against a committed CEILING. ``--update`` records
  ``max(0.10, 2 x measured)`` as the ceiling — headroom over today's
  number, still a hard fail for a pipeline that becomes the bottleneck.
  Self-test seam: ``--inject-data-wait S`` sleeps S seconds in every
  batch's production path (the ``ShardedLoader.load_delay_s`` seam) —
  verify.sh asserts the gate FAILS with an injected starved pipeline.
* ``--quick`` (the verify stage; CPU-viable, ~seconds) — times a small
  fixed conv+dense workload through the REAL ``TrainEngine`` chained-step
  path, plus a fixed matmul *calibration* kernel on the same machine, and
  gates the **ratio** ``step_per_calib``. Absolute CPU milliseconds vary
  across dev machines; the ratio of two programs on one machine is stable,
  so one committed baseline serves every contributor (tolerance 50%:
  generous against scheduler noise, still a hard fail for the regressions
  that matter — an accidental per-window retrace is 10x, a lost chained
  dispatch path is 2-3x).
* default (no ``--quick``; the TPU bench host) — times the headline
  ``BENCH_MODEL`` (vgg16) chained executable exactly as ``bench.py`` does
  and gates absolute ``step_ms`` (tolerance 8%: beyond shared-chip noise,
  inside any real regression).

The update ritual (documented in docs/profiling.md): when a PR
*legitimately* changes step time (new fusion, different default), re-record
with ``--update`` in the same PR and say why in the PR body — the diff to
``PERF_BASELINE.json`` is the reviewable perf claim.

Self-test seam: ``--inject-slowdown F`` multiplies the measured step time by
``F`` after measurement (the measurement itself is untouched) — verify.sh
asserts the gate FAILS with an injected 3x regression, so the gate's teeth
are themselves tested on every run.

FAIL pre-diagnosis (ISSUE 14): quick mode traces one extra window after the
timed pairs and attaches the StepProfile category fractions to the
measurement; a ``--update``-recorded baseline carries them too, and a FAIL
prints the per-category attribution of its own measured-vs-baseline step_ms
delta — the SAME ``profiling.diff`` implementation ``scripts/run_compare.py``
uses (test-enforced: this script defines no attribution of its own).

Exit codes: 0 pass, 1 regression, 2 refused (``--update`` combined with
``--inject-slowdown`` — a poisoned baseline would mask real regressions),
3 no baseline entry for this key (record one with ``--update``), 4 baseline
present but unusable (malformed file or an entry that cannot gate this
measurement's metric — re-record with ``--update``).
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.profiling import diff as diff_lib
from distributed_training_pytorch_tpu.profiling import gate as gate_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

QUICK_STEPS = 8
QUICK_TOLERANCE = 0.5
FULL_TOLERANCE = 0.08
# data_wait mode: the committed entry is a ceiling with built-in headroom
# (see measure_data_wait), so the gate tolerance can stay tight-ish.
DATA_WAIT_TOLERANCE = 0.25
DATA_WAIT_FLOOR_CEILING = 0.10


def _paired_ratio(run_step, run_calib, pairs: int = 5) -> tuple[float, float, float]:
    """Median of ADJACENT-pair ratios: each (workload, calibration) pair runs
    back to back, so machine load cancels within the pair — far more stable
    than best-of(workload)/best-of(calib), whose two minima can come from
    different interference regimes. Returns the MEDIAN pair's
    (ratio, step_s, calib_s) — all three figures come from the same pair, so
    the step_ms/calib_ms a baseline records reproduce its gated ratio exactly
    (a maintainer re-deriving the ratio from the committed numbers must not
    land on a different value)."""
    samples = []
    for _ in range(pairs):
        t0 = time.perf_counter()
        run_step()
        step_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        run_calib()
        calib_s = time.perf_counter() - t0
        samples.append((step_s / calib_s, step_s, calib_s))
    samples.sort(key=lambda s: s[0])
    return samples[len(samples) // 2]


def measure_quick() -> dict:
    """The CPU-viable measurement: a fixed conv+dense train step through the
    real chained-engine path, normalized by a fixed matmul calibration
    kernel. Warmup (compile) excluded from both."""
    from flax import linen as nn

    class GateNet(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = nn.relu(nn.Conv(8, (3, 3))(x))
            x = nn.relu(nn.Conv(16, (3, 3), strides=(2, 2))(x))
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(10)(x)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    model = GateNet()
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh_lib.create_mesh(),
    )
    rng = np.random.RandomState(0)
    batch = engine.shard_batch(
        {
            "image": rng.randn(64, 16, 16, 3).astype(np.float32),
            "label": rng.randint(0, 10, size=(64,)).astype(np.int32),
        }
    )
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
    )
    compiled = engine.compile_chained_train_steps(state, batch, QUICK_STEPS)

    def run_window():
        nonlocal state
        state, metrics = compiled(state, batch)
        _ = float(metrics["loss"])

    # Calibration kernel: fixed matmul chain, jitted once — pure machine
    # speed, no framework surface, so the step/calib ratio cancels the
    # machine and isolates the framework + XLA program.
    w = jnp.asarray(rng.randn(384, 384).astype(np.float32) * 0.05)

    @jax.jit
    def calib(x):
        for _ in range(8):
            x = jnp.tanh(x @ w)
        return x

    x0 = jnp.ones((384, 384), jnp.float32)
    run_window()  # warmup: first dispatch pays relay/dispatch setup
    jax.block_until_ready(calib(x0))  # compile
    ratio, step_s, calib_s = _paired_ratio(
        run_window, lambda: jax.block_until_ready(calib(x0))
    )

    measurement = {
        "workload": "gatenet-conv16x16-b64-chain8",
        "platform": jax.devices()[0].platform,
        "steps": QUICK_STEPS,
        "step_ms": round(step_s / QUICK_STEPS * 1e3, 4),
        "calib_ms": round(calib_s * 1e3, 4),
        "step_per_calib": round(ratio / QUICK_STEPS, 4),
    }
    # Category capture (ISSUE 14): trace ONE extra window of the exact
    # workload AFTER the timed pairs (the trace gates nothing it measures)
    # and attach the StepProfile category fractions. A baseline recorded
    # with --update then carries them, and a later FAIL arrives
    # pre-diagnosed — the attribution of its own measured-vs-baseline
    # step_ms delta, through the SAME profiling.diff implementation
    # run_compare uses (test-enforced). Degrades to an unattributed
    # measurement on any capture/analysis failure.
    import shutil
    import tempfile

    from distributed_training_pytorch_tpu import profiling as profiling_lib

    prof_dir = tempfile.mkdtemp(prefix="perf_gate_prof_")
    try:
        with profiling_lib.trace(prof_dir):
            run_window()
        prof = profiling_lib.analyze_trace(prof_dir, steps=QUICK_STEPS)
        measurement["categories"] = {
            k: round(v, 4) for k, v in prof.categories.items() if v
        }
    except (ValueError, FileNotFoundError, OSError, RuntimeError) as e:
        print(f"perf_gate: category capture failed ({e}) — a FAIL against "
              "this measurement will be unattributed", file=sys.stderr)
    finally:
        shutil.rmtree(prof_dir, ignore_errors=True)
    return measurement


def measure_data_wait(inject_delay_s: float | None = None) -> dict:
    """The input-pipeline measurement: a short real-Trainer digits run with
    telemetry on; the gated figure is the steady-state ``data_wait``
    goodput fraction (``telemetry.doctor.steady_fractions`` — compile /
    restart / overlapped-commit wall excluded from the denominator, so a
    short run's XLA warmup cannot dilute a starved pipeline). The workload
    is ``scripts/run_doctor.py``'s self-test harness — the gate's ceiling
    and the doctor's ``data_bound`` verdict measure the same program
    through the same fraction definition, so they cannot drift. Since
    ISSUE 19 the harness runs ``streaming=True``: the gated pipeline is
    the ``StreamingLoader`` record path (the production input path), not
    the in-memory array loader. The loader runs with ``num_workers=0``
    (the serial decode path) so production time is on the consuming
    thread — the regime where pipeline cost is visible as ``data_wait``
    rather than hidden by the decode pool's prefetch overlap (the gate
    measures the pipeline, not the pool's ability to paper over it; the
    pool's overlap is what the doctor-healthy check in
    ``scripts/data_soak.py`` asserts)."""
    import shutil
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import run_doctor

    from distributed_training_pytorch_tpu.telemetry import Telemetry
    from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib

    tmp = tempfile.mkdtemp(prefix="perf_gate_data_wait_")
    try:
        trainer = run_doctor._self_test_trainer(
            tmp,
            load_delay_s=float(inject_delay_s or 0.0),
            streaming=True,
            telemetry=Telemetry(anomaly=None, mfu=False),
            save_period=None,  # the gate measures the pipeline, not saves
        )
        trainer.train()
        seconds = trainer.goodput.to_state()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    steady = doctor_lib.steady_fractions(seconds)
    return {
        "workload": "digits-conv-streaming-b128-chain2",
        "platform": jax.devices()[0].platform,
        # max vs epsilon: gate.check requires measured > 0, and a pipeline
        # this healthy is a pass at any positive ceiling.
        "data_wait_frac": round(max(steady["data_wait"], 1e-6), 4),
        "data_wait_s": round(seconds["data_wait"], 4),
        "injected_delay_s": inject_delay_s or 0,
    }


def measure_full() -> dict:
    """The bench-host measurement: the headline BENCH_MODEL chained
    executable, timed with bench.py's own window protocol (same env knobs),
    gated on absolute step_ms."""
    import bench

    from distributed_training_pytorch_tpu.utils.tpu import enable_fast_rng

    enable_fast_rng()
    setup = bench.build_bench_setup()
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    windows = int(os.environ.get("BENCH_WINDOWS", "6"))
    compiled = setup["engine"].compile_chained_train_steps(
        setup["state"], setup["gbatch"], steps,
        compiler_options=setup["compiler_options"],
    )
    state, dt = bench._time_windows(
        lambda st: compiled(st, setup["gbatch"]), setup["state"], steps, windows,
        os.environ.get("BENCH_REDUCE", "min"),
    )
    return {
        "workload": setup["model_name"],
        "platform": jax.devices()[0].platform,
        "batch": setup["batch"],
        "steps": steps,
        "step_ms": round(dt * 1e3, 4),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--quick", action="store_true",
                        help="CPU-viable calibrated-ratio mode (the verify stage)")
    parser.add_argument("--data-wait", action="store_true",
                        help="gate the steady-state data_wait goodput fraction "
                             "of a real digits Trainer run against the "
                             "committed ceiling (ROADMAP item 5)")
    parser.add_argument("--inject-data-wait", type=float, default=None, metavar="S",
                        help="self-test seam: sleep S seconds per produced "
                             "batch (loader load_delay_s) before measuring")
    parser.add_argument("--baseline", default=gate_lib.DEFAULT_BASELINE_PATH,
                        help="baseline JSON path (default: repo PERF_BASELINE.json)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="relative tolerance override (e.g. 0.5 = +50%%)")
    parser.add_argument("--update", action="store_true",
                        help="record this measurement as the new baseline entry")
    parser.add_argument("--inject-slowdown", type=float, default=None, metavar="F",
                        help="self-test seam: multiply measured step time by F")
    parser.add_argument("--events", default=None,
                        help="append a perf_gate record to this JSONL event log")
    args = parser.parse_args()
    if args.update and (args.inject_slowdown or args.inject_data_wait):
        print("perf_gate: refusing --update with an injection seam "
              "(a poisoned baseline would mask real regressions)")
        return 2
    if args.tolerance is not None and args.tolerance <= 0:
        parser.error("--tolerance must be > 0 (a zero-tolerance gate would "
                     "fail on measurement noise alone)")
    if args.data_wait and args.quick:
        parser.error("--data-wait and --quick are distinct measurements — "
                     "run them as separate invocations (verify.sh does)")
    if args.inject_data_wait and not args.data_wait:
        parser.error("--inject-data-wait only applies to --data-wait mode")
    if args.data_wait and args.inject_slowdown:
        parser.error("--inject-slowdown multiplies step time; the data-wait "
                     "measurement has none — use --inject-data-wait")

    if args.data_wait:
        if args.inject_data_wait:
            print(f"perf_gate: SELF-TEST — injecting a {args.inject_data_wait}s "
                  "per-batch loader sleep (the gate below must fail)")
        measurement = measure_data_wait(args.inject_data_wait)
        key = "data-wait-" + measurement["platform"]
    else:
        measurement = measure_quick() if args.quick else measure_full()
        key = ("quick-" if args.quick else f"{measurement['workload']}-") + measurement["platform"]
    if args.inject_slowdown:
        factor = float(args.inject_slowdown)
        measurement["step_ms"] = round(measurement["step_ms"] * factor, 4)
        if "step_per_calib" in measurement:
            measurement["step_per_calib"] = round(
                measurement["step_per_calib"] * factor, 4
            )
        measurement["injected_slowdown"] = factor
        print(f"perf_gate: SELF-TEST — injected x{factor} slowdown into the "
              "measurement (the gate below must fail)")
    print(f"perf_gate: {key}: " + json.dumps(measurement))

    if args.data_wait:
        default_tol = DATA_WAIT_TOLERANCE
    else:
        default_tol = QUICK_TOLERANCE if args.quick else FULL_TOLERANCE
    if args.update and args.data_wait:
        # The entry is a CEILING, not the measurement: record headroom over
        # today's number so scheduler noise on a healthy pipeline never
        # fails the gate, while a pipeline that becomes the bottleneck
        # (fraction 2x+ over healthy) still does. The raw measurement is
        # kept alongside as the reviewable claim.
        measurement = dict(
            measurement,
            measured_data_wait_frac=measurement["data_wait_frac"],
            data_wait_frac=round(
                max(DATA_WAIT_FLOOR_CEILING, 2 * measurement["data_wait_frac"]), 4
            ),
        )
        print(f"perf_gate: recording data_wait ceiling "
              f"{measurement['data_wait_frac']} (measured "
              f"{measurement['measured_data_wait_frac']})")
    if args.update:
        if args.tolerance is not None:
            tol = args.tolerance
        else:
            # preserve a curated per-entry tolerance across re-records; the
            # mode default applies only to entries that never had one
            try:
                existing = gate_lib.load_baseline(args.baseline).get("tolerance", {})
            except (FileNotFoundError, ValueError):
                existing = {}
            tol = existing.get(key, default_tol)
        gate_lib.update_baseline(args.baseline, key, measurement, tolerance=tol)
        print(f"perf_gate: baseline entry {key!r} recorded in {args.baseline} — "
              "commit the diff with a sentence on why perf legitimately changed")
        return 0

    try:
        baseline = gate_lib.load_baseline(args.baseline)
        result = gate_lib.evaluate(
            baseline, key, measurement,
            tolerance=args.tolerance, default_tolerance=default_tol,
        )
    except (FileNotFoundError, KeyError) as e:
        print(f"perf_gate: NO BASELINE — {e}")
        return 3
    except ValueError as e:
        print(f"perf_gate: BAD BASELINE — {e}")
        return 4
    print("perf_gate: " + result.describe())
    attribution = None
    if not result.passed:
        # FAIL upgrade (ISSUE 14): pre-diagnose the regression — attribute
        # the measured-vs-baseline step_ms delta per category through the
        # ONE profiling.diff implementation run_compare uses.
        attribution = diff_lib.attribute_entry_delta(
            baseline["entries"].get(key, {}), measurement
        )
        if attribution:
            print("perf_gate: FAIL attribution (step_ms delta by category): "
                  + diff_lib.describe_rows(attribution))
        elif args.quick:
            print("perf_gate: FAIL unattributed — the baseline entry or this "
                  "measurement lacks `categories`; re-record with --update so "
                  "future failures arrive pre-diagnosed (docs/profiling.md)")
        elif not args.data_wait:
            # Full mode records no category capture (only measure_quick
            # traces a window), so the --update ritual cannot attribute it —
            # point at the bench-side instrument instead.
            print("perf_gate: FAIL unattributed — full mode captures no "
                  "categories; run `BENCH_PROFILE=1 python bench.py` "
                  "before/after and `scripts/run_compare.py` for the "
                  "attribution (docs/profiling.md)")
    if args.events:
        from distributed_training_pytorch_tpu.telemetry import EventLog

        EventLog(args.events, process_index=0).emit(
            "perf_gate",
            key=key,
            metric=result.metric,
            measured=result.measured,
            baseline=result.baseline,
            ratio=result.ratio,
            tolerance=result.tolerance,
            passed=result.passed,
            attribution=(
                [r.to_dict() for r in attribution] if attribution else None
            ),
        )
    return 0 if result.passed else 1


if __name__ == "__main__":
    sys.exit(main())
