#!/usr/bin/env python
"""Static audit gate — ruff/generic + jaxlint + HLO audit + comm audit.

Six PRs of reliability work fixed the same bug classes after the fact:
cross-thread mutation without a lock (PR 5's EventLog t_mono fix), host
syncs sneaking into the hot path, rank-0 file-ownership violations,
undonated device buffers (ROADMAP item 3). This gate makes those invariants
machine-checked (ISSUE 7; rule catalog and history in
docs/static_analysis.md). Four passes, strictest-first cheap-first:

1. **generic** (``analysis.generic``): ruff with the repo's
   ``[tool.ruff]`` config when installed; a stdlib fallback (syntax +
   unused-import) in hermetic environments. jaxlint deliberately carries
   NO generic rules — this layer owns them.
2. **jaxlint** (``analysis.lint``): the seven project rules over the
   package source. Findings are fatal unless waived inline
   (``# jaxlint: disable=<rule> -- <reason>``); every waiver in effect is
   printed so the exception list is reviewed on every run.
3. **HLO audit** (``analysis.hlo_audit``): lowers the REAL single-step and
   chained train programs on abstract avals (CPU-viable, nothing executes)
   and verifies 100% of param/optimizer-state input bytes are donated, a
   bf16 program leaks no fp32 dot/conv, and the chained program contains
   no host callbacks.
4. **comm audit** (``analysis.comm_audit``, ISSUE 11): inventories every
   collective of the SPMD-partitioned single-step AND chained programs on
   the dp8/fsdp8/tp2x4/dp2fsdp2tp2 meshes (byte volume + mesh-axis
   attribution), checks them against the analytic expected-comm model
   (accidental full-param gathers on the tensor axis; totals past the
   model's bound), and gates per-mesh totals against the committed
   ``COMM_BASELINE.json`` — the perf gate's one-rule/--update/stale-nudge
   ritual applied to communication bytes.

Self-test seams (the perf gate's ``--inject-slowdown`` analog):
``--inject-violation lint`` lints a synthetic module with one violation of
every rule merged into the real run; ``--inject-violation hlo`` audits the
probes lowered WITHOUT donation; ``--inject-violation comm`` audits a
deliberately mis-ruled TP spec whose optimizer update must all-gather the
full parameter every step. Each must make this gate FAIL — verify.sh
asserts all three, so the gate's teeth are themselves tested on every run.

``--update-comm-baseline`` is the comm twin of ``perf_gate.py --update``:
re-measure every audited mesh and rewrite ``COMM_BASELINE.json`` (refused
while injecting — a baseline must never memorialize a mis-ruled program).

``--events PATH`` appends a ``static_audit`` record to a telemetry JSONL
log (rule counts, waiver counts, undonated bytes, per-mesh comm bytes) so
audit results are greppable next to ``perf_gate`` records.

Exit codes: 0 clean, 1 generic findings, 2 jaxlint findings, 3 HLO audit
violations, 4 comm audit violations (first failing pass wins).
"""

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# 8 virtual host devices (the tests/conftest.py convention, via the shared
# compat helper) so the HLO audit's SPMD pass and the comm audit — donation
# + precision + collective inventories over data/fsdp/tensor meshes with
# genuinely sharded state — always run in the verify gate, not only under
# pytest. Must happen before jax first initializes its CPU client; the
# helper appends (never overwrites) caller-supplied XLA_FLAGS.
from distributed_training_pytorch_tpu import compat  # noqa: E402

compat.force_host_devices(8)

# Paths are anchored to the repo root (NOT the cwd): run from anywhere, the
# gate scans the same tree — a cwd-relative scan that finds zero files would
# print PASS having checked nothing.
PACKAGE = os.path.join(REPO_ROOT, "distributed_training_pytorch_tpu")
# The generic layer covers everything Python; jaxlint covers the package
# (scripts/examples are single-process host-side drivers — the multi-host
# and compiled-region rules do not apply to them by construction).
GENERIC_PATHS = [PACKAGE] + [
    os.path.join(REPO_ROOT, p)
    for p in ("scripts", "tests", "examples", "bench.py", "__graft_entry__.py")
]
LINT_PATHS = [PACKAGE]

# One violation of every jaxlint rule, in ~25 lines — the lint self-test
# fixture. If a rule rewrite stops catching its class of bug, the injection
# run passes and verify.sh fails the build.
INJECTED_LINT_SNIPPET = '''\
import threading
import time
import numpy as np
import jax


def train_step(state, batch):
    loss = state["params"].sum() + batch.sum()
    host = float(loss)                      # host-sync-in-step
    t = time.time()                         # wall-clock-in-step
    _ = np.asarray(loss)                    # host-sync-in-step
    return state, {"loss": host, "t": t}


stepped = jax.jit(train_step)               # missing-donate-on-jit


def leaf_pairs(a, b):
    return list(zip(jax.tree.leaves(a), jax.tree.leaves(b)))  # zip-no-strict


def write_log(line):
    with open("audit.log", "a") as f:       # file-write-without-rank-gate
        f.write(line)


class Worker:
    def __init__(self):
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        try:
            self.count += 1                 # cross-thread-mutation-without-lock
        except:                             # bare-except
            pass
'''


def run_generic_pass() -> tuple[int, dict]:
    # Submodule import: the generic/lint passes never need hlo_audit's
    # XLA machinery loaded (the package __init__ would pull it in).
    from distributed_training_pytorch_tpu.analysis.generic import run_generic

    paths = [p for p in GENERIC_PATHS if os.path.exists(p)]
    if not paths:
        print(f"static_audit: [1/4] generic: NO scan paths exist under "
              f"{REPO_ROOT} — refusing a vacuous pass")
        return 1, {"generic_tool": "none", "generic_findings": 1}
    report = run_generic(paths)
    print(f"static_audit: [1/4] generic ({report.tool}): "
          f"{len(report.findings)} finding(s)")
    for finding in report.findings:
        print("  " + finding.describe())
    return len(report.findings), {"generic_tool": report.tool,
                                  "generic_findings": len(report.findings)}


def run_lint_pass(inject: bool, lint_paths_override=None) -> tuple[int, dict]:
    from distributed_training_pytorch_tpu.analysis.lint import (
        lint_paths,
        lint_source,
    )

    paths = [p for p in (lint_paths_override or LINT_PATHS) if os.path.exists(p)]
    if not paths:
        print("static_audit: [2/4] jaxlint: NO scan paths exist — refusing "
              "a vacuous pass")
        return 1, {"lint_findings": 1, "lint_waived": 0, "lint_rule_counts": {}}
    result = lint_paths(paths)
    if inject:
        result = result.merge(
            lint_source(INJECTED_LINT_SNIPPET, "<injected-violation>")
        )
        print("static_audit: SELF-TEST — injected a synthetic module "
              "violating every jaxlint rule (this gate must fail)")
    unwaived = result.unwaived
    counts = result.counts()
    print(f"static_audit: [2/4] jaxlint: {len(unwaived)} unwaived finding(s), "
          f"{len(result.waived)} waived, rule counts: "
          + (str(counts) if counts else "{}"))
    for finding in unwaived:
        print("  " + finding.describe())
    for finding in result.waived:
        print("  " + finding.describe())
    for waiver in result.unused_waivers:
        print(f"  NOTE unused waiver at {waiver.path}:{waiver.line} "
              f"(rules {','.join(waiver.rules)}) — the finding it covered "
              "is gone; delete the comment")
    fields = {
        "lint_findings": len(unwaived),
        "lint_waived": len(result.waived),
        "lint_rule_counts": counts,
        "lint_unused_waivers": len(result.unused_waivers),
    }
    return len(unwaived), fields


def run_hlo_pass(inject: bool, chain_steps: int) -> tuple[int, dict]:
    from distributed_training_pytorch_tpu.analysis.hlo_audit import run_hlo_audit

    if inject:
        print("static_audit: SELF-TEST — auditing probes lowered WITHOUT "
              "donation (this gate must fail)")
    report = run_hlo_audit(chain_steps=chain_steps, inject_violation=inject)
    print(f"static_audit: [3/4] HLO audit (chain_steps={chain_steps}):")
    print(report.describe())
    return (0 if report.ok else 1), report.to_fields()


def run_comm_pass(inject: bool, chain_steps: int) -> tuple[int, dict]:
    from distributed_training_pytorch_tpu.analysis.comm_audit import (
        COMM_BASELINE_PATH,
        load_comm_baseline,
        run_comm_audit,
    )

    if inject:
        print("static_audit: SELF-TEST — auditing a deliberately MIS-RULED "
              "TP spec (full-param all-gather; this gate must fail)")
    try:
        baseline = load_comm_baseline()
    except FileNotFoundError:
        baseline = None
        print(f"static_audit: [4/4] comm audit: NO {COMM_BASELINE_PATH} — "
              "record one with --update-comm-baseline")
    except ValueError as e:  # torn/malformed file: the --update ritual is
        baseline = None      # the documented recovery (perf-gate contract)
        print(f"static_audit: [4/4] comm audit: MALFORMED baseline ({e}) — "
              "re-record with --update-comm-baseline")
    report = run_comm_audit(
        chain_steps=chain_steps, inject_violation=inject, baseline=baseline
    )
    print(f"static_audit: [4/4] comm audit (chain_steps={chain_steps}):")
    print(report.describe())
    bad = 0 if report.ok else 1
    if baseline is None and report.skipped is None:
        bad = 1  # measured fine, but an ungated audit is not a gate
    return bad, report.to_fields()


def update_comm_baseline(chain_steps: int) -> int:
    from distributed_training_pytorch_tpu.analysis.comm_audit import (
        COMM_BASELINE_PATH,
        record_comm_baseline,
    )

    try:
        report = record_comm_baseline(chain_steps=chain_steps)
    except ValueError as e:
        print(f"static_audit: --update-comm-baseline REFUSED — {e}")
        return 4
    print(report.describe())
    print(f"static_audit: recorded {len(report.specs)} comm baseline "
          f"entries -> {COMM_BASELINE_PATH}")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--inject-violation", choices=("lint", "hlo", "comm"), default=None,
        help="self-test seam: make the named pass audit a known-bad input; "
             "the gate must exit non-zero (verify.sh asserts it)")
    parser.add_argument(
        "--chain-steps", type=int, default=4,
        help="window length of the chained programs the HLO/comm audits lower")
    parser.add_argument(
        "--skip-hlo", action="store_true",
        help="skip the HLO (donation/precision/callback) pass; combine with "
             "--skip-comm for the source-only fast path editor/pre-commit "
             "hooks want — verify.sh always runs the full gate, and its "
             "injection self-tests use the skips to pay only for the pass "
             "they target")
    parser.add_argument(
        "--skip-comm", action="store_true",
        help="skip the comm audit (verify.sh uses this on the hlo-injection "
             "self-test run, whose target is the donation pass)")
    parser.add_argument(
        "--update-comm-baseline", action="store_true",
        help="re-measure every audited mesh and rewrite COMM_BASELINE.json "
             "(the perf gate's --update ritual for comm bytes); runs ONLY "
             "the comm measurement, refuses under --inject-violation")
    parser.add_argument(
        "--lint-path", action="append", default=None, metavar="PATH",
        help="override the jaxlint scan roots (repeatable) — the seam the "
             "CLI tests use to lint a known tree; default is the package")
    parser.add_argument(
        "--events", default=None,
        help="append a static_audit record to this JSONL event log")
    args = parser.parse_args()
    if args.skip_hlo and args.inject_violation == "hlo":
        # The perf_gate flag-conflict discipline: refuse BEFORE doing any
        # work — skipping the very pass the injection targets would print
        # PASS having verified nothing.
        parser.error("--inject-violation hlo requires the HLO pass; "
                     "drop --skip-hlo")
    if args.skip_comm and args.inject_violation == "comm":
        parser.error("--inject-violation comm requires the comm pass; "
                     "drop --skip-comm")
    if args.update_comm_baseline and args.inject_violation:
        parser.error("--update-comm-baseline must not record an injected "
                     "violation; drop --inject-violation")
    if args.update_comm_baseline:
        return update_comm_baseline(args.chain_steps)

    fields: dict = {"injected": args.inject_violation}
    generic_count, f = run_generic_pass()
    fields.update(f)
    lint_count, f = run_lint_pass(
        inject=args.inject_violation == "lint",
        lint_paths_override=args.lint_path,
    )
    fields.update(f)
    hlo_bad = comm_bad = 0
    if not args.skip_hlo:
        try:
            hlo_bad, f = run_hlo_pass(
                inject=args.inject_violation == "hlo",
                chain_steps=args.chain_steps,
            )
            fields.update(f)
        except Exception as e:  # audit infrastructure failure, not a finding
            print(f"static_audit: [3/4] HLO audit ERROR — {type(e).__name__}: "
                  f"{e}\n  (audit infrastructure failure: the lowering or the "
                  "leaf->parameter mapping broke, not a lintable finding)")
            hlo_bad = 1
            fields["hlo_error"] = f"{type(e).__name__}: {e}"
    if not args.skip_comm:
        try:
            comm_bad, f = run_comm_pass(
                inject=args.inject_violation == "comm",
                chain_steps=args.chain_steps,
            )
            fields.update(f)
        except Exception as e:  # same contract as the HLO pass
            print(f"static_audit: [4/4] comm audit ERROR — "
                  f"{type(e).__name__}: {e}\n  (audit infrastructure "
                  "failure: the inventory parse or the model broke, not "
                  "a comm finding)")
            comm_bad = 1
            fields["comm_error"] = f"{type(e).__name__}: {e}"

    if generic_count:
        rc = 1
    elif lint_count:
        rc = 2
    elif hlo_bad:
        rc = 3
    elif comm_bad:
        rc = 4
    else:
        rc = 0
    fields["passed"] = rc == 0
    fields["injected"] = args.inject_violation  # which pass, not a bool
    verdict = "PASS" if rc == 0 else f"FAIL (exit {rc})"
    print(f"static_audit: {verdict}")

    if args.events:
        from distributed_training_pytorch_tpu.telemetry import EventLog

        EventLog(args.events, process_index=0).emit("static_audit", **fields)
    return rc


if __name__ == "__main__":
    sys.exit(main())
