#!/usr/bin/env python
"""Sharded-training smoke gate (ISSUE 10; docs/parallelism.md).

Runs the REAL Trainer/TrainEngine hot path on 8 forced-host CPU devices
(the tests/conftest.py convention) and asserts the three contracts that
make ``Trainer(mesh=MeshConfig(fsdp=..., tensor=...).build())`` trustworthy:

1. **Mesh parity.** An ``fsdp=8`` engine run is BIT-EXACT with pure DP —
   per-step losses and final params identical (the batch stays 8-way
   sharded, so every cross-device reduction has the same participant set
   and order; ``jax_threefry_partitionable`` was forced on in PR 1 for
   exactly this). A ``data=2/fsdp=2/tensor=2`` mesh re-GROUPS those
   reductions (4-way batch shards, TP contraction splits), which legally
   reorders float summation — its per-step losses must still match DP to
   float32-ULP tolerance, and its *initial* state must be bit-exact
   (sharded init reproduces replicated init exactly; drift is earned by
   arithmetic, never by initialization).

2. **One compile per shape.** The sharded chained trainer's trace_counts
   must show exactly one ``chained_N`` trace — the retrace-guard rule
   extended to SPMD: a sharding-induced silent retrace per window would be
   the same multi-minute-per-window disaster scripts/retrace_guard.py
   exists to catch.

3. **Resharding kill/resume.** A sharded (fsdp=8) run killed by a real
   mid-epoch SIGTERM must resume under a DIFFERENT mesh (pure DP) from its
   auto-saved sharded checkpoint and finish BIT-EXACT with an entirely
   uninterrupted DP run — the checkpoint's host shards + sharding-metadata
   record restore through the resharding path (orbax relayout against the
   target's shardings) with zero value drift. This is ROADMAP item 4's
   elasticity prerequisite, test-enforced end to end.

Runs in ~2 minutes on CPU; wired as a verify.sh stage.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_training_pytorch_tpu import compat  # noqa: E402

compat.force_host_devices(8)

import tempfile  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from distributed_training_pytorch_tpu.data import ArrayDataSource  # noqa: E402
from distributed_training_pytorch_tpu.fault import FaultPlan  # noqa: E402
from distributed_training_pytorch_tpu.models import VGG16  # noqa: E402
from distributed_training_pytorch_tpu.models.vit import ViTTiny  # noqa: E402
from distributed_training_pytorch_tpu.ops import cross_entropy_loss  # noqa: E402
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib  # noqa: E402
from distributed_training_pytorch_tpu.parallel import (  # noqa: E402
    transformer_tp_rules,
)
from distributed_training_pytorch_tpu.train import (  # noqa: E402
    TrainEngine,
    make_supervised_loss,
)
from distributed_training_pytorch_tpu.trainer import Trainer  # noqa: E402

CHECK = {"passed": 0}


def ok(cond, msg):
    if not cond:
        print(f"sharding_smoke: FAIL — {msg}")
        sys.exit(1)
    CHECK["passed"] += 1
    print(f"sharding_smoke: ok — {msg}")


def params_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(jax.device_get(b)), strict=True)
    )


# ---------------------------------------------------------------- stage 1
# Engine-level mesh parity on ViTTiny (the TP rules' native model).

def criterion(logits, batch):
    loss = cross_entropy_loss(logits, batch["label"])
    return loss, {"loss": loss}


def engine_run(mesh, rules, steps=5):
    model = ViTTiny(num_classes=4)
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        sharding_rules=rules,
        fsdp_min_size=1024,
    )
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
    )
    init_params = jax.device_get(state.params)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        batch = engine.shard_batch(
            {
                "image": rng.randn(16, 16, 16, 3).astype(np.float32),
                "label": rng.randint(0, 4, size=(16,)).astype(np.int32),
            }
        )
        state, m = engine.train_step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses, init_params


def stage_engine_parity():
    dp_state, dp_losses, dp_init = engine_run(
        mesh_lib.create_mesh({"data": 8}), None
    )
    f8_state, f8_losses, f8_init = engine_run(
        mesh_lib.MeshConfig(data=1, fsdp=8).build(), None
    )
    ok(f8_losses == dp_losses, "fsdp=8 per-step losses BIT-EXACT with pure DP")
    ok(params_equal(f8_state.params, dp_state.params),
       "fsdp=8 final params BIT-EXACT with pure DP")
    specs = [
        str(leaf.sharding.spec) for leaf in jax.tree.leaves(f8_state.params)
    ]
    ok(any("fsdp" in s for s in specs),
       "fsdp=8 state is genuinely sharded (not a replicated pass-through)")

    mix_state, mix_losses, mix_init = engine_run(
        mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2).build(),
        transformer_tp_rules(),
    )
    ok(params_equal(mix_init, dp_init),
       "data=2/fsdp=2/tensor=2 sharded INIT is bit-exact with replicated init")
    ok(mix_losses[0] == dp_losses[0],
       "data=2/fsdp=2/tensor=2 first-step loss bit-exact with DP")
    worst = max(abs(a - b) for a, b in zip(mix_losses, dp_losses, strict=True))
    ok(worst <= 5e-6,
       f"data=2/fsdp=2/tensor=2 losses match DP to ULP tolerance (worst {worst:.2e})")
    specs = [
        str(leaf.sharding.spec) for leaf in jax.tree.leaves(mix_state.params)
    ]
    ok(any("tensor" in s for s in specs) and any("fsdp" in s for s in specs),
       "TP rules AND the FSDP fallback both took effect on the mixed mesh")


# ---------------------------------------------------------------- stage 2+3
# Trainer-level: the real hot path (chained windows, checkpoints, SIGTERM).

def synthetic_images(n, num_classes=3, size=32, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(n,)).astype(np.int32)
    images = rng.randn(n, size, size, 3).astype(np.float32)
    images += labels[:, None, None, None].astype(np.float32) * 1.5
    return images, labels


class SmokeTrainer(Trainer):
    def build_train_dataset(self):
        images, labels = synthetic_images(64, seed=0)
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return VGG16(
            num_classes=3, stage_features=(4, 8), stage_layers=(1, 1),
            classifier_widths=(16,),
        )

    def build_criterion(self):
        def criterion(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"ce_loss": loss}

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return 0.05


class ViTSmokeTrainer(SmokeTrainer):
    """ViT variant for the kill/resume bit-exactness leg: an fsdp=8 ViT run
    is bit-exact with pure DP (dense matmul wgrads reduce in the same
    participant order either way), so an interrupted-and-resharded run can
    be compared bit-for-bit against an uninterrupted one. VGG's conv wgrad
    reduce-scatter reorders a summation at ~1e-9 under fsdp (measured) —
    real drift earned by arithmetic, which is why the trainer-parity stage
    above uses a tolerance and THIS stage uses a model where zero-drift is
    the truth."""

    def build_model(self):
        return ViTTiny(num_classes=3)


def make_trainer(folder, mesh, *, cls=SmokeTrainer, **kw):
    kw.setdefault("max_epoch", 2)
    kw.setdefault("batch_size", 16)
    kw.setdefault("chain_steps", 2)
    kw.setdefault("log_every", 4)
    kw.setdefault("num_workers", 0)
    kw.setdefault("progress", False)
    kw.setdefault("fsdp_min_size", 256)
    return cls(save_folder=str(folder), mesh=mesh, **kw)


def stage_trainer(tmp):
    dp = make_trainer(os.path.join(tmp, "dp"), mesh_lib.create_mesh({"data": 8}))
    dp.train()

    mix = make_trainer(
        os.path.join(tmp, "mix"),
        mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2).build(),
    )
    mix.train()
    counts = dict(mix.engine.trace_counts)
    ok(counts.get("chained_2") == 1,
       f"sharded chained window compiled exactly once per shape ({counts})")
    dp_epoch = epoch_mean_loss(dp)
    mix_epoch = epoch_mean_loss(mix)
    ok(abs(dp_epoch - mix_epoch) <= 2e-5,
       f"sharded trainer epoch loss matches DP trainer "
       f"({mix_epoch:.8f} vs {dp_epoch:.8f})")


def epoch_mean_loss(trainer):
    # Both trainers log identical epoch means; re-derive from the final
    # state-independent signal: one eval pass over the train set.
    images, labels = synthetic_images(64, seed=0)
    batch = trainer.engine.shard_batch(
        {"image": images[:16], "label": labels[:16]}
    )
    metrics = trainer.engine.eval_step(trainer.state, batch)
    return float(jax.device_get(metrics["ce_loss"]))


def stage_kill_resume_reshard(tmp):
    kw = dict(
        have_validate=False, save_best_for=None, save_period=None,
        cls=ViTSmokeTrainer,
    )
    baseline = make_trainer(
        os.path.join(tmp, "base"), mesh_lib.create_mesh({"data": 8}), **kw
    )
    baseline.train()

    sharded_mesh = mesh_lib.MeshConfig(data=1, fsdp=8).build()
    plan = FaultPlan().add("sigterm", epoch=1, step=2)
    interrupted = make_trainer(
        os.path.join(tmp, "kill"), sharded_mesh, fault_plan=plan, **kw
    )
    interrupted.train()
    ok(interrupted._preempted and interrupted._epoch_interrupted,
       "sharded run was killed mid-epoch by the injected SIGTERM")
    meta = interrupted.checkpoints.read_meta("last")
    ok((meta.get("sharding") or {}).get("mesh", {}).get("fsdp") == 8,
       "emergency save recorded the fsdp=8 sharding metadata")

    resumed = make_trainer(
        os.path.join(tmp, "kill"),
        mesh_lib.create_mesh({"data": 8}),  # DIFFERENT mesh: pure DP
        snapshot_path=interrupted.checkpoints.path("last"),
        **kw,
    )
    ok(params_equal(resumed.state.params, interrupted.state.params),
       "resharding RESTORE is bit-exact (fsdp=8 shards -> replicated values)")
    ok(resumed._resume_step_in_epoch == 2,
       "resume realigned to the killed run's mid-epoch position")
    specs = [str(leaf.sharding.spec) for leaf in jax.tree.leaves(resumed.state.params)]
    ok(all("fsdp" not in s for s in specs),
       "restored state landed in the DP mesh's replicated layout")
    resumed.train()
    ok(int(resumed.state.step) == int(baseline.state.step),
       "resumed run reached the uninterrupted run's step count")
    ok(params_equal(resumed.state.params, baseline.state.params),
       "kill(fsdp=8) -> resume(DP) final params BIT-EXACT with uninterrupted DP run")


def main():
    import time

    t0 = time.perf_counter()
    stage_engine_parity()
    with tempfile.TemporaryDirectory(prefix="sharding_smoke_") as tmp:
        stage_trainer(tmp)
        stage_kill_resume_reshard(tmp)
    print(
        f"sharding_smoke: PASS ({CHECK['passed']} checks, "
        f"{time.perf_counter() - t0:.1f}s)"
    )


if __name__ == "__main__":
    main()
