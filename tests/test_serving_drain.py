"""ISSUE 20 tests: graceful drain, live re-plan, and the actuated offer.

Acceptance pillars:

* the server's admission state machine: a drain stops admission with a
  typed 503 + ``Retry-After``, flushes in-flight micro-batches to
  completion under the bounded deadline (a batch executing AT the
  deadline is still answered 200), sheds past-deadline queued rows as
  typed 503s (never a hang, never a dropped row), and no dispatch
  threads leak across N drain/resume cycles (the DecodePool-style
  accounting of satellite 3);
* ``InferEngine.replan_onto``: bit-identical outputs for identical
  params across a device-set change, executables rebuilt, and an
  infeasible target refused with the old plan untouched (the
  controller's revert path depends on that);
* the hot-swap watcher is gated behind the drain's state machine — a
  checkpoint commit landing mid-drain must not flip params (satellite 2
  regression);
* ``Retry-After`` on 429/503 derived from queue depth, recorded on the
  ``admission_reject`` event with its ``reason`` (satellite 1);
* :class:`serving.client.RetryClient`: honors ``Retry-After`` over its
  own backoff, retries only 429/503/transport, bounded attempts with a
  typed give-up — on an injected transport, no sockets or sleeps;
* :class:`telemetry.controller.OfferHandshake`: the chip-count-scaled
  A/B judge (absorbing a chip halves per-chip QPS under fixed open-loop
  load — the naive compare would always revert), SLO-primacy, decline
  and timeout terminality;
* the monitor reads a draining replica as ``draining`` — never ``dead``
  (the tentpole's monitor clause).
"""

import json
import os
import threading
import time
import urllib.error

import jax
import numpy as np
import pytest

from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec
from distributed_training_pytorch_tpu.serving import MicroBatcher
from distributed_training_pytorch_tpu.serving.client import (
    RetriesExhausted,
    RetryClient,
)
from distributed_training_pytorch_tpu.serving.engine import InferEngine
from distributed_training_pytorch_tpu.serving.server import InferenceServer
from distributed_training_pytorch_tpu.telemetry.controller import OfferHandshake
from distributed_training_pytorch_tpu.telemetry.events import (
    read_events,
    resolve_events_path,
)
from distributed_training_pytorch_tpu.telemetry.monitor import (
    AlertConfig,
    RunMonitor,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _linear_params(seed=0, d=4):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((d, d)).astype(np.float32)}


def _linear_apply(params, x):
    return x @ params["w"]


class _Dev:
    def __init__(self, i):
        self.id = i


class _StubMesh:
    shape = {"data": 1}
    devices = np.array([_Dev(0)], dtype=object)


class StubEngine:
    """The engine surface the server's drain path reads, with an optional
    per-call gate so a test can hold a micro-batch in flight across a
    drain deadline (impossible with a jitted engine — blocking inside the
    traced body would block per-trace, not per-call)."""

    buckets = (1, 2, 4)
    params_version = "stub@e0"
    swap_count = 0

    def __init__(self, gate=None):
        self.gate = gate
        self.mesh = _StubMesh()
        self.replan_count = 0
        self.predicted = 0

    def predict(self, inputs):
        if self.gate is not None:
            assert self.gate.wait(10.0), "test gate never released"
        self.predicted += int(np.asarray(inputs).shape[0])
        return np.asarray(inputs) * 2.0, self.params_version

    def warmup(self, row):
        return 0.0


def _wait(predicate, timeout=5.0, tick=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(tick)
    return False


# ---------------------------------------------------------------------------
# Retry-After derivation + admission_reject vocabulary (satellite 1).


def test_retry_after_on_429_and_event_reason(tmp_path):
    server = InferenceServer(
        StubEngine(),
        batcher=MicroBatcher(buckets=(1, 2, 4), max_queue_depth=0),
        run_dir=str(tmp_path),
        process_index=0,
    )
    code, body, headers = server.handle_predict(
        "t0", np.ones((1, 4), np.float32)
    )
    assert code == 429
    # The 429 body is the pre-existing exact contract (soak-pinned): the
    # Retry-After signal is header-only, never a body change.
    assert json.loads(body) == {
        "error": "overload", "tenant": "t0", "depth": 0, "bound": 0,
    }
    assert int(headers["Retry-After"]) >= 1
    server.events.close()
    recs = [
        r for r in read_events(resolve_events_path(str(tmp_path)))
        if r.get("event") == "admission_reject"
    ]
    assert recs and recs[0]["reason"] == "overload"
    assert recs[0]["retry_after_s"] == int(headers["Retry-After"])


def test_retry_after_floored_by_drain_deadline():
    server = InferenceServer(StubEngine(), process_index=0)
    server.state = "draining"
    server._drain_deadline = server._clock() + 7.0
    assert server.retry_after_s() >= 7


# ---------------------------------------------------------------------------
# The drain state machine (tentpole a).


def test_drain_sheds_queued_rows_as_typed_503(tmp_path):
    """No dispatch loop running: everything queued at the deadline is shed
    — answered (typed 503 + Retry-After), never hung, never dropped."""
    server = InferenceServer(
        StubEngine(),
        batcher=MicroBatcher(buckets=(1, 2, 4), max_delay_s=0.005),
        run_dir=str(tmp_path),
        process_index=0,
    )
    results = {}

    def call():
        results["r"] = server.handle_predict(
            "t0", np.ones((2, 4), np.float32)
        )

    t = threading.Thread(target=call)
    t.start()
    assert _wait(lambda: server.batcher.pending() == 2)
    summary = server.drain(deadline_s=0.05)
    t.join(timeout=5.0)
    assert not t.is_alive(), "shed request hung instead of answering"
    assert summary["shed"] == 2
    code, body, headers = results["r"]
    payload = json.loads(body)
    assert code == 503 and payload["error"] == "draining"
    assert "drain deadline exceeded" in payload["detail"]
    assert int(headers["Retry-After"]) >= 1
    # Drained => quiesced; a second drain is a caller bug, typed.
    assert server.state == "replanning"
    with pytest.raises(RuntimeError, match="already replanning"):
        server.drain()
    # Admission while quiesced: immediate typed 503, nothing queued.
    code, body, headers = server.handle_predict(
        "t0", np.ones((1, 4), np.float32)
    )
    assert code == 503 and json.loads(body)["state"] == "replanning"
    assert server.batcher.pending() == 0
    server.resume()
    assert server.state == "serving"
    server.events.close()
    recs = list(read_events(resolve_events_path(str(tmp_path))))
    drains = [r for r in recs if r.get("event") == "drain_start"]
    assert len(drains) == 1 and drains[0]["pending"] == 2
    rejects = [r for r in recs if r.get("event") == "admission_reject"]
    assert rejects and rejects[0]["reason"] == "replanning"


def test_batch_in_flight_at_deadline_completes_200():
    """Satellite 3 boundary: a micro-batch already EXECUTING when the
    drain deadline passes is never shed — its rows answer 200."""
    gate = threading.Event()
    server = InferenceServer(
        StubEngine(gate),
        batcher=MicroBatcher(buckets=(1, 2, 4), max_delay_s=0.001),
        process_index=0,
    ).start()
    try:
        results = {}

        def call():
            results["r"] = server.handle_predict(
                "t0", np.ones((1, 4), np.float32)
            )

        t = threading.Thread(target=call)
        t.start()
        assert _wait(lambda: server._inflight == 1)
        drain_summary = {}

        def run_drain():
            drain_summary.update(server.drain(deadline_s=0.05))

        dt = threading.Thread(target=run_drain)
        dt.start()
        # Let the deadline pass with the batch still executing, then
        # release it: drain must wait it out, not shed it.
        time.sleep(0.1)
        gate.set()
        dt.join(timeout=5.0)
        t.join(timeout=5.0)
        assert not dt.is_alive() and not t.is_alive()
        code, body, _ = results["r"]
        assert code == 200, body
        assert json.loads(body)["outputs"] == [[2.0, 2.0, 2.0, 2.0]]
        assert drain_summary["shed"] == 0
        server.resume()
    finally:
        gate.set()
        server.close()


def test_no_thread_leak_across_drain_resume_cycles():
    """Satellite 3: DecodePool-style accounting — N drain/resume cycles
    reuse the same dispatch machinery; thread count stays flat and the
    in-flight counter returns to zero every cycle."""
    server = InferenceServer(
        StubEngine(),
        batcher=MicroBatcher(buckets=(1, 2, 4), max_delay_s=0.001),
        process_index=0,
    ).start()
    try:
        baseline_threads = threading.active_count()
        n_started = len(server._threads)
        for cycle in range(5):
            code, body, _ = server.handle_predict(
                "t0", np.ones((1, 4), np.float32)
            )
            assert code == 200, f"cycle {cycle}: {body}"
            server.drain(deadline_s=0.05)
            assert server._inflight == 0
            server.resume()
        code, _, _ = server.handle_predict("t0", np.ones((1, 4), np.float32))
        assert code == 200
        assert server.drain_count == 5
        assert len(server._threads) == n_started
        assert threading.active_count() <= baseline_threads
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Engine re-plan: bit identity + pre-mutation refusal.


def test_engine_replan_bit_identity_and_refusal():
    devs = jax.devices()
    eng = InferEngine(
        _linear_apply,
        mesh_config_from_spec("dp1").build(devs[:1]),
        buckets=(2, 4, 8),
    )
    eng.swap_params(_linear_params(seed=3), version="best@e1")
    x = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
    before, v0 = eng.predict(x)

    eng.replan_onto(mesh_config_from_spec("dp2").build(devs[:2]))
    assert eng.replan_count == 1
    assert eng._executables == {}  # old-mesh closures dropped
    after, v1 = eng.predict(x)
    # Identical bytes for identical params: dp growth replicates params
    # and only re-splits the batch axis — per-row math is unchanged.
    assert v1 == v0 == "best@e1"
    np.testing.assert_array_equal(before, after)

    # Infeasible target (2 % 3 != 0): refused BEFORE any state mutation —
    # the engine keeps serving the dp2 plan it had.
    with pytest.raises(ValueError, match="batch-shard extent"):
        eng.replan_onto(mesh_config_from_spec("dp3").build(devs[:3]))
    assert eng.replan_count == 1
    assert dict(eng.mesh.shape) == {"data": 2}
    again, _ = eng.predict(x)
    np.testing.assert_array_equal(before, again)


def test_server_replan_refusal_keeps_serving():
    """handle_replan on an infeasible target: typed 400, admission never
    stopped, no drain consumed (the controller's revert contract)."""
    devs = jax.devices()
    eng = InferEngine(
        _linear_apply,
        mesh_config_from_spec("dp1").build(devs[:1]),
        buckets=(2, 4, 8),
    )
    eng.swap_params(_linear_params(seed=3), version="best@e1")
    server = InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(2, 4, 8), max_delay_s=0.002),
        process_index=0,
    ).start()
    try:
        code, body, _ = server.handle_replan({"device_ids": [0, 1, 2]})
        assert code == 400
        payload = json.loads(body)
        assert payload["error"] == "replan_failed"
        assert payload["state"] == "serving"
        assert server.drain_count == 0 and eng.replan_count == 0
        code, _, _ = server.handle_predict(
            "t0", np.ones((2, 4), np.float32)
        )
        assert code == 200
        # Unknown device ids are refused the same way.
        code, body, _ = server.handle_replan({"device_ids": [0, 99]})
        assert code == 400 and "unknown device" in json.loads(body)["detail"]
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Satellite 2: the hot-swap watcher is gated behind the drain.


def test_swap_watcher_gated_during_drain(tmp_path):
    """A checkpoint commit landing mid-drain must NOT flip params; the
    watcher re-arms after resume and then swaps (regression for the
    swap-vs-replan race)."""

    class StubState:
        def __init__(self, params):
            self.params = params

    ckpt_root = tmp_path / "ckpts"

    class StubManager:
        MANIFEST = "manifest.json"

        def __init__(self):
            self.store = {}

        def commit(self, name, params, epoch):
            d = ckpt_root / name
            d.mkdir(parents=True, exist_ok=True)
            self.store[name] = (params, epoch)
            tmp = d / ".manifest.tmp"
            tmp.write_text(json.dumps({"epoch": epoch}))
            os.replace(tmp, d / self.MANIFEST)

        def exists(self, name):
            return name in self.store

        def path(self, name):
            return str(ckpt_root / name)

        def latest_valid_name(self):
            return None

        def restore(self, name, target_state, params_only=False):
            params, epoch = self.store[name]
            return StubState(params), epoch

    import distributed_training_pytorch_tpu.checkpoint.manager as mgr_mod

    manager = StubManager()
    manager.commit("best", _linear_params(seed=11), epoch=1)
    eng = InferEngine(
        _linear_apply,
        mesh_config_from_spec("dp1").build(jax.devices()[:1]),
        buckets=(1, 2),
    )
    real_manifest = mgr_mod.MANIFEST_NAME
    try:
        mgr_mod.MANIFEST_NAME = StubManager.MANIFEST
        server = InferenceServer(
            eng,
            batcher=MicroBatcher(buckets=(1, 2), max_delay_s=0.002),
            manager=manager,
            target_state=object(),
            serve_name="best",
            swap_poll_s=0.02,
            process_index=0,
        ).start()
        try:
            assert _wait(lambda: eng.params_version == "best@e1")
            swaps_before = eng.swap_count
            server.drain(deadline_s=0.05)
            # A new epoch lands while quiesced: the watcher must sit out.
            manager.commit("best", _linear_params(seed=12), epoch=2)
            time.sleep(6 * server.swap_poll_s)
            assert eng.swap_count == swaps_before
            assert eng.params_version == "best@e1"
            server.resume()
            # First poll after resume re-derives the candidate from disk:
            # nothing was missed, the gated commit lands now.
            assert _wait(lambda: eng.params_version == "best@e2")
        finally:
            server.close()
    finally:
        mgr_mod.MANIFEST_NAME = real_manifest


# ---------------------------------------------------------------------------
# The replica's offer decision.


def test_handle_offer_decline_under_slo_pressure(tmp_path):
    server = InferenceServer(
        StubEngine(), run_dir=str(tmp_path), slo_p99_ms=10.0,
        process_index=0,
    )
    # Healthy (no traffic in window): accept.
    code, body, _ = server.handle_offer({"chip": 3})
    assert code == 200 and json.loads(body)["decision"] == "accept"
    # Breaching: the replica must not take a drain+recompile window on
    # top of an SLO breach — decline, with the evidence in the record.
    now = server._clock()
    for _ in range(20):
        server.window.add(now, 500.0)
    code, body, _ = server.handle_offer({"chip": 3})
    payload = json.loads(body)
    assert payload["decision"] == "decline"
    assert "SLO pressure" in payload["reason"]
    # Mid-drain: decline too.
    server.state = "draining"
    code, body, _ = server.handle_offer({"chip": 3})
    assert json.loads(body)["reason"] == "replica is draining"
    server.state = "serving"
    # No chip: typed 400.
    code, _, _ = server.handle_offer({})
    assert code == 400
    server.events.close()
    kinds = [
        r["event"] for r in read_events(resolve_events_path(str(tmp_path)))
        if r.get("event", "").startswith("offer_")
    ]
    assert kinds == ["offer_accept", "offer_decline", "offer_decline"]


# ---------------------------------------------------------------------------
# OfferHandshake: the chip-scaled A/B judge.


def test_offer_handshake_keep_requires_chip_scaled_floor():
    hs = OfferHandshake(
        1,
        before={"qps_per_chip": 100.0, "p99_ms": 5.0, "slo_ok": True,
                "chips": 1},
        now=0.0, timeout_s=60.0, settle_s=2.0,
    )
    hs.note_decision("accept", "healthy")
    hs.note_actuated({"shed": 0}, now=1.0)
    assert not hs.ready_to_judge(2.0) and hs.ready_to_judge(3.0)
    # Fixed-rate open-loop load over 1 -> 2 chips: per-chip QPS halves BY
    # CONSTRUCTION. 50/chip is the expected value, not a regression — a
    # naive before>=after compare would revert every absorb ever made.
    verdict, evidence = hs.judge(
        {"qps_per_chip": 48.0, "p99_ms": 4.0, "slo_ok": True, "chips": 2}
    )
    assert verdict == "keep" and hs.state == "kept"
    row = next(e for e in evidence if e["metric"] == "qps_per_chip")
    assert row["expected_floor"] == pytest.approx(45.0)  # 100*(1/2)*0.9
    assert {"p99_ms", "slo_ok"} <= {e["metric"] for e in evidence}


def test_offer_handshake_reverts_on_slo_or_throughput():
    def fresh():
        hs = OfferHandshake(
            1, before={"qps_per_chip": 100.0, "chips": 1, "slo_ok": True},
            now=0.0, settle_s=0.0,
        )
        hs.note_decision("accept")
        hs.note_actuated({}, now=0.0)
        return hs

    # SLO is primary: great throughput cannot save a breached absorb.
    hs = fresh()
    verdict, _ = hs.judge(
        {"qps_per_chip": 60.0, "chips": 2, "slo_ok": False}
    )
    assert verdict == "revert" and "SLO" in hs.reason
    # Below the chip-scaled floor (45.0): revert.
    hs = fresh()
    verdict, _ = hs.judge(
        {"qps_per_chip": 30.0, "chips": 2, "slo_ok": True}
    )
    assert verdict == "revert" and hs.state == "reverted"


def test_offer_handshake_decline_and_expiry_are_terminal():
    hs = OfferHandshake(2, before={}, now=0.0, timeout_s=10.0)
    hs.note_decision("decline", "under SLO pressure")
    assert hs.done and hs.state == "declined"
    assert not hs.expired(100.0)  # terminal states never expire
    with pytest.raises(RuntimeError):
        hs.note_actuated({}, now=1.0)

    hs2 = OfferHandshake(2, before={}, now=0.0, timeout_s=10.0)
    assert not hs2.expired(9.9)
    assert hs2.expired(10.0) and hs2.state == "expired"
    with pytest.raises(RuntimeError):
        hs2.note_decision("accept")


# ---------------------------------------------------------------------------
# RetryClient: policy on an injected transport (no sockets, no sleeps).


def _fake_transport(script):
    """Each entry: (status, body_dict, headers) or an Exception to raise."""
    calls = []

    def transport(url, body, timeout):
        step = script[min(len(calls), len(script) - 1)]
        calls.append(json.loads(body.decode()))
        if isinstance(step, Exception):
            raise step
        status, payload, headers = step
        return status, json.dumps(payload).encode(), headers

    return transport, calls


def test_retry_client_honors_retry_after_then_succeeds():
    transport, calls = _fake_transport([
        (503, {"error": "draining"}, {"Retry-After": "3"}),
        (429, {"error": "overload"}, {"retry-after": "2"}),  # any case
        (200, {"outputs": [[1.0]]}, {}),
    ])
    sleeps = []
    cli = RetryClient(
        max_attempts=5, base_delay_s=0.01, jitter=0.0,
        transport=transport, sleep=sleeps.append,
    )
    status, body = cli.post_json("http://x/predict", {"inputs": [[1]]})
    assert status == 200 and body == {"outputs": [[1.0]]}
    assert len(calls) == 3
    # The server's Retry-After dominates the (tiny) exponential backoff.
    assert sleeps == [3.0, 2.0]
    assert cli.retries == 2 and cli.gave_up == 0


def test_retry_client_bounded_attempts_typed_give_up():
    transport, calls = _fake_transport([
        (503, {"error": "draining"}, {"Retry-After": "1"}),
    ])
    cli = RetryClient(
        max_attempts=3, base_delay_s=0.001, jitter=0.0,
        transport=transport, sleep=lambda s: None,
    )
    with pytest.raises(RetriesExhausted) as exc:
        cli.post_json("http://x/predict", {"inputs": [[1]]})
    assert len(calls) == 3 and cli.gave_up == 1
    assert [a["status"] for a in exc.value.attempts] == [503, 503, 503]
    assert all(a["retry_after_s"] == 1.0 for a in exc.value.attempts)


def test_retry_client_does_not_retry_terminal_statuses():
    for status in (400, 500):
        transport, calls = _fake_transport([(status, {"error": "x"}, {})])
        cli = RetryClient(transport=transport, sleep=lambda s: None)
        got, body = cli.post_json("http://x/predict", {})
        assert got == status and len(calls) == 1 and cli.retries == 0


def test_retry_client_retries_connection_errors():
    transport, calls = _fake_transport([
        urllib.error.URLError("connection refused"),
        (200, {"ok": True}, {}),
    ])
    cli = RetryClient(
        max_attempts=4, base_delay_s=0.001, jitter=0.0,
        transport=transport, sleep=lambda s: None,
    )
    status, body = cli.post_json("http://x/predict", {})
    assert status == 200 and body == {"ok": True} and len(calls) == 2


# ---------------------------------------------------------------------------
# Monitor: a draining replica is draining — never dead.


def _serve_log(run_dir, recs):
    os.makedirs(os.path.dirname(resolve_events_path(run_dir)), exist_ok=True)
    now = time.time()
    out = [{"event": "serve_start", "t_wall": now - 3.0, "attempt": 1,
            "port": 1234}]
    for r in recs:
        out.append({"t_wall": now, "attempt": 1, **r})
    with open(resolve_events_path(run_dir), "w") as f:
        for r in out:
            f.write(json.dumps(r) + "\n")


def test_monitor_reads_drain_as_draining_never_dead(tmp_path):
    run = str(tmp_path / "srv")
    _serve_log(run, [
        {"event": "request_batch", "qps": 10.0, "p99_ms": 2.0,
         "slo_ok": True, "state": "serving", "qps_per_chip": 10.0,
         "mesh_chips": 1},
        {"event": "drain_start", "deadline_s": 10.0, "pending": 4},
        {"event": "request_batch", "qps": 0.0, "p99_ms": None,
         "slo_ok": True, "state": "draining", "shed_total": 2},
    ])
    st = RunMonitor(run, AlertConfig(stale_after_s=60.0)).poll()
    assert st.kind == "serve"
    assert st.status == "draining"  # NOT dead, NOT stale
    assert st.exit_code != 2
    assert st.serve["state"] == "draining" and st.serve["shed_total"] == 2

    # replan_done flips it back, and carries the grown chip count.
    run2 = str(tmp_path / "srv2")
    _serve_log(run2, [
        {"event": "drain_start", "deadline_s": 10.0, "pending": 0},
        {"event": "replan_done", "from_mesh": {"data": 1},
         "to_mesh": {"data": 2}, "device_ids": [0, 1], "shed": 0},
        {"event": "request_batch", "qps": 10.0, "p99_ms": 2.0,
         "slo_ok": True, "state": "serving", "mesh_chips": 2},
    ])
    st2 = RunMonitor(run2, AlertConfig(stale_after_s=60.0)).poll()
    assert st2.status == "serving" and st2.verdict == "healthy"
    assert st2.serve["mesh_chips"] == 2
