"""Streaming data subsystem tests (ISSUE 19; docs/data.md): global-sequence
purity, host-split/elastic-re-split equivalence, checkpoint-carried reader
state, the decode pool's respawn/drain contract, and the injection seams the
doctor/perf-gate/fleet machinery depends on."""

import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.data import (
    ArrayDataSource,
    StreamingLoader,
    shard_array_source,
)
from distributed_training_pytorch_tpu.data.records import write_shards
from distributed_training_pytorch_tpu.data.streaming import (
    DecodePool,
    ReaderState,
    WorkerCrash,
    assignment_version,
    global_sequence,
)
from distributed_training_pytorch_tpu.parallel.elastic import replan_reader

SIZES = [25, 25, 25, 25]  # 100 records over 4 shards


def _source(n=100, seed=0):
    rng = np.random.RandomState(seed)
    return ArrayDataSource(
        image=rng.randn(n, 4, 4, 1).astype(np.float32),
        label=(np.arange(n) % 10).astype(np.int32),
    )


def _loader(n=100, G=20, **kw):
    kw.setdefault("num_workers", 0)
    return StreamingLoader(shard_array_source(_source(n), 4), G, seed=3, **kw)


# ---------------------------------------------------------------------------
# Global-sequence contract: a pure function of (seed, epoch, shard structure).


def test_global_sequence_pure_function():
    a = global_sequence(7, 2, SIZES)
    b = global_sequence(7, 2, SIZES)
    np.testing.assert_array_equal(a, b)
    assert sorted(a.tolist()) == list(range(100))  # a permutation, no loss
    assert not np.array_equal(a, global_sequence(7, 3, SIZES))
    assert not np.array_equal(a, global_sequence(8, 2, SIZES))


def test_global_sequence_unshuffled_is_identity():
    np.testing.assert_array_equal(
        global_sequence(7, 2, SIZES, shuffle=False), np.arange(100)
    )


def test_global_sequence_is_shard_major():
    """Shuffle = shard-order permutation + within-shard permutations: each
    consecutive size-25 slice of the sequence stays inside ONE shard's id
    range (streaming reads touch one shard at a time)."""
    seq = global_sequence(7, 0, SIZES)
    for lo in range(0, 100, 25):
        chunk = seq[lo : lo + 25]
        assert chunk.max() - chunk.min() < 25
        assert chunk.min() % 25 == 0


def test_host_split_disjoint_cover():
    """Every host's rows per batch tile the global batch exactly — no record
    read twice, none dropped."""
    G, P = 20, 4
    loaders = [
        _loader(G=G, process_index=p, process_count=P) for p in range(P)
    ]
    batches = [list(ld.iter_batches(0)) for ld in loaders]
    ref = _loader(G=G)
    for b, full in enumerate(ref.iter_batches(0)):
        got = np.concatenate([batches[p][b]["label"] for p in range(P)])
        np.testing.assert_array_equal(got, full["label"])


def test_resplit_equivalence_8_4():
    """The tentpole claim: 8 hosts, 4 hosts, and 1 host consume the SAME
    global record sequence — per-host splits change, the sequence does not
    — including when resuming mid-epoch from a cursor."""
    G = 40
    for start in (0, 1):  # fresh epoch and a mid-epoch resume
        seqs = {}
        for P in (1, 4, 8):
            parts = [
                [b["label"] for b in _loader(
                    G=G, process_index=p, process_count=P
                ).iter_batches(start)]
                for p in range(P)
            ]
            seqs[P] = [
                np.concatenate([parts[p][i] for p in range(P)])
                for i in range(len(parts[0]))
            ]
        for P in (4, 8):
            assert len(seqs[P]) == len(seqs[1])
            for a, b in zip(seqs[P], seqs[1], strict=True):
                np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Reader state: the checkpoint-carried cursor.


def test_reader_state_round_trip():
    ld = _loader(G=20)
    ld.set_epoch(2)
    state = ld.reader_state(batches_consumed=3)
    assert state["epoch"] == 2 and state["cursor"] == 60
    fresh = _loader(G=20)
    assert fresh.apply_reader_state(state) == 3  # resume batch, O(1)
    assert fresh._epoch == 2


def test_reader_state_json_schema_guard():
    state = ReaderState.from_json(_loader().reader_state())
    assert state.schema == 1
    newer = dict(_loader().reader_state(), schema=99)
    with pytest.raises(ValueError, match="schema"):
        ReaderState.from_json(newer)


def test_apply_reader_state_rejects_foreign_stream():
    state = _loader(G=20).reader_state()
    other = StreamingLoader(shard_array_source(_source(80), 4), 20, seed=3)
    with pytest.raises(ValueError, match="record count"):
        other.apply_reader_state(state)


def test_manager_data_item_round_trip(tmp_path, devices):
    """The data/ composite item mirrors the PR 3 scale-item rule: present →
    restored verbatim; absent (a pre-streaming checkpoint) → None, meaning
    the reader keeps its fresh default cursor."""
    from tests.test_checkpoint import _small_state

    _, state = _small_state(devices, seed=0)
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    data_state = _loader(G=20).reader_state(epoch=1, batches_consumed=2)
    mgr.save("with_data", state, epoch=1, data_state=data_state)
    mgr.save("without_data", state, epoch=1)
    restored = mgr.read_data_state("with_data")
    assert restored == dict(data_state)
    assert mgr.read_data_state("without_data") is None  # fresh-cursor rule
    mgr.close()


def test_replan_reader_resplits_from_cursor():
    """parallel/elastic.py's data-plane half: the re-planned axes produce a
    new assignment version + per-host split, but the same resume batch."""
    old = replan_reader(
        {"data": 1, "fsdp": 8}, shard_sizes=SIZES, global_batch_size=20,
        cursor=60, process_index=0, process_count=1,
    )
    new = replan_reader(
        {"data": 1, "fsdp": 4}, shard_sizes=SIZES, global_batch_size=20,
        cursor=60, process_index=0, process_count=1,
    )
    assert old["batch_extent"] == 8 and new["batch_extent"] == 4
    assert old["version"] != new["version"]  # the re-split is visible
    assert old["resume_batch"] == new["resume_batch"] == 3  # the cursor is not
    assert new["version"] == assignment_version(
        record_count=100, shard_count=4, global_batch_size=20,
        process_count=1, batch_extent=4,
    )


# ---------------------------------------------------------------------------
# Decode pool: bounded workers, crash respawn, shutdown drain.


def _stream_threads():
    return [t for t in threading.enumerate() if t.name.startswith("stream-decode")]


def test_pool_shutdown_drains_workers():
    with DecodePool(3) as pool:
        tasks = [pool.submit(lambda x: x * x, i) for i in range(20)]
        assert [t.result(pool) for t in tasks] == [i * i for i in range(20)]
        assert len(_stream_threads()) == 3
    assert pool.shutdown() == []  # idempotent, nothing leaked
    assert _stream_threads() == []


def test_pool_respawns_crashed_worker():
    crashed = []

    def work(i):
        if i == 5 and not crashed:
            crashed.append(i)
            raise WorkerCrash("injected")
        return i

    with DecodePool(2) as pool:
        tasks = [pool.submit(work, i) for i in range(10)]
        assert [t.result(pool) for t in tasks] == list(range(10))
        assert pool.respawns >= 1 and pool.crashes >= 1
    assert _stream_threads() == []


def test_pool_ordinary_error_does_not_kill_worker():
    def work(i):
        if i == 1:
            raise ValueError("bad record")
        return i

    with DecodePool(1) as pool:
        tasks = [pool.submit(work, i) for i in range(3)]
        assert tasks[0].result(pool) == 0
        with pytest.raises(ValueError, match="bad record"):
            tasks[1].result(pool)
        assert tasks[2].result(pool) == 2  # the worker survived
        assert pool.respawns == 0


def test_loader_crash_on_batch_reproduces_batch():
    """A decode-worker death re-enqueues the batch: pooled output equals the
    serial loader's, respawn counted, no threads leaked."""
    serial = [b["label"] for b in _loader(G=20)]
    pooled_loader = _loader(G=20, num_workers=2)
    pooled_loader.crash_on_batch = 1
    pooled = [b["label"] for b in pooled_loader]
    for a, b in zip(serial, pooled, strict=True):
        np.testing.assert_array_equal(a, b)
    assert pooled_loader.respawns >= 1 and pooled_loader.crashes >= 1
    assert _stream_threads() == []


# ---------------------------------------------------------------------------
# The seams the doctor / perf gate / fleet controller depend on.


def test_injection_seams_present():
    """load_delay_s + prefetch_batches are load-bearing API: run_doctor's
    data_bound self-test, perf_gate --inject-data-wait, and the fleet
    controller's prefetch tune all reach through them (ISSUE 19 satellite)."""
    ld = _loader(G=20, num_workers=2, prefetch_batches=5)
    assert ld.load_delay_s == 0.0
    assert ld.prefetch_batches == 5


def test_load_delay_seam_starves_serial_path():
    ld = _loader(G=20)
    ld.load_delay_s = 0.02
    t0 = time.perf_counter()
    n = sum(1 for _ in ld)
    assert time.perf_counter() - t0 >= n * 0.02  # every batch slept


def test_skip_corrupt_accounting(tmp_path):
    def records():
        for i in range(40):
            payload = np.full((4,), i, np.float32).tobytes()
            if i == 7:
                payload = b"XXX"  # not a multiple of 4: undecodable
            yield payload, i % 10

    write_shards(str(tmp_path / "s"), records(), num_shards=4)
    decode = lambda p: np.frombuffer(p, np.float32)  # noqa: E731

    ld = StreamingLoader.from_records(
        str(tmp_path), 10, decode=decode, skip_corrupt=True, seed=0,
    )
    batches = list(ld)
    assert len(batches) == 4 and all(len(b["label"]) == 10 for b in batches)
    assert ld.corrupt_skipped >= 1

    strict = StreamingLoader.from_records(str(tmp_path), 10, decode=decode, seed=0)
    with pytest.raises(Exception, match="(?i)corrupt|decode"):
        list(strict)


def test_record_log_reconstructs_sequence(tmp_path):
    log_path = str(tmp_path / "records.jsonl")
    ld = _loader(G=20, record_log_path=log_path)
    consumed = [b["label"] for b in ld.iter_batches(0)]
    lines = [json.loads(x) for x in open(log_path)]
    assert [r["batch"] for r in lines] == list(range(len(consumed)))
    order = ld._global_order()
    for rec in lines:
        b = rec["batch"]
        np.testing.assert_array_equal(rec["ids"], order[b * 20 : (b + 1) * 20])


# ---------------------------------------------------------------------------
# Trainer integration: the data/ item rides every save; resume applies it.


class _StreamNet:
    pass


@pytest.fixture(scope="module")
def stream_trained(tmp_path_factory, devices):
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.ops import cross_entropy_loss
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.trainer import Trainer

    tmp = tmp_path_factory.mktemp("stream_trained")

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            return nn.Dense(10)(x.reshape(x.shape[0], -1))

    class StreamTrainer(Trainer):
        def build_train_dataset(self):
            return _source(96, seed=0)

        def build_dataloader(self, dataset, phase="train"):
            return StreamingLoader(
                shard_array_source(dataset, 4), self.batch_size,
                seed=self.seed, num_workers=0, drop_last=True,
            )

        def build_model(self):
            return Net()

        def build_criterion(self):
            def criterion(logits, batch):
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, {"loss": loss}

            return criterion

        def build_optimizer(self, schedule):
            return optax.sgd(schedule)

        def build_scheduler(self):
            return 0.1

    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: len(devices)}, devices=devices
    )

    def make(max_epoch):
        return StreamTrainer(
            max_epoch=max_epoch, batch_size=16, save_folder=str(tmp),
            snapshot_path="latest_valid", save_period=1, have_validate=False,
            telemetry="on", num_workers=0, log_every=0, progress=False,
            async_checkpoint=False, mesh=mesh,
        )

    trainer = make(2)
    trainer.train()
    resumed = make(3)
    resumed.train()
    events = [
        json.loads(line)
        for line in open(os.path.join(tmp, "telemetry", "events.jsonl"))
    ]
    return trainer, resumed, events, str(tmp)


def test_trainer_marks_streaming_and_extent(stream_trained, devices):
    trainer, _, _, _ = stream_trained
    assert trainer._streaming_train
    assert trainer.train_dataloader.batch_extent == len(devices)


def test_every_save_carries_data_item(stream_trained):
    _, _, _, tmp = stream_trained
    weights = os.path.join(tmp, "weights")
    saves = [d for d in os.listdir(weights) if not d.startswith(".")]
    assert saves
    for name in saves:
        meta = os.path.join(weights, name, "data", "metadata")
        assert os.path.isfile(meta), f"{name} missing its data/ item"
        item = json.load(open(meta))
        assert item["record_count"] == 96 and item["global_batch_size"] == 16


def test_streaming_events_emitted(stream_trained, devices):
    _, _, events, _ = stream_trained
    assigns = [e for e in events if e["event"] == "shard_assignment"]
    states = [e for e in events if e["event"] == "data_reader_state"]
    assert len(assigns) >= 2  # one per attempt (initial + resume)
    assert all(a["batch_extent"] == len(devices) for a in assigns)
    assert states and all(
        e["assignment_version"] == assigns[0]["version"] for e in states
    )


def test_resume_applies_reader_state(stream_trained):
    _, resumed, events, _ = stream_trained
    restores = [e for e in events if e["event"] == "checkpoint_restore"]
    assert restores  # the epoch-3 run resumed from the epoch-2 save
    assert int(resumed.state.step) == 3 * 6  # 96/16 batches x 3 epochs total
