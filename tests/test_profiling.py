"""Profiling subsystem tests (ISSUE 6): xplane codec, trace analysis /
device-time attribution, perf-regression gate, hot-path capture, and the
trainer integration's acceptance pillars:

* ``analyze_trace`` category fractions sum to 1 on a checked-in synthetic
  ``.xplane.pb`` fixture with hand-computable attribution (busy/idle split,
  per-category shares, roofline join);
* the report schema (``REPORT_FIELDS``) is stable — consumers (bench JSON,
  ``profile_capture`` events) may rely on the keys across PRs;
* gate pass/fail logic is exact on synthetic baselines, including the
  injected-regression case verify.sh exercises end to end;
* ``Trainer(profile=None)`` reproduces the historical program exactly —
  final params bit-exact and ``TrainEngine.trace_counts`` identical to a
  ``profile=``-on run (the telemetry-off parity convention).

Cost note: trainer tests reuse test_telemetry's TinyTrainer (seconds of CPU
compile); everything else is pure parsing/logic on synthetic bytes.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_training_pytorch_tpu import profiling
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.profiling import gate as gate_lib
from distributed_training_pytorch_tpu.profiling import xplane
from distributed_training_pytorch_tpu.profiling.capture import StepTraceCapture
from distributed_training_pytorch_tpu.utils import profiling as legacy_profiling

from test_telemetry import assert_trees_equal, make_tiny

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "synthetic_step.xplane.pb")

US = 1_000_000  # picoseconds per microsecond

# The spec behind tests/fixtures/synthetic_step.xplane.pb — five sequential
# critical-path events with one 5us gap at 60us and one at 90us (10us idle
# over a 100us span), one category each, plus an overlapped Async-line event
# the device attribution must ignore. Regenerate the fixture by piping this
# spec through xplane.encode_xspace (test_fixture_bytes_are_encode_xspace
# proves file and spec never drift).
SYNTHETIC_SPEC = [
    {
        "name": "/device:TPU:0",
        "lines": [
            {
                "name": "XLA Ops",
                "timestamp_ns": 0,
                "events": [
                    ("%convolution.1 = f32[8,16,16,8] convolution(%p0, %p1)", 0 * US, 40 * US),
                    ("%fusion.7 = f32[8,16,16,8] fusion(%param.4)", 40 * US, 20 * US),
                    ("%copy.3 = f32[8,8,16,16] copy(%fusion.7)", 65 * US, 10 * US),
                    ("%all-reduce.2 = f32[10] all-reduce(%copy.3)", 75 * US, 15 * US),
                    ("%dot.5 = f32[8,10] dot(%fusion.7, %p2)", 95 * US, 5 * US),
                ],
            },
            {
                "name": "Async XLA Ops",
                "timestamp_ns": 0,
                "events": [("copy-start.9", 0, 100 * US)],
            },
        ],
    }
]

# Exact attribution of the spec: 90us busy over the 100us span, op self-time
# shares scaled by busy_frac 0.9, idle takes the remaining 0.1.
SYNTHETIC_FRACTIONS = {
    "convolution": 0.40,
    "fusion(elementwise)": 0.20,
    "copy/transpose": 0.10,
    "collective": 0.15,
    "matmul": 0.05,
    profiling.IDLE: 0.10,
}


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


# ---------------------------------------------------------------------------
# Legacy utils.profiling surface (the shim must keep the seed behavior).


def test_trace_writes_xplane_and_parser_reads_it(tmp_path):
    with legacy_profiling.trace(str(tmp_path)):
        with legacy_profiling.annotate("tiny_matmul"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    path = legacy_profiling.latest_trace_file(str(tmp_path))
    assert path is not None and path.endswith(".xplane.pb")
    # On the CPU test platform there are no TPU/GPU device planes, so the op
    # table is empty — but the wire-format parse itself must succeed.
    ops = legacy_profiling.top_ops(str(tmp_path))
    assert isinstance(ops, list)
    for name, total_us, count in ops:
        assert isinstance(name, str) and total_us >= 0 and count >= 1


def test_top_ops_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        legacy_profiling.top_ops(str(tmp_path / "nope"))


def test_varint_fields_roundtrip():
    """The hand-rolled protobuf reader handles all wire types it claims."""
    # field 1 varint=300, field 2 bytes"abc", field 3 fixed32, field 4 fixed64
    buf = (
        b"\x08\xac\x02"  # 1<<3|0, varint 300
        b"\x12\x03abc"  # 2<<3|2, len 3
        b"\x1d\x01\x00\x00\x00"  # 3<<3|5
        b"\x21\x02\x00\x00\x00\x00\x00\x00\x00"  # 4<<3|1
    )
    fields = list(xplane._fields(buf))
    assert fields[0] == (1, 0, 300)
    assert fields[1] == (2, 2, b"abc")
    assert fields[2][0] == 3 and len(fields[2][2]) == 4
    assert fields[3][0] == 4 and len(fields[3][2]) == 8


# ---------------------------------------------------------------------------
# xplane codec: the write side must be the read side's exact inverse.


def test_fixture_bytes_are_encode_xspace():
    """The checked-in fixture IS encode_xspace(SYNTHETIC_SPEC) — codec drift
    in either direction (or a stale fixture) fails here byte-for-byte."""
    with open(FIXTURE, "rb") as f:
        assert f.read() == xplane.encode_xspace(SYNTHETIC_SPEC)


def test_encode_read_roundtrip(tmp_path):
    path = str(tmp_path / "t.xplane.pb")
    with open(path, "wb") as f:
        f.write(xplane.encode_xspace(SYNTHETIC_SPEC))
    planes = xplane.read_trace(path)
    assert [p.name for p in planes] == ["/device:TPU:0"]
    (plane,) = planes
    assert [ln.name for ln in plane.lines] == ["XLA Ops", "Async XLA Ops"]
    got = [
        (e.name, e.start_ps, e.duration_ps) for e in plane.lines[0].events
    ]
    assert got == list(SYNTHETIC_SPEC[0]["lines"][0]["events"])
    assert plane.lines[0].events[0].end_ps == 40 * US


# ---------------------------------------------------------------------------
# analyze_trace: device-time attribution on the synthetic fixture.


def test_synthetic_attribution_exact():
    prof = profiling.analyze_trace(FIXTURE, steps=5)
    assert prof.source == "device"
    assert prof.span_us == pytest.approx(100.0)
    assert prof.busy_us == pytest.approx(90.0)
    assert prof.idle_us == pytest.approx(10.0)
    assert prof.step_us == pytest.approx(20.0)
    assert prof.device_busy_frac == pytest.approx(0.9)
    assert prof.dispatch_gap_frac == pytest.approx(0.1)
    assert set(prof.categories) == set(SYNTHETIC_FRACTIONS)
    for cat, frac in SYNTHETIC_FRACTIONS.items():
        assert prof.categories[cat] == pytest.approx(frac), cat
    # the overlapped Async-line window never leaks into the attribution
    assert prof.busy_us < 100.0


def test_category_fractions_sum_to_one():
    prof = profiling.analyze_trace(FIXTURE)
    assert math.isclose(sum(prof.categories.values()), 1.0, rel_tol=0, abs_tol=1e-9)


def test_report_schema_stable():
    """to_dict() carries exactly REPORT_FIELDS — the contract bench JSON and
    profile_capture events build on. Additions append to REPORT_FIELDS;
    renames/removals fail here."""
    prof = profiling.analyze_trace(FIXTURE, steps=5)
    d = prof.to_dict()
    assert tuple(d) == profiling.REPORT_FIELDS
    assert json.loads(json.dumps(d)) == d  # event-log/bench serializable
    for row in d["top_ops"]:
        assert {"name", "category", "total_us", "count", "frac_busy"} <= set(row)


def test_roofline_join_lands_on_top_ops():
    flops_by_op = {
        "convolution.1": {"flops": 2.0e9, "bytes": 1.0e7, "arith_intensity": 200.0}
    }
    prof = profiling.analyze_trace(FIXTURE, flops_by_op=flops_by_op)
    by_cat = {row.category: row for row in prof.top_ops}
    conv = by_cat["convolution"]
    assert conv.flops == 2.0e9 and conv.bytes == 1.0e7
    assert conv.arith_intensity == pytest.approx(200.0)
    assert conv.to_dict()["arith_intensity"] == pytest.approx(200.0)
    # unjoined rows (no HLO itemization — fusions etc.) carry None and omit
    # the roofline keys from their dicts
    fusion = by_cat["fusion(elementwise)"]
    assert fusion.flops is None and "flops" not in fusion.to_dict()


def test_host_xla_fallback_uses_interval_union(tmp_path):
    """CPU traces have no device plane: the tf_XLA* runtime threads carry the
    op events. Threads overlap, so busy time is the interval UNION (sum would
    double-count) and runtime bookkeeping noise is excluded."""
    path = str(tmp_path / "host.xplane.pb")
    spec = [
        {
            "name": "/host:CPU",
            "lines": [
                {
                    "name": "tf_XLA_0",
                    "timestamp_ns": 0,
                    "events": [
                        ("dot.1", 0, 50 * US),
                        ("ThreadpoolListener::fire", 0, 100 * US),  # noise
                    ],
                },
                {
                    "name": "tf_XLA_1",
                    "timestamp_ns": 0,
                    # overlaps dot.1 for 25us
                    "events": [("fusion.2", 25 * US, 50 * US)],
                },
            ],
        }
    ]
    with open(path, "wb") as f:
        f.write(xplane.encode_xspace(spec))
    prof = profiling.analyze_trace(path)
    assert prof.source == "host-xla"
    assert prof.span_us == pytest.approx(75.0)
    assert prof.busy_us == pytest.approx(75.0)  # union, not 100us sum
    assert prof.dispatch_gap_frac == pytest.approx(0.0)
    # op self-time splits evenly (50us each) even though threads overlapped
    assert prof.categories["matmul"] == pytest.approx(0.5)
    assert prof.categories["fusion(elementwise)"] == pytest.approx(0.5)
    assert math.isclose(sum(prof.categories.values()), 1.0, abs_tol=1e-9)


def test_async_only_device_plane_never_becomes_critical_path(tmp_path):
    """A TPU window where only async DMA lines carry events (or the op line
    is empty) must raise, not promote overlapped 'Async XLA Ops' spans to
    the critical path — that would fabricate a near-1 busy fraction."""
    for lines in (
        # no "XLA Ops" line at all
        [{"name": "Async XLA Ops", "timestamp_ns": 0, "events": [("copy-start.1", 0, 9 * US)]}],
        # op line present but empty this window
        [
            {"name": "XLA Ops", "timestamp_ns": 0, "events": []},
            {"name": "Async XLA Ops", "timestamp_ns": 0, "events": [("copy-start.1", 0, 9 * US)]},
        ],
    ):
        path = str(tmp_path / "async_only.xplane.pb")
        with open(path, "wb") as f:
            f.write(xplane.encode_xspace([{"name": "/device:TPU:0", "lines": lines}]))
        with pytest.raises(ValueError, match="no XLA op events"):
            profiling.analyze_trace(path)


def test_cross_line_events_rebased_by_line_timestamp(tmp_path):
    """XEvent.offset_ps is line-LOCAL (relative to XLine.timestamp_ns):
    interval analysis across lines must rebase onto the shared trace clock,
    or a thread starting later is misaligned onto the first thread's
    timeline and busy/idle/gap figures are silently wrong."""
    path = str(tmp_path / "skewed.xplane.pb")
    spec = [
        {
            "name": "/host:CPU",
            "lines": [
                {
                    "name": "tf_XLA_0",
                    "timestamp_ns": 0,
                    "events": [("dot.1", 0, 50 * US)],
                },
                {
                    # starts 50us into the trace: its local offset 0 is
                    # absolute 50us — back-to-back with dot.1, NOT overlapped
                    "name": "tf_XLA_1",
                    "timestamp_ns": 50_000,
                    "events": [("fusion.2", 0, 25 * US)],
                },
            ],
        }
    ]
    with open(path, "wb") as f:
        f.write(xplane.encode_xspace(spec))
    prof = profiling.analyze_trace(path)
    # unrebased timelines would union [0,50) with [0,25) -> span/busy 50us
    assert prof.span_us == pytest.approx(75.0)
    assert prof.busy_us == pytest.approx(75.0)
    assert prof.dispatch_gap_frac == pytest.approx(0.0)


def test_multichip_attribution_uses_one_representative_plane(tmp_path):
    """A multi-chip host writes one device plane per chip. Attribution is per
    chip (like step_ms/MFU): pooling N planes would sum op self-time N×
    against one span and count idle only where EVERY chip is simultaneously
    idle — hiding per-chip dispatch gaps. The busiest plane is analyzed."""
    path = str(tmp_path / "multichip.xplane.pb")
    spec = [
        {
            "name": "/device:TPU:0",
            "lines": [
                {
                    "name": "XLA Ops",
                    "timestamp_ns": 0,
                    # 90us self-time over a 100us span: THE representative chip
                    "events": [
                        ("%convolution.1 = f32[8] convolution(%p0, %p1)", 0, 40 * US),
                        ("%dot.5 = f32[8] dot(%p2, %p3)", 50 * US, 50 * US),
                    ],
                },
            ],
        },
        {
            "name": "/device:TPU:1",
            "lines": [
                {
                    "name": "XLA Ops",
                    "timestamp_ns": 0,
                    # 30us self-time, and busy exactly where chip 0 idles —
                    # a pooled union would report zero idle
                    "events": [("%fusion.9 = f32[8] fusion(%p4)", 40 * US, 30 * US)],
                },
            ],
        },
    ]
    with open(path, "wb") as f:
        f.write(xplane.encode_xspace(spec))
    prof = profiling.analyze_trace(path)
    assert prof.source == "device"
    # chip 0 alone: 100us span, 90us busy, the 10us gap at 40us is VISIBLE
    assert prof.span_us == pytest.approx(100.0)
    assert prof.busy_us == pytest.approx(90.0)
    assert prof.dispatch_gap_frac == pytest.approx(0.10)
    # chip 1's fusion never leaks into chip 0's attribution (self-time would
    # otherwise sum to 120us against the 100us span)
    assert "fusion(elementwise)" not in prof.category_us
    assert sum(prof.category_us.values()) == pytest.approx(90.0)
    assert prof.categories["convolution"] == pytest.approx(0.40)
    assert prof.categories["matmul"] == pytest.approx(0.50)
    assert math.isclose(sum(prof.categories.values()), 1.0, abs_tol=1e-9)


def test_analyze_trace_error_contract(tmp_path):
    with pytest.raises(FileNotFoundError):
        profiling.analyze_trace(str(tmp_path))  # no trace under dir
    empty = str(tmp_path / "empty.xplane.pb")
    with open(empty, "wb") as f:
        f.write(xplane.encode_xspace([{"name": "/host:CPU", "lines": []}]))
    with pytest.raises(ValueError, match="no XLA op events"):
        profiling.analyze_trace(empty)
    # a torn write (crashed profiler, disk-full) is ValueError, never a bare
    # IndexError — the type every analysis-failure net (capture, bench)
    # catches, so a corrupt trace degrades to a warning not a dead run
    torn = str(tmp_path / "torn.xplane.pb")
    with open(torn, "wb") as f:
        f.write(b"\x80")  # varint continuation bit with no next byte
    with pytest.raises(ValueError, match="truncated or corrupt"):
        xplane.read_trace(torn)
    with pytest.raises(ValueError):
        profiling.analyze_trace(torn)
    # mid-payload cuts raise too (a Python slice would silently truncate the
    # payload and parse a confidently wrong partial trace) — the fixture is
    # one top-level plane field, so any interior cut lands inside a payload
    with open(FIXTURE, "rb") as f:
        whole = f.read()
    for cut in (len(whole) // 4, len(whole) // 2, len(whole) - 1):
        with open(torn, "wb") as f:
            f.write(whole[:cut])
        with pytest.raises(ValueError, match="truncated or corrupt"):
            xplane.read_trace(torn)


def test_shared_categorizer_is_the_one_source():
    """The dedupe satellite: scripts/profile_step.py no longer carries a
    private categorize(); every category the report emits is in CATEGORIES."""
    import ast

    script = os.path.join(
        os.path.dirname(__file__), "..", "scripts", "profile_step.py"
    )
    with open(script, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    defs = [n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
    assert "categorize" not in defs  # the CLI is thin: one categorizer, shared
    for name, _, _ in SYNTHETIC_SPEC[0]["lines"][0]["events"]:
        assert profiling.categorize(name) in profiling.CATEGORIES
    assert profiling.IDLE not in profiling.CATEGORIES  # idle is not an op


def test_categorize_matches_instruction_head_not_operands():
    """A full HLO line's operand list must never leak into the bucket: the
    consumer of a conv/collective result is categorized by what IT is —
    otherwise the copy/transpose bucket (the one the dispatch/copy audit
    exists to expose) shrinks into convolution/collective."""
    assert profiling.categorize(
        "%copy.3 = f32[8,8] copy(%convolution.2)"
    ) == "copy/transpose"
    assert profiling.categorize(
        "%fusion.4 = f32[8] fusion(%all-reduce.1), kind=kLoop"
    ) == "fusion(elementwise)"
    assert profiling.categorize(
        "%transpose.7 = f32[8,8] transpose(%reduce-window.2)"
    ) == "copy/transpose"
    # bare trace-event names (no " = ") still bucket by their own head
    assert profiling.categorize("convolution.5") == "convolution"
    assert profiling.categorize("all-reduce.9") == "collective"


# ---------------------------------------------------------------------------
# Perf-regression gate: pure pass/fail logic on synthetic baselines.


def _baseline(tmp_path, *, step_per_calib=2.0, tolerance=0.5):
    path = str(tmp_path / "PERF_BASELINE.json")
    gate_lib.update_baseline(
        path,
        "quick-cpu",
        {"step_ms": 20.0, "calib_ms": 10.0, "step_per_calib": step_per_calib},
        tolerance=tolerance,
    )
    return path


def test_gate_check_boundary_semantics():
    at_tolerance = gate_lib.check(3.0, 2.0, 0.5, key="k", metric="m")
    assert at_tolerance.passed and at_tolerance.ratio == pytest.approx(1.5)
    just_past = gate_lib.check(3.01, 2.0, 0.5, key="k", metric="m")
    assert not just_past.passed
    assert "REGRESSION" in just_past.describe()
    # much faster than baseline = pass, flagged stale (re-record nudge)
    stale = gate_lib.check(0.9, 2.0, 0.5, key="k", metric="m")
    assert stale.passed and stale.stale and "re-record" in stale.describe()
    for bad in ((0.0, 2.0, 0.5), (2.0, 0.0, 0.5), (2.0, 2.0, 0.0)):
        with pytest.raises(ValueError):
            gate_lib.check(*bad, key="k", metric="m")


def test_gate_clean_measurement_passes(tmp_path):
    baseline = gate_lib.load_baseline(_baseline(tmp_path))
    result = gate_lib.evaluate(
        baseline, "quick-cpu", {"step_ms": 21.0, "step_per_calib": 2.1}
    )
    assert result.passed and result.metric == "step_per_calib"
    assert result.tolerance == 0.5  # from the file's tolerance table


def test_gate_injected_regression_fails(tmp_path):
    """The verify.sh self-test case: a 3x injected slowdown must FAIL."""
    baseline = gate_lib.load_baseline(_baseline(tmp_path))
    result = gate_lib.evaluate(
        baseline, "quick-cpu", {"step_ms": 60.0, "step_per_calib": 6.0}
    )
    assert not result.passed and result.ratio == pytest.approx(3.0)


def test_gate_metric_and_tolerance_resolution(tmp_path):
    path = _baseline(tmp_path)
    baseline = gate_lib.load_baseline(path)
    # measurement without the ratio falls back to absolute step_ms
    absolute = gate_lib.evaluate(baseline, "quick-cpu", {"step_ms": 25.0})
    assert absolute.metric == "step_ms" and absolute.passed
    # explicit tolerance beats the file's table
    strict = gate_lib.evaluate(
        baseline, "quick-cpu", {"step_ms": 25.0}, tolerance=0.1
    )
    assert not strict.passed and strict.tolerance == 0.1
    # a tolerance table lost in a merge must NOT soften the gate to some
    # constant: the caller's mode default applies, and with none given the
    # gate refuses to guess
    orphaned = dict(baseline, tolerance={})
    fallback = gate_lib.evaluate(
        orphaned, "quick-cpu", {"step_ms": 25.0}, default_tolerance=0.08
    )
    assert not fallback.passed and fallback.tolerance == 0.08
    with pytest.raises(ValueError, match="no tolerance"):
        gate_lib.evaluate(orphaned, "quick-cpu", {"step_ms": 25.0})


def test_gate_missing_entry_and_malformed_baseline(tmp_path):
    baseline = gate_lib.load_baseline(_baseline(tmp_path))
    with pytest.raises(KeyError, match="no baseline entry"):
        gate_lib.evaluate(baseline, "tpu-v5e", {"step_ms": 1.0})
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(ValueError, match="no 'entries' key"):
        gate_lib.load_baseline(str(bad))
    # --update is the documented recovery for a malformed baseline: it must
    # rewrite a fresh file (no-entries AND torn-JSON cases), never crash
    for content in ("{}", "<<<<<<< torn"):
        bad.write_text(content)
        written = gate_lib.update_baseline(
            str(bad), "quick-cpu", {"step_per_calib": 2.0}, tolerance=0.5
        )
        assert written["entries"]["quick-cpu"] == {"step_per_calib": 2.0}
        assert gate_lib.load_baseline(str(bad))["entries"]["quick-cpu"]


def test_gate_update_preserves_other_entries(tmp_path):
    path = _baseline(tmp_path)
    gate_lib.update_baseline(path, "vgg16-tpu", {"step_ms": 77.0}, tolerance=0.08)
    baseline = gate_lib.load_baseline(path)
    assert set(baseline["entries"]) == {"quick-cpu", "vgg16-tpu"}
    assert baseline["tolerance"] == {"quick-cpu": 0.5, "vgg16-tpu": 0.08}
    # re-recording one entry leaves the other (and its tolerance) alone
    gate_lib.update_baseline(path, "quick-cpu", {"step_per_calib": 2.2})
    baseline = gate_lib.load_baseline(path)
    assert baseline["entries"]["vgg16-tpu"] == {"step_ms": 77.0}
    assert baseline["entries"]["quick-cpu"] == {"step_per_calib": 2.2}


def test_committed_baseline_is_wellformed():
    """The repo's PERF_BASELINE.json must always be loadable and carry the
    quick-cpu entry the verify stage gates against."""
    baseline = gate_lib.load_baseline()
    entry = baseline["entries"]["quick-cpu"]
    assert entry["step_per_calib"] > 0
    assert gate_lib.evaluate(baseline, "quick-cpu", entry).passed  # self-parity


# ---------------------------------------------------------------------------
# ProfileConfig / capture state machine.


def test_profile_config_validation():
    with pytest.raises(ValueError, match="steps"):
        profiling.ProfileConfig(steps=0)
    with pytest.raises(ValueError, match="skip_steps"):
        profiling.ProfileConfig(skip_steps=-1)


def test_resolve_profile():
    assert profiling.resolve_profile(None) is None
    assert profiling.resolve_profile(False) is None
    cfg = profiling.resolve_profile("/tmp/traces")
    assert isinstance(cfg, profiling.ProfileConfig) and cfg.dir == "/tmp/traces"
    same = profiling.ProfileConfig(dir="x", steps=3)
    assert profiling.resolve_profile(same) is same
    with pytest.raises(TypeError):
        profiling.resolve_profile(7)


class _Events:
    def __init__(self):
        self.emitted = []

    def emit(self, event, **fields):
        self.emitted.append({"event": event, **fields})


def test_capture_nonzero_rank_never_traces(tmp_path):
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path)), process_index=1
    )
    assert not cap.active and cap.state == "done"
    cap.maybe_start(5)
    cap.maybe_stop(10, force=True)
    assert cap.state == "done" and not os.listdir(tmp_path)


def test_capture_state_machine_skips_compile_and_is_one_shot(tmp_path):
    events = _Events()
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "prof"), steps=2, skip_steps=1),
        log=lambda *a, **k: None,
        events=events,
        process_index=0,
    )
    cap.maybe_start(0)  # step 0 = compile step: below skip prefix
    assert cap.state == "waiting"
    cap.maybe_start(2)  # first boundary past the skip (chained window of 2)
    assert cap.state == "tracing" and cap.start_step == 2
    x = jnp.ones((32, 32))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))  # traced device work
    cap.maybe_stop(3)  # 1 of 2 steps covered: keeps tracing
    assert cap.state == "tracing"
    cap.maybe_stop(4)  # window complete
    assert cap.state == "done" and cap.steps_traced == 2
    assert legacy_profiling.latest_trace_file(str(tmp_path / "prof")) is not None
    # one-shot: later boundaries are cheap no-ops
    cap.maybe_start(6)
    assert cap.state == "done"
    # the capture emitted exactly one profile_capture event (with a report
    # summary when CPU-host analysis succeeded, an error field when not)
    kinds = [e["event"] for e in events.emitted]
    assert kinds == ["profile_capture"]
    assert events.emitted[0]["steps"] == 2


def test_capture_force_stop_closes_short_epoch(tmp_path):
    cap = StepTraceCapture(
        profiling.ProfileConfig(
            dir=str(tmp_path / "p"), steps=100, skip_steps=0, analyze=False
        ),
        log=lambda *a, **k: None,
        events=None,
        process_index=0,
    )
    cap.maybe_start(1)
    assert cap.state == "tracing"
    cap.maybe_stop(3)  # 2 of 100: stays open
    assert cap.state == "tracing"
    cap.maybe_stop(3, force=True)  # epoch ended
    assert cap.state == "done" and cap.steps_traced == 2


def test_capture_skip_is_process_local_not_epoch_index(tmp_path):
    """A mid-epoch resume starts at a large epoch-local step index, but the
    resumed process's FIRST dispatched unit still pays XLA compilation — the
    skip prefix must count units this process ran, not trust step_in_epoch."""
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "p"), steps=2, analyze=False),
        log=lambda *a, **k: None,
        events=None,
        process_index=0,
    )
    # resumed at step 40: the first unit (the compile payer) is NOT traced
    cap.maybe_start(40)
    assert cap.state == "waiting"
    cap.maybe_stop(42)  # compile unit completed (chained window of 2)
    cap.maybe_start(42)  # second unit: past the process-local skip prefix
    assert cap.state == "tracing" and cap.start_step == 42
    cap.maybe_stop(44, force=True)
    assert cap.state == "done" and cap.steps_traced == 2


def test_capture_skip_longer_than_epoch_accumulates_across_epochs(tmp_path):
    """skip_steps >= steps-per-epoch must delay the capture into a later
    epoch, not silently never fire (the count does not reset per epoch)."""
    cap = StepTraceCapture(
        profiling.ProfileConfig(
            dir=str(tmp_path / "p"), steps=1, skip_steps=5, analyze=False
        ),
        log=lambda *a, **k: None,
        events=None,
        process_index=0,
    )
    # epoch 1: 4 steps in 2-step windows — all inside the skip prefix
    for s in (0, 2):
        cap.maybe_start(s)
        cap.maybe_stop(s + 2)
    assert cap.state == "waiting"  # 4 of 5 skip steps seen
    # epoch 2: the first window finishes the prefix, the second is traced
    cap.maybe_start(0)
    cap.maybe_stop(2)
    cap.maybe_start(2)
    assert cap.state == "tracing" and cap.start_step == 2
    cap.maybe_stop(4, force=True)
    assert cap.state == "done" and cap.steps_traced == 2


def test_capture_start_failure_never_kills_training(tmp_path, monkeypatch):
    """An unwritable trace dir or an already-active profiler session must
    degrade to a warning that parks the capture in 'done' — the same
    never-kill-training policy the analysis path enforces."""
    warnings = []
    events = _Events()
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "p"), skip_steps=0),
        log=lambda msg, log_type="info": warnings.append((log_type, msg)),
        events=events,
        process_index=0,
    )
    monkeypatch.setattr(
        jax.profiler,
        "start_trace",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("session active")),
    )
    cap.maybe_start(0)  # must not raise
    assert cap.state == "done"
    assert any(t == "warning" for t, _ in warnings)
    assert events.emitted and "error" in events.emitted[0]


def test_capture_abort_stops_session_without_analysis(tmp_path):
    """Exception-path teardown (maybe_stop(abort=True)) must close the
    profiler session WITHOUT paying trace analysis or the roofline probe
    compile — an emergency save racing a preemption grace window cannot
    wait on either. The raw trace still lands on disk."""
    called = []
    events = _Events()
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "p"), steps=100, skip_steps=0),
        log=lambda *a, **k: None,
        events=events,
        process_index=0,
        flops_source=lambda: called.append("probe"),
    )
    cap.maybe_start(0)
    jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((8, 8))))
    cap.maybe_stop(1, force=True, abort=True)
    assert cap.state == "done"
    assert called == [] and cap.report is None  # no probe, no parse
    assert legacy_profiling.latest_trace_file(str(tmp_path / "p")) is not None
    # the raw capture record still lands in the event log
    assert [e["event"] for e in events.emitted] == ["profile_capture"]
    assert "error" not in events.emitted[0]


def test_capture_passes_flops_source_to_analysis(tmp_path, monkeypatch):
    """The roofline join: a completed capture evaluates its lazy flops_source
    and hands the mapping to analyze_trace, so Trainer(profile=...) reports
    carry the documented FLOPs/bytes/intensity columns."""
    from distributed_training_pytorch_tpu.profiling import report as report_mod

    sentinel = {"convolution.1": {"flops": 1e9, "bytes": 1e6, "arith_intensity": 1e3}}
    seen = {}
    real_analyze = report_mod.analyze_trace

    def spy(path, **kw):
        seen.update(kw)
        return real_analyze(FIXTURE, **kw)  # deterministic device-plane trace

    monkeypatch.setattr(report_mod, "analyze_trace", spy)
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "p"), steps=1, skip_steps=0),
        log=lambda *a, **k: None,
        events=None,
        process_index=0,
        flops_source=lambda: sentinel,
    )
    cap.maybe_start(0)
    jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((8, 8))))
    cap.maybe_stop(1)
    assert cap.state == "done"
    assert seen["flops_by_op"] is sentinel
    joined = {r.name: r for r in cap.report.top_ops}
    conv = next(r for n, r in joined.items() if n.startswith("%convolution.1"))
    assert conv.flops == 1e9 and conv.arith_intensity == pytest.approx(1e3)


def test_capture_flops_source_failure_degrades_to_warning(tmp_path):
    """A probe compile that fails (OOM, custom step, lowering error) must
    cost only the roofline columns — the attribution report still lands."""
    warnings = []
    cap = StepTraceCapture(
        profiling.ProfileConfig(dir=str(tmp_path / "p"), steps=1, skip_steps=0),
        log=lambda msg, log_type="info": warnings.append((log_type, msg)),
        events=None,
        process_index=0,
        flops_source=lambda: (_ for _ in ()).throw(RuntimeError("probe failed")),
    )
    cap.maybe_start(0)
    jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((8, 8))))
    cap.maybe_stop(1)  # must not raise
    assert cap.state == "done"
    assert any(t == "warning" and "roofline join" in m for t, m in warnings)


# ---------------------------------------------------------------------------
# Trainer integration: the acceptance pillars.


def test_trainer_rejects_profile_with_legacy_profile_dir(tmp_path, mesh):
    with pytest.raises(ValueError, match="not both"):
        make_tiny(tmp_path, mesh, profile="x", profile_dir=str(tmp_path / "y"))
    # profile=False means OFF — it composes with the legacy knob
    trainer = make_tiny(tmp_path, mesh, profile=False, profile_dir=str(tmp_path / "y"))
    assert trainer._profile_capture is None


def test_trainer_abort_mid_capture_stops_profiler_session(tmp_path, mesh):
    """An exception with the capture window open (anomaly raise, watchdog)
    must still stop the process-global jax.profiler session — a leaked
    session would fail every later start_trace in this process."""
    from distributed_training_pytorch_tpu.fault import FaultPlan

    plan = FaultPlan().add("nan_loss", epoch=0, step=3)
    trainer = make_tiny(
        tmp_path,
        mesh,
        profile=profiling.ProfileConfig(steps=100),  # analyze=True: the default
        chain_steps=1,
        fault_plan=plan,
        nan_policy="raise",
    )
    with pytest.raises(Exception, match="[Nn]on-finite|nan"):
        trainer.train()
    assert trainer._profile_capture.state == "done"  # closed, not leaked
    # abort teardown skipped analysis: no report, no probe compile paid
    assert trainer._profile_capture.report is None
    # the proof: a fresh trace session starts cleanly afterwards
    with legacy_profiling.trace(str(tmp_path / "after")):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))


def test_trainer_abort_with_legacy_profile_dir_stops_session(tmp_path, mesh):
    """The legacy profile_dir bracket holds the same process-global
    jax.profiler session as the ProfileConfig capture: an abort while it is
    tracing must stop it too, or every later start_trace in this process
    fails."""
    from distributed_training_pytorch_tpu.fault import FaultPlan

    plan = FaultPlan().add("nan_loss", epoch=0, step=3)
    trainer = make_tiny(
        tmp_path,
        mesh,
        profile_dir=str(tmp_path / "prof"),
        chain_steps=1,
        fault_plan=plan,
        nan_policy="raise",
    )
    with pytest.raises(Exception, match="[Nn]on-finite|nan"):
        trainer.train()
    assert trainer._profiled is True  # closed, not leaked
    # the proof: a fresh trace session starts cleanly afterwards
    with legacy_profiling.trace(str(tmp_path / "after")):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))


def test_trainer_preemption_stops_capture_without_analysis(tmp_path, mesh):
    """A preemption-interrupted epoch is on the emergency-save clock: the
    still-open capture must be force-stopped WITHOUT trace analysis or the
    roofline probe compile (the exception-teardown contract), so the grace
    window goes to the checkpoint, not a report."""
    trainer = make_tiny(
        tmp_path,
        mesh,
        profile=profiling.ProfileConfig(steps=100),  # window outlives the run
        chain_steps=1,
    )
    trainer._preemption_requested = lambda step: step >= 4
    trainer.train()
    assert trainer._epoch_interrupted is True  # the preemption branch ran
    cap = trainer._profile_capture
    assert cap.state == "done"  # session closed, not leaked
    assert cap.report is None  # analysis skipped: no parse, no probe compile
    # the proof the process-global session was released:
    with legacy_profiling.trace(str(tmp_path / "after")):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))


def test_encode_rejects_negative_varint_fields():
    """Arithmetic right-shift floors at -1: a negative timestamp/duration fed
    to the write-side codec must raise, not hang appending 0xFF forever."""
    spec = [{"name": "p", "lines": [{"name": "l", "timestamp_ns": -1, "events": []}]}]
    with pytest.raises(ValueError, match="varint"):
        xplane.encode_xspace(spec)


def test_trainer_flops_index_honest_under_chaining(tmp_path, mesh):
    """chain_steps > 1 traces the chained-scan executable, whose per-module
    instruction numbering does not line up with the single-step probe's — the
    roofline join must be SKIPPED (None), not attach a different
    instruction's flops to a colliding name. Single-step runs keep it."""
    chained = make_tiny(tmp_path, mesh, max_epoch=1, chain_steps=2,
                        telemetry="on", save_folder=str(tmp_path / "c"))
    chained.train()
    assert chained._abstract_batch is not None  # shapes known; gate is chaining
    assert chained._profile_flops_index() is None
    single = make_tiny(tmp_path, mesh, max_epoch=1, chain_steps=1,
                       telemetry="on", save_folder=str(tmp_path / "s"))
    single.train()
    index = single._profile_flops_index()
    assert index and all("flops" in row for row in index.values())


def test_trainer_profile_off_is_the_historical_program(tmp_path, mesh):
    """THE acceptance test: profile=None (the default) and a profile=-on run
    have identical TrainEngine.trace_counts (same compiles, same dispatch
    structure) and bit-exact final params — the capture observes the run at
    unit boundaries, it never alters execution."""
    off = make_tiny(tmp_path / "off", mesh)
    off.train()
    on = make_tiny(
        tmp_path / "on",
        mesh,
        profile=profiling.ProfileConfig(steps=2, analyze=False),
    )
    on.train()
    assert dict(off.engine.trace_counts) == dict(on.engine.trace_counts)
    assert_trees_equal(off.state.params, on.state.params)
    assert_trees_equal(off.state.opt_state, on.state.opt_state)
    # off = historical: no capture object, no profile dir
    assert off._profile_capture is None
    assert not os.path.exists(os.path.join(off.save_folder, "profile"))
    # on actually captured a window of the real chained run into the default
    # <save_folder>/profile location
    cap = on._profile_capture
    assert cap is not None and cap.state == "done" and cap.steps_traced >= 2
    assert legacy_profiling.latest_trace_file(
        os.path.join(on.save_folder, "profile")
    ) is not None
