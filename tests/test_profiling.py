"""utils/profiling: trace capture + headless xplane parsing (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.utils import profiling


def test_trace_writes_xplane_and_parser_reads_it(tmp_path):
    with profiling.trace(str(tmp_path)):
        with profiling.annotate("tiny_matmul"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    path = profiling.latest_trace_file(str(tmp_path))
    assert path is not None and path.endswith(".xplane.pb")
    # On the CPU test platform there are no TPU/GPU device planes, so the op
    # table is empty — but the wire-format parse itself must succeed.
    ops = profiling.top_ops(str(tmp_path))
    assert isinstance(ops, list)
    for name, total_us, count in ops:
        assert isinstance(name, str) and total_us >= 0 and count >= 1


def test_top_ops_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        profiling.top_ops(str(tmp_path / "nope"))


def test_varint_fields_roundtrip():
    """The hand-rolled protobuf reader handles all wire types it claims."""
    # field 1 varint=300, field 2 bytes"abc", field 3 fixed32, field 4 fixed64
    buf = (
        b"\x08\xac\x02"  # 1<<3|0, varint 300
        b"\x12\x03abc"  # 2<<3|2, len 3
        b"\x1d\x01\x00\x00\x00"  # 3<<3|5
        b"\x21\x02\x00\x00\x00\x00\x00\x00\x00"  # 4<<3|1
    )
    fields = list(profiling._fields(buf))
    assert fields[0] == (1, 0, 300)
    assert fields[1] == (2, 2, b"abc")
    assert fields[2][0] == 3 and len(fields[2][2]) == 4
    assert fields[3][0] == 4 and len(fields[3][2]) == 8
