import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.models.vgg import adaptive_avg_pool_2d


def test_adaptive_pool_identity():
    x = jnp.arange(2 * 7 * 7 * 3, dtype=jnp.float32).reshape(2, 7, 7, 3)
    assert (adaptive_avg_pool_2d(x, (7, 7)) == x).all()


def test_adaptive_pool_downsample_matches_torch_semantics():
    # 4 -> 2: torch bins are [0:2], [2:4]
    x = jnp.asarray(np.arange(4, dtype=np.float32)).reshape(1, 4, 1, 1)
    out = adaptive_avg_pool_2d(x, (2, 1))
    np.testing.assert_allclose(np.asarray(out).ravel(), [0.5, 2.5])


def test_adaptive_pool_upsample_replicates():
    # 1 -> 7: every output bin covers the single input pixel
    x = jnp.full((1, 1, 1, 2), 3.0)
    out = adaptive_avg_pool_2d(x, (7, 7))
    assert out.shape == (1, 7, 7, 2)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_vgg16_forward_shapes_and_param_count():
    # eval_shape: the full 134M-param model never materializes (init + forward
    # of the real thing costs ~20s of CPU suite time for shape-only checks).
    model = VGG16(num_classes=3)
    variables = jax.eval_shape(model.init, jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"]))
    # torchvision VGG16 with 3 classes: 134_285_128 params minus head diff.
    # conv: 14_714_688; fc: 512*7*7*4096+4096 + 4096*4096+4096 + 4096*3+3
    expected = 14_714_688 + (512 * 7 * 7 * 4096 + 4096) + (4096 * 4096 + 4096) + (4096 * 3 + 3)
    assert n_params == expected
    logits = jax.eval_shape(model.apply, variables, jnp.zeros((2, 32, 32, 3)))
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32


def test_vgg16_bf16_compute_f32_params():
    model = VGG16(num_classes=3, dtype=jnp.bfloat16)
    variables = jax.eval_shape(model.init, jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))
    logits = jax.eval_shape(model.apply, variables, jnp.zeros((2, 32, 32, 3)))
    assert logits.dtype == jnp.float32


def test_vgg16_dropout_active_in_train_mode():
    # slim stages: dropout lives in the classifier head, conv width irrelevant
    model = VGG16(
        num_classes=3,
        dropout_rate=0.5,
        stage_features=(4, 8),
        stage_layers=(1, 1),
        classifier_widths=(64,),
    )
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.ones((4, 32, 32, 3))
    a = model.apply(variables, x, train=True, rngs={"dropout": jax.random.key(1)})
    b = model.apply(variables, x, train=True, rngs={"dropout": jax.random.key(2)})
    assert not np.allclose(np.asarray(a), np.asarray(b))
