import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from distributed_training_pytorch_tpu.ops import cross_entropy_loss, accuracy, multistep_lr
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss


class TinyMLP(nn.Module):
    num_classes: int = 3

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def criterion(logits, batch):
    loss = cross_entropy_loss(logits, batch["label"])
    return loss, {"ce_loss": loss, "accuracy": accuracy(logits, batch["label"])}


def make_engine(accum_steps=1, schedule=None):
    mesh = mesh_lib.create_mesh()
    model = TinyMLP()
    tx = optax.sgd(schedule if schedule else 0.05, momentum=0.9)
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        tx,
        mesh,
        accum_steps=accum_steps,
        schedule=schedule,
    )
    state = engine.init_state(
        jax.random.key(0), lambda rng: model.init(rng, jnp.zeros((1, 4, 4, 3)))
    )
    return engine, state


def synthetic_batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 3, size=(n,)).astype(np.int32)
    # class-dependent mean makes the task learnable
    images = rng.randn(n, 4, 4, 3).astype(np.float32) + labels[:, None, None, None]
    return {"image": images, "label": labels}


def test_train_step_runs_and_loss_decreases(devices):
    engine, state = make_engine()
    batch = engine.shard_batch(synthetic_batch())
    losses = []
    for _ in range(30):
        state, metrics = engine.train_step(state, batch)
        losses.append(float(metrics["ce_loss"]))
    assert losses[-1] < losses[0] * 0.5, losses
    assert int(state.step) == 30


def test_eval_step_metrics(devices):
    engine, state = make_engine()
    batch = engine.shard_batch(synthetic_batch())
    for _ in range(50):
        state, _ = engine.train_step(state, batch)
    metrics = engine.eval_step(state, batch)
    assert float(metrics["accuracy"]) > 0.8


def test_grad_accum_matches_full_batch(devices):
    # Same data, same init: accum_steps=4 must equal accum_steps=1 with SGD
    batch_np = synthetic_batch(32)
    engine1, state1 = make_engine(accum_steps=1)
    engine4, state4 = make_engine(accum_steps=4)
    b1 = engine1.shard_batch(batch_np)
    b4 = engine4.shard_batch(batch_np)
    for _ in range(3):
        state1, m1 = engine1.train_step(state1, b1)
        state4, m4 = engine4.train_step(state4, b4)
    for p1, p4 in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state4.params), strict=True):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p4), rtol=2e-4, atol=2e-5)


def test_schedule_reported_and_applied(devices):
    sched = multistep_lr(0.1, milestones=[1], gamma=0.1, steps_per_epoch=2)
    engine, state = make_engine(schedule=sched)
    batch = engine.shard_batch(synthetic_batch())
    _, m0 = engine.train_step(state, batch)
    assert np.isclose(float(m0["lr"]), 0.1)
    assert np.isclose(float(sched(2)), 0.01)


def test_determinism_same_seed(devices):
    engine_a, state_a = make_engine()
    engine_b, state_b = make_engine()
    batch = engine_a.shard_batch(synthetic_batch())
    for _ in range(3):
        state_a, _ = engine_a.train_step(state_a, batch)
        state_b, _ = engine_b.train_step(state_b, batch)
    for pa, pb in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params), strict=True):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_state_sharding_rejects_foreign_state(devices):
    """Regression (round-1 VERDICT): a reused engine applied the FIRST state's
    cached sharding tree to any later state; now a different tree structure
    raises instead of mis-sharding silently."""
    import pytest

    engine, state = make_engine()

    class OtherMLP(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.Dense(8)(x)
            x = nn.Dense(16)(x)  # extra layer -> different param tree
            return nn.Dense(3)(x)

    other = OtherMLP()
    with pytest.raises(ValueError, match="different structure or leaf shapes"):
        engine.init_state(
            jax.random.key(1), lambda rng: other.init(rng, jnp.zeros((1, 4, 4, 3)))
        )

    class SameTreeDifferentWidth(nn.Module):
        # same layer count as TinyMLP -> identical tree STRUCTURE, different
        # leaf shapes; must still be rejected.
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape(x.shape[0], -1)
            x = nn.Dense(64)(x)
            x = nn.relu(x)
            return nn.Dense(3)(x)

    widened = SameTreeDifferentWidth()
    with pytest.raises(ValueError, match="different structure or leaf shapes"):
        engine.init_state(
            jax.random.key(2), lambda rng: widened.init(rng, jnp.zeros((1, 4, 4, 3)))
        )
    # The original state keeps working.
    batch = engine.shard_batch(synthetic_batch())
    state, metrics = engine.train_step(state, batch)
    assert np.isfinite(float(metrics["ce_loss"]))


def test_chained_steps_match_sequential(devices):
    """compile_chained_train_steps(K) == K sequential train_steps (same RNG
    advance via state.step, same params) — the bench's one-dispatch window."""
    batch_np = synthetic_batch(16)
    eng_a, state_a = make_engine()
    eng_b, state_b = make_engine()
    ba = eng_a.shard_batch(batch_np)
    bb = eng_b.shard_batch(batch_np)
    for _ in range(4):
        state_a, m_a = eng_a.train_step(state_a, ba)
    chained = eng_b.compile_chained_train_steps(state_b, bb, 4)
    state_b, m_b = chained(state_b, bb)
    assert int(state_b.step) == int(state_a.step) == 4
    np.testing.assert_allclose(float(m_b["ce_loss"]), float(m_a["ce_loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
