"""Sharded training end-to-end (ISSUE 10): FSDP/TP meshes in the real
Trainer/TrainEngine hot path.

The acceptance pillars, each test-enforced here (the heavyweight
kill/resume + full-trainer parity legs live in ``scripts/sharding_smoke.py``
— verify.sh stage 7 — so the tier-1 suite stays fast):

* **Mesh parity** — an ``fsdp=8`` engine run is BIT-EXACT with pure DP
  (losses and params; the batch stays 8-way sharded so every reduction has
  the same participant order), and a sharded INIT reproduces the
  replicated init bit-for-bit (``jax_threefry_partitionable``, forced on
  in PR 1 for exactly this).
* **Chained windows on sharded state** — bit-exact with sharded
  single-step execution, one compile per shape (the PR-2 invariants
  extended to SPMD).
* **Resharding checkpoints** — a checkpoint written under one mesh
  restores under another (DP <-> FSDP both directions) value-exact, with
  the sharding-metadata record in meta and a ``checkpoint_reshard`` event.
* **Historical program** — a pure-DP mesh with the sharding knobs at their
  defaults lowers the byte-identical program the pre-sharding engine did.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_training_pytorch_tpu.checkpoint.manager import CheckpointManager
from distributed_training_pytorch_tpu.models.vit import ViTTiny
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel import sharding as sharding_lib
from distributed_training_pytorch_tpu.parallel import transformer_tp_rules
from distributed_training_pytorch_tpu.telemetry import mfu as mfu_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss


def criterion(logits, batch):
    loss = cross_entropy_loss(logits, batch["label"])
    return loss, {"loss": loss}


def make_vit_engine(mesh, rules=None, fsdp_min_size=1024):
    model = ViTTiny(num_classes=4)
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        sharding_rules=rules,
        fsdp_min_size=fsdp_min_size,
    )
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
    )
    return engine, state


def host_batch(seed=0, n=16):
    rng = np.random.RandomState(seed)
    return {
        "image": rng.randn(n, 16, 16, 3).astype(np.float32),
        "label": rng.randint(0, 4, size=(n,)).astype(np.int32),
    }


def trees_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(
            jax.tree.leaves(jax.device_get(a)),
            jax.tree.leaves(jax.device_get(b)),
            strict=True,
        )
    )


# ---------------------------------------------------------------------------
# Mesh-spec grammar + batch-shard extent (the shared MESH/BENCH_MESH knob).


def test_mesh_config_from_spec_grammar():
    assert mesh_lib.mesh_config_from_spec("dp8") == mesh_lib.MeshConfig(data=8)
    assert mesh_lib.mesh_config_from_spec("fsdp4x2") == mesh_lib.MeshConfig(
        data=2, fsdp=4
    )
    assert mesh_lib.mesh_config_from_spec("tp2x4") == mesh_lib.MeshConfig(
        data=4, tensor=2
    )
    assert mesh_lib.mesh_config_from_spec("dp2fsdp2tp2") == mesh_lib.MeshConfig(
        data=2, fsdp=2, tensor=2
    )
    assert mesh_lib.mesh_config_from_spec("fsdp8") == mesh_lib.MeshConfig(
        data=1, fsdp=8
    )


@pytest.mark.parametrize("bad", ["", "bogus3", "dp2dp4", "fsdp2y4", "8dp"])
def test_mesh_config_from_spec_rejects(bad):
    with pytest.raises(ValueError):
        mesh_lib.mesh_config_from_spec(bad)


def test_batch_shard_extent(devices):
    assert mesh_lib.batch_shard_extent(mesh_lib.create_mesh({"data": 8})) == 8
    assert (
        mesh_lib.batch_shard_extent(
            mesh_lib.create_mesh({"data": 2, "fsdp": 2, "tensor": 2})
        )
        == 4
    )
    assert (
        mesh_lib.batch_shard_extent(mesh_lib.create_mesh({"data": 2, "tensor": 4}))
        == 2
    )


def test_throughput_fields_divide_by_batch_replicas(devices):
    mesh = mesh_lib.create_mesh({"data": 2, "tensor": 4})
    fields = mfu_lib.throughput_fields(800.0, mesh)
    assert fields["items_per_sec_chip"] == 100.0  # 8 devices
    assert fields["items_per_sec_replica"] == 400.0  # 2 batch replicas
    assert fields["batch_replicas"] == 2


# ---------------------------------------------------------------------------
# Shard-byte accounting + the checkpoint sharding record.


def test_sharding_record_and_shard_bytes(devices):
    mesh = mesh_lib.create_mesh({"data": 2, "fsdp": 4})
    tree = {
        "kernel": jax.device_put(
            np.ones((48, 512), np.float32), NamedSharding(mesh, P(None, "fsdp"))
        ),
        "bias": jax.device_put(np.ones((32,), np.float32), NamedSharding(mesh, P())),
    }
    record = sharding_lib.sharding_record(tree)
    assert record["mesh"] == {"data": 2, "fsdp": 4}
    assert list(record["specs"].values()) == [str(P(None, "fsdp"))]
    # replicated-only trees carry no record (pre-sharding compatibility)
    assert (
        sharding_lib.sharding_record(
            {"b": jax.device_put(np.ones(4, np.float32), NamedSharding(mesh, P()))}
        )
        is None
    )
    # per-device bytes from the leaves' own shardings
    assert sharding_lib.tree_shard_bytes(tree) == 48 * 512 * 4 / 4 + 32 * 4


# ---------------------------------------------------------------------------
# Engine parity + sharded init (the fast acceptance core; the full-model
# trainer legs live in scripts/sharding_smoke.py).


@pytest.fixture(scope="module")
def parity_runs(devices):
    def run(mesh, rules=None):
        engine, state = make_vit_engine(mesh, rules)
        init_params = jax.device_get(state.params)
        losses = []
        for i in range(3):
            batch = engine.shard_batch(host_batch(seed=i))
            state, m = engine.train_step(state, batch)
            losses.append(float(m["loss"]))
        return engine, state, losses, init_params

    dp = run(mesh_lib.create_mesh({"data": 8}))
    fsdp8 = run(mesh_lib.MeshConfig(data=1, fsdp=8).build())
    mixed = run(
        mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2).build(),
        rules=transformer_tp_rules(),
    )
    return {"dp": dp, "fsdp8": fsdp8, "mixed": mixed}


def test_fsdp_mesh_bit_exact_with_dp(parity_runs):
    _, dp_state, dp_losses, _ = parity_runs["dp"]
    engine, state, losses, _ = parity_runs["fsdp8"]
    assert losses == dp_losses  # bit-exact, not allclose
    assert trees_equal(state.params, dp_state.params)
    assert trees_equal(state.opt_state, dp_state.opt_state)
    specs = [str(leaf.sharding.spec) for leaf in jax.tree.leaves(state.params)]
    assert any("fsdp" in s for s in specs), specs


def test_sharded_init_bit_exact_with_replicated(parity_runs):
    # Sharded init (init_state jitted with sharded out_shardings — no
    # replicate-then-reshard step) must produce the same numbers the
    # replicated init does: threefry partitionable makes per-shard key
    # streams location-invariant.
    _, _, _, dp_init = parity_runs["dp"]
    for name in ("fsdp8", "mixed"):
        _, _, _, init = parity_runs[name]
        assert trees_equal(init, dp_init), name


def test_tp_mesh_matches_dp_to_float_ulp(parity_runs):
    # TP contraction splits + 4-way batch shards legally regroup float
    # sums: first step is bit-exact, the trajectory tracks DP at f32 ULP.
    _, _, dp_losses, _ = parity_runs["dp"]
    engine, state, losses, _ = parity_runs["mixed"]
    assert losses[0] == dp_losses[0]
    np.testing.assert_allclose(losses, dp_losses, rtol=0, atol=5e-6)
    specs = {
        jax.tree_util.keystr(p): str(leaf.sharding.spec)
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    assert any("tensor" in s for s in specs.values()), specs
    assert any("fsdp" in s for s in specs.values()), specs


def test_chained_window_bit_exact_on_sharded_state(parity_runs, devices):
    """PR-2's chained ≡ sequential invariant on genuinely sharded state:
    one chained window of 3 steps == 3 single steps, bit-exact, compiled
    exactly once."""
    mesh = mesh_lib.MeshConfig(data=1, fsdp=8).build()
    engine, state = make_vit_engine(mesh)
    batches = [host_batch(seed=10 + i) for i in range(3)]
    seq_state = state
    for hb in batches:
        seq_state, _ = engine.train_step(seq_state, engine.shard_batch(hb))

    chained_engine, chained_state = make_vit_engine(mesh)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    window = mesh_lib.global_chain_array_from_host_local(stacked, mesh)
    chained_state, metrics = chained_engine.train_steps_chained(
        chained_state, window, 3
    )
    assert trees_equal(chained_state.params, seq_state.params)
    assert trees_equal(chained_state.opt_state, seq_state.opt_state)
    assert chained_engine.trace_counts["chained_3"] == 1
    assert jax.tree.leaves(metrics)[0].shape[0] == 3  # per-step scan outputs


def test_chained_prefetch_window_shards_batch_axis(devices):
    """device_prefetch_chained's staging layout on an fsdp mesh: the
    leading (step) axis stays whole, the batch axis splits over data x
    fsdp — per-chip H2D bytes are global/extent, the tentpole's staging
    claim."""
    mesh = mesh_lib.create_mesh({"data": 2, "fsdp": 4})
    stacked = jax.tree.map(
        lambda *xs: np.stack(xs), *[host_batch(seed=i) for i in range(2)]
    )
    window = mesh_lib.global_chain_array_from_host_local(stacked, mesh)
    leaf = window["image"]
    assert leaf.shape == (2, 16, 16, 16, 3)
    shard = leaf.addressable_shards[0].data
    assert shard.shape == (2, 2, 16, 16, 3)  # batch 16 / (data*fsdp = 8)


# ---------------------------------------------------------------------------
# Resharding checkpoints.


@pytest.fixture(scope="module")
def reshard_states(parity_runs):
    return parity_runs["dp"][:2], parity_runs["fsdp8"][:2]


def test_checkpoint_reshards_both_directions(tmp_path, reshard_states):
    (dp_engine, dp_state), (f_engine, f_state) = reshard_states
    events = tmp_path / "events.jsonl"

    class Log:
        enabled = True

        def emit(self, event, **fields):
            with open(events, "a") as f:
                f.write(json.dumps({"event": event, **fields}) + "\n")

    mgr = CheckpointManager(os.fspath(tmp_path / "ckpt"))
    mgr.event_log = Log()
    # FSDP -> DP
    mgr.save("sharded", f_state, epoch=1)
    mgr.wait()
    meta = mgr.read_meta("sharded")
    assert meta["sharding"]["mesh"] == {"data": 1, "fsdp": 8}
    assert meta["sharding"]["specs"]  # non-replicated leaves recorded
    restored, _ = mgr.restore("sharded", dp_state)
    assert trees_equal(restored.params, f_state.params)
    assert all(
        "fsdp" not in str(leaf.sharding.spec)
        for leaf in jax.tree.leaves(restored.params)
    )
    # DP -> FSDP
    mgr.save("replicated", dp_state, epoch=1)
    mgr.wait()
    assert "sharding" not in mgr.read_meta("replicated")  # pure DP: no record
    restored_f, _ = mgr.restore("replicated", f_state)
    assert trees_equal(restored_f.params, dp_state.params)
    assert any(
        "fsdp" in str(leaf.sharding.spec)
        for leaf in jax.tree.leaves(restored_f.params)
    )
    recorded = [json.loads(line) for line in open(events)]
    reshard = [e for e in recorded if e["event"] == "checkpoint_reshard"]
    assert len(reshard) == 2
    assert reshard[0]["from_mesh"] == {"data": 1, "fsdp": 8}
    assert reshard[0]["to_mesh"] is None  # DP target carries no record


def test_async_saver_records_live_sharding(tmp_path, reshard_states):
    from distributed_training_pytorch_tpu.resilience import AsyncCheckpointSaver

    _, (f_engine, f_state) = reshard_states
    mgr = CheckpointManager(os.fspath(tmp_path / "async_ckpt"))
    with AsyncCheckpointSaver(mgr) as saver:
        saver.save_async("snap", f_state, epoch=2)
        saver.flush()
    meta = mgr.read_meta("snap")
    # the snapshot is host numpy — the record must have been captured from
    # the live sharded arrays before device_get stripped it
    assert meta["sharding"]["mesh"] == {"data": 1, "fsdp": 8}


# ---------------------------------------------------------------------------
# Historical-program parity (the PR-3/4/6/8 opt-in convention).


def test_pure_dp_default_program_byte_identical(devices):
    """A pure-DP engine with the sharding knobs untouched and one with an
    explicitly-empty rule list lower byte-identical programs: the sharding
    machinery is opt-in by MESH, and a data-only mesh reproduces the
    historical program exactly."""
    mesh = mesh_lib.create_mesh({"data": 8})
    default_engine, state = make_vit_engine(mesh, rules=None, fsdp_min_size=2**18)
    explicit_engine = TrainEngine(
        default_engine.loss_fn,
        default_engine.optimizer,
        mesh,
        sharding_rules=(),
        fsdp_min_size=2**18,
    )
    batch = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        host_batch(),
    )
    a = default_engine.lower_step_probe(state, batch, donate=True).as_text()
    b = explicit_engine.lower_step_probe(state, batch, donate=True).as_text()
    assert a == b


# ---------------------------------------------------------------------------
# Trainer surface: divisibility validation + auto rule resolution.


def test_trainer_rejects_indivisible_batch(tmp_path, devices):
    from test_trainer import ToyTrainer

    mesh = mesh_lib.create_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    with pytest.raises(ValueError, match="batch-shard extent"):
        ToyTrainer(
            max_epoch=1,
            batch_size=18,  # not divisible by data x fsdp = 4
            save_folder=os.fspath(tmp_path),
            mesh=mesh,
            progress=False,
            num_workers=0,
        )


def test_trainer_auto_rules_resolve_by_mesh(tmp_path, devices):
    from test_trainer import ToyTrainer

    # No full construction needed to test the hook's resolution rule:
    # build_sharding_rules reads only self.mesh.
    class Probe:
        pass

    probe = Probe()
    probe.mesh = mesh_lib.create_mesh({"data": 2, "tensor": 4})
    rules = ToyTrainer.build_sharding_rules(probe)
    assert rules and any("qkv" in pattern for pattern, _ in rules)
    probe.mesh = mesh_lib.create_mesh({"data": 8})
    assert ToyTrainer.build_sharding_rules(probe) is None


def test_tp_rules_cover_the_lm_naming(devices):
    """ISSUE 10: transformer_lm shards via transformer_tp_rules — its
    attn_out/mlp_in/mlp_out/embed naming must actually match (the ViT-only
    rule set silently left the LM replicated)."""
    from distributed_training_pytorch_tpu.models.transformer_lm import LMTiny

    mesh = mesh_lib.create_mesh({"data": 4, "tensor": 2})
    model = LMTiny(vocab_size=64)

    def lm_loss(params, model_state, batch, rng, train):
        logits = model.apply({"params": params}, batch["tokens"], train=train,
                             rngs={"dropout": rng} if train else None)
        loss = cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), batch["labels"].reshape(-1)
        )
        return loss, ({"loss": loss}, model_state)

    engine = TrainEngine(
        lm_loss, optax.sgd(0.01), mesh,
        sharding_rules=transformer_tp_rules(), fsdp_min_size=2**30,
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
    )
    specs = {
        jax.tree_util.keystr(p): str(leaf.sharding.spec)
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    tp_sharded = [k for k, s in specs.items() if "tensor" in s]
    assert any("qkv" in k for k in tp_sharded), specs
    assert any("attn_out" in k for k in tp_sharded), specs
    assert any("mlp_in" in k for k in tp_sharded), specs
    assert any("mlp_out" in k for k in tp_sharded), specs
    assert any("embed" in k for k in tp_sharded), specs
