"""ISSUE 16 tests: the closed-loop fleet controller's policy engine and
its seams.

Acceptance pillars:

* the :class:`telemetry.controller.RunPolicy` action catalog — dead ->
  restart (subprocess exit acts immediately, log-silence debounces),
  persistent named-chip straggler -> exclude-and-replan, persistent
  tunable alert -> ONE bounded knob change A/B-judged into keep/revert —
  with every action carrying the evidence rows that justified it;
* safety rails, test-enforced: the max-restarts budget ends in ONE
  ``give_up`` then silence, a zero budget refuses outright, exponential
  backoff gates consecutive respawns, never two concurrent actions per
  run, and a respawn's verdict-driven triggers stay gated until the NEW
  attempt reports (no budget-burning flaps off stale status);
* the monotonic ``attempt`` sidecar (``claim_attempt``/``peek_attempt``);
* the deterministic degraded-chip seam: ``FaultPlan`` kind ``slow_chip``
  (membership checked before budget) through
  ``straggler.sample_arrivals``'s injected delay;
* ``parallel.elastic.replan_excluding`` — exclusion as a plain elastic
  shrink, int-only (plannable without a jax backend);
* the doctor's attempt-aware late-compile rule: a resumed attempt's
  starting-epoch recompiles are warmup, not the retrace signature.
"""

import math
import types

import pytest

from distributed_training_pytorch_tpu.fault.inject import FaultPlan
from distributed_training_pytorch_tpu.parallel import elastic
from distributed_training_pytorch_tpu.telemetry import straggler as straggler_lib
from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib
from distributed_training_pytorch_tpu.telemetry.controller import (
    ACTION_KINDS,
    ControllerConfig,
    RunPolicy,
)
from distributed_training_pytorch_tpu.telemetry.events import (
    claim_attempt,
    peek_attempt,
)


# ---------------------------------------------------------------------------
# Fixtures: fake MonitorStatus / Diagnosis shapes (duck-typed — the policy
# reads attributes, never isinstance).


def _diag(verdicts=(), slowest_chip=None, goodput=None):
    return types.SimpleNamespace(
        verdicts=list(verdicts),
        signals=types.SimpleNamespace(
            slowest_chip=slowest_chip,
            goodput_seconds=dict(goodput or {"productive_step": 10.0}),
        ),
    )


def _verdict(kind, score, evidence=()):
    return types.SimpleNamespace(kind=kind, score=score, evidence=list(evidence))


def _status(
    status="training",
    verdict="healthy",
    attempt=1,
    fractions=None,
    active=(),
    alerts=(),
    diagnosis=None,
    last_event_age_s=1.0,
):
    return types.SimpleNamespace(
        run_dir="/run",
        status=status,
        verdict=verdict,
        diagnosis=diagnosis if diagnosis is not None else _diag(),
        steady_fractions=dict(fractions or {}),
        last_event_age_s=last_event_age_s,
        progress_age_s=None,
        headline={},
        alerts=list(alerts),
        active_alerts=tuple(active),
        attempt=attempt,
    )


def _stub_diff(calls=None):
    def steady_diff(before, after, *, noise_floor=0.10):
        if calls is not None:
            calls.append((dict(before), dict(after), noise_floor))
        return {"rows": [], "max_delta": 0.0, "clean": True, "fractions": {}}

    return steady_diff


# ---------------------------------------------------------------------------
# Dead -> restart.


def test_proc_exit_restarts_immediately_with_evidence():
    """An abnormal subprocess exit is definitive — no debounce polls —
    and the action's evidence carries the exit code it acted on."""
    pol = RunPolicy(ControllerConfig(confirm_polls=5))
    act = pol.decide(_status(), proc_running=False, exit_code=137, now=0.0)
    assert act is not None and act.kind == "restart" and act.reason == "dead"
    assert {"metric": "exit_code", "value": 137} in act.evidence
    assert act.kind in ACTION_KINDS


def test_log_silence_dead_is_debounced():
    """Monitor-derived death (an adopted run, no exit code) must hold for
    confirm_polls consecutive polls — one stale read never respawns."""
    pol = RunPolicy(ControllerConfig(confirm_polls=2))
    dead = dict(status="dead", verdict="dead", last_event_age_s=240.0)
    assert pol.decide(_status(**dead), proc_running=True, exit_code=None,
                      now=0.0) is None
    # blip clears -> counter resets
    assert pol.decide(_status(), proc_running=True, exit_code=None,
                      now=1.0) is None
    assert pol.decide(_status(**dead), proc_running=True, exit_code=None,
                      now=2.0) is None
    act = pol.decide(_status(**dead), proc_running=True, exit_code=None, now=3.0)
    assert act is not None and act.kind == "restart"
    assert act.evidence[0]["metric"] == "last_event_age_s"


def test_pending_action_blocks_second_decision():
    """decide() never hands out two concurrent actions: the first stays
    in flight until note_applied releases it."""
    pol = RunPolicy(ControllerConfig())
    act = pol.decide(_status(), proc_running=False, exit_code=1, now=0.0)
    assert act is not None
    assert pol.decide(_status(), proc_running=False, exit_code=1, now=0.1) is None
    pol.note_applied(act, now=0.2)
    assert pol.restarts_used == 1


def test_backoff_budget_give_up_then_silence():
    """Exponential backoff between respawns; the budget's exhaustion is
    ONE give_up action, then permanent silence."""
    pol = RunPolicy(ControllerConfig(max_restarts=3, backoff_s=5.0,
                                     backoff_factor=2.0, confirm_polls=1))

    def dead(now):
        return pol.decide(_status(), proc_running=False, exit_code=1, now=now)

    a1 = dead(0.0)
    assert a1.kind == "restart"
    pol.note_applied(a1, now=0.0)
    assert dead(3.0) is None  # inside the 5s backoff window
    a2 = dead(6.0)
    assert a2.kind == "restart"
    pol.note_applied(a2, now=6.0)
    assert dead(10.0) is None  # backoff doubled: 6 + 10 = 16
    a3 = dead(17.0)
    assert a3.kind == "restart"
    pol.note_applied(a3, now=17.0)
    assert pol.restarts_used == 3
    a4 = dead(100.0)
    assert a4.kind == "give_up" and pol.gave_up
    assert a4.params["restarts_used"] == 3 and a4.params["max_restarts"] == 3
    pol.note_applied(a4, now=100.0)
    assert dead(200.0) is None  # surfaced to a human; nothing more


def test_zero_budget_refuses_once():
    pol = RunPolicy(ControllerConfig(max_restarts=0))
    act = pol.decide(_status(), proc_running=False, exit_code=1, now=0.0)
    assert act.kind == "refuse" and pol.gave_up and pol.restarts_used == 0
    pol.note_applied(act, now=0.0)
    assert pol.decide(_status(), proc_running=False, exit_code=1,
                      now=1.0) is None


# ---------------------------------------------------------------------------
# Straggler -> exclude-and-replan.


def _straggler_status(attempt=1, chip=3):
    rows = [{"metric": "straggler_ratio", "value": 2.1, "threshold": 1.5}]
    return _status(
        verdict="straggler",
        attempt=attempt,
        diagnosis=_diag([_verdict("straggler", 1.4, rows)], slowest_chip=chip),
    )


def test_straggler_needs_persistence_and_a_named_chip():
    pol = RunPolicy(ControllerConfig(confirm_polls=2))
    # named chip, first sighting -> confirm counter only
    assert pol.decide(_straggler_status(), proc_running=True, exit_code=None,
                      now=0.0) is None
    # score over the line but NO named chip -> never acts, counter resets
    anon = _status(verdict="straggler",
                   diagnosis=_diag([_verdict("straggler", 1.4)], slowest_chip=None))
    assert pol.decide(anon, proc_running=True, exit_code=None, now=1.0) is None
    assert pol.decide(_straggler_status(), proc_running=True, exit_code=None,
                      now=2.0) is None
    act = pol.decide(_straggler_status(), proc_running=True, exit_code=None,
                     now=3.0)
    assert act.kind == "restart_excluding" and act.params["exclude_chip"] == 3
    assert act.evidence[0]["metric"] == "straggler_ratio"
    pol.note_applied(act, now=3.0)
    assert pol.excluded_chips == [3]


def test_respawn_gate_blocks_stale_verdicts_until_new_attempt():
    """After a respawn, the same disease on a status still describing the
    REPLACED attempt must not re-fire — one incident, one action. The
    next attempt's own recurrence re-confirms from scratch."""
    pol = RunPolicy(ControllerConfig(confirm_polls=2, backoff_s=1.0))
    for now in (0.0, 1.0):
        act = pol.decide(_straggler_status(), proc_running=True,
                         exit_code=None, now=now)
    pol.note_applied(act, now=1.0)
    # stale attempt-1 status keeps reporting the straggler: gated
    for now in (5.0, 6.0, 7.0, 8.0):
        assert pol.decide(_straggler_status(attempt=1), proc_running=True,
                          exit_code=None, now=now) is None
    # the new attempt reports the disease again: a fresh confirm cycle
    assert pol.decide(_straggler_status(attempt=2), proc_running=True,
                      exit_code=None, now=9.0) is None
    act2 = pol.decide(_straggler_status(attempt=2), proc_running=True,
                      exit_code=None, now=10.0)
    assert act2 is not None and act2.kind == "restart_excluding"


def test_proc_death_bypasses_the_respawn_gate():
    """The gate holds verdict-driven actions only: a respawned child that
    dies again is a definitive signal and must restart (within budget)."""
    pol = RunPolicy(ControllerConfig(confirm_polls=1, backoff_s=1.0))
    a1 = pol.decide(_status(attempt=1), proc_running=False, exit_code=1, now=0.0)
    pol.note_applied(a1, now=0.0)
    a2 = pol.decide(_status(attempt=1), proc_running=False, exit_code=1, now=2.0)
    assert a2 is not None and a2.kind == "restart"


# ---------------------------------------------------------------------------
# Tunable alerts -> ONE bounded knob change, A/B-judged.


def _data_bound_status(attempt=1, frac=0.6, active=True, steady=5.0):
    return _status(
        verdict="data_bound" if active else "healthy",
        attempt=attempt,
        fractions={"productive_step": 1.0 - frac, "data_wait": frac},
        active=("data_bound",) if active else (),
        alerts=[{"rule": "data_bound", "value": frac, "threshold": 0.2}]
        if active
        else [],
        diagnosis=_diag(goodput={"productive_step": steady}),
    )


def test_tune_is_bounded_and_ab_keeps_on_improvement():
    calls = []
    pol = RunPolicy(
        ControllerConfig(confirm_polls=2, backoff_s=1.0, max_prefetch=8,
                         ab_min_steady_s=0.5),
        knobs={"prefetch_batches": 1, "commit_delay_s": 0.0},
        steady_diff=_stub_diff(calls),
    )
    assert pol.decide(_data_bound_status(), proc_running=True, exit_code=None,
                      now=0.0) is None
    tune = pol.decide(_data_bound_status(), proc_running=True, exit_code=None,
                      now=1.0)
    assert tune.kind == "tune" and tune.reason == "data_bound"
    # bounded: from the current value to the cap, never past it
    assert tune.params == {"knob": "prefetch_batches", "from": 1, "to": 8,
                           "bucket": "data_wait"}
    assert tune.evidence[0]["rule"] == "data_bound"
    pol.note_applied(tune, now=1.0)
    assert pol.knobs["prefetch_batches"] == 8
    # the cured NEW attempt, past backoff, enough steady wall -> keep
    cured = _data_bound_status(attempt=2, frac=0.05, active=False)
    keep = pol.decide(cured, proc_running=True, exit_code=None, now=5.0)
    assert keep.kind == "keep" and keep.params["value"] == 8
    assert keep.evidence[0]["before"] == pytest.approx(0.6)
    assert keep.evidence[0]["after"] == pytest.approx(0.05)
    # the verdict went through the injected run_compare diff
    assert calls and calls[0][0]["data_wait"] == pytest.approx(0.6)
    pol.note_applied(keep, now=5.0)
    assert pol.restarts_used == 1  # keep is record-only, not a respawn


def test_ab_waits_for_the_tuned_attempt_and_steady_floor():
    pol = RunPolicy(
        ControllerConfig(confirm_polls=1, backoff_s=0.5, ab_min_steady_s=2.0),
        knobs={"prefetch_batches": 1},
        steady_diff=_stub_diff(),
    )
    tune = pol.decide(_data_bound_status(), proc_running=True, exit_code=None,
                      now=0.0)
    pol.note_applied(tune, now=0.0)
    # still the pre-tune attempt -> no verdict
    assert pol.decide(_data_bound_status(attempt=1, frac=0.05, active=False),
                      proc_running=True, exit_code=None, now=2.0) is None
    # tuned attempt but under the steady floor -> no verdict
    assert pol.decide(
        _data_bound_status(attempt=2, frac=0.05, active=False, steady=0.5),
        proc_running=True, exit_code=None, now=3.0) is None
    act = pol.decide(
        _data_bound_status(attempt=2, frac=0.05, active=False, steady=5.0),
        proc_running=True, exit_code=None, now=4.0)
    assert act is not None and act.kind == "keep"


def test_ab_reverts_then_recurrence_gives_up():
    """A tune that does not move the bucket is reverted (one respawn);
    the same disease recurring after the revert has no further automatic
    cure — give_up, not a tune/revert flap."""
    pol = RunPolicy(
        ControllerConfig(confirm_polls=1, backoff_s=0.5, ab_min_steady_s=0.5),
        knobs={"prefetch_batches": 1},
        steady_diff=_stub_diff(),
    )
    tune = pol.decide(_data_bound_status(), proc_running=True, exit_code=None,
                      now=0.0)
    pol.note_applied(tune, now=0.0)
    worse = _data_bound_status(attempt=2, frac=0.7)
    rev = pol.decide(worse, proc_running=True, exit_code=None, now=2.0)
    assert rev.kind == "revert"
    assert rev.params["knob"] == "prefetch_batches" and rev.params["to"] == 1
    pol.note_applied(rev, now=2.0)
    assert pol.knobs["prefetch_batches"] == 1 and pol.restarts_used == 2
    # recurrence on the post-revert attempt -> a human's turn
    act = pol.decide(_data_bound_status(attempt=3), proc_running=True,
                     exit_code=None, now=10.0)
    assert act.kind == "give_up" and pol.gave_up
    assert act.params == {"knob": "prefetch_batches", "state": "reverted"}


def test_finished_run_judges_final_without_reverting():
    """A failed tune on a run that then finished cleanly is recorded as a
    moot give_up — respawning to revert would redo completed work."""
    pol = RunPolicy(
        ControllerConfig(confirm_polls=1, backoff_s=0.5, ab_min_steady_s=0.5),
        knobs={"prefetch_batches": 1},
        steady_diff=_stub_diff(),
    )
    tune = pol.decide(_data_bound_status(), proc_running=True, exit_code=None,
                      now=0.0)
    pol.note_applied(tune, now=0.0)
    done = _data_bound_status(attempt=2, frac=0.7)
    done.status = "finished"
    act = pol.decide(done, proc_running=False, exit_code=0, now=2.0)
    assert act.kind == "give_up" and "moot" in act.message
    assert pol.knobs["prefetch_batches"] == 8  # no respawn, no knob rollback


# ---------------------------------------------------------------------------
# The monotonic attempt sidecar.


def test_claim_attempt_monotonic_and_peek_is_side_effect_free(tmp_path):
    run = str(tmp_path / "run")
    assert peek_attempt(run) == 0
    assert claim_attempt(run) == 1
    assert peek_attempt(run) == 1 and peek_attempt(run) == 1
    assert claim_attempt(run) == 2 and claim_attempt(run) == 3
    assert peek_attempt(run) == 3
    # a torn counter degrades to 0, the next claim recovers to 1
    with open(tmp_path / "run" / "telemetry" / "attempt", "w") as f:
        f.write("garbage")
    assert peek_attempt(run) == 0
    assert claim_attempt(run) == 1


# ---------------------------------------------------------------------------
# The deterministic degraded-chip seam.


def test_fault_plan_slow_chip_membership_before_budget():
    plan = FaultPlan().add("slow_chip", count=1,
                           payload={"device": 1, "delay_ms": 60.0})
    # named device absent (post-exclusion topology): inert, budget intact
    assert plan.slow_chip([0, 2]) is None
    assert plan.slow_chip([0, 2]) is None
    hit = plan.slow_chip([0, 1])
    assert hit == (1, pytest.approx(0.06))
    assert ("slow_chip", {"epoch": None, "device": 1}) in plan.fired
    # budget of 1 consumed
    assert plan.slow_chip([0, 1]) is None


def test_fault_plan_slow_chip_epoch_pinned():
    plan = FaultPlan().add("slow_chip", epoch=2, count=5,
                           payload={"device": 0, "delay_ms": 10.0})
    assert plan.slow_chip([0, 1], epoch=1) is None
    assert plan.slow_chip([0, 1], epoch=2) == (0, pytest.approx(0.01))


class _FakeShard:
    class _Data:
        @staticmethod
        def block_until_ready():
            pass

    class _Device:
        def __init__(self, i):
            self.id = i

    def __init__(self, device_id):
        self.device = self._Device(device_id)
        self.data = self._Data()


class _FakeArray:
    def __init__(self, n):
        self.addressable_shards = [_FakeShard(i) for i in range(n)]


def test_sample_arrivals_slow_chip_seam_names_the_injected_device():
    """The slow_chip injection lands as the named device's arrival delay
    — the attribution machinery then blames exactly that chip, which is
    what the controller's exclusion decision keys on."""
    fields = straggler_lib.sample_arrivals({"m": _FakeArray(3)},
                                           slow_chip=(1, 0.05))
    assert fields["slowest_chip"] == 1
    assert fields["chip_skew_ms"] > 30.0
    # without the seam the same fake fleet shows no straggler
    fields = straggler_lib.sample_arrivals({"m": _FakeArray(3)})
    assert fields["chip_skew_ms"] < 30.0


# ---------------------------------------------------------------------------
# replan_excluding: exclusion as a plain elastic shrink.


def test_replan_excluding_shrinks_onto_survivors():
    plan = elastic.replan_excluding({"data": 1, "fsdp": 2}, [0, 1], [1],
                                    batch_size=128, accum_steps=1)
    assert sum(plan.new_axes.values()) >= 1
    assert math.prod(plan.new_axes.values()) == 1  # one survivor
    assert plan.accum_steps == 2  # global batch preserved via accumulation
    assert "excluding" in plan.reason


def test_replan_excluding_ignores_absent_and_refuses_empty():
    # excluding an id that is already gone is a no-op shrink
    plan = elastic.replan_excluding({"data": 4}, [0, 1, 2, 3], [7],
                                    batch_size=64)
    assert math.prod(plan.new_axes.values()) == 4
    with pytest.raises(elastic.ElasticReplanError):
        elastic.replan_excluding({"data": 2}, [0, 1], [0, 1])


# ---------------------------------------------------------------------------
# Doctor: attempt-aware late-compile accounting.


def test_resumed_attempt_starting_epoch_compiles_are_warmup():
    """A controller-restarted run recompiles its executables in the epoch
    it resumed at — warmup, exactly like a cold start's epoch-0 compiles.
    Only compiles PAST the attempt's starting epoch count as retracing."""
    sig = doctor_lib.Signals()
    doctor_lib.update_signals(sig, {"event": "run_start", "attempt": 2,
                                    "epoch": 3})
    assert sig.start_epoch == 3
    doctor_lib.update_signals(sig, {"event": "compile", "epoch": 3,
                                    "seconds": 2.0})
    assert sig.late_compiles == 0  # the resume's warmup recompile
    doctor_lib.update_signals(sig, {"event": "compile", "epoch": 4,
                                    "seconds": 2.0})
    assert sig.late_compiles == 1  # a genuine mid-run retrace


def test_fresh_run_late_compile_rule_unchanged():
    sig = doctor_lib.Signals()
    doctor_lib.update_signals(sig, {"event": "run_start", "epoch": 0})
    doctor_lib.update_signals(sig, {"event": "compile", "epoch": 0,
                                    "seconds": 2.0})
    assert sig.late_compiles == 0
    doctor_lib.update_signals(sig, {"event": "compile", "epoch": 1,
                                    "seconds": 2.0})
    assert sig.late_compiles == 1
    # the MFU probe's one-off compile never counts (existing rule)
    doctor_lib.update_signals(sig, {"event": "compile", "epoch": 2,
                                    "kind": "mfu_probe", "seconds": 1.0})
    assert sig.late_compiles == 1
