"""Pipeline parallelism (parallel/pipeline.py): parity vs sequential stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu import compat
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel.pipeline import (
    PIPE_AXIS,
    pipeline_apply,
    stack_stage_params,
)


def stage_fn(params, x):
    # One MLP block per stage: x + gelu(x @ w1) @ w2 (shape-preserving).
    h = jax.nn.gelu(x @ params["w1"])
    return x + h @ params["w2"]


def make_stages(n_stages, d, hidden, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "w1": jnp.asarray(rng.randn(d, hidden) * 0.2, jnp.float32),
            "w2": jnp.asarray(rng.randn(hidden, d) * 0.2, jnp.float32),
        }
        for _ in range(n_stages)
    ]


def sequential_reference(stages, microbatches):
    out = []
    for x in microbatches:
        for p in stages:
            x = stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


@pytest.fixture(scope="module")
def pipe_mesh(devices):
    return mesh_lib.create_mesh({PIPE_AXIS: 4}, devices=devices[:4])


def test_pipeline_matches_sequential(pipe_mesh):
    stages = make_stages(4, d=16, hidden=32)
    rng = np.random.RandomState(1)
    micro = jnp.asarray(rng.randn(6, 8, 16), jnp.float32)  # 6 microbatches of 8
    out = pipeline_apply(stack_stage_params(stages), micro, stage_fn, pipe_mesh)
    ref = sequential_reference(stages, micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_pipeline_gradients_match(pipe_mesh):
    stages = make_stages(4, d=8, hidden=16, seed=2)
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(3)
    micro = jnp.asarray(rng.randn(5, 4, 8), jnp.float32)

    def loss_pipe(stacked):
        return jnp.sum(pipeline_apply(stacked, micro, stage_fn, pipe_mesh) ** 2)

    def loss_ref(stacked):
        stages = [jax.tree.map(lambda x: x[i], stacked) for i in range(4)]
        return jnp.sum(sequential_reference(stages, micro) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_pipeline_single_microbatch(pipe_mesh):
    stages = make_stages(4, d=8, hidden=8, seed=4)
    micro = jnp.ones((1, 2, 8), jnp.float32)
    out = pipeline_apply(stack_stage_params(stages), micro, stage_fn, pipe_mesh)
    ref = sequential_reference(stages, micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_rejects_stage_mismatch(pipe_mesh):
    stages = make_stages(3, d=8, hidden=8)  # 3 stages on a 4-device pipe axis
    micro = jnp.ones((2, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        pipeline_apply(stack_stage_params(stages), micro, stage_fn, pipe_mesh)


def test_pipeline_runs_decoder_blocks(pipe_mesh):
    """The real model family through the pipeline: 4 DecoderBlocks as stages
    (stacked params) match the same blocks applied sequentially."""
    from distributed_training_pytorch_tpu.models import DecoderBlock

    block = DecoderBlock(num_heads=2, mlp_dim=16, attention_impl="plain")
    rng = np.random.RandomState(6)
    x0 = jnp.asarray(rng.randn(3, 10, 8), jnp.float32)  # [mb, T, d]
    stage_vars = [
        block.init(jax.random.key(i), x0)["params"] for i in range(4)
    ]
    stacked = stack_stage_params(stage_vars)

    def block_stage_fn(params, x):
        return block.apply({"params": params}, x)

    micro = jnp.asarray(rng.randn(5, 3, 10, 8), jnp.float32)  # 5 microbatches
    out = pipeline_apply(stacked, micro, block_stage_fn, pipe_mesh)

    ref = []
    for m in micro:
        y = m
        for p in stage_vars:
            y = block.apply({"params": p}, y)
        ref.append(y)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jnp.stack(ref)), atol=2e-4
    )


def test_pipeline_training_step_through_engine(pipe_mesh, devices):
    """Pipeline parallelism is trainable, not just a forward schedule: a
    TrainEngine loss_fn routes activations through pipeline_apply (stacked
    stage params sharded over `pipe`), grads flow through the ppermute ring,
    and the loss decreases."""
    import optax

    from distributed_training_pytorch_tpu.train import TrainEngine

    d, hidden = 8, 16

    def loss_fn(params, model_state, batch, rng, train):
        out = pipeline_apply(params["stages"], batch["image"], stage_fn, pipe_mesh)
        pred = jnp.einsum("mbd,dk->mbk", out, params["head"])
        loss = jnp.mean((pred[..., 0] - batch["label"]) ** 2)
        return loss, ({"loss": loss}, model_state)

    engine = TrainEngine(loss_fn, optax.adam(3e-3), pipe_mesh)
    rng = np.random.RandomState(12)
    stages = make_stages(4, d=d, hidden=hidden, seed=12)

    def init_fn(_):
        return {
            "params": {
                "stages": stack_stage_params(stages),
                "head": jnp.asarray(rng.randn(d, 1) * 0.3, jnp.float32),
            }
        }

    state = engine.init_state(jax.random.key(0), init_fn)
    micro = jnp.asarray(rng.randn(6, 4, d), jnp.float32)  # 6 microbatches of 4
    target = jnp.asarray(rng.randn(6, 4), jnp.float32)
    batch = {"image": micro, "label": target}
    losses = []
    for _ in range(25):
        state, m = engine.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses


def test_bubble_fraction_interleaved_beats_gpipe():
    from distributed_training_pytorch_tpu.parallel.pipeline import (
        bubble_fraction,
        schedule_stats,
    )

    gpipe = bubble_fraction(8, 4, n_virtual=1)
    inter = bubble_fraction(8, 4, n_virtual=2)
    assert np.isclose(gpipe, 3 / 11)
    assert np.isclose(inter, 3 / 19)
    assert inter < gpipe
    # The counted tick grid agrees with the closed form (both schedules).
    for v in (1, 2):
        stats = schedule_stats(8, 4, n_virtual=v)
        assert np.isclose(stats["bubble_fraction"], bubble_fraction(8, 4, v))


def test_pipeline_interleaved_matches_sequential(pipe_mesh):
    # 8 virtual stages over 4 devices (2 chunks each), M=8 microbatches.
    stages = make_stages(8, d=16, hidden=32, seed=7)
    rng = np.random.RandomState(8)
    micro = jnp.asarray(rng.randn(8, 4, 16), jnp.float32)
    out = pipeline_apply(
        stack_stage_params(stages), micro, stage_fn, pipe_mesh, n_virtual=2
    )
    ref = sequential_reference(stages, micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_sharded_feed_matches_replicated(pipe_mesh):
    stages = make_stages(4, d=8, hidden=16, seed=9)
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(10)
    micro = jnp.asarray(rng.randn(8, 4, 8), jnp.float32)  # M % S == 0
    out_sharded = pipeline_apply(stacked, micro, stage_fn, pipe_mesh, feed="sharded")
    out_repl = pipeline_apply(stacked, micro, stage_fn, pipe_mesh, feed="replicated")
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(out_repl), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out_sharded), np.asarray(sequential_reference(stages, micro)), atol=1e-5
    )


@pytest.mark.slow
def test_pipeline_interleaved_gradients_match(pipe_mesh):
    stages = make_stages(8, d=8, hidden=16, seed=11)
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(12)
    micro = jnp.asarray(rng.randn(8, 4, 8), jnp.float32)

    def loss_pipe(stacked):
        out = pipeline_apply(stacked, micro, stage_fn, pipe_mesh, n_virtual=2)
        return jnp.sum(out**2)

    def loss_ref(stacked):
        stages = [jax.tree.map(lambda x: x[i], stacked) for i in range(8)]
        return jnp.sum(sequential_reference(stages, micro) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@pytest.mark.slow
def test_pipeline_remat_matches(pipe_mesh):
    stages = make_stages(4, d=8, hidden=16, seed=13)
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(14)
    micro = jnp.asarray(rng.randn(4, 4, 8), jnp.float32)

    def loss(stacked, remat):
        out = pipeline_apply(stacked, micro, stage_fn, pipe_mesh, remat=remat)
        return jnp.sum(out**2)

    g_plain = jax.grad(lambda p: loss(p, False))(stacked)
    g_remat = jax.grad(lambda p: loss(p, True))(stacked)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_embed_blocks_head(pipe_mesh):
    """Heterogeneous ends: token-id feed -> embedding -> 4 trunk stages ->
    head, all inside one pipeline_apply call (the embed/head run sharded over
    the pipe group, not replicated)."""
    d, vocab = 8, 32
    rng = np.random.RandomState(15)
    stages = make_stages(4, d=d, hidden=16, seed=15)
    embed = {"table": jnp.asarray(rng.randn(vocab, d) * 0.3, jnp.float32)}
    head = {"w": jnp.asarray(rng.randn(d, vocab) * 0.3, jnp.float32)}

    def embed_fn(p, ids):
        return p["table"][ids]  # [mb, T] int32 -> [mb, T, d]

    def head_fn(p, x):
        return x @ p["w"]  # [mb, T, d] -> [mb, T, vocab]

    ids = jnp.asarray(rng.randint(0, vocab, size=(8, 3, 5)), jnp.int32)
    out = pipeline_apply(
        stack_stage_params(stages),
        ids,
        stage_fn,
        pipe_mesh,
        first=(embed, embed_fn),
        last=(head, head_fn),
    )
    ref = []
    for m in ids:
        x = embed_fn(embed, m)
        for p in stages:
            x = stage_fn(p, x)
        ref.append(head_fn(head, x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.stack(ref)), atol=1e-5)


@pytest.mark.slow
def test_pipeline_end_gradients_flow(pipe_mesh):
    """Grads reach the embed table and head weights through the ring."""
    d, vocab = 8, 16
    rng = np.random.RandomState(16)
    stages = stack_stage_params(make_stages(4, d=d, hidden=8, seed=16))
    embed = {"table": jnp.asarray(rng.randn(vocab, d) * 0.3, jnp.float32)}
    head = {"w": jnp.asarray(rng.randn(d, 1) * 0.3, jnp.float32)}
    ids = jnp.asarray(rng.randint(0, vocab, size=(4, 2, 3)), jnp.int32)

    def loss(ends):
        out = pipeline_apply(
            stages, ids, stage_fn, pipe_mesh,
            first=(ends["e"], lambda p, m: p["table"][m]),
            last=(ends["h"], lambda p, x: x @ p["w"]),
        )
        return jnp.sum(out**2)

    g = jax.grad(loss)({"e": embed, "h": head})
    assert float(jnp.abs(g["e"]["table"]).sum()) > 0
    assert float(jnp.abs(g["h"]["w"]).sum()) > 0


def test_pipeline_interleaved_rejects_indivisible(pipe_mesh):
    stages = stack_stage_params(make_stages(8, d=8, hidden=8))
    micro = jnp.ones((6, 2, 8), jnp.float32)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="n_micro"):
        pipeline_apply(stages, micro, stage_fn, pipe_mesh, n_virtual=2)


@pytest.mark.skipif(
    not compat.HAS_PARTIAL_MANUAL,
    reason="partial-manual shard_map needs jax>=0.6 (experimental auto= aborts in XLA)",
)
@pytest.mark.parametrize("combo", ["data", "expert", "tensor"])
def test_pipeline_composes_on_one_mesh(devices, combo):
    """Matrix composition on ONE multi-axis mesh (r3 VERDICT item 7):
    data x pipe x {expert|tensor}; pipeline_apply is manual over `pipe`
    only, so GSPMD distributes the within-stage compute over the other axes
    of the SAME mesh.

    combo="data":   dense stages, microbatch feed sharded over `data` (each
                    tick's stage body is data-parallel).
    combo="expert": MoE stages with expert-sharded weights (each tick's MoE
                    einsums are expert-parallel), feed replicated.
    combo="tensor": dense stages whose w1/w2 are Megatron-sharded over the
                    `tensor` axis (column- then row-parallel) via
                    with_sharding_constraint on the stacked params before
                    the ring — GSPMD runs each tick's MLP tensor-parallel
                    inside the pipe-manual region.

    All three combos check loss AND gradients against the sequential
    single-device reference. The data x expert x pipe TRIPLE (data-sharded activations
    meeting expert-sharded weights inside the pipe-manual region) is blocked
    by an upstream XLA bug — spmd_partitioner_util.cc:495 "Check failed:
    partition_group_list.num_replica_groups() * ..." (bisected on jax 0.9
    CPU: any such program aborts regardless of dispatch impl or constraint
    placement; see moe._constrain). When it compiles again, merge these two
    params into one.
    """
    from distributed_training_pytorch_tpu.parallel import EXPERT_AXIS, MoEMlp

    third = mesh_lib.TENSOR_AXIS if combo == "tensor" else EXPERT_AXIS
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, PIPE_AXIS: 2, third: 2}, devices=devices
    )
    d, hidden, S = 8, 16, 2
    rng = np.random.RandomState(21)
    moe = MoEMlp(num_experts=2, hidden_dim=hidden, top_k=2, capacity_factor=4.0,
                 num_groups=2)
    x0 = jnp.asarray(rng.randn(4, 8, d), jnp.float32)  # one microbatch shape
    moe_vars = [moe.init(jax.random.key(10 + i), x0)["params"] for i in range(S)]
    stages = [
        {
            "w1": jnp.asarray(rng.randn(d, hidden) * 0.2, jnp.float32),
            "w2": jnp.asarray(rng.randn(hidden, d) * 0.2, jnp.float32),
            **({"moe": moe_vars[i]} if combo == "expert" else {}),
        }
        for i in range(S)
    ]

    def stage_body(params, x):
        h = jax.nn.gelu(x @ params["w1"])
        x = x + h @ params["w2"]
        if combo == "expert":
            x = x + moe.apply({"params": params["moe"]}, x)
        return x

    micro = jnp.asarray(rng.randn(4, 4, 8, d), jnp.float32)  # M=4 microbatches
    stacked = stack_stage_params(stages)

    def pipe_loss(stacked):
        fed = micro
        if combo == "tensor":
            # Megatron MLP sharding constrained on the stacked params
            # before the ring, carried through the pipe-manual region's
            # auto axes: w1 [VS, d, hidden] column-parallel, w2
            # [VS, hidden, d] row-parallel over `tensor`.
            stacked = {
                "w1": jax.lax.with_sharding_constraint(
                    stacked["w1"],
                    jax.sharding.PartitionSpec(None, None, mesh_lib.TENSOR_AXIS),
                ),
                "w2": jax.lax.with_sharding_constraint(
                    stacked["w2"],
                    jax.sharding.PartitionSpec(None, mesh_lib.TENSOR_AXIS, None),
                ),
            }
        if combo == "data":
            # Data parallelism rides the feed's sharding: [M, mb, T, d] with
            # mb over `data`, carried through the pipe-manual region's auto
            # axes into every stage body.
            fed = jax.lax.with_sharding_constraint(
                micro, jax.sharding.PartitionSpec(None, mesh_lib.DATA_AXIS)
            )
        out = pipeline_apply(stacked, fed, stage_body, mesh)
        return jnp.sum(out**2)

    with compat.set_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pipe_loss))(stacked)

    def seq_loss(stacked):
        acc = 0.0
        for m in range(micro.shape[0]):
            x = micro[m]
            for i in range(S):
                p = jax.tree.map(lambda leaf, i=i: leaf[i], stacked)
                x = stage_body(p, x)
            acc = acc + jnp.sum(x**2)
        return acc

    ref_loss, ref_grads = jax.value_and_grad(seq_loss)(stacked)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.skipif(
    not compat.HAS_PARTIAL_MANUAL,
    reason="partial-manual shard_map needs jax>=0.6 (experimental auto= aborts in XLA)",
)
def test_pipeline_triple_data_expert_pipe(devices):
    """The data x expert x pipe TRIPLE (r4 VERDICT item 7): GSPMD's
    constraint-driven expert sharding CHECK-crashes inside the pipe-manual
    region (scripts/repro_triple_check.py), so the supported composition is
    pipeline_apply(extra_manual_axes=('expert',)) with a
    moe.manual_expert_ffn_local stage body — parity-checked against the
    sequential MoEMlp reference, gradients finite."""
    from jax.sharding import PartitionSpec as P

    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.parallel.moe import (
        MoEMlp,
        manual_expert_ffn_local,
    )

    rng = np.random.RandomState(0)
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 2, mesh_lib.EXPERT_AXIS: 2}
    )
    d, hid, pipe, G, E = 8, 16, 2, 4, 2
    moe = MoEMlp(num_experts=E, hidden_dim=hid, top_k=2, capacity_factor=4.0,
                 num_groups=G, dispatch_impl="einsum")
    x0 = jnp.asarray(rng.randn(4, 8, d), jnp.float32)
    micro = jnp.asarray(rng.randn(4, 4, 8, d), jnp.float32)
    stages = [
        {"w1": jnp.asarray(rng.randn(d, hid) * 0.2, jnp.float32),
         "w2": jnp.asarray(rng.randn(hid, d) * 0.2, jnp.float32),
         "moe": moe.init(jax.random.key(30 + i), x0)["params"]}
        for i in range(pipe)
    ]

    def stage(p, x):
        x = x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]
        mb, t, dd = x.shape
        y = manual_expert_ffn_local(
            p["moe"], x.reshape(G, (mb * t) // G, dd),
            num_experts=E, n_expert_shards=2, top_k=2, capacity_factor=4.0,
        )
        return x + y.reshape(x.shape)

    specs = {
        "w1": P(), "w2": P(),
        "moe": {"router": {"kernel": P(), "bias": P()},
                "w_in": P("expert"), "w_out": P("expert")},
    }
    stacked = stack_stage_params(stages)

    def loss(stacked):
        fed = jax.lax.with_sharding_constraint(micro, P(None, mesh_lib.DATA_AXIS))
        return jnp.sum(
            pipeline_apply(
                stacked, fed, stage, mesh,
                extra_manual_axes=("expert",), stage_param_specs=specs,
            ) ** 2
        )

    with compat.set_mesh(mesh):
        l, g = jax.jit(jax.value_and_grad(loss))(stacked)

    def stage_ref(p, x):
        x = x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]
        mb, t, dd = x.shape
        y = moe.apply({"params": p["moe"]}, x.reshape(G, (mb * t) // G, dd))
        return x + y.reshape(x.shape)

    ref = micro
    for i in range(pipe):
        p = jax.tree.map(lambda leaf, i=i: leaf[i], stacked)
        ref = jax.vmap(lambda m, p=p: stage_ref(p, m))(ref)
    np.testing.assert_allclose(float(l), float(jnp.sum(ref**2)), rtol=2e-4)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))
