"""Input-pipeline tests: determinism, sharding semantics, transform parity
with the reference op list (SURVEY.md §4, §7 step 4)."""

import numpy as np
import pytest

from distributed_training_pytorch_tpu.data import (
    ArrayDataSource,
    ImageFolderDataSource,
    ShardedLoader,
    device_prefetch,
    eval_transform,
    train_transform,
)
from distributed_training_pytorch_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """Tiny image-folder tree: 3 labels x 8 images (the reference layout,
    dataset/example_dataset.py:24-30)."""
    import cv2

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for label in ("cat", "dog", "snake"):
        d = root / label
        d.mkdir()
        for i in range(8):
            img = rng.randint(0, 255, size=(40, 48, 3), dtype=np.uint8)
            cv2.imwrite(str(d / f"{i}.png"), img)
    return str(root)


def test_image_folder_scan(image_root):
    src = ImageFolderDataSource(image_root, ["cat", "dog", "snake"])
    assert len(src) == 24
    rec = src[0]
    assert rec["image"].shape == (40, 48, 3)
    assert rec["image"].dtype == np.uint8
    labels = [src[i]["label"] for i in range(24)]
    assert sorted(set(int(l) for l in labels)) == [0, 1, 2]
    # Deterministic scan order: first 8 records are label 0 ("cat"), sorted.
    assert all(int(l) == 0 for l in labels[:8])


def test_image_folder_missing_label(image_root):
    with pytest.raises(FileNotFoundError):
        ImageFolderDataSource(image_root, ["cat", "bird"])


def test_transforms_deterministic():
    img = np.random.RandomState(1).randint(0, 255, size=(50, 50, 3), dtype=np.uint8)
    t = train_transform(32, 32, seed=7)
    a = t(img, epoch=3, index=11)
    b = t(img, epoch=3, index=11)
    np.testing.assert_array_equal(a, b)
    c = t(img, epoch=4, index=11)
    assert not np.array_equal(a, c), "different epoch must give different augmentation"
    assert a.shape == (32, 32, 3) and a.dtype == np.float32


def test_eval_transform_is_resize_normalize_only():
    img = np.full((10, 10, 3), 128, np.uint8)
    out = eval_transform(8, 8)(img)
    expected = (128 / 255.0 - IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(out[0, 0], expected, rtol=1e-5)
    # Deterministic regardless of epoch/index (no random ops).
    np.testing.assert_array_equal(out, eval_transform(8, 8)(img, epoch=9, index=9))


def test_loader_global_batch_semantics():
    n = 40
    src = ArrayDataSource(x=np.arange(n, dtype=np.int32), label=np.zeros(n, np.int32))
    # Simulate 4 hosts: each must see a disjoint quarter of the same permutation.
    loaders = [
        ShardedLoader(
            src, 8, shuffle=True, seed=3, num_workers=0, process_index=p, process_count=4
        )
        for p in range(4)
    ]
    for ld in loaders:
        ld.set_epoch(2)
        assert len(ld) == 5
        assert ld.local_batch_size == 2
    per_host = [list(ld) for ld in loaders]
    for b in range(5):
        rows = np.concatenate([per_host[p][b]["x"] for p in range(4)])
        assert len(set(rows.tolist())) == 8, "hosts must cover disjoint rows"
    all_rows = np.concatenate([per_host[p][b]["x"] for b in range(5) for p in range(4)])
    assert sorted(all_rows.tolist()) == list(range(40)), "epoch covers each record once"


def test_loader_epoch_reshuffle_and_resume_determinism():
    src = ArrayDataSource(x=np.arange(16, dtype=np.int32))
    ld = ShardedLoader(src, 4, shuffle=True, seed=0, num_workers=0)
    ld.set_epoch(0)
    e0 = np.concatenate([b["x"] for b in ld])
    ld.set_epoch(1)
    e1 = np.concatenate([b["x"] for b in ld])
    assert not np.array_equal(e0, e1), "set_epoch must reshuffle"
    ld2 = ShardedLoader(src, 4, shuffle=True, seed=0, num_workers=0)
    ld2.set_epoch(1)
    np.testing.assert_array_equal(e1, np.concatenate([b["x"] for b in ld2]))


def test_loader_drop_last_vs_pad_final():
    src = ArrayDataSource(x=np.arange(10, dtype=np.int32))
    ld = ShardedLoader(src, 4, shuffle=False, num_workers=0)  # drop_last default
    batches = list(ld)
    assert len(batches) == 2 and all(len(b["x"]) == 4 for b in batches)

    ld = ShardedLoader(src, 4, shuffle=False, num_workers=0, drop_last=False, pad_final=True)
    batches = list(ld)
    assert len(batches) == 3
    assert batches[-1]["x"].shape == (4,), "final batch must be padded to static shape"
    np.testing.assert_array_equal(batches[-1]["mask"], [1, 1, 0, 0])
    np.testing.assert_array_equal(batches[0]["mask"], [1, 1, 1, 1])
    # Padding repeats the last real row.
    np.testing.assert_array_equal(batches[-1]["x"], [8, 9, 9, 9])


def test_loader_pad_final_multihost_uneven_remainder():
    """Regression: 21 records / global batch 16 / 4 hosts — the 5-row final
    batch must still give every host exactly L=4 rows, with a globally
    consistent mask (hosts 2-3 get all-padding rows, not a crash)."""
    src = ArrayDataSource(x=np.arange(21, dtype=np.int32))
    loaders = [
        ShardedLoader(
            src, 16, shuffle=False, num_workers=0, drop_last=False, pad_final=True,
            process_index=p, process_count=4,
        )
        for p in range(4)
    ]
    per_host = [list(ld) for ld in loaders]
    assert all(len(b) == 2 for b in per_host)
    final_rows = np.concatenate([per_host[p][1]["x"] for p in range(4)])
    final_mask = np.concatenate([per_host[p][1]["mask"] for p in range(4)])
    np.testing.assert_array_equal(final_mask, (np.arange(16) < 5).astype(np.float32))
    # Real rows 16..20 then the last real row repeated as padding.
    np.testing.assert_array_equal(final_rows[:5], np.arange(16, 21))
    np.testing.assert_array_equal(final_rows[5:], np.full(11, 20))
    # Host-independent aggregation weight.
    assert loaders[0].global_real_count(0) == 16
    assert loaders[0].global_real_count(1) == 5
    assert all(ld.global_real_count(1) == 5 for ld in loaders)


def test_loader_threaded_matches_serial(image_root):
    src = ImageFolderDataSource(image_root, ["cat", "dog", "snake"])
    t = train_transform(24, 24, seed=5)
    kw = dict(shuffle=True, seed=9, transform=t)
    serial = list(ShardedLoader(src, 8, num_workers=0, **kw))
    threaded = list(ShardedLoader(src, 8, num_workers=4, **kw))
    assert len(serial) == len(threaded) == 3
    for a, b in zip(serial, threaded, strict=True):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_device_prefetch(devices):
    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    src = ArrayDataSource(
        image=np.random.RandomState(0).randn(32, 8, 8, 3).astype(np.float32),
        label=np.arange(32, dtype=np.int32),
    )
    ld = ShardedLoader(src, 16, shuffle=False, num_workers=0)
    out = list(device_prefetch(iter(ld), mesh))
    assert len(out) == 2
    import jax

    assert isinstance(out[0]["image"], jax.Array)
    assert out[0]["image"].shape == (16, 8, 8, 3)
    assert out[0]["image"].sharding.spec == mesh_lib.batch_sharding(mesh).spec
    np.testing.assert_array_equal(np.asarray(out[1]["label"]), np.arange(16, 32))


def test_device_prefetch_propagates_errors(devices):
    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)

    def bad_iter():
        yield {"x": np.zeros((8,), np.float32)}
        raise RuntimeError("decode failed")

    it = device_prefetch(bad_iter(), mesh)
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(it)


def test_random_resized_crop_deterministic():
    """Same (seed, epoch, index) -> identical crop; output shape fixed; crop
    content comes from the source image."""
    from distributed_training_pytorch_tpu.data import transforms as T

    rng = np.random.RandomState(3)
    img = rng.randint(0, 255, size=(40, 60, 3), dtype=np.uint8)
    tfm = T.Compose([T.random_resized_crop(16, 16)], seed=7)
    a = tfm(img, epoch=2, index=5)
    b = tfm(img, epoch=2, index=5)
    np.testing.assert_array_equal(a, b)
    c = tfm(img, epoch=2, index=6)
    assert a.shape == c.shape == (16, 16, 3)
    assert not np.array_equal(a, c)  # different record -> different crop


class _RaggedSource:
    """Records whose ``tokens`` field is ragged — unstackable without a
    collate (the case the reference serves by forwarding ``dataset.collate_fn``
    to DataLoader, ref trainer/trainer.py:59-71)."""

    def __init__(self, n=12, max_len=9):
        rng = np.random.RandomState(3)
        self.rows = [rng.randint(0, 100, size=(rng.randint(1, max_len),)) for _ in range(n)]

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        return {"tokens": self.rows[i], "label": np.int32(len(self.rows[i]))}

    @staticmethod
    def collate_fn(records):
        """Pad tokens to the batch max and emit lengths."""
        mx = max(len(r["tokens"]) for r in records)
        tokens = np.stack(
            [np.pad(r["tokens"], (0, mx - len(r["tokens"]))) for r in records]
        )
        return {
            "tokens": tokens,
            "length": np.asarray([len(r["tokens"]) for r in records], np.int64),
            "label": np.stack([r["label"] for r in records]),
        }


@pytest.mark.parametrize("num_workers", [0, 2])
def test_loader_collate_fn_ragged(num_workers):
    src = _RaggedSource()
    # Default stacking must fail on ragged records...
    plain = ShardedLoader(
        src, 4, shuffle=False, num_workers=0, process_index=0, process_count=1
    )
    plain.collate_fn = None
    with pytest.raises(ValueError):
        next(iter(plain))
    # ...and the source-attached collate (picked up like the reference picks
    # up dataset.collate_fn) makes the same records batchable.
    loader = ShardedLoader(
        src, 4, shuffle=False, num_workers=num_workers, process_index=0, process_count=1
    )
    batches = list(loader)
    assert len(batches) == 3
    for b in batches:
        assert b["tokens"].shape[0] == 4
        assert b["tokens"].shape[1] == b["length"].max()
        np.testing.assert_array_equal(b["label"], b["length"])


def test_loader_collate_fn_gets_loader_mask():
    src = _RaggedSource(n=6)
    loader = ShardedLoader(
        src, 4, shuffle=False, num_workers=0, drop_last=False, pad_final=True,
        process_index=0, process_count=1,
    )
    batches = list(loader)
    assert len(batches) == 2
    # Padded final batch: mask is loader-owned even under a custom collate.
    np.testing.assert_array_equal(batches[-1]["mask"], [1.0, 1.0, 0.0, 0.0])


def test_loader_defaults_to_source_transform(image_root):
    """A bare ShardedLoader(source) must apply source.transform — dropping it
    silently feeds un-normalized images to eval (measured-accuracy bug found
    by the digits convergence run)."""
    src = ImageFolderDataSource(
        image_root, ["cat", "dog", "snake"], transform=eval_transform(32, 32)
    )
    bare = ShardedLoader(src, 4, shuffle=False, num_workers=0,
                         process_index=0, process_count=1)
    explicit = ShardedLoader(src, 4, shuffle=False, num_workers=0,
                             transform=src.transform,
                             process_index=0, process_count=1)
    a = next(iter(bare))["image"]
    b = next(iter(explicit))["image"]
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.float32 and a.min() < 0, "normalization must have run"
