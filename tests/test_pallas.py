"""Parity tests for the Pallas flash-attention kernel (ops/pallas.py).

Runs the real kernel logic through the Pallas interpreter on the CPU test
platform (strict float32 tolerances; on TPU the MXU's bf16 multiply path adds
~1e-3 noise to both sides, checked separately in the bench toggle). Reference:
the plain O(T^2) softmax attention in ``models/vit.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.ops.pallas import flash_attention
from distributed_training_pytorch_tpu.models.vit import (
    MultiHeadAttention,
    default_attention_fn,
)


def reference_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


CASES = [
    (2, 197, 3, 64, False),  # ViT-B/16 sequence length (197 = 14^2 + cls)
    (1, 256, 2, 32, False),  # block-aligned
    (2, 100, 2, 16, True),  # causal, unaligned T
    (1, 130, 4, 64, True),  # causal, crosses one block boundary
]


@pytest.mark.parametrize("b,t,h,d,causal", CASES)
def test_forward_parity(b, t, h, d, causal):
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.float32) for _ in range(3))
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("b,t,h,d,causal", CASES[:1] + CASES[2:3])
def test_gradient_parity(b, t, h, d, causal):
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.float32) for _ in range(3))
    cotangent = jnp.cos(jnp.arange(b * t * h * d, dtype=jnp.float32)).reshape(b, t, h, d) * 0.1

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * cotangent)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal) * cotangent)

    grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(grads_flash, grads_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=2e-4, err_msg=f"d{name}"
        )


def test_default_attention_fn_selects_by_backend():
    # CPU test platform: auto mode must fall back to plain XLA attention.
    assert default_attention_fn(None) is None
    assert default_attention_fn(False) is None
    assert default_attention_fn(True) is not None


def test_mha_with_flash_kernel_matches_plain():
    """MultiHeadAttention with the kernel plugged into attention_fn matches
    the default path (same params)."""
    from distributed_training_pytorch_tpu.ops.pallas import make_attention_fn

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 50, 32), jnp.float32)
    plain = MultiHeadAttention(num_heads=4)
    # min_seq_len=1 forces the kernel path even at T=50 (the default adapter
    # would route short sequences to the plain implementation).
    flash = MultiHeadAttention(num_heads=4, attention_fn=make_attention_fn(min_seq_len=1))
    variables = plain.init(jax.random.key(0), x)
    out_plain = plain.apply(variables, x)
    out_flash = flash.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_plain), atol=2e-5)
