"""Parity tests for the Pallas flash-attention kernel (ops/pallas.py).

Runs the real kernel logic through the Pallas interpreter on the CPU test
platform (strict float32 tolerances; on TPU the MXU's bf16 multiply path adds
~1e-3 noise to both sides, checked separately in the bench toggle). Reference:
the plain O(T^2) softmax attention in ``models/vit.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.ops.pallas import flash_attention
from distributed_training_pytorch_tpu.models.vit import (
    MultiHeadAttention,
    default_attention_fn,
)


def reference_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


CASES = [
    (2, 197, 3, 64, False),  # ViT-B/16 sequence length (197 = 14^2 + cls)
    (1, 256, 2, 32, False),  # block-aligned
    (2, 100, 2, 16, True),  # causal, unaligned T
    (1, 130, 4, 64, True),  # causal, crosses one block boundary
]


@pytest.mark.parametrize("b,t,h,d,causal", CASES)
def test_forward_parity(b, t, h, d, causal):
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.float32) for _ in range(3))
    out = flash_attention(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("b,t,h,d,causal", CASES[:1] + CASES[2:3])
def test_gradient_parity(b, t, h, d, causal):
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d), jnp.float32) for _ in range(3))
    cotangent = jnp.cos(jnp.arange(b * t * h * d, dtype=jnp.float32)).reshape(b, t, h, d) * 0.1

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * cotangent)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal) * cotangent)

    grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(grads_flash, grads_ref, "qkv", strict=True):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=2e-4, err_msg=f"d{name}"
        )


def test_default_attention_fn_selects_by_backend():
    # CPU test platform: auto mode must fall back to plain XLA attention.
    assert default_attention_fn(None) is None
    assert default_attention_fn(False) is None
    assert default_attention_fn(True) is not None


def test_mha_with_flash_kernel_matches_plain():
    """MultiHeadAttention with the kernel plugged into attention_fn matches
    the default path (same params)."""
    from distributed_training_pytorch_tpu.ops.pallas import make_attention_fn

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 50, 32), jnp.float32)
    plain = MultiHeadAttention(num_heads=4)
    # min_seq_len=1 forces the kernel path even at T=50 (the default adapter
    # would route short sequences to the plain implementation).
    flash = MultiHeadAttention(num_heads=4, attention_fn=make_attention_fn(min_seq_len=1))
    variables = plain.init(jax.random.key(0), x)
    out_plain = plain.apply(variables, x)
    out_flash = flash.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_plain), atol=2e-5)


def test_flash_valid_len_matches_masked_plain():
    """valid_len (caller-padded sequences) masks exactly like the plain
    path's key mask — outputs AND gradients, through the custom VJP."""
    from distributed_training_pytorch_tpu.models.vit import dot_product_attention
    from distributed_training_pytorch_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(5)
    t, valid = 24, 17
    q = jnp.asarray(rng.randn(2, t, 4, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, t, 4, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, t, 4, 8), jnp.float32)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, valid_len=valid, interpret=True)

    def f_plain(q, k, v):
        return dot_product_attention(q, k, v, valid_len=valid)

    out_f, out_p = f_flash(q, k, v), f_plain(q, k, v)
    # Rows past valid_len are inert padding — compare the real rows.
    np.testing.assert_allclose(
        np.asarray(out_f[:, :valid]), np.asarray(out_p[:, :valid]), atol=2e-5
    )
    # Gradient parity with upstream grads zeroed on pad rows (what a model
    # whose loss ignores pad rows produces).
    g = jnp.asarray(rng.randn(2, t, 4, 8), jnp.float32).at[:, valid:].set(0.0)
    gf = jax.grad(lambda *a: jnp.vdot(f_flash(*a), g), argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: jnp.vdot(f_plain(*a), g), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gp, strict=True):
        np.testing.assert_allclose(
            np.asarray(a[:, :valid]), np.asarray(b[:, :valid]), atol=3e-5
        )


@pytest.mark.slow
def test_vit_pad_seq_to_exact_semantics():
    """pad_seq_to changes tiling, not math: same params, same logits and
    same parameter gradients as the unpadded model."""
    from distributed_training_pytorch_tpu.models.vit import ViTTiny

    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(2, 16, 16, 3), jnp.float32)
    base = ViTTiny(num_classes=3)            # T = 16 patches + cls = 17
    padded = ViTTiny(num_classes=3, pad_seq_to=24)
    variables = base.init(jax.random.key(0), x)
    np.testing.assert_allclose(
        np.asarray(padded.apply(variables, x)),
        np.asarray(base.apply(variables, x)),
        atol=2e-5,
    )

    def loss(v, m):
        return jnp.sum(m.apply(v, x) ** 2)

    gb = jax.grad(loss)(variables, base)
    gp = jax.grad(loss)(variables, padded)
    for a, b in zip(jax.tree.leaves(gb), jax.tree.leaves(gp), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


# --------------------------------------------------------------------------
# Fused 1x1-conv + BN-apply + ReLU GEMM kernel (r4 VERDICT item 2)


def test_conv1x1_bn_act_matches_xla():
    """Kernel == relu((x @ w) * a + b) exactly (f32), incl. row padding."""
    from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 7, 5, 24), jnp.float32)  # 70 rows: pads to 32k
    w = jnp.asarray(rng.randn(24, 16) * 0.2, jnp.float32)
    a = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)
    got = conv1x1_bn_act(x, w, a, b, interpret=True, block_rows=32)
    ref = jnp.maximum((x.reshape(-1, 24) @ w) * a + b, 0.0).reshape(2, 7, 5, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)
    # relu=False epilogue
    got = conv1x1_bn_act(x, w, a, b, relu=False, interpret=True, block_rows=32)
    ref = ((x.reshape(-1, 24) @ w) * a + b).reshape(2, 7, 5, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_conv1x1_bn_act_diff_gradients():
    """Custom VJP (Pallas fwd, XLA-dot bwd) == autodiff of the reference for
    every operand, relu on and off."""
    from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act_diff

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(48, 24), jnp.float32)
    w = jnp.asarray(rng.randn(24, 16) * 0.2, jnp.float32)
    a = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)
    for relu in (True, False):
        def f(x, w, a, b, relu=relu):
            return jnp.sum(
                conv1x1_bn_act_diff(x, w, a, b, relu=relu, interpret=True, block_rows=16)
                ** 2
            )

        def ref(x, w, a, b, relu=relu):
            y = (x @ w) * a + b
            if relu:
                y = jnp.maximum(y, 0.0)
            return jnp.sum(y**2)

        gp = jax.grad(f, argnums=(0, 1, 2, 3))(x, w, a, b)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, w, a, b)
        for p, r, name in zip(gp, gr, ("x", "w", "scale", "bias"), strict=True):
            np.testing.assert_allclose(
                np.asarray(p), np.asarray(r), atol=2e-4,
                err_msg=f"d{name} relu={relu}",
            )


def test_conv1x1_bn_act_gelu_epilogue_matches_reference():
    """act="gelu" (the ConvNeXt expand-Dense epilogue, ISSUE 17) == tanh-
    approx gelu((x @ w) * a + b) — the same approximation flax's nn.gelu
    defaults to, so the fused path matches the plain Dense+gelu program."""
    from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 5, 3, 24), jnp.float32)
    w = jnp.asarray(rng.randn(24, 16) * 0.2, jnp.float32)
    a = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)
    got = conv1x1_bn_act(x, w, a, b, act="gelu", interpret=True, block_rows=32)
    ref = jax.nn.gelu((x.reshape(-1, 24) @ w) * a + b, approximate=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref.reshape(2, 5, 3, 16)), atol=1e-5
    )


def test_conv1x1_bn_act_diff_gelu_gradients():
    """Backward parity for the gelu epilogue: the custom VJP's z-recompute +
    jax.vjp gelu backward == autodiff of the plain reference, all operands."""
    from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act_diff

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(48, 24), jnp.float32)
    w = jnp.asarray(rng.randn(24, 16) * 0.2, jnp.float32)
    a = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)

    def f(x, w, a, b):
        return jnp.sum(
            conv1x1_bn_act_diff(
                x, w, a, b, act="gelu", interpret=True, block_rows=16
            ) ** 2
        )

    def ref(x, w, a, b):
        return jnp.sum(jax.nn.gelu((x @ w) * a + b, approximate=True) ** 2)

    gp = jax.grad(f, argnums=(0, 1, 2, 3))(x, w, a, b)
    gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, w, a, b)
    for p, r, name in zip(gp, gr, ("x", "w", "scale", "bias"), strict=True):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), atol=2e-4, err_msg=f"d{name} gelu"
        )


def test_chained_window_parity_fused_vs_plain():
    """The chained-window program (the shape bench.py/autotune actually
    time): a lax.scan whose carry feeds the next trip's input must agree
    between the fused kernel and the plain path — values AND gradients
    survive the scan's repeated VJP."""
    from distributed_training_pytorch_tpu.ops.pallas import conv1x1_bn_act_diff

    rng = np.random.RandomState(5)
    x0 = jnp.asarray(rng.randn(32, 16), jnp.float32)
    w = jnp.asarray(rng.randn(16, 16) * 0.2, jnp.float32)
    a = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)

    def chain(apply, x0, w):
        def body(x, _):
            y = apply(x, w)
            return 0.5 * y + 0.5 * x, jnp.sum(y)
        return jax.lax.scan(body, x0, None, length=4)

    def fused(x, w):
        return conv1x1_bn_act_diff(x, w, a, b, interpret=True, block_rows=16)

    def plain(x, w):
        return jnp.maximum((x @ w) * a + b, 0.0)

    (cf, sf), (cp, sp) = chain(fused, x0, w), chain(plain, x0, w)
    np.testing.assert_allclose(np.asarray(cf), np.asarray(cp), atol=2e-5)
    np.testing.assert_allclose(np.asarray(sf), np.asarray(sp), rtol=2e-6)
    gf = jax.grad(lambda w: jnp.sum(chain(fused, x0, w)[0] ** 2))(w)
    gp = jax.grad(lambda w: jnp.sum(chain(plain, x0, w)[0] ** 2))(w)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gp), atol=5e-4)


def test_pallas_conv1x1_module_matches_nn_conv(monkeypatch):
    """models.resnet.PallasConv1x1 == nn.Conv 1x1 with the same kernel, for
    stride 1 and the strided-projection case."""
    from flax import linen as nn

    import distributed_training_pytorch_tpu.ops.pallas as plmod
    from distributed_training_pytorch_tpu.models.resnet import PallasConv1x1

    orig = plmod.conv1x1_bn_act_diff
    monkeypatch.setattr(
        plmod, "conv1x1_bn_act_diff",
        lambda *a, **k: orig(*a, **{**k, "interpret": True, "block_rows": 32}),
    )
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 8, 8, 12), jnp.float32)
    for strides in (1, 2):
        m = PallasConv1x1(10, strides=strides)
        v = m.init(jax.random.key(0), x)
        assert v["params"]["kernel"].shape == (1, 1, 12, 10)  # nn.Conv layout
        y = m.apply(v, x)
        ref = nn.Conv(10, (1, 1), strides=(strides, strides), use_bias=False).apply(
            {"params": {"kernel": v["params"]["kernel"]}}, x
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
