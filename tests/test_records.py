"""Record-file (data/records.py) round-trip and loader integration tests."""

import numpy as np
import pytest

from distributed_training_pytorch_tpu.data import (
    RecordFileSource,
    ShardedLoader,
    write_shards,
)


def _payloads(n):
    rng = np.random.RandomState(0)
    return [(rng.bytes(rng.randint(1, 64)), int(i % 7)) for i in range(n)]


def test_round_trip(tmp_path):
    items = _payloads(23)
    paths = write_shards(str(tmp_path / "train"), items, num_shards=4)
    assert len(paths) == 4
    src = RecordFileSource(str(tmp_path), decode=lambda b: b)
    assert len(src) == 23
    # round-robin sharding: rebuild the writer's order to compare
    by_shard = [[] for _ in range(4)]
    for i, item in enumerate(items):
        by_shard[i % 4].append(item)
    expected = [item for shard in by_shard for item in shard]
    for i in range(23):
        payload, label = src.read_record(i)
        assert (payload, label) == expected[i]


def test_bad_magic(tmp_path):
    p = tmp_path / "junk-00000-of-00001.rec"
    p.write_bytes(b"NOTAREC" * 4)
    with pytest.raises(ValueError, match="bad magic"):
        RecordFileSource(str(tmp_path))


def test_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        RecordFileSource(str(tmp_path / "none-*.rec"))


def test_image_payloads_through_loader(tmp_path):
    """PNG payloads decode through the default decoder and batch via
    ShardedLoader with a transform."""
    from PIL import Image
    import io

    rng = np.random.RandomState(1)
    items = []
    for i in range(12):
        img = Image.fromarray(rng.randint(0, 255, size=(10 + i, 8, 3), dtype=np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        items.append((buf.getvalue(), i % 3))
    write_shards(str(tmp_path / "t"), items, num_shards=2)

    def tfm(img, *, epoch=0, index=0):
        out = np.zeros((8, 8, 3), np.float32)
        out[: img.shape[0], : img.shape[1]] = img[:8, :8] / 255.0
        return out

    src = RecordFileSource(str(tmp_path), transform=tfm)
    loader = ShardedLoader(src, 4, shuffle=True, seed=0, transform=src.transform, num_workers=2)
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0]["image"].shape == (4, 8, 8, 3)
    assert batches[0]["label"].dtype == np.int32


def _identity(b):
    return b


def test_pickling_drops_file_handles(tmp_path):
    import pickle

    write_shards(str(tmp_path / "t"), _payloads(5), num_shards=1)
    src = RecordFileSource(str(tmp_path), decode=_identity)
    src.read_record(0)  # opens a handle
    clone = pickle.loads(pickle.dumps(src))
    assert clone.read_record(3) == src.read_record(3)


def test_concurrent_reads_are_uncorrupted(tmp_path):
    """Regression: shared-handle seek+read interleaved across loader threads
    and corrupted records; os.pread is atomic per call."""
    from concurrent.futures import ThreadPoolExecutor

    items = _payloads(64)
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = RecordFileSource(str(tmp_path), decode=_identity)
    expected = [src.read_record(i) for i in range(64)]

    def worker(seed):
        rng = np.random.RandomState(seed)
        for _ in range(400):
            i = int(rng.randint(0, 64))
            assert src.read_record(i) == expected[i]
        return True

    with ThreadPoolExecutor(8) as pool:
        assert all(pool.map(worker, range(8)))
