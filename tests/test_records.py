"""Record-file (data/records.py) round-trip and loader integration tests."""

import numpy as np
import pytest

from distributed_training_pytorch_tpu.data import (
    RecordFileSource,
    ShardedLoader,
    write_shards,
)


def _payloads(n):
    rng = np.random.RandomState(0)
    return [(rng.bytes(rng.randint(1, 64)), int(i % 7)) for i in range(n)]


def test_round_trip(tmp_path):
    items = _payloads(23)
    paths = write_shards(str(tmp_path / "train"), items, num_shards=4)
    assert len(paths) == 4
    src = RecordFileSource(str(tmp_path), decode=lambda b: b)
    assert len(src) == 23
    # round-robin sharding: rebuild the writer's order to compare
    by_shard = [[] for _ in range(4)]
    for i, item in enumerate(items):
        by_shard[i % 4].append(item)
    expected = [item for shard in by_shard for item in shard]
    for i in range(23):
        payload, label = src.read_record(i)
        assert (payload, label) == expected[i]


def test_bad_magic(tmp_path):
    p = tmp_path / "junk-00000-of-00001.rec"
    p.write_bytes(b"NOTAREC" * 4)
    with pytest.raises(ValueError, match="bad magic"):
        RecordFileSource(str(tmp_path))


def test_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        RecordFileSource(str(tmp_path / "none-*.rec"))


def test_image_payloads_through_loader(tmp_path):
    """PNG payloads decode through the default decoder and batch via
    ShardedLoader with a transform."""
    from PIL import Image
    import io

    rng = np.random.RandomState(1)
    items = []
    for i in range(12):
        img = Image.fromarray(rng.randint(0, 255, size=(10 + i, 8, 3), dtype=np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        items.append((buf.getvalue(), i % 3))
    write_shards(str(tmp_path / "t"), items, num_shards=2)

    def tfm(img, *, epoch=0, index=0):
        out = np.zeros((8, 8, 3), np.float32)
        out[: img.shape[0], : img.shape[1]] = img[:8, :8] / 255.0
        return out

    src = RecordFileSource(str(tmp_path), transform=tfm)
    loader = ShardedLoader(src, 4, shuffle=True, seed=0, transform=src.transform, num_workers=2)
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0]["image"].shape == (4, 8, 8, 3)
    assert batches[0]["label"].dtype == np.int32


def _identity(b):
    return b


def test_pickling_drops_file_handles(tmp_path):
    import pickle

    write_shards(str(tmp_path / "t"), _payloads(5), num_shards=1)
    src = RecordFileSource(str(tmp_path), decode=_identity)
    src.read_record(0)  # opens a handle
    clone = pickle.loads(pickle.dumps(src))
    assert clone.read_record(3) == src.read_record(3)


def test_concurrent_reads_are_uncorrupted(tmp_path):
    """Regression: shared-handle seek+read interleaved across loader threads
    and corrupted records; os.pread is atomic per call."""
    from concurrent.futures import ThreadPoolExecutor

    items = _payloads(64)
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = RecordFileSource(str(tmp_path), decode=_identity)
    expected = [src.read_record(i) for i in range(64)]

    def worker(seed):
        rng = np.random.RandomState(seed)
        for _ in range(400):
            i = int(rng.randint(0, 64))
            assert src.read_record(i) == expected[i]
        return True

    with ThreadPoolExecutor(8) as pool:
        assert all(pool.map(worker, range(8)))


def _png_bytes(rng, h, w):
    import io

    from PIL import Image

    img = Image.fromarray(rng.randint(0, 255, size=(h, w, 3), dtype=np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def test_native_record_source_matches_python_path(tmp_path):
    """The native in-memory decode+resize+normalize batch path agrees with the
    per-record Python (PIL/cv2 + transforms) fallback."""
    from distributed_training_pytorch_tpu.data import NativeRecordFileSource
    from distributed_training_pytorch_tpu.data import native

    rng = np.random.RandomState(7)
    items = [(_png_bytes(rng, 12 + i, 9 + i), i % 3) for i in range(10)]
    write_shards(str(tmp_path / "t"), items, num_shards=2)
    src = NativeRecordFileSource(str(tmp_path), height=8, width=8)
    rows = np.arange(10)
    batch = src.load_batch(rows, epoch=0)
    assert batch["image"].shape == (10, 8, 8, 3)
    assert batch["image"].dtype == np.float32
    # Python reference path on the same records.
    ref = np.stack([src._py_transform(src.decode(src.read_record(i)[0])) for i in rows])
    if native.available():
        # native bilinear is cv2-compatible; PIL/cv2 resample may differ a bit
        np.testing.assert_allclose(batch["image"], ref, atol=0.35)
    else:
        np.testing.assert_allclose(batch["image"], ref, atol=1e-6)
    # round-robin sharding stores records shard-major: shard0 = items 0,2,..
    writer_order = [0, 2, 4, 6, 8, 1, 3, 5, 7, 9]
    assert batch["label"].tolist() == [j % 3 for j in writer_order]


def test_native_bytes_decoder_roundtrip():
    """decode_resize_normalize_bytes decodes jpeg+png payloads exactly like
    the file-path native call."""
    from distributed_training_pytorch_tpu.data import native

    if not native.available():
        import pytest as _p

        _p.skip("native runtime unavailable")
    import tempfile

    rng = np.random.RandomState(8)
    payloads = [_png_bytes(rng, 20, 16), _png_bytes(rng, 9, 31)]
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    from_mem = native.decode_resize_normalize_bytes(payloads, 10, 10, mean, std)
    with tempfile.TemporaryDirectory() as d:
        paths = []
        for i, p in enumerate(payloads):
            path = f"{d}/{i}.png"
            open(path, "wb").write(p)
            paths.append(path)
        from_files = native.decode_resize_normalize(paths, 10, 10, mean, std)
    np.testing.assert_array_equal(from_mem, from_files)


def test_native_record_source_bmp_fallback(tmp_path):
    """Non-JPEG/PNG payloads (bmp) fall back to the Python decoder per record
    instead of failing the whole native batch."""
    import io

    from PIL import Image

    from distributed_training_pytorch_tpu.data import NativeRecordFileSource

    rng = np.random.RandomState(9)
    items = [(_png_bytes(rng, 14, 11), 0)]
    bmp = io.BytesIO()
    Image.fromarray(rng.randint(0, 255, size=(10, 10, 3), dtype=np.uint8)).save(
        bmp, format="BMP"
    )
    items.append((bmp.getvalue(), 1))
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = NativeRecordFileSource(str(tmp_path), height=8, width=8)
    batch = src.load_batch(np.arange(2), epoch=0)
    assert batch["image"].shape == (2, 8, 8, 3)
    assert np.isfinite(batch["image"]).all()
    assert batch["label"].tolist() == [0, 1]


def test_native_train_source_uint8_deterministic(tmp_path):
    """NativeRecordTrainSource (the production train path): uint8 end to end,
    augmentation deterministic per (seed, epoch, record) and varying across
    epochs; decode agrees with the Python fallback."""
    from distributed_training_pytorch_tpu.data import NativeRecordTrainSource

    rng = np.random.RandomState(11)
    items = [(_png_bytes(rng, 40, 36), i % 5) for i in range(20)]
    write_shards(str(tmp_path / "t"), items, num_shards=2)
    src = NativeRecordTrainSource(str(tmp_path), 32, 32, pad=4, seed=1, hflip=False)
    loader = ShardedLoader(
        src, 8, shuffle=True, seed=1, num_workers=2, process_index=0, process_count=1
    )
    b1 = next(iter(loader))
    b2 = next(iter(loader))
    assert b1["image"].dtype == np.uint8 and b1["image"].shape == (8, 32, 32, 3)
    np.testing.assert_array_equal(b1["image"], b2["image"])
    # FIXED rows at two epochs (a loader iter would also reshuffle, which
    # would mask an augmenter that ignores epoch): same records, new crops.
    e0 = src.load_batch(np.arange(8), epoch=0)
    e1 = src.load_batch(np.arange(8), epoch=1)
    np.testing.assert_array_equal(e0["label"], e1["label"])
    assert not np.array_equal(e0["image"], e1["image"]), "epoch must vary the augmentation"

    # decode parity with the Python (cv2) fallback — augmentation off
    src_n = NativeRecordTrainSource(str(tmp_path), 32, 32, pad=0, seed=1, train=False)
    src_p = NativeRecordTrainSource(str(tmp_path), 32, 32, pad=0, seed=1, train=False)
    src_p._native = None
    bn = src_n.load_batch(np.arange(8), 0)
    bp = src_p.load_batch(np.arange(8), 0)
    assert bp["image"].dtype == np.uint8
    # native bilinear vs cv2: same convention, off-by-one rounding allowed
    assert np.abs(bn["image"].astype(int) - bp["image"].astype(int)).max() <= 1
    np.testing.assert_array_equal(bn["label"], bp["label"])


def test_native_train_source_python_augment_fallback(tmp_path):
    """Without the native lib, the numpy crop/flip fallback is deterministic
    and keyed per record (not per batch position)."""
    from distributed_training_pytorch_tpu.data import NativeRecordTrainSource

    rng = np.random.RandomState(12)
    items = [(_png_bytes(rng, 32, 32), 0) for _ in range(8)]
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = NativeRecordTrainSource(str(tmp_path), 32, 32, pad=4, seed=3, hflip=True)
    src._native = None
    a = src.load_batch(np.arange(8), epoch=2)
    b = src.load_batch(np.arange(8), epoch=2)
    np.testing.assert_array_equal(a["image"], b["image"])
    # reversed row order: each record's augmentation rides its index, so the
    # reversed batch is the row-reversed original
    c = src.load_batch(np.arange(8)[::-1], epoch=2)
    np.testing.assert_array_equal(a["image"][::-1], c["image"])
    d = src.load_batch(np.arange(8), epoch=3)
    assert not np.array_equal(a["image"], d["image"])


def test_decode_resize_u8_matches_float_path():
    """decode_resize_u8_bytes + host normalize == decode_resize_normalize_bytes
    exactly (same decoder, same resize, normalize applied to the same u8)."""
    from distributed_training_pytorch_tpu.data import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    rng = np.random.RandomState(13)
    payloads = [_png_bytes(rng, 21, 17), _png_bytes(rng, 40, 40)]
    mean = np.array([0.4, 0.5, 0.6], np.float32)
    std = np.array([0.2, 0.25, 0.3], np.float32)
    u8 = native.decode_resize_u8_bytes(payloads, 12, 12)
    assert u8.dtype == np.uint8
    f = native.decode_resize_normalize_bytes(payloads, 12, 12, mean, std)
    np.testing.assert_array_equal((u8.astype(np.float32) / 255.0 - mean) / std, f)


def test_mixed_batch_decode_error_names_batch_position(tmp_path):
    """A corrupt payload in a mixed native/fallback batch is reported by its
    BATCH position, not its position within the native-decodable subset."""
    import io

    from PIL import Image

    from distributed_training_pytorch_tpu.data import NativeRecordTrainSource, native

    if not native.available():
        pytest.skip("native runtime unavailable")
    rng = np.random.RandomState(14)
    bmp = io.BytesIO()
    Image.fromarray(rng.randint(0, 255, size=(9, 9, 3), dtype=np.uint8)).save(bmp, format="BMP")
    good = _png_bytes(rng, 16, 16)
    truncated = _png_bytes(rng, 16, 16)[:40]  # valid PNG signature, bad body
    items = [(bmp.getvalue(), 0), (good, 1), (truncated, 2), (good, 3)]
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = NativeRecordTrainSource(str(tmp_path), 8, 8, pad=0, train=False)
    # the message names the GLOBAL record index + its shard file, not a
    # position inside the (shuffled) batch or the native-decodable subset
    with pytest.raises(ValueError, match=r"record 2 \(.*\.rec #2\)"):
        src.load_batch(np.arange(4), epoch=0)
    # shuffled rows: still the same record named, by its global identity
    with pytest.raises(ValueError, match=r"record 2 \("):
        src.load_batch(np.array([3, 2, 1, 0]), epoch=0)


def test_native_train_source_rrc_mode(tmp_path):
    """aug='rrc' (ImageNet random-resized-crop fused with decode): uint8 out,
    deterministic per (seed, epoch, record), varies across epochs, and a
    constant-color source stays constant (any crop+resize of a constant is
    that constant) — content-level sanity for the crop window math."""
    import io

    from PIL import Image

    from distributed_training_pytorch_tpu.data import NativeRecordTrainSource, native

    rng = np.random.RandomState(21)
    items = []
    for i in range(8):
        img = rng.randint(0, 255, (60, 80, 3), np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        items.append((buf.getvalue(), i % 3))
    const = np.full((50, 70, 3), (10, 200, 90), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(const).save(buf, format="PNG")
    items.append((buf.getvalue(), 0))
    write_shards(str(tmp_path / "t"), items, num_shards=2)

    for use_native in ([True, False] if native.available() else [False]):
        src = NativeRecordTrainSource(str(tmp_path), 32, 32, aug="rrc", seed=5)
        if not use_native:
            src._native = None
        b1 = src.load_batch(np.arange(9), epoch=0)
        assert b1["image"].dtype == np.uint8 and b1["image"].shape == (9, 32, 32, 3)
        b2 = src.load_batch(np.arange(9), epoch=0)
        np.testing.assert_array_equal(b1["image"], b2["image"])
        b3 = src.load_batch(np.arange(9), epoch=1)
        assert not np.array_equal(b1["image"], b3["image"])
        # round-robin sharding: the constant record (writer index 8) lands at
        # global index 4 (shard 0 holds writer items 0,2,4,6,8)
        const_row = b1["image"][4]
        np.testing.assert_array_equal(
            const_row, np.broadcast_to((10, 200, 90), (32, 32, 3)).astype(np.uint8)
        )


def test_rrc_mode_val_path_is_plain_resize(tmp_path):
    """train=False in rrc mode ships the plain decode+resize (no random crop)."""
    from distributed_training_pytorch_tpu.data import NativeRecordTrainSource

    rng = np.random.RandomState(22)
    items = [(_png_bytes(rng, 40, 40), 0) for _ in range(4)]
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    src = NativeRecordTrainSource(str(tmp_path), 16, 16, aug="rrc", train=False)
    a = src.load_batch(np.arange(4), epoch=0)
    b = src.load_batch(np.arange(4), epoch=7)  # epoch must not matter
    np.testing.assert_array_equal(a["image"], b["image"])
