"""Mixed-precision subsystem (ISSUE 3): dtype policies, dynamic loss scaling,
and their integration through engine / trainer / checkpoint.

THE acceptance properties: the default ``precision="fp32"`` path is bit-exact
with pre-precision behavior; bf16 computes in bf16 while master weights and
optimizer state stay fp32; fp16 dynamic scaling grows/backs-off/skips fully
inside the compiled step; an overflow-skip and a nan-skip are ONE counted
event; chained bf16 windows are bit-exact with single-step bf16; and scale
state survives checkpoint/resume (including restoring a pre-precision
checkpoint with a fresh default scale).

Cost note: trainer-level tests use a tiny Dense net (seconds of CPU compile),
not the toy VGG of test_trainer.py — every case here constructs its own
trainer, so each must stay cheap.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.fault import FaultPlan
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.precision import (
    DynamicScale,
    NoOpScale,
    Policy,
    compute_dtype,
    get_policy,
    is_dynamic,
    model_dtype_for_entry,
    resolve_loss_scale,
)
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.trainer import Trainer

from test_engine import TinyMLP, criterion, synthetic_batch


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def make_engine(seen=None, **engine_kw):
    """TinyMLP engine; ``seen`` (a dict) records the param dtype the loss fn
    actually receives — i.e. what dtype the model computes in."""
    mesh = mesh_lib.create_mesh()
    model = TinyMLP()
    base = make_supervised_loss(model, criterion)

    def loss_fn(params, model_state, batch, rng, train):
        if seen is not None:  # trace-time probe
            seen["param_dtype"] = str(jax.tree.leaves(params)[0].dtype)
            seen["input_dtype"] = str(batch["image"].dtype)
        return base(params, model_state, batch, rng, train)

    engine = TrainEngine(loss_fn, optax.sgd(0.05, momentum=0.9), mesh, **engine_kw)
    state = engine.init_state(
        jax.random.key(0), lambda rng: model.init(rng, jnp.zeros((1, 4, 4, 3)))
    )
    return engine, state


def stack_batches(host_batches):
    return jax.tree.map(lambda *xs: np.stack(xs), *host_batches)


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb, strict=True):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Policy resolution + casting rules.


def test_policy_presets_and_aliases():
    assert get_policy(None).name == "fp32" and not get_policy(None).active
    assert get_policy("bfloat16") is get_policy("bf16")
    assert get_policy("fp16").compute_dtype == jnp.float16
    for name in ("fp32", "bf16", "fp16"):
        assert get_policy(name).param_dtype == jnp.float32  # master weights
    assert compute_dtype("bf16") == jnp.bfloat16
    p = Policy(jnp.float32, jnp.bfloat16, jnp.float32, name="custom")
    assert get_policy(p) is p
    with pytest.raises(ValueError, match="unknown precision"):
        get_policy("int8")


def test_cast_inputs_leaves_integers_alone():
    policy = get_policy("bf16")
    batch = {
        "image": jnp.zeros((2, 4), jnp.float32),
        "label": jnp.zeros((2,), jnp.int32),
        "raw": jnp.zeros((2,), jnp.uint8),
    }
    cast = policy.cast_inputs(batch)
    assert cast["image"].dtype == jnp.bfloat16
    assert cast["label"].dtype == jnp.int32
    assert cast["raw"].dtype == jnp.uint8


def test_model_dtype_for_entry_follows_resolved_policy():
    """The one entry-knob resolution rule: an ACTIVE policy wins however it
    was set (explicit ctor override included), the inactive fp32 policy with
    an explicit env 'fp32' means float32, and an unset knob keeps the
    entry's legacy dtype."""
    # explicit precision= override, env unset: the policy wins (the bug this
    # helper replaced: a per-entry env read built a bf16 model under fp16)
    assert model_dtype_for_entry("fp16", True, jnp.bfloat16) == jnp.float16
    assert model_dtype_for_entry("bf16", True, jnp.bfloat16) == jnp.bfloat16
    # an EXPLICIT fp32 request (env knob or ctor arg) means float32 even
    # though the resolved policy is identical to the unset default
    assert model_dtype_for_entry("fp32", True, jnp.bfloat16) == jnp.float32
    # fully unset knob + default policy = the entry's historical program
    assert model_dtype_for_entry(None, False, jnp.bfloat16) == jnp.bfloat16
    assert model_dtype_for_entry(None, False) == jnp.float32  # digits-style


def test_resolve_loss_scale_auto():
    assert resolve_loss_scale(None, get_policy("bf16")) is None
    assert is_dynamic(resolve_loss_scale(None, get_policy("fp16")))
    assert isinstance(resolve_loss_scale("none", get_policy("bf16")), NoOpScale)
    assert is_dynamic(resolve_loss_scale("dynamic", get_policy("bf16")))
    with pytest.raises(ValueError, match="unknown loss_scale"):
        resolve_loss_scale("static", get_policy("fp16"))


# ---------------------------------------------------------------------------
# DynamicScale protocol (pure, no engine).


def test_dynamic_scale_grow_backoff_skip():
    s = DynamicScale.create(initial_scale=1024.0, growth_interval=2)
    ok = jnp.asarray(True)
    bad = jnp.asarray(False)
    s = s.adjust(ok)  # counter 1, no growth yet
    assert float(s.scale) == 1024.0 and int(s.growth_counter) == 1
    s = s.adjust(ok)  # counter hits interval -> x2, counter resets
    assert float(s.scale) == 2048.0 and int(s.growth_counter) == 0
    s = s.adjust(bad)  # overflow -> /2, skip counted, counter resets
    assert float(s.scale) == 1024.0
    assert int(s.skipped_steps) == 1
    assert int(s.growth_counter) == 0
    # clamps: backoff floors at min_scale, growth caps at max_scale
    tiny = DynamicScale.create(initial_scale=1.0, min_scale=1.0)
    assert float(tiny.adjust(bad).scale) == 1.0
    big = DynamicScale.create(initial_scale=2.0**24, growth_interval=1, max_scale=2.0**24)
    assert float(big.adjust(ok).scale) == 2.0**24


def test_dynamic_scale_unscale_is_exact():
    s = DynamicScale.create(initial_scale=2.0**15)
    grads = {"w": jnp.asarray([3.0, -7.25], jnp.float32)}
    scaled = jax.tree.map(lambda g: g * s.scale, grads)
    np.testing.assert_array_equal(
        np.asarray(s.unscale_grads(scaled)["w"]), np.asarray(grads["w"])
    )


# ---------------------------------------------------------------------------
# Engine: default fp32 bit-exactness, bf16 master weights, fp16 scaling.


def test_default_fp32_bit_exact_with_explicit_policy(devices):
    """The pre-PR acceptance proxy: the default engine (no precision args —
    the exact pre-precision construction) and an engine with the fp32 policy
    + NoOpScale spelled out produce bit-identical params/opt_state/metrics."""
    e1, s1 = make_engine()
    e2, s2 = make_engine(precision="fp32", loss_scale=NoOpScale())
    assert s1.loss_scale is None  # default state layout unchanged
    b = synthetic_batch(16, seed=0)
    for _ in range(3):
        s1, m1 = e1.train_step(s1, e1.shard_batch(b))
        s2, m2 = e2.train_step(s2, e2.shard_batch(b))
    assert_trees_equal(s1.params, s2.params)
    assert_trees_equal(s1.opt_state, s2.opt_state)
    for k in dict(m1):
        np.testing.assert_array_equal(np.asarray(m1[k]), np.asarray(m2[k]))
    assert "nonfinite" not in dict(m1)  # no guard materialized by default


def test_bf16_master_weights_fp32_round_trip(devices):
    """bf16 policy: the loss fn sees bf16 params/inputs (compute dtype) while
    the state's master weights stay fp32 and keep taking fp32 updates."""
    seen = {}
    engine, state = make_engine(seen=seen, precision="bf16")
    b = synthetic_batch(16, seed=1)
    losses = []
    for _ in range(20):
        state, metrics = engine.train_step(state, engine.shard_batch(b))
        losses.append(float(metrics["ce_loss"]))
    assert seen["param_dtype"] == "bfloat16"
    assert seen["input_dtype"] == "bfloat16"
    for leaf in jax.tree.leaves(state.params):
        assert str(leaf.dtype) == "float32"
    for leaf in jax.tree.leaves(state.opt_state):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert str(leaf.dtype) == "float32"
    assert losses[-1] < losses[0] * 0.5, losses
    # fp32 master accumulation: 20 bf16-rounded updates of lr*grad ~1e-3
    # magnitude must actually move the weights (a bf16 master would stall
    # once updates drop below ~1/256 of the weight scale).
    assert int(state.step) == 20


def test_fp16_dynamic_scale_grows_in_engine(devices):
    engine, state = make_engine(
        precision="fp16", loss_scale=DynamicScale.create(growth_interval=4)
    )
    b = synthetic_batch(16, seed=2)
    for _ in range(8):
        state, metrics = engine.train_step(state, engine.shard_batch(b))
    # two full growth intervals of finite steps: 2^15 -> 2^17
    assert float(state.loss_scale.scale) == 2.0**17
    assert int(state.loss_scale.skipped_steps) == 0
    m = dict(metrics)
    assert float(m["nonfinite"]) == 0.0
    # the metric reports the scale the step USED (pre-adjust): step 8 ran at
    # 2^16 and grew to 2^17 on completion
    assert float(m["loss_scale"]) == 2.0**16
    # the reported loss is the UNSCALED fp32 loss
    assert float(m["ce_loss"]) < 10.0


def test_fp16_overflow_skips_step_and_backs_off(devices):
    engine, state = make_engine(precision="fp16", loss_scale=DynamicScale.create())
    b = synthetic_batch(16, seed=3)
    state, _ = engine.train_step(state, engine.shard_batch(b))
    params_before = jax.tree.map(lambda x: np.array(x), state.params)
    poisoned = dict(b, image=np.full_like(b["image"], np.nan))
    state, metrics = engine.train_step(state, engine.shard_batch(poisoned))
    assert float(metrics["nonfinite"]) == 1.0
    assert_trees_equal(params_before, state.params)  # update dropped
    assert float(state.loss_scale.scale) == 2.0**14  # backed off
    assert int(state.loss_scale.skipped_steps) == 1
    assert int(state.step) == 2  # step still advances past the poison


def test_bf16_chained_bit_exact_with_single_step(devices):
    """The PR 2 invariant extended to mixed precision: a bf16 chained window
    == the same steps run singly, bit-for-bit (params, opt_state, metrics)."""
    host = [synthetic_batch(16, seed=60 + i) for i in range(4)]
    eng_a, state_a = make_engine(precision="bf16")
    eng_b, state_b = make_engine(precision="bf16")
    seq_metrics = []
    for hb in host:
        state_a, m = eng_a.train_step(state_a, eng_a.shard_batch(hb))
        seq_metrics.append(jax.device_get(m))
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 4)
    assert_trees_equal(state_a.params, state_b.params)
    assert_trees_equal(state_a.opt_state, state_b.opt_state)
    stacked = jax.device_get(stacked)
    for i, m in enumerate(seq_metrics):
        for k, v in m.items():
            np.testing.assert_array_equal(np.asarray(v), np.asarray(stacked[k][i]))


def test_fp16_chained_carries_scale_state(devices):
    """Dynamic-scale state rides the chained scan: growth inside a window
    matches the sequential run exactly."""
    host = [synthetic_batch(16, seed=70 + i) for i in range(4)]
    kw = dict(precision="fp16", loss_scale=DynamicScale.create(growth_interval=2))
    eng_a, state_a = make_engine(**kw)
    eng_b, state_b = make_engine(**kw)
    for hb in host:
        state_a, _ = eng_a.train_step(state_a, eng_a.shard_batch(hb))
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 4)
    assert float(state_b.loss_scale.scale) == float(state_a.loss_scale.scale) == 2.0**17
    assert_trees_equal(state_a.params, state_b.params)
    # per-step loss_scale metrics stack as scan outputs
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(stacked)["loss_scale"]),
        np.array([2.0**15, 2.0**15, 2.0**16, 2.0**16]),
    )


def test_fp16_microbatch_accumulation_unscales_once(devices):
    """The accum scan accumulates SCALED grads and unscales after: fp16
    accum-2 must track fp16 accum-1 closely on the same data (same policy,
    same scale — values differ only by half-precision summation order)."""
    b = synthetic_batch(32, seed=4)
    e1, s1 = make_engine(precision="fp16", loss_scale=DynamicScale.create())
    e2, s2 = make_engine(
        precision="fp16", loss_scale=DynamicScale.create(), accum_steps=2
    )
    s1, m1 = e1.train_step(s1, e1.shard_batch(b))
    s2, m2 = e2.train_step(s2, e2.shard_batch(b))
    assert float(m1["nonfinite"]) == float(m2["nonfinite"]) == 0.0
    for a, c in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=2e-3)


# ---------------------------------------------------------------------------
# Checkpoint: scale state round-trip + pre-precision compatibility.


def test_checkpoint_scale_state_round_trip(devices, tmp_path):
    engine, state = make_engine(precision="fp16", loss_scale=DynamicScale.create())
    state = state.replace(
        loss_scale=state.loss_scale.replace(
            scale=jnp.asarray(1024.0, jnp.float32),
            growth_counter=jnp.asarray(5, jnp.int32),
            skipped_steps=jnp.asarray(7, jnp.int32),
        )
    )
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    mgr.save("last", state, 3)
    assert mgr.read_meta("last")["loss_scale"] == "DynamicScale"
    _, target = make_engine(precision="fp16", loss_scale=DynamicScale.create())
    restored, epoch = mgr.restore("last", target)
    assert epoch == 3
    assert float(restored.loss_scale.scale) == 1024.0
    assert int(restored.loss_scale.growth_counter) == 5
    assert int(restored.loss_scale.skipped_steps) == 7


def test_checkpoint_pre_precision_loads_with_fresh_scale(devices, tmp_path):
    """A checkpoint saved WITHOUT scale state (the pre-precision layout —
    default engines still write exactly it) restores into a dynamic-scale
    target with the target's fresh default scale."""
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    _, old_state = make_engine()  # loss_scale=None -> no scale item on disk
    mgr.save("last", old_state, 1)
    assert not os.path.isdir(os.path.join(str(tmp_path / "ckpt"), "last", "scale"))
    assert "loss_scale" not in mgr.read_meta("last")
    _, target = make_engine(
        precision="fp16", loss_scale=DynamicScale.create(initial_scale=2.0**15)
    )
    restored, _ = mgr.restore("last", target)
    assert float(restored.loss_scale.scale) == 2.0**15  # fresh default
    assert int(restored.loss_scale.skipped_steps) == 0
    # and the reverse: a scale-carrying checkpoint under an fp32 target
    eng_fp16, st_fp16 = make_engine(precision="fp16", loss_scale=DynamicScale.create())
    mgr.save("fp16", st_fp16, 2)
    _, plain_target = make_engine()
    restored2, _ = mgr.restore("fp16", plain_target)
    assert restored2.loss_scale is None


# ---------------------------------------------------------------------------
# Trainer integration: ctor knob + validation, single-count accounting,
# TensorBoard emission.


class MiniNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(3)(x)


class MiniTrainer(Trainer):
    """Cheap trainer (Dense net, 4x4 images) — each precision case builds its
    own, so construction must cost seconds, not the toy VGG's ~15-40s."""

    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(64,)).astype(np.int32)
        images = (rng.randn(64, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return MiniNet()

    def build_criterion(self):
        def crit(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return crit

    def build_optimizer(self, schedule):
        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


def make_mini(tmp_path, mesh, **kw):
    defaults = dict(
        max_epoch=2,
        batch_size=16,
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=0,
        async_checkpoint=False,
        mesh=mesh,
        progress=False,
        logger=type("Q", (), {"log": staticmethod(lambda *a, **k: None)})(),
    )
    defaults.update(kw)
    return MiniTrainer(**defaults)


def test_trainer_rejects_fp16_without_scaling(tmp_path, mesh):
    with pytest.raises(ValueError, match="requires dynamic loss scaling"):
        make_mini(tmp_path, mesh, precision="fp16", loss_scale="none")


def test_trainer_rejects_dynamic_scale_with_nan_raise(tmp_path, mesh):
    with pytest.raises(ValueError, match="incompatible with dynamic loss"):
        make_mini(tmp_path, mesh, precision="fp16", nan_policy="raise")
    # restore_last_good would roll the whole state back (and undo the
    # backoff) on every benign calibration overflow — also rejected
    with pytest.raises(ValueError, match="incompatible with dynamic loss"):
        make_mini(tmp_path, mesh, precision="fp16", nan_policy="restore_last_good")


def test_trainer_fp16_defaults_to_dynamic_scale(tmp_path, mesh):
    t = make_mini(tmp_path, mesh, precision="fp16")
    assert is_dynamic(t.state.loss_scale)
    assert t.model_dtype == jnp.float16
    t.train()
    assert int(t.state.loss_scale.skipped_steps) == 0
    assert int(t.state.step) == 8


def test_trainer_overflow_and_nan_counted_once(tmp_path, mesh):
    """The reconciliation clause: with BOTH nan_policy='skip' (engine guard)
    and a DynamicScale active, a poisoned step is one event — one engine
    skip, one nonfinite_steps count, one loss-scale skip — never two."""
    plan = FaultPlan().add("nan_loss", epoch=0, step=1)
    t = make_mini(
        tmp_path,
        mesh,
        precision="fp16",
        nan_policy="skip",
        fault_plan=plan,
    )
    t.train()
    assert t.fault_plan.count_fired("nan_loss") == 1
    assert t.nonfinite_steps == 1  # counted once, not twice
    assert int(t.state.loss_scale.skipped_steps) == 1
    assert float(t.state.loss_scale.scale) == 2.0**14  # one backoff
    for leaf in jax.tree.leaves(t.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_trainer_default_precision_is_fp32_and_scale_free(tmp_path, mesh):
    t = make_mini(tmp_path, mesh)
    assert t.precision.name == "fp32" and not t.precision.active
    assert t.state.loss_scale is None
    assert t.model_dtype == jnp.float32
    assert t.precision_requested is False
    # an explicit "fp32" resolves to the same policy but records the request
    t2 = make_mini(tmp_path, mesh, precision="fp32")
    assert t2.precision.name == "fp32" and t2.precision_requested is True


def test_metrics_writer_noop_without_tensorboardx(tmp_path, mesh, monkeypatch):
    """tensorboard_dir set but tensorboardX unimportable: the writer stays a
    no-op and the precision scalars path (loss_scale/skipped_steps emission)
    runs silently through a full dynamic-scale training."""
    monkeypatch.setitem(sys.modules, "tensorboardX", None)  # import -> ImportError
    t = make_mini(
        tmp_path,
        mesh,
        precision="fp16",
        tensorboard_dir=str(tmp_path / "tb"),
    )
    assert not t.metrics_writer.active
    t.train()  # _write_precision_scalars must be a silent no-op throughout
    assert not t.metrics_writer.active
    assert not os.path.exists(str(tmp_path / "tb"))  # nothing was written


def test_trainer_bf16_resume_preserves_behavior(tmp_path, mesh):
    """bf16 trainer saves/resumes through the normal checkpoint path (scale
    layout = pre-precision: NoOpScale-free state, no scale item)."""
    t = make_mini(tmp_path, mesh, precision="bf16", max_epoch=1, save_period=1)
    t.train()
    ckpt = os.path.join(t.save_weight_folder, "checkpoint_epoch_1")
    t2 = make_mini(
        tmp_path, mesh, precision="bf16", max_epoch=2,
        save_period=1, snapshot_path=ckpt if os.path.isdir(ckpt) else "latest_valid",
    )
    assert int(t2.state.step) == 4  # resumed mid-schedule
    t2.train()
    assert int(t2.state.step) == 8
