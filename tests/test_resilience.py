"""Resilience-layer tests (ISSUE 5): async checkpointing around the
crash-consistent manager, plus the satellite guards.

Acceptance pillars:

* a save's hot-loop cost is the device->host snapshot only; the commit runs
  on the background worker through the UNchanged staging+manifest+rename
  machinery, in enqueue order, with newest-wins coalescing per name;
* emergency saves (SIGTERM / watchdog) flush — complete, never abandon —
  in-flight background commits before committing synchronously, with no
  interleaved staging directories;
* background commit failures surface on the training thread (flush / next
  save), exactly as loud as a failed synchronous save;
* `restore_latest_valid` rejections land in the JSONL event log;
* a TensorBoard backend failure disables the MetricsWriter with one
  warning — never kills training.
"""

import os
import time

import jax
import numpy as np
import pytest
from flax import linen as nn

from distributed_training_pytorch_tpu.checkpoint import (
    BEST,
    LAST,
    CheckpointError,
    CheckpointManager,
)
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.fault import FaultPlan, corrupt_checkpoint
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.resilience import AsyncCheckpointSaver
from distributed_training_pytorch_tpu.telemetry import EventLog, read_events
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils.tensorboard import MetricsWriter

from test_fault import _tiny_state


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def _assert_params_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb, strict=True):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# AsyncCheckpointSaver around a bare manager (no trainer — fast).


def test_async_save_commits_in_background_and_restores(tmp_path):
    commits = []
    with CheckpointManager(tmp_path / "c", async_save=False) as mgr:
        saver = AsyncCheckpointSaver(mgr, on_commit=lambda n, s: commits.append((n, s)))
        state = _tiny_state(seed=1, step=5)
        stall = saver.save_async(LAST, state, epoch=3, loop_state={"step_in_epoch": 2})
        assert stall >= 0.0
        saver.flush()
        assert saver.committed == 1 and saver.in_flight is False
        assert commits and commits[0][0] == LAST and commits[0][1] > 0
        assert mgr.is_valid(LAST)
        restored, epoch = mgr.restore(LAST, _tiny_state(seed=9))
        assert epoch == 3
        assert mgr.read_meta(LAST)["loop"] == {"step_in_epoch": 2}
        _assert_params_equal(restored.params, state.params)
        saver.close()


def test_newest_wins_supersedes_queued_same_name(tmp_path):
    """Depth-1 per name: while a commit is held in flight, a newer `last`
    snapshot replaces the queued older one — the superseded snapshot was
    never visible on disk, and the final committed `last` is the newest."""
    with CheckpointManager(tmp_path / "c", async_save=False) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saver.commit_delay_s = 0.5  # hold the worker in `committing`
        states = [_tiny_state(seed=s, step=s) for s in (1, 2, 3)]
        for i, state in enumerate(states):
            saver.save_async(LAST, state, epoch=i)
        saver.commit_delay_s = 0.0
        saver.flush()
        assert saver.superseded >= 1
        assert saver.committed + saver.superseded == 3
        restored, epoch = mgr.restore(LAST, _tiny_state(seed=9))
        assert epoch == 2
        _assert_params_equal(restored.params, states[-1].params)
        saver.close()


def test_distinct_names_queue_fifo_never_dropped(tmp_path):
    """`best` then `last` at an epoch boundary: different names must BOTH
    commit (newest-wins applies per name), in enqueue order."""
    with CheckpointManager(tmp_path / "c", async_save=False) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saver.commit_delay_s = 0.3
        saver.save_async(BEST, _tiny_state(seed=1), epoch=1)
        saver.save_async(LAST, _tiny_state(seed=2), epoch=2)
        saver.commit_delay_s = 0.0
        saver.flush()
        assert saver.committed == 2 and saver.superseded == 0
        assert mgr.is_valid(BEST) and mgr.is_valid(LAST)
        # commit (= mtime) order matches enqueue order
        assert os.path.getmtime(mgr.path(BEST)) <= os.path.getmtime(mgr.path(LAST))
        saver.close()


def test_background_commit_error_surfaces_on_flush(tmp_path):
    """A background save that exhausts its retries must fail the TRAINING
    thread at the next barrier, not vanish on the worker."""
    plan = FaultPlan().add("checkpoint_write", count=10)
    with CheckpointManager(
        tmp_path / "c", async_save=False, save_retries=1, retry_backoff=0.01,
        fault_plan=plan,
    ) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saver.save_async(LAST, _tiny_state(), epoch=1)
        with pytest.raises(CheckpointError):
            saver.flush()
        assert saver.flush() is None  # error consumed exactly once
        saver.close()


def test_save_sync_defers_but_never_drops_prior_background_error(tmp_path):
    """An emergency save must run even when the preceding background commit
    failed — but that failure is re-stashed, not swallowed: the next flush
    still raises it."""
    plan = FaultPlan().add("checkpoint_write", count=2)  # async save's 2 attempts
    with CheckpointManager(
        tmp_path / "c", async_save=False, save_retries=1, retry_backoff=0.01,
        fault_plan=plan,
    ) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saver.save_async("checkpoint_epoch_1", _tiny_state(seed=1), epoch=1)
        saver.save_sync(LAST, _tiny_state(seed=2), epoch=1)  # must not raise
        assert mgr.is_valid(LAST)
        with pytest.raises(CheckpointError):
            saver.flush()
        saver.close()


def test_emergency_save_flushes_in_flight_commit_first(tmp_path):
    """save_sync completes the queued background save before its own commit:
    both checkpoints land, in order, via the single committer."""
    with CheckpointManager(tmp_path / "c", async_save=False) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saver.commit_delay_s = 0.4
        saver.save_async("checkpoint_epoch_1", _tiny_state(seed=1), epoch=1)
        saver.commit_delay_s = 0.0
        saver.save_sync(LAST, _tiny_state(seed=2), epoch=1, loop_state={"step_in_epoch": 3})
        # the emergency save is durable the moment save_sync returns
        assert mgr.is_valid(LAST) and mgr.is_valid("checkpoint_epoch_1")
        assert saver.committed == 1  # the async one; `last` went inline
        assert os.path.getmtime(mgr.path("checkpoint_epoch_1")) <= os.path.getmtime(
            mgr.path(LAST)
        )
        saver.close()


def test_maybe_save_best_async_applies_rule_on_thread(tmp_path):
    with CheckpointManager(
        tmp_path / "c", async_save=False, save_best_for=("accuracy", "geq")
    ) as mgr:
        saver = AsyncCheckpointSaver(mgr)
        saved, _ = saver.maybe_save_best({"accuracy": 0.5}, _tiny_state(seed=1), 1)
        assert saved
        saved, _ = saver.maybe_save_best({"accuracy": 0.4}, _tiny_state(seed=2), 2)
        assert not saved  # no improvement: nothing queued
        saver.flush()
        assert saver.committed == 1 and mgr.best_value == 0.5
        restored, epoch = mgr.restore(BEST, _tiny_state(seed=9))
        assert epoch == 1
        saver.close()


# ---------------------------------------------------------------------------
# Satellite: restore_latest_valid rejections are telemetry events.


def test_restore_latest_valid_emits_rejected_events(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    with CheckpointManager(tmp_path / "c", async_save=False) as mgr:
        mgr.event_log = EventLog(log_path)
        mgr.save("checkpoint_epoch_1", _tiny_state(seed=1, step=10), epoch=1)
        time.sleep(0.05)  # distinct mtimes for newest-first ordering
        mgr.save(LAST, _tiny_state(seed=2, step=20), epoch=2)
        corrupt_checkpoint(mgr.path(LAST), mode="truncate")
        _, epoch, name = mgr.restore_latest_valid(_tiny_state(seed=9))
        assert name == "checkpoint_epoch_1" and epoch == 1
        rejected = [
            e for e in read_events(log_path) if e["event"] == "checkpoint_rejected"
        ]
        assert [e["name"] for e in rejected] == [LAST]
        assert "torn write" in rejected[0]["reason"]
        mgr.event_log.close()


# ---------------------------------------------------------------------------
# Satellite: MetricsWriter try-once-then-disable.


class _ExplodingBackend:
    def __init__(self, exc):
        self.exc = exc
        self.closed = False

    def add_scalar(self, *args):
        raise self.exc

    def flush(self):
        raise self.exc

    def close(self):
        self.closed = True


@pytest.mark.parametrize("exc", [OSError("disk full"), RuntimeError("backend died")])
def test_metrics_writer_disables_on_backend_failure(exc):
    writer = MetricsWriter(None)
    writer._log_dir = "/nonexistent/tb"  # simulate an active backend
    backend = _ExplodingBackend(exc)
    writer._writer = backend
    with pytest.warns(UserWarning, match="MetricsWriter disabled"):
        writer.write(1, {"loss": 1.0})
    assert not writer.active and backend.closed
    writer.write(2, {"loss": 2.0})  # silent no-op: no raise, no new warning
    writer.reopen()  # a disabled writer must STAY disabled
    assert not writer.active


# ---------------------------------------------------------------------------
# Trainer integration: a tiny real run with async checkpointing.


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(3)(x)


class TinyTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(48,)).astype(np.int32)
        images = (rng.randn(48, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return TinyNet()

    def build_criterion(self):
        def crit(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return crit

    def build_optimizer(self, schedule):
        import optax

        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


class _Quiet:
    def log(self, *a, **k):
        pass


def make_tiny(tmp_path, mesh, **kw):
    defaults = dict(
        max_epoch=2,
        batch_size=8,
        have_validate=False,
        save_period=1,  # periodic save every epoch (the async stream)
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=0,
        async_checkpoint=True,
        mesh=mesh,
        progress=False,
        logger=_Quiet(),
    )
    defaults.update(kw)
    return TinyTrainer(**defaults)


def test_trainer_async_saves_commit_and_params_match_sync(tmp_path, mesh):
    """Async checkpointing observes the run, it does not alter it: final
    params bit-exact with a sync-save run, every periodic save fully
    committed by the end-of-training flush, and the flight record carries
    the async narrative (save mode, commit events, checkpoint_async time)."""
    a = make_tiny(tmp_path / "async", mesh, telemetry="on")
    a.train()
    b = make_tiny(tmp_path / "sync", mesh, async_checkpoint=False)
    b.train()
    _assert_params_equal(a.state.params, b.state.params)

    # every epoch's periodic checkpoint committed and validates
    for name in ("checkpoint_epoch_1", "checkpoint_epoch_2"):
        assert a.checkpoints.is_valid(name), name
    assert a.saver.committed == 2

    events = list(
        read_events(os.path.join(a.save_folder, "telemetry", "events.jsonl"))
    )
    saves = [e for e in events if e["event"] == "checkpoint_save"]
    assert saves and all(e["mode"] == "async" for e in saves)
    assert all(e["snapshot_ms"] >= 0 for e in saves)
    commits = [e for e in events if e["event"] == "checkpoint_commit"]
    assert len(commits) == 2 and all(e["commit_ms"] > 0 for e in commits)
    # the flight record stays strictly ordered despite the worker emitting
    mono = [e["t_mono"] for e in events]
    assert mono == sorted(mono)
    # goodput: the background commit time is visible, split from the stall,
    # and the stall (snapshot-only) is a fraction of the commit it replaced
    assert a.goodput.buckets["checkpoint_async"] > 0
    assert 0 < a.goodput.buckets["checkpoint"] < a.goodput.buckets["checkpoint_async"]
    assert abs(sum(a.goodput.fractions().values()) - 1.0) < 1e-9


def test_watchdog_fires_with_async_commit_in_flight(tmp_path, mesh):
    """Satellite 3 (the watchdog x async interplay): epoch 0's periodic save
    is still committing (held by the chaos seam) when a hung step in epoch 1
    trips the StepWatchdog. The preemption-style emergency save must FLUSH
    the in-flight commit — both checkpoints land, ordered, with no
    interleaved staging directories — and record the hung step's position."""
    plan = FaultPlan().add("hang", epoch=1, step=1, payload=0.8)
    trainer = make_tiny(
        tmp_path, mesh, step_timeout=0.2, fault_plan=plan, telemetry="on"
    )
    trainer.saver.commit_delay_s = 3.0  # hold epoch 0's commit in flight
    trainer.train()

    assert trainer._preempted
    # the held background save was completed, not abandoned
    assert trainer.checkpoints.is_valid("checkpoint_epoch_1")
    assert trainer.saver.committed == 1
    # the emergency save landed after it and is valid + resumable
    assert trainer.checkpoints.is_valid(LAST)
    meta = trainer.checkpoints.read_meta(LAST)
    assert meta["loop"]["step_in_epoch"] == 1  # step 0 done, step 1 hung
    assert os.path.getmtime(
        trainer.checkpoints.path("checkpoint_epoch_1")
    ) <= os.path.getmtime(trainer.checkpoints.path(LAST))
    # single-committer invariant: no staging leftovers from interleaving
    staging = os.path.join(trainer.save_weight_folder, ".staging")
    leftovers = [e for e in os.listdir(staging)] if os.path.isdir(staging) else []
    assert leftovers == []
    # the flight record shows the whole story in order
    events = list(
        read_events(os.path.join(trainer.save_folder, "telemetry", "events.jsonl"))
    )
    kinds = [e["event"] for e in events]
    assert "hung_step" in kinds and "checkpoint_commit" in kinds
    sync_saves = [
        e for e in events if e["event"] == "checkpoint_save" and e["mode"] == "sync"
    ]
    assert any(e["reason"] == "preemption" for e in sync_saves)


def test_nan_rollback_waits_for_async_commit(tmp_path, mesh):
    """restore_last_good under async saves: the rollback target is the
    fully-committed newest checkpoint (the trainer flushes before
    restoring), never a half-committed one."""
    plan = FaultPlan().add("nan_loss", epoch=1, step=2)
    trainer = make_tiny(
        tmp_path, mesh, nan_policy="restore_last_good", fault_plan=plan,
        telemetry="on",
    )
    trainer.saver.commit_delay_s = 1.0  # epoch 0's commit still in flight
    trainer.train()
    assert trainer.nonfinite_steps == 1
    assert trainer.nonfinite_rollbacks == 1
    for leaf in jax.tree.leaves(trainer.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
