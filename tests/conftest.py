"""Test bootstrap: force an 8-device virtual CPU platform.

SURVEY.md §4: multi-device semantics are tested without a pod via
``--xla_force_host_platform_device_count=8`` — real Mesh/jit/collective paths,
no TPU required. The setup lives in ``compat.force_host_devices`` (ISSUE 11
satellite: one implementation shared with ``scripts/static_audit.py`` and
``scripts/sharding_smoke.py``): it sets the env vars AND flips
``jax_platforms`` via config post-import, because the environment may
pre-import jax with a TPU plugin registered (sitecustomize) while the CPU
client reads XLA_FLAGS only at its own first initialization — which has not
happened yet at conftest import time.
"""

from distributed_training_pytorch_tpu import compat

compat.force_host_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {devs}"
    return devs
