"""Test bootstrap: force an 8-device virtual CPU platform.

SURVEY.md §4: multi-device semantics are tested without a pod via
``--xla_force_host_platform_device_count=8`` — real Mesh/jit/collective paths,
no TPU required. The environment may pre-import jax with a TPU plugin
registered (sitecustomize), so we both set the env vars AND flip
``jax_platforms`` via config post-import; the CPU client reads XLA_FLAGS at
its own first initialization, which has not happened yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {devs}"
    return devs
