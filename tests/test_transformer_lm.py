"""TransformerLM (models/transformer_lm.py): causality, attention impls,
MoE blocks, engine integration, ring-attention sequence parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_pytorch_tpu.models import LMTiny
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine


def tokens_batch(b, t, vocab=256, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, size=(b, t)), jnp.int32)


def test_forward_shape_and_dtype():
    model = LMTiny()
    toks = tokens_batch(2, 16)
    variables = model.init(jax.random.key(0), toks)
    logits = model.apply(variables, toks)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32


def test_causality():
    """Changing suffix tokens must not change prefix logits."""
    model = LMTiny()
    toks = tokens_batch(1, 20, seed=1)
    variables = model.init(jax.random.key(0), toks)
    base = model.apply(variables, toks)
    perturbed = toks.at[0, 12:].set((toks[0, 12:] + 7) % 256)
    out = model.apply(variables, perturbed)
    np.testing.assert_allclose(
        np.asarray(base[0, :12]), np.asarray(out[0, :12]), atol=1e-5
    )
    assert not np.allclose(np.asarray(base[0, 12:]), np.asarray(out[0, 12:]))


def test_flash_impl_matches_plain():
    """Forced Pallas kernel (interpreter on CPU) agrees with the plain path."""
    toks = tokens_batch(1, 24, seed=2)
    plain = LMTiny(attention_impl="plain")
    variables = plain.init(jax.random.key(0), toks)
    flash = LMTiny(attention_impl="flash")
    np.testing.assert_allclose(
        np.asarray(flash.apply(variables, toks)),
        np.asarray(plain.apply(variables, toks)),
        atol=2e-4,
    )


def test_moe_blocks_present_and_finite():
    model = LMTiny(moe_every=2, num_experts=4)
    toks = tokens_batch(2, 8, seed=3)
    variables = model.init(jax.random.key(0), toks)
    # block 1 (index 1, 1-indexed 2) is MoE; block 0 dense.
    params = variables["params"]
    assert "moe" in params["DecoderBlock_1"]
    assert "mlp_in" in params["DecoderBlock_0"]
    logits = model.apply(variables, toks)
    assert np.isfinite(np.asarray(logits)).all()


def test_lm_overfits_with_engine(devices):
    """End-to-end: next-token objective through TrainEngine on the data mesh;
    loss decreases on a tiny repeated corpus."""
    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    model = LMTiny(vocab_size=64)

    def criterion(logits, batch):
        targets = batch["label"]  # next tokens [B, T]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = jnp.mean(nll)
        return loss, {"loss": loss}

    def loss_fn(params, model_state, batch, rng, train):
        logits = model.apply({"params": params}, batch["image"], train=train,
                             rngs={"dropout": rng} if train else None)
        loss, metrics = criterion(logits, batch)
        return loss, (metrics, model_state)

    engine = TrainEngine(loss_fn, optax.adam(1e-2), mesh)
    rng = np.random.RandomState(4)
    seq = rng.randint(0, 64, size=(16, 17)).astype(np.int32)
    batch = engine.shard_batch({"image": seq[:, :-1], "label": seq[:, 1:]})
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))
    )
    losses = []
    for _ in range(30):
        state, m = engine.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


@pytest.mark.slow
def test_ring_attention_impl_matches_plain(devices):
    """attention_impl='ring' over a seq mesh matches the plain causal path."""
    mesh = mesh_lib.create_mesh({mesh_lib.SEQ_AXIS: 4}, devices=devices[:4])
    toks = tokens_batch(2, 32, seed=5)
    plain = LMTiny(attention_impl="plain")
    variables = plain.init(jax.random.key(0), toks)
    ring = LMTiny(attention_impl="ring", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(ring.apply(variables, toks)),
        np.asarray(plain.apply(variables, toks)),
        atol=2e-4,
    )


def test_gpt_small_factory_accepts_max_len_override():
    """Regression: GPTSmall(max_len=...) must not collide with its default
    (eval_shape only — the 124M-param model never materializes)."""
    from distributed_training_pytorch_tpu.models import GPTSmall

    model = GPTSmall(vocab_size=1000, max_len=256)
    toks = jnp.zeros((1, 256), jnp.int32)
    shapes = jax.eval_shape(model.init, jax.random.key(0), toks)
    assert shapes["params"]["pos_embed"].shape == (1, 256, 768)
    logits = jax.eval_shape(
        model.apply, shapes, jnp.zeros((2, 64), jnp.int32)
    )
    assert logits.shape == (2, 64, 1000)


def test_cached_decode_matches_full_forward():
    """Single-token KV-cache decode produces the same logits as the full
    causal forward at every position."""
    model = LMTiny(vocab_size=32, max_len=16)
    toks = tokens_batch(2, 10, vocab=32, seed=6)
    variables = model.init(jax.random.key(0), toks)
    full = model.apply(variables, toks)  # [B, T, V]

    cache = None
    step_logits = []
    for t in range(10):
        inputs = {**variables} if cache is None else {**variables, "cache": cache}
        logits, state = model.apply(inputs, toks[:, t : t + 1], decode=True, mutable=["cache"])
        cache = state["cache"]
        step_logits.append(logits[:, 0])
    stepped = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full), atol=2e-4)


def test_generate_greedy_continues_prompt():
    from distributed_training_pytorch_tpu.models.transformer_lm import generate

    model = LMTiny(vocab_size=32, max_len=24)
    prompt = tokens_batch(2, 6, vocab=32, seed=7)
    variables = model.init(jax.random.key(0), prompt)
    out = generate(model, variables, prompt, num_steps=8, rng=jax.random.key(1))
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))
    # Greedy continuation must equal argmax of the full forward at each step.
    full = model.apply(variables, out[:, :-1])
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(full[:, 5:], axis=-1)), np.asarray(out[:, 6:])
    )


def test_generate_sampling_is_seeded():
    from distributed_training_pytorch_tpu.models.transformer_lm import generate

    model = LMTiny(vocab_size=32, max_len=24)
    prompt = tokens_batch(1, 4, vocab=32, seed=8)
    variables = model.init(jax.random.key(0), prompt)
    a = generate(model, variables, prompt, 8, jax.random.key(5), temperature=1.0)
    b = generate(model, variables, prompt, 8, jax.random.key(5), temperature=1.0)
    c = generate(model, variables, prompt, 8, jax.random.key(6), temperature=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_return_hidden_matches_logits_projection():
    """hidden @ E^T == the model's own logits (the fused-CE contract)."""
    model = LMTiny(vocab_size=32, max_len=16)
    toks = tokens_batch(2, 8, vocab=32, seed=9)
    variables = model.init(jax.random.key(0), toks)
    logits = model.apply(variables, toks)
    hidden = model.apply(variables, toks, return_hidden=True)
    emb = variables["params"]["embed"]["embedding"]
    recon = hidden.astype(jnp.float32) @ emb.T.astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(recon), np.asarray(logits), atol=1e-5)


def test_fused_loss_includes_moe_aux(devices):
    """MoE LM through the fused loss: router aux losses join the objective and
    the engine step runs with finite metrics."""
    from distributed_training_pytorch_tpu.models.transformer_lm import make_fused_lm_loss

    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    model = LMTiny(vocab_size=64, moe_every=2, num_experts=4)
    engine = TrainEngine(make_fused_lm_loss(model), optax.adam(1e-3), mesh)
    rng = np.random.RandomState(13)
    seq = rng.randint(0, 64, size=(16, 17)).astype(np.int32)
    batch = engine.shard_batch({"image": seq[:, :-1], "label": seq[:, 1:]})
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))
    )
    state, m = engine.train_step(state, batch)
    assert float(m["moe_load_balance"]) >= 1.0 - 1e-5  # >= 1 by Cauchy-Schwarz
    assert np.isfinite(float(m["moe_router_z"]))
    assert float(m["loss"]) > float(m["nll"])  # aux terms actually added


@pytest.mark.slow
def test_moe_lm_cached_decode_and_generate():
    """KV-cache decode works through MoE blocks: with capacity headroom the
    training-time router drops nothing, so the capacity-free decode router
    produces the same logits as the full causal forward; generate() runs."""
    from distributed_training_pytorch_tpu.models.transformer_lm import (
        TransformerLM,
        generate,
    )

    model = TransformerLM(
        vocab_size=32, hidden_dim=16, depth=2, num_heads=2, mlp_dim=32,
        max_len=16, moe_every=2, num_experts=4, moe_capacity_factor=16.0,
        attention_impl="plain",
    )
    toks = tokens_batch(2, 6, vocab=32, seed=21)
    variables = model.init(jax.random.key(0), toks)
    full = model.apply(variables, toks)

    cache = None
    step_logits = []
    for t in range(6):
        inputs = {**variables} if cache is None else {**variables, "cache": cache}
        logits, state = model.apply(
            inputs, toks[:, t : t + 1], decode=True, mutable=["cache"]
        )
        cache = state["cache"]
        step_logits.append(logits[:, 0])
    stepped = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full), atol=2e-4)

    out = generate(model, variables, toks, num_steps=5, rng=jax.random.key(1))
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(toks))
