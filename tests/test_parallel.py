"""Parallelism tests on the 8-virtual-device CPU mesh (SURVEY.md §4):
ring/Ulysses attention parity vs dense attention, FSDP state sharding, and
tensor-parallel ViT matching the pure-DP run numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_pytorch_tpu.models.vit import ViTTiny, dot_product_attention
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel import (
    ring_attention,
    transformer_tp_rules,
    ulysses_attention,
)
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss


def qkv(shape, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(*shape), jnp.float32) for _ in range(3))


@pytest.fixture
def seq_mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.SEQ_AXIS: 8}, devices=devices)


def test_ring_attention_matches_dense(seq_mesh):
    q, k, v = qkv((2, 64, 4, 8))
    dense = dot_product_attention(q, k, v)
    ring = ring_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=2e-5)


def test_ring_attention_causal(seq_mesh):
    q, k, v = qkv((1, 32, 2, 8), seed=1)
    ring = ring_attention(q, k, v, seq_mesh, causal=True)
    # Dense causal reference.
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    T = q.shape[1]
    mask = jnp.tril(jnp.ones((T, T), bool))
    logits = jnp.where(mask[None, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    dense = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=2e-5)


def test_ulysses_attention_matches_dense(seq_mesh):
    q, k, v = qkv((2, 64, 8, 4), seed=2)  # 8 heads = seq devices
    dense = dot_product_attention(q, k, v)
    uly = ulysses_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense), atol=2e-5)


@pytest.mark.slow
def test_ulysses_causal_matches_ring(seq_mesh):
    q, k, v = qkv((1, 64, 8, 4), seed=3)
    a = ulysses_attention(q, k, v, seq_mesh, causal=True)
    b = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ulysses_rejects_bad_head_count(seq_mesh):
    q, k, v = qkv((1, 64, 6, 4))
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, seq_mesh)


# -- sharding rules ---------------------------------------------------------


def test_fsdp_spec_shards_largest_divisible_dim(devices):
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.FSDP_AXIS: 4}, devices=devices
    )
    from distributed_training_pytorch_tpu.parallel.sharding import spec_for_leaf

    # Large 2D kernel: largest dim (4096) sharded over fsdp.
    assert spec_for_leaf("kernel", (1024, 4096), mesh) == P(None, "fsdp")
    # Below size cutoff: replicated.
    assert spec_for_leaf("bias", (128,), mesh) == P()
    # Indivisible large dim: falls to next divisible dim.
    assert spec_for_leaf("kernel", (4098, 1024), mesh) == P(None, "fsdp")


@pytest.mark.slow
def test_state_shardings_fsdp_end_to_end(devices):
    """FSDP engine: params actually land sharded, training still works, and
    numerics match the replicated run."""
    mesh_dp = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    mesh_fsdp = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.FSDP_AXIS: 4}, devices=devices
    )
    model = ViTTiny(num_classes=4)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    def run(mesh, min_size):
        engine = TrainEngine(
            make_supervised_loss(model, criterion),
            optax.sgd(0.05, momentum=0.9),
            mesh,
            fsdp_min_size=min_size,
        )
        state = engine.init_state(
            jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
        )
        rng = np.random.RandomState(0)
        batch = engine.shard_batch(
            {
                "image": rng.randn(16, 16, 16, 3).astype(np.float32),
                "label": rng.randint(0, 4, size=(16,)).astype(np.int32),
            }
        )
        losses = []
        for _ in range(3):
            state, m = engine.train_step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    state_f, losses_f = run(mesh_fsdp, min_size=1024)
    state_d, losses_d = run(mesh_dp, min_size=2**18)
    # At least one param leaf is genuinely sharded over fsdp.
    specs = [
        l.sharding.spec for l in jax.tree.leaves(state_f.params) if hasattr(l, "sharding")
    ]
    assert any("fsdp" in str(s) for s in specs), specs
    # Momentum (opt_state) shards the same way.
    opt_specs = [
        l.sharding.spec for l in jax.tree.leaves(state_f.opt_state) if hasattr(l, "sharding")
    ]
    assert any("fsdp" in str(s) for s in opt_specs), opt_specs
    np.testing.assert_allclose(losses_f, losses_d, rtol=2e-4)


def test_tensor_parallel_vit_matches_dp(devices):
    """Megatron-style TP rules on the ViT: params shard over `tensor`, loss
    trajectory matches pure DP."""
    mesh_dp = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)
    mesh_tp = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.TENSOR_AXIS: 4}, devices=devices
    )
    model = ViTTiny(num_classes=4)

    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss}

    def run(mesh, rules):
        engine = TrainEngine(
            make_supervised_loss(model, criterion),
            optax.sgd(0.05, momentum=0.9),
            mesh,
            sharding_rules=rules,
        )
        state = engine.init_state(
            jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
        )
        rng = np.random.RandomState(1)
        batch = engine.shard_batch(
            {
                "image": rng.randn(16, 16, 16, 3).astype(np.float32),
                "label": rng.randint(0, 4, size=(16,)).astype(np.int32),
            }
        )
        losses = []
        for _ in range(3):
            state, m = engine.train_step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    state_t, losses_t = run(mesh_tp, transformer_tp_rules())
    state_d, losses_d = run(mesh_dp, None)
    specs = {
        jax.tree_util.keystr(p): l.sharding.spec
        for p, l in jax.tree_util.tree_leaves_with_path(state_t.params)
    }
    tp_sharded = [k for k, s in specs.items() if "tensor" in str(s)]
    assert any("qkv" in k for k in tp_sharded), tp_sharded
    assert any("MlpBlock" in k for k in tp_sharded), tp_sharded
    np.testing.assert_allclose(losses_t, losses_d, rtol=2e-4)


@pytest.mark.slow
def test_ulysses_flash_matches_plain(devices):
    """Ulysses with the Pallas kernel for its local attention (interpreter on
    CPU) agrees with the plain local-attention path, fwd and grad."""
    mesh = mesh_lib.create_mesh({mesh_lib.SEQ_AXIS: 4}, devices=devices[:4])
    q, k, v = qkv((2, 32, 4, 16), seed=11)

    for causal in (False, True):
        plain = ulysses_attention(q, k, v, mesh, causal=causal, use_flash=False)
        flash = ulysses_attention(q, k, v, mesh, causal=causal, use_flash=True)
        np.testing.assert_allclose(np.asarray(flash), np.asarray(plain), atol=2e-4)

    def loss(fn_flash):
        def f(q, k, v):
            out = ulysses_attention(q, k, v, mesh, causal=True, use_flash=fn_flash)
            return jnp.sum(out**2)

        return f

    g_plain = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_plain, strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense_ring(seq_mesh, causal):
    """impl="flash" (Pallas kernel per ring step, LSE merge) is numerically
    the same attention as the dense-block ring."""
    q, k, v = qkv((2, 64, 4, 8), seed=3)
    dense = ring_attention(q, k, v, seq_mesh, causal=causal, impl="dense")
    flash = ring_attention(q, k, v, seq_mesh, causal=causal, impl="flash")
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match(seq_mesh, causal):
    """The ring-level custom VJP (blockwise flash backward on a reverse ring)
    produces the same q/k/v grads as autodiff through the dense ring."""
    q, k, v = qkv((1, 32, 2, 8), seed=4)

    def loss(inputs, impl):
        out = ring_attention(*inputs, seq_mesh, causal=causal, impl=impl)
        return jnp.sum(out**2)

    g_dense = jax.grad(lambda t: loss(t, "dense"))((q, k, v))
    g_flash = jax.grad(lambda t: loss(t, "flash"))((q, k, v))
    for a, b in zip(g_dense, g_flash, strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ring_flash_composes_with_ulysses_flash(seq_mesh):
    """Parity across all three SP formulations on the same inputs."""
    q, k, v = qkv((1, 64, 8, 8), seed=5)
    ring_f = ring_attention(q, k, v, seq_mesh, causal=True, impl="flash")
    uly = ulysses_attention(q, k, v, seq_mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(ring_f), np.asarray(uly), atol=2e-4)
