"""Model-zoo tests: parameter-count parity with the canonical torch
implementations (shape-level, via eval_shape — no big allocations), forward
shapes, and compiled train-step smoke on the 8-device mesh (SURVEY.md §7
step 8 / BASELINE configs 3-5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_pytorch_tpu.models import (
    ConvNeXtL,
    ConvNeXtTiny,
    ResNet18Slim,
    ResNet50,
    ViTB16,
    ViTTiny,
    create_model,
)
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss


def param_count(model, input_shape):
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros(input_shape)), jax.random.key(0)
    )
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes["params"]))


def test_resnet50_param_count():
    # torchvision resnet50(num_classes=1000): 25,557,032 params.
    assert param_count(ResNet50(1000), (1, 224, 224, 3)) == 25_557_032


def test_vit_b16_param_count():
    # timm vit_base_patch16_224 (cls token + learned pos embed, qkv bias):
    # 86,567,656 params.
    assert param_count(ViTB16(1000), (1, 224, 224, 3)) == 86_567_656


def test_convnext_l_param_count():
    # Official ConvNeXt-L @1k: 197,767,336 params.
    assert param_count(ConvNeXtL(num_classes=1000), (1, 224, 224, 3)) == 197_767_336


def test_create_model_factory():
    assert create_model("resnet50", 10).num_classes == 10
    assert create_model("vit-b/16", 10).num_classes == 10
    assert create_model("convnext-l", 10).num_classes == 10
    assert create_model("vgg16", 10).num_classes == 10
    with pytest.raises(ValueError):
        create_model("alexnet", 10)


def _smoke(model, mesh, image_size=32, num_classes=10, has_model_state=False):
    def criterion(logits, batch):
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"loss": loss, "accuracy": accuracy(logits, batch["label"])}

    engine = TrainEngine(
        make_supervised_loss(model, criterion), optax.sgd(0.01, momentum=0.9), mesh
    )
    state = engine.init_state(
        jax.random.key(0),
        lambda rng: model.init(rng, jnp.zeros((1, image_size, image_size, 3))),
    )
    rng = np.random.RandomState(0)
    batch = engine.shard_batch(
        {
            "image": rng.randn(16, image_size, image_size, 3).astype(np.float32),
            "label": rng.randint(0, num_classes, size=(16,)).astype(np.int32),
        }
    )
    # The engine donates the input state; snapshot stats before stepping.
    old = jax.device_get(state.model_state) if has_model_state else None
    new_state, metrics = engine.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1
    if has_model_state:
        new = jax.device_get(new_state.model_state)
        assert any(
            not np.allclose(a, b)
            for a, b in zip(jax.tree.leaves(old), jax.tree.leaves(new), strict=True)
        ), "batch_stats must update during training"
    return new_state


@pytest.fixture
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


@pytest.mark.slow
def test_resnet_train_step_updates_batch_stats(mesh):
    _smoke(ResNet18Slim(num_classes=10), mesh, has_model_state=True)


def test_vit_train_step(mesh):
    _smoke(ViTTiny(num_classes=10), mesh)


@pytest.mark.slow
def test_convnext_train_step_with_droppath(mesh):
    _smoke(ConvNeXtTiny(num_classes=10, drop_path_rate=0.2), mesh)


def test_resnet_eval_deterministic(mesh):
    """Eval mode uses running stats — two eval calls agree, and differ from
    train-mode output."""
    model = ResNet18Slim(num_classes=10)
    # jitted: un-jitted op-by-op apply costs ~25s of suite time on CPU.
    variables = jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32, 3), jnp.float32)
    eval_fn = jax.jit(lambda v, x: model.apply(v, x, train=False))
    e1 = eval_fn(variables, x)
    e2 = eval_fn(variables, x)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_vit_rejects_bad_patch_grid():
    model = ViTTiny()
    with pytest.raises(ValueError, match="not divisible"):
        model.init(jax.random.key(0), jnp.zeros((1, 30, 30, 3)))


def test_droppath_zero_at_eval():
    """drop_path is identity at eval; train mode with rate ~1 kills the branch."""
    from distributed_training_pytorch_tpu.models.convnext import DropPath

    x = jnp.ones((4, 3))
    mod = DropPath(0.99)
    out = mod.apply({}, x, train=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_input_normalizer_uint8_vs_float_dispatch():
    """InputNormalizer normalizes uint8 batches on device and passes float
    batches through untouched (they arrive pre-normalized, e.g. from the
    native val decode) — the mixed uint8-train / f32-val contract of
    examples/train_imagenet.py SHIP_UINT8."""
    from flax import linen as nn

    from distributed_training_pytorch_tpu.models.wrappers import InputNormalizer

    class Echo(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            return x

    mean, std = [0.5, 0.5, 0.5], [0.25, 0.25, 0.25]
    model = InputNormalizer(inner=Echo(), mean=mean, std=std)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, size=(2, 4, 4, 3)).astype(np.uint8)
    variables = model.init(jax.random.key(0), jnp.asarray(raw))
    out_u8 = model.apply(variables, jnp.asarray(raw))
    expect = (raw.astype(np.float32) / 255.0 - np.asarray(mean)) / np.asarray(std)
    np.testing.assert_allclose(np.asarray(out_u8), expect, atol=1e-6)
    pre = jnp.asarray(expect)
    out_f32 = model.apply(variables, pre)
    np.testing.assert_array_equal(np.asarray(out_f32), np.asarray(pre))
