"""End-to-end tests of the example surfaces: ExampleTrainer (main.py twin),
offline eval (eval.py twin), and the CIFAR-10 north-star entry — the
example-as-smoke-test role the reference fills with main.py (SURVEY.md §4).

Models are shrunk (tiny VGG stages) so the 8-virtual-device CPU compiles stay
fast; the full-size path is covered by bench.py on real TPU.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    """train/val/test image-folder trees with 3 labels (reference layout)."""
    import cv2

    root = tmp_path_factory.mktemp("data")
    rng = np.random.RandomState(0)
    for split, n in (("train", 8), ("val", 4), ("test", 4)):
        for li, label in enumerate(("cat", "dog", "snake")):
            d = root / split / label
            d.mkdir(parents=True)
            for i in range(n):
                img = rng.randint(0, 255, size=(48, 48, 3), dtype=np.uint8)
                img[:, :, li % 3] = np.minimum(255, img[:, :, li % 3] + 80)  # separable
                cv2.imwrite(str(d / f"{i}.png"), img)
    return root


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def tiny_vgg(num_classes):
    return VGG16(
        num_classes=num_classes,
        stage_features=(4, 8),
        stage_layers=(1, 1),
        classifier_widths=(16,),
    )


def make_example_trainer(data_root, mesh, tmp_path, **kw):
    from examples.example_trainer import ExampleTrainer

    class TinyExampleTrainer(ExampleTrainer):
        def build_model(self):
            return tiny_vgg(len(self.labels))

    defaults = dict(
        train_path=str(data_root / "train"),
        val_path=str(data_root / "val"),
        labels=["cat", "dog", "snake"],
        height=32,
        width=32,
        max_epoch=2,
        batch_size=8,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=1,
        save_folder=str(tmp_path / "runs"),
        mesh=mesh,
        num_workers=2,
        log_every=0,
        async_checkpoint=False,
    )
    defaults.update(kw)
    return TinyExampleTrainer(**defaults)


@pytest.fixture(scope="module")
def trained_example(data_root, mesh, tmp_path_factory):
    """One ExampleTrainer run shared by the end-to-end and offline-eval tests
    (each extra run costs ~30s of CPU compile/train time)."""
    tmp_path = tmp_path_factory.mktemp("example")
    trainer = make_example_trainer(data_root, mesh, tmp_path, progress=False)
    trainer.train()
    return trainer, tmp_path


def test_example_trainer_end_to_end(trained_example, data_root):
    trainer, _ = trained_example
    assert trainer.checkpoints.exists("best")
    assert trainer.checkpoints.exists("last")
    # val dataset reads val_path (the reference's train_path bug is fixed).
    assert trainer.val_dataset.data_path == str(data_root / "val")
    # Reference optimizer recipe: schedule starts at lr 0.1.
    assert float(trainer.schedule(0)) == pytest.approx(0.1)


def test_offline_eval(trained_example, data_root, mesh):
    from examples import eval as eval_mod

    _, tmp_path = trained_example
    results = eval_mod.evaluate(
        str(tmp_path / "runs" / "weights" / "last"),
        str(data_root / "test"),
        batch=8,
        model=tiny_vgg(3),
        height=32,
        width=32,
        mesh=mesh,
    )
    assert set(results) == {"top1", "top2"}
    assert 0.0 <= results["top1"] <= results["top2"] <= 1.0


@pytest.mark.slow
def test_cifar10_synthetic_fallback(tmp_path, mesh):
    from examples.train_cifar10 import Cifar10Trainer, load_cifar10

    x, y, tx, ty = load_cifar10(str(tmp_path / "missing"))
    assert x.shape == (50000, 32, 32, 3) and x.dtype == np.uint8
    assert tx.shape == (10000, 32, 32, 3)

    class TinyCifar(Cifar10Trainer):
        def build_model(self):
            return tiny_vgg(10)

    trainer = TinyCifar(
        data_dir=str(tmp_path / "missing"),
        base_lr=0.025,
        max_epoch=1,
        batch_size=64,
        have_validate=False,
        save_period=100,
        save_folder=str(tmp_path / "runs"),
        mesh=mesh,
        num_workers=0,
        log_every=0,
        async_checkpoint=False,
    )
    # One short epoch on a subset: shrink the dataset for test speed.
    trainer.train_x = trainer.train_x[:256]
    trainer.train_y = trainer.train_y[:256]
    trainer.train_dataset = trainer.build_train_dataset()
    trainer.train_dataloader = trainer.build_dataloader(trainer.train_dataset, "train")
    metrics = trainer.train_epoch(0)
    assert np.isfinite(metrics["ce_loss"])


def test_cifar10_pickle_reader(tmp_path):
    """Write the canonical cifar-10-batches-py layout and read it back."""
    import pickle

    from examples.train_cifar10 import load_cifar10

    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.RandomState(0)
    for name, n in [(f"data_batch_{i}", 20) for i in range(1, 6)] + [("test_batch", 10)]:
        data = {
            b"data": rng.randint(0, 255, size=(n, 3072), dtype=np.uint8),
            b"labels": rng.randint(0, 10, size=(n,)).tolist(),
        }
        with open(d / name, "wb") as f:
            pickle.dump(data, f)
    x, y, tx, ty = load_cifar10(str(d))
    assert x.shape == (100, 32, 32, 3) and tx.shape == (10, 32, 32, 3)
    assert y.dtype == np.int32


def test_cifar10_transform_determinism():
    from examples.train_cifar10 import Cifar10Transform

    img = np.random.RandomState(0).randint(0, 255, size=(32, 32, 3), dtype=np.uint8)
    t = Cifar10Transform(seed=1, train=True)
    np.testing.assert_array_equal(t(img, epoch=2, index=3), t(img, epoch=2, index=3))
    assert not np.array_equal(t(img, epoch=2, index=3), t(img, epoch=3, index=3))
    # Val transform is deterministic normalization only.
    tv = Cifar10Transform(train=False)
    np.testing.assert_array_equal(tv(img), tv(img, epoch=7, index=7))


def test_digits_data_materializes_reference_tree(tmp_path):
    """The real-data accuracy entry: sklearn digits -> the reference's
    image-folder layout, stratified 80/20, idempotent via the marker file."""
    pytest.importorskip("sklearn")
    from examples.digits_data import LABELS, materialize

    counts = materialize(str(tmp_path / "digits"))
    assert counts == {"train": 1438, "test": 359}
    for split in ("train", "test"):
        for lb in LABELS:
            d = tmp_path / "digits" / split / lb
            assert d.is_dir() and any(d.iterdir()), (split, lb)
    # idempotent: second call reads the marker, same counts
    assert materialize(str(tmp_path / "digits")) == counts
    # images decode as 32x32 RGB
    import cv2

    sample = next((tmp_path / "digits" / "train" / "3").iterdir())
    img = cv2.imread(str(sample))
    assert img.shape == (32, 32, 3)


def test_digits_curve_parser(tmp_path):
    from examples.train_digits import parse_curve

    log = tmp_path / "logfile.log"
    log.write_text(
        "x | INFO | [process 0] Epoch 1/2\n"
        "x | INFO | VALIDATE RESULTS:  | accuracy = 0.5 |  | ce_loss = 1.0 | \n"
        "x | INFO | TOTAL GLOBAL TRAINING LOSS:  | ce_loss = 2.0 | \n"
        "x | INFO | [process 0] Epoch 2/2\n"
        "x | INFO | TOTAL GLOBAL TRAINING LOSS:  | ce_loss = 1.5 | \n"
    )
    curve = parse_curve(str(log))
    assert curve == [
        {"epoch": 1, "val_acc": 0.5, "train_ce": 2.0},
        {"epoch": 2, "train_ce": 1.5},
    ]
