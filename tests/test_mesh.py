import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib


def test_default_mesh_is_1d_data(devices):
    mesh = mesh_lib.create_mesh()
    assert mesh.axis_names == ("data",)
    assert mesh.devices.size == 8


def test_mesh_wildcard_and_order(devices):
    mesh = mesh_lib.create_mesh({"tensor": 2, "data": -1})
    # canonical order keeps data outermost
    assert mesh.axis_names == ("data", "tensor")
    assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2


def test_mesh_bad_sizes(devices):
    with pytest.raises(ValueError):
        mesh_lib.create_mesh({"data": 3})
    with pytest.raises(ValueError):
        mesh_lib.create_mesh({"data": -1, "tensor": -1})


def test_batch_sharding_splits_leading_axis(devices):
    mesh = mesh_lib.create_mesh()
    batch = {"image": np.ones((16, 8, 8, 3), np.float32), "label": np.zeros((16,), np.int32)}
    garr = mesh_lib.global_array_from_host_local(batch, mesh)
    assert garr["image"].shape == (16, 8, 8, 3)
    assert garr["image"].sharding.spec == P(("data",))
    # each device holds 2 rows
    assert garr["image"].addressable_shards[0].data.shape[0] == 2


def test_local_batch_size_single_process(devices):
    mesh = mesh_lib.create_mesh()
    assert mesh_lib.local_batch_size(16, mesh) == 16  # one process holds all rows


def test_mesh_config(devices):
    mesh = mesh_lib.MeshConfig(data=-1, tensor=2).build()
    assert mesh.shape == {"data": 4, "tensor": 2}


def test_mesh_unknown_axis_rejected(devices):
    with pytest.raises(ValueError, match="unknown mesh axes"):
        mesh_lib.create_mesh({"data": -1, "modle": 2})


def test_full_six_axis_mesh(devices):
    # Every canonical axis at once (sizes 2,2,2,1,1,1 over the 8-device CPU
    # mesh); declarative config and direct build agree on canonical order.
    mesh = mesh_lib.MeshConfig(data=2, fsdp=2, pipe=2, expert=1, seq=1, tensor=1).build()
    assert mesh.axis_names == ("data", "fsdp", "pipe")
    full = mesh_lib.create_mesh(
        {"tensor": 1, "seq": 1, "expert": 2, "pipe": 1, "fsdp": 2, "data": -1}
    )
    assert full.axis_names == ("data", "fsdp", "pipe", "expert", "seq", "tensor")
    assert full.shape == {"data": 2, "fsdp": 2, "pipe": 1, "expert": 2, "seq": 1, "tensor": 1}
