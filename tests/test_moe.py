"""Expert parallelism (parallel/moe.py): routing parity, capacity dropping,
expert-sharded execution under jit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu import compat
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel import moe as moe_lib
from distributed_training_pytorch_tpu.parallel.moe import EXPERT_AXIS, MoEMlp


def dense_reference(variables, x, top_k):
    """Per-token loop: top-k experts, renormalized gates, no capacity limit."""
    params = variables["params"]
    w_r, b_r = params["router"]["kernel"], params["router"]["bias"]
    w_in, w_out = params["w_in"], params["w_out"]
    tokens = np.asarray(x, np.float64).reshape(-1, x.shape[-1])
    logits = tokens @ np.asarray(w_r, np.float64) + np.asarray(b_r, np.float64)
    gates = np.exp(logits - logits.max(-1, keepdims=True))
    gates /= gates.sum(-1, keepdims=True)
    out = np.zeros_like(tokens)
    for si in range(tokens.shape[0]):
        top = np.argsort(-gates[si])[:top_k]
        norm = gates[si][top].sum()
        for ei in top:
            h = tokens[si] @ np.asarray(w_in[ei], np.float64)
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)), np.float64)
            out[si] += (gates[si][ei] / norm) * (h @ np.asarray(w_out[ei], np.float64))
    return out.reshape(x.shape)


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_dense_reference(top_k):
    """With generous capacity nothing drops -> exact top-k mixture parity."""
    model = MoEMlp(num_experts=4, hidden_dim=16, top_k=top_k, capacity_factor=8.0)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 12, 8), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    ref = dense_reference(variables, x, top_k)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)


def test_moe_capacity_drops_deterministically():
    """capacity 1 with many tokens: per expert only the first token (in order)
    is served per choice; output is finite and some tokens are zero."""
    model = MoEMlp(num_experts=2, hidden_dim=8, top_k=1, capacity_factor=1e-9)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 16, 4), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    out1 = model.apply(variables, x)
    out2 = model.apply(variables, x)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out = np.asarray(out1).reshape(-1, 4)
    assert np.isfinite(out).all()
    assert (np.abs(out).sum(-1) == 0).any(), "capacity 1 must drop some tokens"
    assert (np.abs(out).sum(-1) > 0).any(), "but serve at least one"


def test_moe_aux_losses_sown():
    model = MoEMlp(num_experts=4, hidden_dim=8, top_k=2)
    x = jnp.ones((1, 8, 4))
    variables = model.init(jax.random.key(0), x)
    _, state = model.apply(variables, x, mutable=["intermediates"])
    inter = state["intermediates"]
    (lb,) = inter["load_balance_loss"]
    (zl,) = inter["router_z_loss"]
    assert np.isfinite(float(lb)) and float(lb) >= 1.0 - 1e-6  # >= 1 by Cauchy-Schwarz
    assert np.isfinite(float(zl))


def test_moe_expert_sharded_under_jit(devices):
    """data x expert mesh: expert-stacked params and buffers shard over the
    expert axis; jitted output matches the single-device result."""
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, EXPERT_AXIS: 4}, devices=devices
    )
    model = MoEMlp(num_experts=4, hidden_dim=16, top_k=2, capacity_factor=8.0)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 8, 8), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    expected = model.apply(variables, x)

    with compat.set_mesh(mesh):
        out = jax.jit(model.apply)(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_moe_grouped_routing_matches_dense(devices):
    """num_groups > 1 (the at-scale layout): with generous per-group capacity
    nothing drops, so grouped routing still matches the dense mixture; and the
    grouped buffers run expert+data sharded under jit."""
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, EXPERT_AXIS: 4}, devices=devices
    )
    model = MoEMlp(num_experts=4, hidden_dim=16, top_k=2, capacity_factor=8.0, num_groups=2)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(4, 8, 8), jnp.float32)  # 32 tokens -> 2 groups of 16
    variables = model.init(jax.random.key(0), x)
    ref = dense_reference(variables, x, top_k=2)
    out = model.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)
    with compat.set_mesh(mesh):
        out_sharded = jax.jit(model.apply)(variables, x)
    np.testing.assert_allclose(np.asarray(out_sharded), ref, atol=2e-4)


def test_moe_rejects_indivisible_groups():
    model = MoEMlp(num_experts=2, hidden_dim=4, num_groups=3)
    x = jnp.ones((1, 8, 4))  # 8 tokens, 3 groups
    with pytest.raises(ValueError, match="not divisible by num_groups"):
        model.init(jax.random.key(0), x)


def test_engine_establishes_ambient_mesh(devices):
    """Regression: TrainEngine must set the ambient mesh while tracing, or
    in-model with_sharding_constraint (bare PartitionSpecs, as MoE uses)
    silently no-ops on the production path."""
    import optax
    from flax import linen as nn

    from distributed_training_pytorch_tpu.train import TrainEngine

    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, EXPERT_AXIS: 4}, devices=devices
    )
    seen = []

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            seen.append(compat.get_abstract_mesh().axis_names)
            return nn.Dense(3)(x.reshape(x.shape[0], -1))

    model = Probe()

    def loss_fn(params, ms, batch, rng, train):
        logits = model.apply({"params": params}, batch["image"], train=train)
        loss = jnp.mean(logits**2)
        return loss, ({"loss": loss}, ms)

    engine = TrainEngine(loss_fn, optax.sgd(0.01), mesh)
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 4)))
    )
    batch = engine.shard_batch({"image": np.zeros((8, 4), np.float32)})
    engine.train_step(state, batch)
    assert seen and all(EXPERT_AXIS in axes for axes in seen if axes), seen
    assert any(axes for axes in seen), "ambient mesh was never set during trace"


@pytest.mark.parametrize("top_k,num_groups", [(1, 1), (2, 1), (2, 2)])
@pytest.mark.slow
def test_moe_sort_dispatch_matches_einsum(top_k, num_groups):
    """The argsort/scatter dispatch is semantics-identical to the GShard
    one-hot path: same outputs AND same grads, including under capacity
    pressure (drops follow the same choice-major priority order)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
    for cap in (8.0, 0.6):  # generous and dropping
        kw = dict(
            num_experts=4, hidden_dim=16, top_k=top_k,
            capacity_factor=cap, num_groups=num_groups,
        )
        m_ein = MoEMlp(dispatch_impl="einsum", **kw)
        m_sort = MoEMlp(dispatch_impl="sort", **kw)
        variables = m_ein.init(jax.random.key(1), x)
        out_ein = m_ein.apply(variables, x)
        out_sort = m_sort.apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(out_ein), np.asarray(out_sort), atol=2e-5,
            err_msg=f"cap={cap}",
        )

        def loss(v, m):
            return jnp.sum(m.apply(v, x) ** 2)

        g_ein = jax.grad(loss)(variables, m_ein)
        g_sort = jax.grad(loss)(variables, m_sort)
        for a, b in zip(jax.tree.leaves(g_ein), jax.tree.leaves(g_sort), strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_moe_sort_dispatch_sharded_under_jit(devices):
    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, EXPERT_AXIS: 4}, devices=devices
    )
    model = MoEMlp(
        num_experts=4, hidden_dim=16, top_k=2, capacity_factor=8.0,
        num_groups=2, dispatch_impl="sort",
    )
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(4, 8, 8), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    expected = dense_reference(variables, x, top_k=2)
    with compat.set_mesh(mesh):
        out = jax.jit(model.apply)(variables, x)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-4)


def test_moe_decode_capacity_free_matches_dense():
    """decode=True routes every token to its full top-k (no capacity, no
    drops) — exactly the dense per-token mixture, with the same parameters
    the capacity-routed training path uses."""
    model = MoEMlp(num_experts=4, hidden_dim=16, top_k=2, capacity_factor=1e-9)
    rng = np.random.RandomState(9)
    # decode: T=1 tokens; 8 of them so the starved training path (capacity 1,
    # 16 choice-entries for 4 slots) provably zeroes some tokens entirely
    x = jnp.asarray(rng.randn(8, 1, 8), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x, decode=True)
    ref = dense_reference(variables, x, top_k=2)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)
    # Training path under the same starved capacity drops tokens; decode
    # must not (that is the point of the capacity-free router).
    out_train = np.asarray(model.apply(variables, x)).reshape(-1, 8)
    assert (np.abs(out_train).sum(-1) == 0).any()
    assert (np.abs(np.asarray(out).reshape(-1, 8)).sum(-1) > 0).all()


@pytest.mark.parametrize(
    "tokens,expected_impl",
    [(16, "einsum"), (moe_lib.SORT_DISPATCH_MIN_GROUP, "sort")],
)
def test_moe_auto_dispatch_selects_by_group_size(tokens, expected_impl, monkeypatch):
    """dispatch_impl='auto' (the default) resolves from the static group size
    at the measured ~4k crossover — and produces the same numbers as the impl
    it selects."""
    seen = []
    orig_vmap = jax.vmap

    def spy_vmap(fn, *a, **kw):
        if getattr(fn, "__name__", "") in ("route", "route_sort"):
            seen.append(fn.__name__)
        return orig_vmap(fn, *a, **kw)

    monkeypatch.setattr(moe_lib.jax, "vmap", spy_vmap)
    kw = dict(num_experts=4, hidden_dim=8, top_k=2, capacity_factor=2.0)
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(1, tokens, 8), jnp.float32)
    auto = MoEMlp(dispatch_impl="auto", **kw)
    variables = auto.init(jax.random.key(2), x)
    seen.clear()
    out_auto = auto.apply(variables, x)
    assert seen == [{"einsum": "route", "sort": "route_sort"}[expected_impl]]
    out_explicit = MoEMlp(dispatch_impl=expected_impl, **kw).apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(out_auto), np.asarray(out_explicit), atol=2e-5
    )


def test_moe_rejects_unknown_dispatch_impl():
    model = MoEMlp(num_experts=2, hidden_dim=4, dispatch_impl="hash")
    with pytest.raises(ValueError, match="dispatch_impl"):
        model.init(jax.random.key(0), jnp.ones((1, 4, 4)))


def test_manual_expert_mlp_matches_gspmd_path(devices):
    """manual_expert_mlp (nested-shard_map manual expert parallelism): both
    exchange formulations match the GSPMD-constraint MoEMlp forward AND
    gradient on a data x expert mesh."""
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.parallel.moe import manual_expert_mlp

    rng = np.random.RandomState(0)
    kw = dict(num_experts=4, hidden_dim=16, top_k=2, capacity_factor=2.0, num_groups=4)
    moe = MoEMlp(dispatch_impl="einsum", **kw)
    x = jnp.asarray(rng.randn(4, 8, 8), jnp.float32)
    variables = moe.init(jax.random.key(1), x)
    ref = moe.apply(variables, x)
    g_ref = jax.grad(lambda p: jnp.sum(moe.apply({"params": p}, x) ** 2))(
        variables["params"]
    )

    mesh = mesh_lib.create_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.EXPERT_AXIS: 2}, devices=devices[:4]
    )
    for exchange in ("all_to_all", "psum"):
        def fwd(p, x, exchange=exchange):
            return manual_expert_mlp(
                p, x, num_experts=4, top_k=2, capacity_factor=2.0,
                num_groups=4, mesh=mesh, exchange=exchange,
            )

        with compat.set_mesh(mesh):
            got = jax.jit(fwd)(variables["params"], x)
            g_man = jax.jit(jax.grad(lambda p: jnp.sum(fwd(p, x) ** 2)))(
                variables["params"]
            )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_man), strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.skipif(
    not compat.HAS_PARTIAL_MANUAL,
    reason="the enclosing region is itself partial-manual (pipe manual, expert auto)",
)
def test_manual_expert_mlp_rejects_nesting(devices):
    """Inside an enclosing manual region the GSPMD/nested paths are both
    unusable (Shardy rejections quoted in the docstring) — the error must
    point at the supported workaround, not die in the lowering."""
    from jax.sharding import PartitionSpec as P

    from distributed_training_pytorch_tpu.compat import set_mesh, shard_map
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.parallel.moe import manual_expert_mlp

    mesh = mesh_lib.create_mesh(
        {mesh_lib.PIPE_AXIS: 2, mesh_lib.EXPERT_AXIS: 2}, devices=devices[:4]
    )
    rng = np.random.RandomState(0)
    moe = MoEMlp(num_experts=2, hidden_dim=8, top_k=1, num_groups=2)
    x = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
    params = moe.init(jax.random.key(0), x)["params"]

    def outer(x):
        return manual_expert_mlp(
            params, x, num_experts=2, top_k=1, num_groups=2, mesh=mesh
        )

    with pytest.raises(ValueError, match="extra_manual_axes"):
        with set_mesh(mesh):
            jax.jit(
                shard_map(
                    outer, mesh=mesh, in_specs=P(), out_specs=P(),
                    axis_names=frozenset({mesh_lib.PIPE_AXIS}),
                )
            )(x)


def test_manual_expert_mlp_degenerate_mesh(devices):
    """On a mesh without an expert axis the specs reference only present
    axes and the collectives compile out — exact parity with plain apply."""
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.parallel.moe import manual_expert_mlp

    rng = np.random.RandomState(3)
    moe = MoEMlp(num_experts=2, hidden_dim=8, top_k=1, num_groups=2,
                 dispatch_impl="einsum")
    x = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
    v = moe.init(jax.random.key(0), x)
    mesh = mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 2}, devices=devices[:2])
    with compat.set_mesh(mesh):
        got = jax.jit(
            lambda p, x: manual_expert_mlp(
                p, x, num_experts=2, top_k=1, num_groups=2, mesh=mesh
            )
        )(v["params"], x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(moe.apply(v, x)), atol=1e-6)
