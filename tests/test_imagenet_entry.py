"""examples/train_imagenet.py entry — recipe wiring + slow end-to-end smoke.

The full model compiles are minutes on CPU, so only the wiring tests run by
default; the end-to-end pass is ``-m slow`` (the CI/driver runs it on TPU
implicitly via ``MODEL=... ./run.sh``).
"""

import os
import runpy
import sys

import pytest


def _load_module():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "examples"))
    try:
        import importlib

        return importlib.import_module("train_imagenet")
    finally:
        sys.path.pop(0)


def test_recipes_resolve_models():
    """Every RECIPES entry must build through the model-zoo factory."""
    from distributed_training_pytorch_tpu.models import create_model

    mod = _load_module()
    for name, recipe in mod.RECIPES.items():
        model = create_model(name, num_classes=5)
        assert model is not None, name
        assert recipe["accum"] >= 1 and recipe["optimizer"] in ("sgd", "adamw")


def test_limited_source_caps_length():
    mod = _load_module()
    src = mod.synthetic_source(100, 16, 5, None, seed=0)
    capped = mod._LimitedSource(src, 24)
    assert len(capped) == 24
    assert capped[3]["image"].shape == (16, 16, 3)


@pytest.mark.slow
def test_end_to_end_resnet50_synthetic(tmp_path, monkeypatch):
    monkeypatch.setenv("MODEL", "resnet50")
    monkeypatch.setenv("EPOCHS", "1")
    monkeypatch.setenv("BATCH", "16")
    monkeypatch.setenv("IMAGE_SIZE", "64")
    monkeypatch.setenv("NUM_CLASSES", "5")
    monkeypatch.setenv("STEPS_PER_EPOCH", "2")
    monkeypatch.setenv("SAVE_DIR", str(tmp_path))
    monkeypatch.delenv("IMAGENET_RECORDS", raising=False)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runpy.run_path(os.path.join(repo, "examples", "train_imagenet.py"), run_name="__main__")
    assert (tmp_path / "weights").exists()
