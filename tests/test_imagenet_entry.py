"""examples/train_imagenet.py entry — recipe wiring + slow end-to-end smoke.

The full model compiles are minutes on CPU, so only the wiring tests run by
default; the end-to-end pass is ``-m slow`` (the CI/driver runs it on TPU
implicitly via ``MODEL=... ./run.sh``).
"""

import os
import runpy
import sys

import pytest


def _load_module():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "examples"))
    try:
        import importlib

        return importlib.import_module("train_imagenet")
    finally:
        sys.path.pop(0)


def test_recipes_resolve_models():
    """Every RECIPES entry must build through the model-zoo factory."""
    from distributed_training_pytorch_tpu.models import create_model

    mod = _load_module()
    for name, recipe in mod.RECIPES.items():
        model = create_model(name, num_classes=5)
        assert model is not None, name
        assert recipe["accum"] >= 1 and recipe["optimizer"] in ("sgd", "adamw")


def test_limited_source_caps_length():
    mod = _load_module()
    src = mod.synthetic_source(100, 16, 5, None, seed=0)
    capped = mod._LimitedSource(src, 24)
    assert len(capped) == 24
    assert capped[3]["image"].shape == (16, 16, 3)


@pytest.mark.slow
def test_end_to_end_resnet50_synthetic(tmp_path, monkeypatch):
    monkeypatch.setenv("MODEL", "resnet50")
    monkeypatch.setenv("EPOCHS", "1")
    monkeypatch.setenv("BATCH", "16")
    monkeypatch.setenv("IMAGE_SIZE", "64")
    monkeypatch.setenv("NUM_CLASSES", "5")
    monkeypatch.setenv("STEPS_PER_EPOCH", "2")
    monkeypatch.setenv("SAVE_DIR", str(tmp_path))
    monkeypatch.delenv("IMAGENET_RECORDS", raising=False)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runpy.run_path(os.path.join(repo, "examples", "train_imagenet.py"), run_name="__main__")
    assert (tmp_path / "weights").exists()


def test_build_train_dataset_records_native_rrc(tmp_path, monkeypatch):
    """With IMAGENET_RECORDS set (+ uint8 ship + native lib), the trainer's
    train dataset is the fused native decode+RRC source producing uint8
    batches; RECORDS_NATIVE=0 falls back to the per-record Python path."""
    import io

    import numpy as np
    from PIL import Image

    from distributed_training_pytorch_tpu.data import (
        NativeRecordTrainSource,
        RecordFileSource,
        native,
        write_shards,
    )

    rng = np.random.RandomState(0)
    items = []
    for i in range(8):
        buf = io.BytesIO()
        Image.fromarray(rng.randint(0, 255, (40, 40, 3), np.uint8)).save(
            buf, format="JPEG"
        )
        items.append((buf.getvalue(), i % 3))
    write_shards(str(tmp_path / "train"), items, num_shards=1)
    monkeypatch.setenv("IMAGENET_RECORDS", str(tmp_path))
    monkeypatch.delenv("SHIP_UINT8", raising=False)
    monkeypatch.delenv("RECORDS_NATIVE", raising=False)

    mod = _load_module()
    trainer = object.__new__(mod.ImageNetTrainer)  # dataset hook only
    trainer.model_name = "resnet50"
    trainer.image_size = 32
    trainer.seed = 0
    trainer.batch_size = 4
    trainer.num_classes = 3
    trainer.train_records = str(tmp_path)
    trainer.log = lambda *a, **k: None
    src = trainer.build_train_dataset()
    if native.available():
        assert isinstance(src, NativeRecordTrainSource) and src.aug == "rrc"
        batch = src.load_batch(np.arange(4), epoch=0)
        assert batch["image"].dtype == np.uint8
        assert batch["image"].shape == (4, 32, 32, 3)
    monkeypatch.setenv("RECORDS_NATIVE", "0")
    src2 = trainer.build_train_dataset()
    assert isinstance(src2, RecordFileSource)
    assert not isinstance(src2, NativeRecordTrainSource)


def test_limited_source_forwards_load_batch(tmp_path):
    """STEPS_PER_EPOCH's _LimitedSource must not hide a source's whole-batch
    native path — regression: hiding load_batch dropped decode+augment and
    fed raw full-size records (r5 review finding)."""
    import io

    import numpy as np
    from PIL import Image

    from distributed_training_pytorch_tpu.data import (
        NativeRecordTrainSource,
        ShardedLoader,
        write_shards,
    )

    rng = np.random.RandomState(1)
    items = []
    for i in range(8):
        buf = io.BytesIO()
        Image.fromarray(rng.randint(0, 255, (40 + i, 50, 3), np.uint8)).save(
            buf, format="PNG"
        )
        items.append((buf.getvalue(), i % 2))
    write_shards(str(tmp_path / "t"), items, num_shards=1)
    mod = _load_module()
    src = NativeRecordTrainSource(str(tmp_path), 32, 32, aug="rrc", seed=0)
    capped = mod._LimitedSource(src, 4)
    loader = ShardedLoader(
        capped, 4, shuffle=False, num_workers=0, process_index=0, process_count=1
    )
    batch = next(iter(loader))
    # augmented uint8 at target size — NOT raw variable-size decodes
    assert batch["image"].dtype == np.uint8
    assert batch["image"].shape == (4, 32, 32, 3)
