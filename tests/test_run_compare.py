"""Run-comparison layer tests (ISSUE 14; docs/profiling.md §before/after).

Four layers, mirroring the subsystem:

* ``profiling.diff`` — the ONE delta-attribution rule (exact hand-computed
  deltas, fractions of delta summing to 1 by construction) and
  ``diff_profiles`` on synthetic ``encode_xspace`` trace pairs (exact
  category deltas, new/removed op detection, roofline shifts);
* ``analysis.diff`` — HLO op-category/fusion-count deltas and the comm
  inventory delta on hand-built programs (per-axis byte deltas, replica
  group changes named);
* ``telemetry.history`` + ``telemetry.provenance`` — flat-streak detector
  boundary cases (N-1 rounds flat = quiet, N = fires), regression
  direction, round-file ingestion, provenance compare semantics — plus the
  committed-BENCH self-parity: the r02→r05 plateau MUST be detected on the
  repo's own committed files;
* the CLIs — scripts/run_compare.py + scripts/perf_gate.py share ONE diff
  implementation (AST-enforced: neither defines a private attribution),
  and run_compare compares two committed bench rounds end to end.
"""

import ast
import json
import math
import os
import subprocess
import sys

import pytest

from distributed_training_pytorch_tpu.analysis import diff as analysis_diff
from distributed_training_pytorch_tpu.analysis.comm_audit import collective_inventory
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.profiling import IDLE, analyze_trace, xplane
from distributed_training_pytorch_tpu.profiling import diff as diff_lib
from distributed_training_pytorch_tpu.telemetry import history as history_lib
from distributed_training_pytorch_tpu.telemetry import provenance as prov_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
US = 1_000_000  # picoseconds per microsecond


# ---------------------------------------------------------------------------
# attribute_delta: the one rule
# ---------------------------------------------------------------------------


class TestAttributeDelta:
    def test_exact_deltas_and_fraction_sum(self):
        rows = diff_lib.attribute_delta(
            {"conv": 40.0, "idle": 10.0}, {"conv": 120.0, "idle": 10.0}
        )
        assert [r.key for r in rows] == ["conv", "idle"]
        assert rows[0].delta == 80.0 and rows[1].delta == 0.0
        assert math.isclose(sum(r.frac_of_delta for r in rows), 1.0)

    def test_union_of_keys_absent_is_zero(self):
        rows = diff_lib.attribute_delta({"a": 5.0}, {"b": 3.0})
        by_key = {r.key: r for r in rows}
        assert by_key["a"].delta == -5.0 and by_key["a"].after == 0.0
        assert by_key["b"].delta == 3.0 and by_key["b"].before == 0.0
        # deltas sum to the total delta exactly; signed fractions sum to 1
        assert math.isclose(sum(r.delta for r in rows), -2.0)
        assert math.isclose(sum(r.frac_of_delta for r in rows), 1.0)

    def test_ranked_by_abs_delta(self):
        rows = diff_lib.attribute_delta(
            {"a": 1.0, "b": 1.0, "c": 1.0}, {"a": 2.0, "b": 10.0, "c": 0.5}
        )
        assert [r.key for r in rows] == ["b", "a", "c"]

    def test_identical_totals_zero_fractions(self):
        rows = diff_lib.attribute_delta({"a": 2.0, "b": 1.0}, {"a": 1.0, "b": 2.0})
        # total delta is 0: per-key deltas exist, fractions refuse to divide
        assert all(r.frac_of_delta == 0.0 for r in rows)
        assert {r.key: r.delta for r in rows} == {"a": -1.0, "b": 1.0}

    def test_entry_delta_exact_and_degrades(self):
        before = {"step_ms": 10.0, "categories": {"conv": 0.8, "idle": 0.2}}
        after = {"step_ms": 14.0, "categories": {"conv": 0.9, "idle": 0.1}}
        rows = diff_lib.attribute_entry_delta(before, after)
        by_key = {r.key: r for r in rows}
        assert math.isclose(by_key["conv"].delta, 12.6 - 8.0)
        assert math.isclose(by_key["idle"].delta, 1.4 - 2.0)
        assert math.isclose(sum(r.delta for r in rows), 4.0)
        assert diff_lib.attribute_entry_delta({"step_ms": 10.0}, after) is None
        assert diff_lib.attribute_entry_delta(
            {"step_ms": 10.0, "categories": {}}, after) is None


# ---------------------------------------------------------------------------
# diff_profiles on synthetic encode_xspace pairs (hand-computed)
# ---------------------------------------------------------------------------


def _write_trace(tmp_path, name: str, conv_us: int) -> str:
    """One device plane, sequential critical path: conv (parameterized) +
    fusion 20 + a 5us gap + copy 10 + all-reduce 15 + dot 5, then a 5us
    trailing gap closed by a 0-width marker? No — the span ends at the last
    event, so idle is exactly the one 5us gap + nothing else. Events are
    laid out so category self-times are round numbers and idle is 10us:
    two 5us gaps (after fusion, after all-reduce)."""
    c = conv_us
    events = [
        (f"%convolution.1 = f32[8,16,16,8] convolution(%p0, %p1)", 0 * US, c * US),
        ("%fusion.7 = f32[8,16,16,8] fusion(%param.4)", c * US, 20 * US),
        ("%copy.3 = f32[8,8,16,16] copy(%fusion.7)", (c + 25) * US, 10 * US),
        ("%all-reduce.2 = f32[10] all-reduce(%copy.3)", (c + 35) * US, 15 * US),
        ("%dot.5 = f32[8,10] dot(%fusion.7, %p2)", (c + 55) * US, 5 * US),
    ]
    path = str(tmp_path / f"{name}.xplane.pb")
    with open(path, "wb") as f:
        f.write(xplane.encode_xspace([{
            "name": "/device:TPU:0",
            "lines": [{"name": "XLA Ops", "timestamp_ns": 0, "events": events}],
        }]))
    return path


class TestDiffProfiles:
    def test_hand_computed_category_deltas(self, tmp_path):
        # before: conv 40 -> span 100 (busy 90, idle 10);
        # after:  conv 120 -> span 180 (busy 170, idle 10).
        # Per-category per-step us both sides are the raw self-times + idle,
        # so the ONLY delta is convolution +80us — 100% of the step delta.
        before = analyze_trace(_write_trace(tmp_path, "before", 40))
        after = analyze_trace(_write_trace(tmp_path, "after", 120))
        diff = diff_lib.diff_profiles(before, after)
        assert math.isclose(diff.step_delta_us, 80.0, abs_tol=1e-6)
        top = diff.categories[0]
        assert top.key == "convolution"
        assert math.isclose(top.delta, 80.0, abs_tol=1e-6)
        assert math.isclose(top.frac_of_delta, 1.0, abs_tol=1e-9)
        for row in diff.categories[1:]:
            assert abs(row.delta) < 1e-6, row
        # the exhaustive-partition invariant, across runs
        assert math.isclose(sum(r.frac_of_delta for r in diff.categories), 1.0)
        assert math.isclose(
            sum(r.delta for r in diff.categories), diff.step_delta_us, abs_tol=1e-6
        )
        assert {r.key for r in diff.categories} >= {IDLE, "convolution", "matmul"}
        # op join: the conv op carries the same +80us; everything matched
        assert diff.ops[0].name.startswith("%convolution.1")
        assert math.isclose(diff.ops[0].delta_us, 80.0, abs_tol=1e-6)
        assert not diff.new_ops and not diff.removed_ops
        assert diff.describe()  # renders

    def test_identical_twins_diff_clean(self, tmp_path):
        a = analyze_trace(_write_trace(tmp_path, "a", 40))
        b = analyze_trace(_write_trace(tmp_path, "b", 40))
        diff = diff_lib.diff_profiles(a, b)
        assert diff.max_category_delta_frac() == 0.0
        assert all(r.delta == 0 for r in diff.categories)

    def test_new_and_removed_ops_called_out(self):
        def report(ops):
            return {
                "trace_path": "t", "source": "device", "steps": 1,
                "span_us": 100.0, "busy_us": 100.0, "idle_us": 0.0,
                "step_us": 100.0, "categories": {"convolution": 1.0},
                "category_us": {}, "top_ops": ops,
            }

        before = report([
            {"name": "%convolution.1", "category": "convolution",
             "total_us": 60.0, "count": 1, "frac_busy": 0.6},
            {"name": "%dot.2", "category": "matmul",
             "total_us": 40.0, "count": 1, "frac_busy": 0.4},
        ])
        after = report([
            {"name": "%convolution.1", "category": "convolution",
             "total_us": 60.0, "count": 1, "frac_busy": 0.6},
            {"name": "%pallas_call.9", "category": "matmul",
             "total_us": 20.0, "count": 1, "frac_busy": 0.4},
        ])
        diff = diff_lib.diff_profiles(before, after)
        assert [o.name for o in diff.new_ops] == ["%pallas_call.9"]
        assert [o.name for o in diff.removed_ops] == ["%dot.2"]
        removed = {o.name: o for o in diff.ops}["%dot.2"]
        assert removed.after_us == 0.0 and removed.delta_us == -40.0

    def test_roofline_shift_classified_against_ridge(self):
        def report(intensity):
            return {
                "trace_path": "t", "source": "device", "steps": 1,
                "span_us": 100.0, "busy_us": 100.0, "idle_us": 0.0,
                "step_us": 100.0, "categories": {"convolution": 1.0},
                "category_us": {}, "top_ops": [
                    {"name": "%convolution.1", "category": "convolution",
                     "total_us": 100.0, "count": 1, "frac_busy": 1.0,
                     "arith_intensity": intensity},
                ],
            }

        # 80 F/B -> 250 F/B across a 200 F/B ridge: the Pallas-win signature
        diff = diff_lib.diff_profiles(report(80), report(250), ridge_intensity=200)
        assert [o.bound_shift for o in diff.roofline_shifts] == ["memory->compute"]
        # no ridge given -> intensities carried, shift not classified
        diff = diff_lib.diff_profiles(report(80), report(250))
        assert not diff.roofline_shifts
        assert diff.ops[0].intensity_before == 80
        # same side of the ridge -> no shift
        diff = diff_lib.diff_profiles(report(80), report(150), ridge_intensity=200)
        assert not diff.roofline_shifts

    def test_per_step_normalization_uses_step_us(self):
        def report(step_us, steps):
            return {
                "trace_path": "t", "source": "device", "steps": steps,
                "span_us": step_us * steps, "busy_us": step_us * steps,
                "idle_us": 0.0, "step_us": step_us,
                "categories": {"matmul": 1.0}, "category_us": {}, "top_ops": [],
            }

        # 4-step trace vs 2-step trace with the SAME per-step time: clean.
        diff = diff_lib.diff_profiles(report(50.0, 4), report(50.0, 2))
        assert diff.step_delta_us == 0.0


# ---------------------------------------------------------------------------
# analysis.diff: HLO structural + comm deltas on hand-built programs
# ---------------------------------------------------------------------------


HLO_BEFORE = """\
HloModule step
ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %fusion.1 = f32[8,8]{1,0} fusion(%p0), kind=kLoop, calls=%fused_computation
  %convolution.2 = f32[8,8]{1,0} convolution(%fusion.1, %p0), window={size=3x3}
  %dot.3 = f32[8,8]{1,0} dot(%convolution.2, %p0), lhs_contracting_dims={1}
  ROOT %copy.4 = f32[8,8]{1,0} copy(%dot.3)
}
"""

# The "Pallas landed" twin: the conv became a custom-call, one fusion split
# into two, and a collective appeared.
HLO_AFTER = """\
HloModule step
ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %fusion.1 = f32[8,8]{1,0} fusion(%p0), kind=kLoop, calls=%fused_computation
  %fusion.5 = f32[8,8]{1,0} fusion(%fusion.1), kind=kLoop, calls=%fc2
  %custom-call.2 = f32[8,8]{1,0} custom-call(%fusion.5, %p0), custom_call_target="pallas_conv"
  %dot.3 = f32[8,8]{1,0} dot(%custom-call.2, %p0), lhs_contracting_dims={1}
  %all-reduce.6 = f32[8,8]{1,0} all-reduce(%dot.3), replica_groups=[1,8]<=[8], to_apply=%add
  ROOT %copy.4 = f32[8,8]{1,0} copy(%all-reduce.6)
}
"""


class TestHloStructuralDiff:
    def test_signature_hand_counts(self):
        sig = analysis_diff.hlo_signature(HLO_BEFORE)
        assert sig.instructions == 5
        assert sig.fusions == 1
        assert sig.collectives == 0
        assert sig.category_counts == {
            "other": 1,  # parameter
            "fusion(elementwise)": 1,
            "convolution": 1,
            "matmul": 1,
            "copy/transpose": 1,
        }
        assert sig.opcode_counts["parameter"] == 1

    def test_tuple_typed_instruction_parses(self):
        text = "  %t = (f32[2]{0}, s32[]) tuple(%a, %b)\n"
        assert list(analysis_diff.iter_instruction_opcodes(text)) == [("%t", "tuple")]

    def test_diff_hand_computed(self):
        diff = analysis_diff.diff_hlo(HLO_BEFORE, HLO_AFTER)
        assert diff.instruction_delta == 2
        assert diff.fusion_delta == 1
        assert diff.collective_delta == 1
        deltas = {r.key: r.delta for r in diff.category_deltas}
        # conv -> custom-call: convolution bucket -1, matmul (custom-call) +1
        assert deltas["convolution"] == -1
        assert deltas["matmul"] == 1
        assert deltas["fusion(elementwise)"] == 1
        assert deltas["collective"] == 1
        assert not diff.identical
        assert "fusions 1 -> 2" in diff.describe()

    def test_identical_program(self):
        diff = analysis_diff.diff_hlo(HLO_BEFORE, HLO_BEFORE)
        assert diff.identical
        assert "identical" in diff.describe()


class TestCommDiff:
    @pytest.fixture()
    def mesh(self, devices):
        return mesh_lib.create_mesh(
            {mesh_lib.DATA_AXIS: 4, mesh_lib.TENSOR_AXIS: 2}, devices=devices
        )

    def test_per_axis_deltas_and_regroup_named(self, mesh):
        # before: one all-reduce over the tensor pairs (groups of 2);
        # after: the SAME instruction name regrouped over the data columns.
        before = collective_inventory(
            "  %all-reduce.3 = f32[10,512]{1,0} all-reduce(f32[10,512]{1,0} "
            "%dot.2), channel_id=8, replica_groups=[4,2]<=[8], "
            "use_global_device_ids=true, to_apply=%add\n",
            mesh,
        )
        after = collective_inventory(
            "  %all-reduce.3 = f32[10,512]{1,0} all-reduce(f32[10,512]{1,0} "
            "%dot.2), channel_id=8, replica_groups=[2,4]<=[4,2]T(1,0), "
            "use_global_device_ids=true, to_apply=%add\n",
            mesh,
        )
        bytes_ = 10 * 512 * 4
        assert before.collectives[0].axes == ("tensor",)
        assert after.collectives[0].axes == ("data",)
        diff = analysis_diff.diff_comm(before, after)
        deltas = {r.key: r.delta for r in diff.axis_deltas}
        assert deltas == {"tensor": -bytes_, "data": bytes_}
        assert diff.total_delta == 0
        assert len(diff.group_changes) == 1
        change = diff.group_changes[0]
        assert change.startswith("REGROUPED %all-reduce.3")
        assert "4 group(s) of 2 over tensor -> 2 group(s) of 4 over data" in change

    def test_new_and_removed_collectives_named(self, mesh):
        before = collective_inventory(
            "  %all-reduce.1 = f32[512]{0} all-reduce(f32[512]{0} %g), "
            "replica_groups=[2,4]<=[4,2]T(1,0), to_apply=%add\n",
            mesh,
        )
        after = collective_inventory(
            "  %all-gather.9 = f32[512,8]{1,0} all-gather(f32[512,4]{1,0} %w), "
            "replica_groups=[4,2]<=[8], dimensions={1}\n",
            mesh,
        )
        diff = analysis_diff.diff_comm(before, after)
        kinds = sorted(c.split()[0] for c in diff.group_changes)
        assert kinds == ["NEW", "REMOVED"]
        assert any("%all-gather.9" in c for c in diff.group_changes if "NEW" in c)
        op_deltas = {r.key: r.delta for r in diff.op_deltas}
        assert op_deltas["all-reduce"] == -(512 * 4)
        assert op_deltas["all-gather"] == 512 * 8 * 4

    def test_identical_inventories(self, mesh):
        text = ("  %all-reduce.1 = f32[512]{0} all-reduce(f32[512]{0} %g), "
                "replica_groups=[1,8]<=[8], to_apply=%add\n")
        diff = analysis_diff.diff_comm(
            collective_inventory(text, mesh), collective_inventory(text, mesh)
        )
        assert diff.identical
        assert "identical" in diff.describe()


# ---------------------------------------------------------------------------
# telemetry.history: detectors + round ingestion + committed self-parity
# ---------------------------------------------------------------------------


class TestFlatStreakDetector:
    def test_n_minus_one_quiet_n_fires(self):
        flat3 = [(1, 100.0), (2, 100.5), (3, 99.8)]
        assert history_lib.detect_flat_streaks(flat3, min_rounds=4) == []
        flat4 = flat3 + [(4, 100.2)]
        streaks = history_lib.detect_flat_streaks(flat4, min_rounds=4)
        assert len(streaks) == 1
        assert streaks[0].rounds == [1, 2, 3, 4]
        assert streaks[0].spread < 0.02

    def test_band_boundary(self):
        # spread 2.96% > 2% band: no streak even at min_rounds=2
        assert history_lib.detect_flat_streaks(
            [(1, 100.0), (2, 103.0)], min_rounds=2) == []
        # spread 1.49% fits
        assert len(history_lib.detect_flat_streaks(
            [(1, 100.0), (2, 101.5)], min_rounds=2)) == 1

    def test_maximal_windows_not_suffixes(self):
        # two plateaus split by a jump: exactly two maximal streaks, no
        # sub-window double-reports
        points = [(i, 100.0) for i in range(1, 4)] + [(i, 200.0) for i in range(4, 8)]
        streaks = history_lib.detect_flat_streaks(points, min_rounds=3)
        assert [s.rounds for s in streaks] == [[1, 2, 3], [4, 5, 6, 7]]

    def test_improving_series_is_not_flat(self):
        points = [(i, 100.0 * (1.10 ** i)) for i in range(1, 6)]
        assert history_lib.detect_flat_streaks(points, min_rounds=4) == []

    def test_min_rounds_validated(self):
        with pytest.raises(ValueError):
            history_lib.detect_flat_streaks([(1, 1.0)], min_rounds=1)


class TestRegressionDetector:
    def test_direction_aware(self):
        up = [(1, 100.0), (2, 110.0)]
        down = [(1, 100.0), (2, 90.0)]
        # step_ms up = bad
        assert len(history_lib.detect_regressions(up, "step_ms")) == 1
        assert history_lib.detect_regressions(down, "step_ms") == []
        # value down = bad
        assert len(history_lib.detect_regressions(down, "value")) == 1
        assert history_lib.detect_regressions(up, "value") == []
        # unknown direction: tracked, never accused
        assert history_lib.detect_regressions(up, "mystery_metric") == []

    def test_tolerance_boundary(self):
        assert history_lib.detect_regressions(
            [(1, 100.0), (2, 104.9)], "step_ms", rel_tol=0.05) == []
        found = history_lib.detect_regressions(
            [(1, 100.0), (2, 105.1)], "step_ms", rel_tol=0.05)
        assert len(found) == 1 and found[0].round_after == 2


class TestRoundIngestion:
    def test_tail_lines_preferred_and_parsed(self, tmp_path):
        path = str(tmp_path / "BENCH_r07.json")
        lines = [
            {"metric": "m", "value": 1.0, "dtype": "bf16", "step_ms": 10.0,
             "goodput": {"productive_step": 0.9, "compile": 0.1}},
            {"metric": "m", "value": 2.0, "dtype": "fp32", "step_ms": 20.0},
        ]
        with open(path, "w") as f:
            json.dump({
                "n": 7,
                "tail": "noise\n" + "\n".join(json.dumps(ln) for ln in lines),
                "parsed": {"metric": "m", "value": 1.0},
            }, f)
        entries = history_lib.load_round_file(path)
        assert len(entries) == 2  # both tail lines, parsed NOT duplicated
        assert entries[0].round == 7 and entries[0].kind == "bench"
        assert entries[0].series_label != entries[1].series_label  # dtype facet
        nums = entries[0].numeric_fields()
        assert nums["goodput.productive_step"] == 0.9
        assert "metric" not in nums

    def test_parsed_fallback(self, tmp_path):
        path = str(tmp_path / "MULTICHIP_r03.json")
        with open(path, "w") as f:
            json.dump({"tail": "no json here",
                       "parsed": {"metric": "m", "value": 3.0}}, f)
        entries = history_lib.load_round_file(path)
        assert len(entries) == 1 and entries[0].kind == "multichip"

    def test_non_round_file_rejected(self, tmp_path):
        path = str(tmp_path / "whatever.json")
        with open(path, "w") as f:
            f.write("{}")
        with pytest.raises(ValueError):
            history_lib.load_round_file(path)


def test_committed_rounds_flat_streak_self_parity():
    """The acceptance case on the repo's own committed files: the r02->r05
    plateau (spread 1.4%) must be detected on step_ms AND value."""
    report = history_lib.analyze_history(REPO)
    assert report.entries, "no committed BENCH_r files found"
    for field in ("step_ms", "value"):
        hits = [s for s in report.streaks
                if s.series.endswith(f":: {field}")
                and s.rounds[0] <= 2 and s.rounds[-1] >= 5]
        assert hits, (field, [s.describe() for s in report.streaks])
        assert len(hits[0].rounds) >= 4
    # r01 (45.8k img/s) must NOT be part of the value plateau
    value_hit = [s for s in report.streaks if s.series.endswith(":: value")][0]
    assert 1 not in value_hit.rounds


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------


class TestProvenance:
    def test_fields_present(self):
        prov = prov_lib.provenance_fields(
            mesh={"data": 8}, dtype="bf16", chain_steps=10, batch=4096
        )
        for key in ("git_sha", "jax", "jaxlib", "xla_flags", "mesh", "dtype",
                    "chain_steps", "batch"):
            assert key in prov
        assert prov["git_sha"]  # a sha in a checkout, "unknown" outside one
        assert prov["chain_steps"] == 10

    def test_differing_keys_names_config_not_sha(self):
        a = prov_lib.provenance_fields(dtype="bf16", chain_steps=10)
        b = dict(a, git_sha="deadbeef", dtype="fp32", chain_steps=1)
        keys = prov_lib.differing_keys(a, b)
        assert keys == ["dtype", "chain_steps"]
        assert "git_sha" not in keys

    def test_absent_sides_and_fields_compatible(self):
        a = prov_lib.provenance_fields(dtype="bf16")
        assert prov_lib.differing_keys(None, a) == []
        assert prov_lib.differing_keys(a, None) == []
        # a key absent/None on one side never disagrees (old entries)
        b = dict(a)
        b.pop("dtype")
        assert prov_lib.differing_keys(a, b) == []


# ---------------------------------------------------------------------------
# The CLIs: one shared diff implementation + end-to-end on committed rounds
# ---------------------------------------------------------------------------


def _script_tree(name: str) -> ast.Module:
    with open(os.path.join(REPO, "scripts", name), encoding="utf-8") as f:
        return ast.parse(f.read(), filename=name)


@pytest.mark.parametrize("script", ["run_compare.py", "perf_gate.py"])
def test_scripts_share_the_one_diff_implementation(script):
    """Satellite 6 (test-enforced no drift): both CLIs import
    profiling.diff and define NO attribution/formatting of their own."""
    tree = _script_tree(script)
    imports_diff = any(
        isinstance(node, ast.ImportFrom)
        and node.module
        and node.module.endswith("profiling")
        and any(alias.name == "diff" for alias in node.names)
        for node in ast.walk(tree)
    )
    assert imports_diff, f"{script} must import profiling.diff (the ONE diff impl)"
    forbidden = ("attribute_delta", "attribute_entry_delta", "describe_rows",
                 "diff_profiles")
    local_defs = [
        node.name for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and (node.name in forbidden or "attribut" in node.name)
    ]
    assert not local_defs, (
        f"{script} defines a private attribution {local_defs} — the diff "
        "implementation lives in profiling/diff.py only"
    )


def test_run_compare_cli_on_committed_rounds():
    """End to end on the repo's own committed bench record: r02 vs r05 must
    produce a headline comparison (no provenance on the old rounds — a note,
    not a refusal)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_compare.py"),
         os.path.join(REPO, "BENCH_r02.json"), os.path.join(REPO, "BENCH_r05.json")],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert "step_ms" in proc.stdout
    assert "provenance" in proc.stdout  # the unstamped-artifact note
    assert "value" in proc.stdout


def test_run_compare_provenance_refusal_and_force(tmp_path):
    """Two bench entries whose stamped configuration differs are refused
    (exit 2, keys named); --force compares them."""
    a = {"metric": "m", "value": 1.0, "step_ms": 10.0,
         "provenance": {"jax": "1", "dtype": "bf16"}}
    b = {"metric": "m", "value": 2.0, "step_ms": 12.0,
         "provenance": {"jax": "1", "dtype": "fp32"}}
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cmd = [sys.executable, os.path.join(REPO, "scripts", "run_compare.py"), pa, pb]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=180, env=env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "dtype" in proc.stdout
    proc = subprocess.run(cmd + ["--force"], capture_output=True, text=True,
                          timeout=180, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "--force" in proc.stdout or "forced" in proc.stdout or "anyway" in proc.stdout


def test_bench_history_events_record(tmp_path):
    """--events appends a bench_history record (the vocabulary satellite —
    the doc-drift test in test_timeline covers the docs side)."""
    from distributed_training_pytorch_tpu.telemetry import read_events

    events = str(tmp_path / "events.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_history.py"),
         "--events", events],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    records = [r for r in read_events(events) if r["event"] == "bench_history"]
    assert len(records) == 1
    assert records[0]["entries"] >= 5
    assert any(s["rounds"][0] <= 2 and s["rounds"][-1] >= 5
               for s in records[0]["streaks"])
