"""ISSUE 18 tests: the serving subsystem — continuous micro-batching on
the training machinery.

Acceptance pillars:

* the :class:`serving.batcher.MicroBatcher` flush policy, pinned at its
  boundaries WITHOUT devices (pure Python, injected clock): bucket
  boundary-exactness, deadline flush at exactly ``max_delay_s``, full
  flush the instant the largest bucket fills, round-robin fairness under
  a greedy tenant, typed + counted overload, and the zero-capacity
  refuse-not-hang contract;
* :class:`serving.engine.InferEngine` mirrors ``TrainEngine``'s executable
  contract: one compile per (bucket, row signature) with ``trace_counts``
  bumped in-body, a structure-checked one-engine-one-model binding,
  bucket/mesh-extent validation up front, and bit-identical outputs for
  identical params across a hot-swap (the soak's determinism leg, unit
  sized);
* :class:`serving.server.InferenceServer` end to end on the virtual CPU
  mesh: /predict, /status, /metrics, HTTP 429 on overload, the
  ``serve_start``/``request_batch``/``hot_swap``/``admission_reject``
  flight-recorder vocabulary, and hot-swap under load via a manifest
  identity change;
* the monitor reads a server run as a first-class fleet member (status
  ``serving``, verdict ``healthy``/``slo_breach``, qps/p99 fleet columns)
  and the fleet controller's mixed-fleet ``offer_chip`` advisory;
* import neutrality: ``distributed_training_pytorch_tpu.serving`` pulls
  NO jax at package import — a trainer that imports-but-ignores serving
  cannot perturb a training run.
"""

import json
import os
import subprocess
import sys
import time
import types
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_training_pytorch_tpu.parallel import mesh_config_from_spec
from distributed_training_pytorch_tpu.serving import (
    MicroBatcher,
    OverloadRejected,
    pick_bucket,
)
from distributed_training_pytorch_tpu.serving.engine import InferEngine
from distributed_training_pytorch_tpu.serving.server import (
    InferenceServer,
    LatencyWindow,
)
from distributed_training_pytorch_tpu.telemetry.events import (
    resolve_events_path,
)
from distributed_training_pytorch_tpu.telemetry.monitor import (
    AlertConfig,
    RunMonitor,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# pick_bucket: boundary exactness.


def test_pick_bucket_boundary_exact():
    buckets = (1, 2, 4, 8)
    assert pick_bucket(1, buckets) == 1
    assert pick_bucket(2, buckets) == 2
    assert pick_bucket(3, buckets) == 4
    assert pick_bucket(4, buckets) == 4  # exactly on a boundary: that bucket
    assert pick_bucket(5, buckets) == 8  # one over: the next
    assert pick_bucket(8, buckets) == 8
    with pytest.raises(ValueError):
        pick_bucket(9, buckets)
    with pytest.raises(ValueError):
        pick_bucket(0, buckets)


# ---------------------------------------------------------------------------
# MicroBatcher: the flush policy on a fake clock.


def _batcher(**kw):
    kw.setdefault("buckets", (1, 2, 4, 8))
    kw.setdefault("max_delay_s", 0.02)
    kw.setdefault("clock", FakeClock())
    return MicroBatcher(**kw)


def test_deadline_flush_exact():
    clock = FakeClock()
    b = _batcher(clock=clock)
    for _ in range(3):
        b.submit("a", 0)
    # Just under the deadline: keep admitting.
    clock.advance(0.019)
    assert b.next_batch() is None
    # At the deadline: flush, padded to the covering bucket.
    clock.advance(0.001)
    batch = b.next_batch()
    assert batch is not None
    assert batch.flushed_by == "deadline"
    assert len(batch.requests) == 3
    assert batch.bucket == 4 and batch.pad == 1
    assert b.pending() == 0


def test_full_flush_immediate():
    b = _batcher()
    for _ in range(8):
        b.submit("a", 0)
    # No clock advance at all: the largest bucket is occupied, flush now.
    batch = b.next_batch()
    assert batch is not None
    assert batch.flushed_by == "full"
    assert batch.bucket == 8 and batch.pad == 0


def test_next_deadline_tracks_oldest():
    clock = FakeClock(100.0)
    b = _batcher(clock=clock)
    assert b.next_deadline() is None
    b.submit("a", 0)
    assert b.next_deadline() == pytest.approx(100.02)
    clock.advance(0.01)
    b.submit("b", 0)  # younger request must not push the deadline back
    assert b.next_deadline() == pytest.approx(100.02)


def test_fairness_greedy_tenant_cannot_starve_quiet_one():
    b = _batcher(max_queue_depth=200)
    for _ in range(100):
        b.submit("greedy", "g")
    for _ in range(4):
        b.submit("quiet", "q")
    batch = b.next_batch()  # full flush at bucket 8
    assert batch is not None and batch.bucket == 8
    by_tenant = {}
    for r in batch.requests:
        by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
    # Round-robin drafting: the quiet tenant gets every slot it can fill.
    assert by_tenant == {"greedy": 4, "quiet": 4}


def test_rotation_rotates_the_draft_start():
    b = _batcher(buckets=(1,), max_queue_depth=8)
    for _ in range(2):
        b.submit("a", 0)
        b.submit("b", 0)
    order = [b.next_batch(drain=True).requests[0].tenant for _ in range(4)]
    # The rotation start advances per batch: strict alternation, so no
    # tenant is structurally first in every single-slot bucket.
    assert order == ["a", "b", "a", "b"]


def test_fifo_within_tenant():
    b = _batcher()
    r1 = b.submit("a", "first")
    r2 = b.submit("a", "second")
    batch = b.next_batch(drain=True)
    ids = [r.id for r in batch.requests if r.tenant == "a"]
    assert ids == sorted(ids) and ids == [r1.id, r2.id]


def test_overload_typed_and_counted():
    b = _batcher(max_queue_depth=2)
    b.submit("a", 0)
    b.submit("a", 0)
    with pytest.raises(OverloadRejected) as exc:
        b.submit("a", 0)
    assert exc.value.tenant == "a"
    assert exc.value.depth == 2 and exc.value.bound == 2
    assert b.rejected["a"] == 1
    assert b.submitted == 2  # the rejected request was never admitted
    # Another tenant still has room: bounds are per tenant.
    b.submit("b", 0)
    assert b.pending() == 3


def test_submit_many_all_or_nothing():
    b = _batcher(max_queue_depth=4)
    b.submit("a", 0)
    # 3 more rows fit exactly; a 4-row request must not half-admit.
    with pytest.raises(OverloadRejected) as exc:
        b.submit_many("a", [1, 2, 3, 4])
    assert exc.value.depth == 1 and exc.value.bound == 4
    assert b.pending() == 1  # no orphan rows from the rejected request
    assert b.submitted == 1 and b.rejected["a"] == 1
    reqs = b.submit_many("a", [1, 2, 3])
    assert len(reqs) == 3 and b.pending() == 4
    assert [r.id for r in reqs] == sorted(r.id for r in reqs)  # FIFO ids
    assert b.submit_many("a", []) == []  # empty list: no-op, not a reject


def test_zero_capacity_refuses_never_hangs():
    b = _batcher(max_queue_depth=0)
    t0 = time.monotonic()
    with pytest.raises(OverloadRejected):
        b.submit("anyone", 0)
    assert time.monotonic() - t0 < 1.0  # refused, not queued/blocked
    assert b.rejected["anyone"] == 1 and b.pending() == 0


def test_drain_flush_reason_and_counters():
    b = _batcher()
    b.submit("a", 0)
    batch = b.next_batch(drain=True)
    assert batch.flushed_by == "drain"
    assert b.flushes == {"drain": 1}
    stats = b.stats()
    assert stats["batches"] == 1 and stats["pending"] == 0
    assert stats["padded_slots"] == 0  # 1 request -> bucket 1


def test_stats_pad_frac():
    b = _batcher()
    for _ in range(3):
        b.submit("a", 0)
    b.next_batch(drain=True)  # 3 -> bucket 4, one padded slot
    assert b.stats()["pad_frac"] == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# LatencyWindow.


def test_latency_window_quantiles_and_qps():
    clock = FakeClock(0.0)
    w = LatencyWindow(window_s=10.0, clock=clock)
    for i in range(100):
        w.add(float(i) * 0.05, latency_ms=float(i + 1))
    clock.t = 5.0
    snap = w.snapshot()
    assert snap["window_n"] == 100
    assert snap["p50_ms"] == 51.0
    assert snap["p99_ms"] == 100.0
    assert snap["qps"] == pytest.approx(20.0, rel=0.05)
    # Old completions age out of the trailing window.
    clock.t = 50.0
    assert w.snapshot()["window_n"] == 0


# ---------------------------------------------------------------------------
# InferEngine on the virtual CPU mesh.


@pytest.fixture(scope="module")
def tp_mesh(devices=None):
    # tensor=2 over two devices: batch-shard extent 1, so every bucket is
    # legal — and the TP path exercises the ambient-mesh/sharding plumbing.
    return mesh_config_from_spec("tp2").build(jax.devices()[:2])


def _linear_params(seed=0, d=4):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((d, d)).astype(np.float32)}


def _linear_apply(params, x):
    return x @ params["w"]


def test_engine_bucket_extent_validation():
    dp8 = mesh_config_from_spec("dp8").build()
    with pytest.raises(ValueError, match="batch-shard extent"):
        InferEngine(_linear_apply, dp8, buckets=(1, 2, 4, 8))
    # Buckets the extent divides are fine.
    InferEngine(_linear_apply, dp8, buckets=(8, 16))


def test_engine_pads_dispatches_and_never_retraces(tp_mesh):
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4, 8))
    with pytest.raises(RuntimeError, match="no params"):
        eng.predict(np.ones((1, 4), np.float32))
    params = _linear_params()
    eng.swap_params(params, version="v1")
    eng.warmup(np.ones((4,), np.float32))
    assert eng.trace_counts["infer_step"] == 4  # one trace per bucket
    x = np.random.default_rng(1).standard_normal((3, 4)).astype(np.float32)
    out, version = eng.predict(x)
    assert version == "v1"
    assert out.shape == (3, 4)  # pad to bucket 4, sliced back off
    np.testing.assert_allclose(out, x @ params["w"], rtol=1e-5)
    # Steady state: same signatures, zero new traces (the retrace guard).
    for n in (1, 2, 3, 5, 8):
        eng.predict(np.ones((n, 4), np.float32))
    assert eng.trace_counts["infer_step"] == 4


def test_engine_structure_check_one_engine_one_model(tp_mesh):
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2))
    eng.swap_params(_linear_params(), version="v1")
    with pytest.raises(ValueError, match="different structure"):
        eng.swap_params({"w": np.ones((8, 8), np.float32)}, version="v2")
    with pytest.raises(ValueError, match="different structure"):
        eng.swap_params({"other": np.ones((4, 4), np.float32)}, version="v2")


def test_engine_same_params_same_bytes_across_swap(tp_mesh):
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4))
    params = _linear_params(seed=7)
    x = np.random.default_rng(3).standard_normal((3, 4)).astype(np.float32)
    eng.swap_params(params, version="best@e1")
    a, _ = eng.predict(x)
    # Hot-swap to an IDENTICAL params tree (a re-commit of the same
    # checkpoint): responses must be bit-identical, not merely close.
    eng.swap_params({k: v.copy() for k, v in params.items()}, version="best@e1")
    b, _ = eng.predict(x)
    assert a.tobytes() == b.tobytes()
    assert eng.swap_count == 2
    # Different params must actually change the answer (the swap is real).
    eng.swap_params(_linear_params(seed=8), version="best@e2")
    c, v = eng.predict(x)
    assert v == "best@e2" and a.tobytes() != c.tobytes()


# ---------------------------------------------------------------------------
# InferenceServer end to end (ephemeral port, virtual CPU mesh).


def _post(port, payload, timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port, route, timeout=10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{route}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


@pytest.fixture()
def served(tmp_path, tp_mesh):
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4))
    eng.swap_params(_linear_params(seed=5), version="best@e1")
    eng.warmup(np.ones((4,), np.float32))
    server = InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(1, 2, 4), max_delay_s=0.005),
        run_dir=str(tmp_path),
        slo_p99_ms=2000.0,
        pulse_every_s=0.2,
        process_index=0,
    ).start()
    assert server.enabled and server.port
    try:
        yield server
    finally:
        server.close()


def test_server_predict_status_metrics(served, tmp_path):
    x = [[1.0, 2.0, 3.0, 4.0], [4.0, 3.0, 2.0, 1.0]]
    code, body = _post(served.port, {"tenant": "t0", "inputs": x})
    assert code == 200
    assert body["params_version"] == "best@e1"
    expect, _ = served.engine.predict(np.asarray(x, np.float32))
    np.testing.assert_allclose(np.asarray(body["outputs"]), expect, rtol=1e-6)
    # The response body is a pure function of (inputs, params): a second
    # identical request returns byte-identical JSON (hot-swap bit-identity
    # rests on this).
    code2, body2 = _post(served.port, {"tenant": "t0", "inputs": x})
    assert code2 == 200 and body2 == body

    code, text = _get(served.port, "/status")
    snap = json.loads(text)
    assert code == 200
    assert snap["kind"] == "server"
    assert snap["requests_total"] >= 4
    assert snap["params_version"] == "best@e1"
    assert snap["qps_per_chip"] >= 0.0
    code, text = _get(served.port, "/metrics")
    assert code == 200
    assert "tpu_serve_up 1" in text
    assert "tpu_serve_requests_total" in text
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(served.port, "/nonsense")
    assert exc.value.code == 404


def test_server_bad_request_is_400(served):
    code, body = _post(served.port, {"tenant": "t0"})  # no inputs
    assert code == 400 and body["error"] == "bad_request"


def test_server_overload_is_typed_429(tmp_path, tp_mesh):
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4))
    eng.swap_params(_linear_params(), version="v1")
    with InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(1, 2, 4), max_queue_depth=0),
        run_dir=str(tmp_path / "overloaded"),
        process_index=0,
    ) as server:
        server.start()
        t0 = time.monotonic()
        code, body = _post(server.port, {"tenant": "t9", "inputs": [[1, 2, 3, 4]]})
        assert time.monotonic() - t0 < 5.0  # refused, not hung
        assert code == 429
        assert body == {"error": "overload", "tenant": "t9", "depth": 0, "bound": 0}
    recs = _read_events(str(tmp_path / "overloaded"))
    rejects = [r for r in recs if r["event"] == "admission_reject"]
    assert len(rejects) == 1
    assert rejects[0]["tenant"] == "t9" and rejects[0]["rejected_total"] == 1


def test_server_multi_row_429_leaves_no_orphans(tmp_path, tp_mesh):
    """A rejected multi-row POST admits nothing: no already-queued rows
    keep dispatching (and burning compute) after the client's 429."""
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4))
    eng.swap_params(_linear_params(), version="v1")
    with InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(1, 2, 4), max_queue_depth=2, max_delay_s=5.0),
        run_dir=str(tmp_path / "orphans"),
        process_index=0,
    ) as server:
        server.start()
        x3 = [[1.0, 2.0, 3.0, 4.0]] * 3  # 3 rows > depth bound of 2
        code, body = _post(server.port, {"tenant": "t0", "inputs": x3})
        assert code == 429 and body["error"] == "overload"
        assert body["depth"] == 0 and body["bound"] == 2
        assert server.batcher.pending() == 0  # nothing half-admitted
        assert server.batcher.submitted == 0
        # The bound still admits a request that fits, whole.
        code, body = _post(server.port, {"tenant": "t0", "inputs": x3[:2]})
        assert code == 200 and len(body["outputs"]) == 2


def test_mixed_shape_batch_survives_dispatch(tmp_path, tp_mesh):
    """Two tenants posting valid rows of different lengths can land in one
    micro-batch; the dispatch thread must answer (not die on np.stack), and
    the well-shaped rows must succeed rather than fail for a neighbor."""
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2, 4))
    params = _linear_params(seed=5)
    eng.swap_params(params, version="v1")
    with InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(1, 2, 4), max_delay_s=0.2),
        run_dir=str(tmp_path / "mixed"),
        process_index=0,
    ) as server:
        server.start()
        # Submit straight into the batcher so both rows share a batch
        # deterministically (the HTTP path cannot force the timing).
        good = server.batcher.submit("a", np.ones((4,), np.float32))
        bad = server.batcher.submit("b", np.ones((8,), np.float32))
        assert good.wait(10.0) and bad.wait(10.0)
        assert good.error is None
        np.testing.assert_allclose(
            np.asarray(good.result), np.ones((4,), np.float32) @ params["w"],
            rtol=1e-5,
        )
        assert bad.error is not None  # answered as a failure, not a hang
        # The dispatch thread survived: the server still serves.
        code, body = _post(server.port, {"inputs": [[1.0, 0.0, 0.0, 0.0]]})
        assert code == 200 and body["params_version"] == "v1"


def test_default_batcher_inherits_server_clock(tp_mesh):
    """Latency is server-clock-now minus Request.arrival: the batcher the
    server builds for itself must stamp arrivals on the same clock."""
    clock = FakeClock(42.0)
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2))
    server = InferenceServer(eng, process_index=0, clock=clock)
    assert server.batcher._clock is clock
    assert server.batcher.submit("t", 0).arrival == 42.0


def _read_events(run_dir):
    path = resolve_events_path(run_dir)
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_server_flight_recorder_and_monitor_integration(served, tmp_path):
    # Traffic + a pulse interval's worth of wall time.
    for _ in range(3):
        _post(served.port, {"tenant": "a", "inputs": [[1.0, 0.0, 0.0, 0.0]]})
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        recs = _read_events(str(tmp_path))
        if any(r["event"] == "request_batch" for r in recs):
            break
        time.sleep(0.05)
    recs = _read_events(str(tmp_path))
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "serve_start"
    start = recs[0]
    assert start["port"] == served.port and start["attempt"] == 1
    assert start["params_version"] == "best@e1"
    pulses = [r for r in recs if r["event"] == "request_batch"]
    assert pulses, f"no request_batch pulse in {kinds}"
    assert pulses[-1]["slo_ok"] is True  # 2 s SLO vs sub-ms linear model

    # The monitor reads this run dir as a serving fleet member.
    mon = RunMonitor(str(tmp_path), AlertConfig(stale_after_s=30.0))
    st = mon.poll()
    assert st.kind == "serve"
    assert st.status == "serving"
    assert st.verdict == "healthy"
    assert st.exit_code == 0
    row = st.fleet_row()
    assert row["qps"] != "?" and row["p99"] != "?"
    assert row["step_ms"] == "-" and row["good%"] == "-"  # trainer-only cols

    # Closing the server emits run_end -> the monitor's finished marker.
    served.close()
    st = RunMonitor(str(tmp_path), AlertConfig()).poll()
    assert st.status == "finished" and st.exit_code == 0


def test_server_hot_swap_under_load(tmp_path, tp_mesh):
    """A manifest identity change mid-traffic swaps params atomically:
    same params -> byte-identical responses, new params -> new answers,
    and a ``hot_swap`` record lands in the flight recorder."""
    ckpt_root = tmp_path / "weights"
    run_dir = tmp_path / "run"

    class StubState:
        def __init__(self, params):
            self.params = params

    class StubManager:
        """The manifest surface the swap watcher reads: exists/path/
        latest_valid_name/restore, driven by a plain dict."""

        MANIFEST = "manifest.json"

        def __init__(self):
            self.store = {}  # name -> (params, epoch)

        def commit(self, name, params, epoch):
            d = ckpt_root / name
            d.mkdir(parents=True, exist_ok=True)
            self.store[name] = (params, epoch)
            tmp = d / ".manifest.tmp"
            tmp.write_text(json.dumps({"epoch": epoch}))
            os.replace(tmp, d / self.MANIFEST)  # the atomic publish

        def exists(self, name):
            return name in self.store

        def path(self, name):
            return str(ckpt_root / name)

        def latest_valid_name(self):
            return None

        def restore(self, name, target_state, params_only=False):
            params, epoch = self.store[name]
            return StubState(params), epoch

    import distributed_training_pytorch_tpu.checkpoint.manager as mgr_mod

    manager = StubManager()
    p1 = _linear_params(seed=11)
    manager.commit("best", p1, epoch=1)

    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2))
    real_manifest = mgr_mod.MANIFEST_NAME
    try:
        mgr_mod.MANIFEST_NAME = StubManager.MANIFEST
        with InferenceServer(
            eng,
            batcher=MicroBatcher(buckets=(1, 2), max_delay_s=0.002),
            run_dir=str(run_dir),
            manager=manager,
            target_state=object(),
            serve_name="best",
            swap_poll_s=0.05,
            process_index=0,
        ) as server:
            server.start()
            x = [[1.0, 2.0, 3.0, 4.0]]

            def wait_version(v, timeout=5.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if eng.params_version == v:
                        return True
                    time.sleep(0.02)
                return False

            assert wait_version("best@e1"), "initial swap from manifest"
            code, before = _post(server.port, {"inputs": x})
            assert code == 200 and before["params_version"] == "best@e1"

            # Re-commit the SAME params at the same epoch: the identity
            # (mtime) changes, the swap fires, the bytes must not.
            time.sleep(0.05)
            manager.commit("best", {k: v.copy() for k, v in p1.items()}, epoch=1)
            deadline = time.monotonic() + 5.0
            while eng.swap_count < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng.swap_count >= 2  # initial manifest swap + re-commit
            code, again = _post(server.port, {"inputs": x})
            assert code == 200 and again == before  # bit-identical

            # A genuinely new checkpoint changes the served answer.
            manager.commit("best", _linear_params(seed=12), epoch=2)
            assert wait_version("best@e2")
            code, after = _post(server.port, {"inputs": x})
            assert code == 200
            assert after["params_version"] == "best@e2"
            assert after["outputs"] != before["outputs"]
    finally:
        mgr_mod.MANIFEST_NAME = real_manifest

    swaps = [r for r in _read_events(str(run_dir)) if r["event"] == "hot_swap"]
    assert len(swaps) >= 2
    assert swaps[0]["checkpoint"] == "best"
    assert swaps[-1]["to_version"] == "best@e2"


def test_preloaded_candidate_skips_startup_swap(tmp_path, tp_mesh):
    """An engine already serving the candidate checkpoint (restored before
    ``start()``) is not redundantly re-restored by the watcher's first
    poll, and no spurious startup ``hot_swap`` lands in the recorder; a
    later re-commit still swaps."""
    from distributed_training_pytorch_tpu.checkpoint.manager import MANIFEST_NAME

    ckpt = tmp_path / "weights" / "best"
    ckpt.mkdir(parents=True)
    manifest = ckpt / MANIFEST_NAME
    manifest.write_text(json.dumps({"epoch": 1}))

    class Mgr:
        def exists(self, name):
            return name == "best"

        def path(self, name):
            return str(ckpt)

        def latest_valid_name(self):
            return "best"

        def restore(self, name, target_state, params_only=False):
            return types.SimpleNamespace(params=_linear_params(seed=11)), 2

    run_dir = tmp_path / "run"
    eng = InferEngine(_linear_apply, tp_mesh, buckets=(1, 2))
    eng.swap_params(_linear_params(seed=11), version="best@e1")  # preloaded
    with InferenceServer(
        eng,
        batcher=MicroBatcher(buckets=(1, 2)),
        run_dir=str(run_dir),
        manager=Mgr(),
        target_state=object(),
        serve_name="best",
        swap_poll_s=0.05,
        process_index=0,
    ) as server:
        server.start()
        time.sleep(0.3)  # several watcher polls
        assert eng.swap_count == 1  # only the preload — no startup re-swap
        assert eng.params_version == "best@e1"
        # A real re-commit (manifest mtime changes) still fires the swap.
        os.utime(manifest, (time.time() + 5, time.time() + 5))
        deadline = time.monotonic() + 5.0
        while eng.swap_count < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.swap_count == 2 and eng.params_version == "best@e2"
    swaps = [r for r in _read_events(str(run_dir)) if r["event"] == "hot_swap"]
    assert len(swaps) == 1  # the re-commit only; no spurious startup record
    assert swaps[0]["from_version"] == "best@e1"
    assert swaps[0]["to_version"] == "best@e2"


# ---------------------------------------------------------------------------
# Monitor: synthetic server logs (no server process needed).


def _write_serve_log(run_dir, pulses):
    os.makedirs(os.path.dirname(resolve_events_path(run_dir)), exist_ok=True)
    now = time.time()
    recs = [
        {"event": "serve_start", "t_wall": now - 2.0, "attempt": 1, "port": 1234}
    ]
    for p in pulses:
        recs.append({"event": "request_batch", "t_wall": now, "attempt": 1, **p})
    with open(resolve_events_path(run_dir), "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_monitor_serve_slo_breach_exit_code(tmp_path):
    run = str(tmp_path / "srv")
    _write_serve_log(
        run,
        [{"qps": 120.0, "p50_ms": 3.0, "p99_ms": 45.0, "slo_p99_ms": 20.0,
          "slo_ok": False, "params_version": "best@e3", "rejected_total": 0}],
    )
    st = RunMonitor(run, AlertConfig(stale_after_s=60.0)).poll()
    assert st.kind == "serve" and st.status == "serving"
    assert st.verdict == "slo_breach"
    assert st.exit_code == 1  # the --once CI contract honors a server SLO
    assert "slo_breach" in st.active_alerts
    row = st.fleet_row()
    assert row["qps"] == "120.00" and row["p99"] == "45.0"
    assert st.serve["params_version"] == "best@e3"


def test_monitor_serve_healthy_and_trainer_row_shape(tmp_path):
    run = str(tmp_path / "srv_ok")
    _write_serve_log(
        run,
        [{"qps": 10.0, "p50_ms": 1.0, "p99_ms": 2.0, "slo_p99_ms": 20.0,
          "slo_ok": True, "params_version": "best@e1", "rejected_total": 0}],
    )
    st = RunMonitor(run, AlertConfig(stale_after_s=60.0)).poll()
    assert st.verdict == "healthy" and st.exit_code == 0
    # A trainer's row carries the same schema with serving columns blanked:
    train_run = str(tmp_path / "trn")
    os.makedirs(os.path.dirname(resolve_events_path(train_run)), exist_ok=True)
    with open(resolve_events_path(train_run), "w") as f:
        f.write(json.dumps({"event": "run_start", "t_wall": time.time(),
                            "attempt": 1}) + "\n")
    trow = RunMonitor(train_run, AlertConfig()).poll().fleet_row()
    srow = st.fleet_row()
    assert set(trow) == set(srow)  # one table renders both
    assert trow["qps"] == "-" and trow["p99"] == "-"


# ---------------------------------------------------------------------------
# Fleet controller: the mixed-fleet offer_chip advisory.


def test_offer_chip_in_action_vocabulary():
    from distributed_training_pytorch_tpu.telemetry.controller import (
        ACTION_KINDS,
        Action,
    )

    assert "offer_chip" in ACTION_KINDS
    a = Action(kind="offer_chip", reason="straggler")
    assert not a.respawns  # advisory: never consumes the restart budget


def test_fleet_controller_offers_freed_chip_to_serving_replica(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import fleet_controller as fc
    from distributed_training_pytorch_tpu.telemetry.controller import (
        Action,
        ControllerConfig,
    )
    from distributed_training_pytorch_tpu.telemetry.events import EventLog

    events_path = str(tmp_path / "ops.jsonl")
    trainer = fc.RunSpec(
        name="trainer0", run_dir=str(tmp_path / "trainer0"),
        adopt=True, device_ids=(0, 1), mesh="fsdp2",
    )
    server = fc.RunSpec(
        name="server0", run_dir=str(tmp_path / "server0"),
        kind="serve", adopt=True,
    )
    fleet = fc.FleetController(
        [trainer, server],
        config=ControllerConfig(max_restarts=3),
        monitor_config=AlertConfig(),
        event_log=EventLog(events_path, process_index=0),
        interval=0.1,
    )
    action = Action(
        kind="restart_excluding",
        reason="straggler",
        params={"exclude_chip": 1},
        evidence=[{"metric": "straggler_ratio", "value": 3.2}],
    )
    status = types.SimpleNamespace(attempt=2, status="training",
                                   verdict="straggler")
    fleet._offer_freed_chip(fleet.runs["trainer0"], action, status)
    fleet.events.close()

    with open(events_path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    offers = [r for r in recs if r.get("action") == "offer_chip"]
    assert len(offers) == 1  # one per serving replica, none to the trainer
    offer = offers[0]
    assert offer["run"] == "server0"
    assert offer["params"] == {
        "chip": 1, "from_run": "trainer0", "to_run": "server0",
    }
    assert offer["reason"] == "straggler"
    assert offer["evidence"]  # the triggering evidence rides along
    assert fleet.runs["server0"].actions[0].kind == "offer_chip"


# ---------------------------------------------------------------------------
# Import neutrality: serving pulls no jax at package import.


def test_serving_package_import_is_neutral():
    """The acceptance neutrality pillar: a trainer that imports serving
    but never uses it cannot perturb training. The package import loads
    ONLY the pure-Python batcher — no engine, no server, no jax device or
    PRNG touch — so it can change neither params nor trace_counts of a
    run that ignores it. (The parent package imports jax on its own;
    neutrality is about what importing ``serving`` ADDS.)"""
    code = (
        "import sys\n"
        "import distributed_training_pytorch_tpu  # parent may pull jax itself\n"
        "before = set(sys.modules)\n"
        "import distributed_training_pytorch_tpu.serving as s\n"
        "added = set(sys.modules) - before\n"
        "pkg = 'distributed_training_pytorch_tpu.serving'\n"
        "extra = {m for m in added if not m.startswith(pkg)}\n"
        "assert not extra, f'serving import pulled foreign modules: {extra}'\n"
        "assert pkg + '.engine' not in added, 'engine (jax) loaded eagerly'\n"
        "assert pkg + '.server' not in added, 'server loaded eagerly'\n"
        "assert s.MicroBatcher and s.pick_bucket\n"
        "print('ok')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr + out.stdout
    assert "ok" in out.stdout
    # And the batcher module itself is statically jax-free.
    src = open(os.path.join(
        REPO, "distributed_training_pytorch_tpu", "serving", "batcher.py"
    )).read()
    assert "import jax" not in src
