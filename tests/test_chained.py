"""On-device chained step execution (ISSUE 2): engine scan windows, chain-major
prefetch staging, and the Trainer's windowed hot loop.

THE acceptance property throughout: chained execution is BIT-EXACT with
single-step execution on the same data/RNG — params, opt_state, and per-step
metrics — across microbatching and the nan guard, with automatic single-step
fallback for epoch tails and fault-injected windows.

Cost note: trainer constructions compile a toy VGG on CPU (~15-40s each), so
trainer-level tests share module-scoped runs the way test_trainer.py does.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.data import ShardedLoader, ArrayDataSource
from distributed_training_pytorch_tpu.data.prefetch import device_prefetch_chained
from distributed_training_pytorch_tpu.fault import FaultPlan
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

from test_engine import TinyMLP, criterion, synthetic_batch
from test_trainer import RecordingToyTrainer, ToyTrainer, make_trainer, synthetic_images


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def make_engine(accum_steps=1, nan_guard=False):
    mesh = mesh_lib.create_mesh()
    model = TinyMLP()
    import optax

    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        accum_steps=accum_steps,
        nan_guard=nan_guard,
    )
    state = engine.init_state(
        jax.random.key(0), lambda rng: model.init(rng, jnp.zeros((1, 4, 4, 3)))
    )
    return engine, state


def stack_batches(host_batches):
    return jax.tree.map(lambda *xs: np.stack(xs), *host_batches)


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb, strict=True):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Engine: train_steps_chained.


def test_train_steps_chained_bit_exact_distinct_batches(devices):
    """4 distinct per-step batches through ONE chained dispatch == 4 sequential
    train_steps — params, opt_state, and every per-step metric bit-exact."""
    host = [synthetic_batch(16, seed=i) for i in range(4)]
    eng_a, state_a = make_engine()
    eng_b, state_b = make_engine()
    seq_metrics = []
    for hb in host:
        state_a, m = eng_a.train_step(state_a, eng_a.shard_batch(hb))
        seq_metrics.append(jax.device_get(m))
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 4)
    assert int(state_b.step) == int(state_a.step) == 4
    assert_trees_equal(state_a.params, state_b.params)
    assert_trees_equal(state_a.opt_state, state_b.opt_state)
    stacked = jax.device_get(stacked)
    for i, m in enumerate(seq_metrics):
        for k, v in m.items():
            np.testing.assert_array_equal(np.asarray(v), np.asarray(stacked[k][i]))


def test_train_steps_chained_microbatched_nan_guard_bit_exact(devices):
    """The chained scan threads the microbatch-accumulation scan AND the
    non-finite guard unchanged (they live inside the step body)."""
    host = [synthetic_batch(16, seed=10 + i) for i in range(3)]
    eng_a, state_a = make_engine(accum_steps=2, nan_guard=True)
    eng_b, state_b = make_engine(accum_steps=2, nan_guard=True)
    for hb in host:
        state_a, m = eng_a.train_step(state_a, eng_a.shard_batch(hb))
        assert float(m["nonfinite"]) == 0.0
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 3)
    assert_trees_equal(state_a.params, state_b.params)
    assert_trees_equal(state_a.opt_state, state_b.opt_state)
    np.testing.assert_array_equal(np.asarray(stacked["nonfinite"]), np.zeros(3))


def test_train_steps_chained_guard_skips_poisoned_step(devices):
    """A NaN batch mid-window: the guard drops that step's update INSIDE the
    chain (per-step nonfinite scan outputs flag exactly it) and the result
    equals the sequential run on the same poisoned stream."""
    host = [synthetic_batch(16, seed=20 + i) for i in range(4)]
    host[2] = dict(host[2], image=np.full_like(host[2]["image"], np.nan))
    eng_a, state_a = make_engine(nan_guard=True)
    eng_b, state_b = make_engine(nan_guard=True)
    for hb in host:
        state_a, _ = eng_a.train_step(state_a, eng_a.shard_batch(hb))
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 4)
    np.testing.assert_array_equal(
        np.asarray(stacked["nonfinite"]), np.array([0.0, 0.0, 1.0, 0.0])
    )
    assert_trees_equal(state_a.params, state_b.params)
    for leaf in jax.tree.leaves(state_b.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # step still advanced past the poison (data/dropout streams move on)
    assert int(state_b.step) == 4


def test_train_steps_chained_compiles_once_per_length(devices):
    """The retrace guard's engine contract: repeated windows of one length
    trace exactly once (jit cache hit), a second length traces separately."""
    eng, state = make_engine()
    host = [synthetic_batch(16, seed=30 + i) for i in range(2)]
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng.mesh)
    for _ in range(3):
        state, _ = eng.train_steps_chained(state, gb, 2)
    assert eng.trace_counts["chained_2"] == 1
    host3 = [synthetic_batch(16, seed=40 + i) for i in range(3)]
    gb3 = mesh_lib.global_chain_array_from_host_local(stack_batches(host3), eng.mesh)
    state, _ = eng.train_steps_chained(state, gb3, 3)
    assert eng.trace_counts["chained_3"] == 1
    assert eng.trace_counts["chained_2"] == 1
    with pytest.raises(ValueError, match="length must be >= 1"):
        eng.train_steps_chained(state, gb, 0)


def test_unstack_window_matches_individual_batches(devices):
    eng, state = make_engine()
    host = [synthetic_batch(16, seed=50 + i) for i in range(2)]
    gb = mesh_lib.global_chain_array_from_host_local(stack_batches(host), eng.mesh)
    for i, hb in enumerate(host):
        single = eng.unstack_window(gb, i)
        expect = eng.shard_batch(hb)
        assert_trees_equal(single, expect)
        assert single["image"].sharding == expect["image"].sharding


# ---------------------------------------------------------------------------
# Prefetch: chain-major staging.


def _loader(n, batch, mesh_unused=None):
    images, labels = synthetic_images(n, seed=3)
    return ShardedLoader(
        ArrayDataSource(image=images, label=labels),
        batch,
        shuffle=False,
        num_workers=0,
    )


def test_device_prefetch_chained_units_and_values(devices):
    """lead singles + full windows + tail singles, values identical to the
    plain batch stream."""
    mesh = mesh_lib.create_mesh()
    loader = _loader(88, 8)  # 11 batches
    units = list(
        device_prefetch_chained(iter(loader), mesh, 4, lead_singles=2)
    )
    assert [n for n, _ in units] == [1, 1, 4, 4, 1]
    flat = []
    for n, b in units:
        if n == 1:
            flat.append(jax.device_get(b))
        else:
            host = jax.device_get(b)
            for i in range(n):
                flat.append(jax.tree.map(lambda x, i=i: x[i], host))
    plain = [dict(b) for b in loader]
    assert len(flat) == len(plain) == 11
    for got, want in zip(flat, plain, strict=True):
        np.testing.assert_array_equal(got["image"], np.asarray(want["image"]))
        np.testing.assert_array_equal(got["label"], np.asarray(want["label"]))


def test_device_prefetch_chained_degenerate_single(devices):
    mesh = mesh_lib.create_mesh()
    loader = _loader(24, 8)
    units = list(device_prefetch_chained(iter(loader), mesh, 1))
    assert [n for n, _ in units] == [1, 1, 1]


def test_device_prefetch_chained_rejects_bad_chain(devices):
    mesh = mesh_lib.create_mesh()
    with pytest.raises(ValueError, match="chain_steps"):
        device_prefetch_chained(iter([]), mesh, 0)


def test_device_prefetch_abandoned_consumer_shuts_down(devices):
    """Abandoning the iterator mid-stream must terminate the producer thread
    and release queued device buffers (the hardened shutdown drain)."""
    import threading
    import time

    mesh = mesh_lib.create_mesh()
    loader = _loader(80, 8)
    it = device_prefetch_chained(iter(loader), mesh, 2, depth=2)
    next(it)
    it.close()  # runs the generator's finally: cancel, drain, join, re-drain
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if not any(t.name == "device-prefetch" for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(t.name == "device-prefetch" for t in threading.enumerate())


# ---------------------------------------------------------------------------
# Trainer: windowed hot loop — bit-exact parity, tails, fallbacks, validation.


TRAIN_KW = dict(max_epoch=2, have_validate=False, save_best_for=None, save_period=None)


@pytest.fixture(scope="module")
def single_run(tmp_path_factory, mesh):
    """The chain_steps=1 baseline every parity assertion compares against."""
    t = make_trainer(
        tmp_path_factory.mktemp("single"), mesh, cls=RecordingToyTrainer, **TRAIN_KW
    )
    t.epoch_metrics = []
    t.train()
    return t


@pytest.fixture(scope="module")
def chained_run(tmp_path_factory, mesh):
    """chain_steps=4 over 4 steps/epoch: every step of every epoch chained."""
    t = make_trainer(
        tmp_path_factory.mktemp("chained"),
        mesh,
        cls=RecordingToyTrainer,
        chain_steps=4,
        **TRAIN_KW,
    )
    t.epoch_metrics = []
    t.train()
    return t


def test_trainer_chained_bit_exact_params_and_metrics(single_run, chained_run):
    """ISSUE 2 acceptance: chain_steps=4 == chain_steps=1, bit-for-bit."""
    assert int(chained_run.state.step) == int(single_run.state.step) == 8
    assert_trees_equal(single_run.state.params, chained_run.state.params)
    assert_trees_equal(single_run.state.opt_state, chained_run.state.opt_state)
    assert len(single_run.epoch_metrics) == len(chained_run.epoch_metrics) == 2
    for ma, mb in zip(single_run.epoch_metrics, chained_run.epoch_metrics, strict=True):
        assert set(ma) == set(mb)
        for k in ma:
            assert ma[k] == mb[k], (k, ma, mb)


def test_trainer_chained_actually_chained(chained_run):
    """Guards against silently falling back to per-step dispatch: with 4
    steps/epoch and chain_steps=4, the single-step executable is never built
    — every step ran inside the chained program."""
    assert chained_run.engine.trace_counts["chained_4"] == 1
    assert chained_run.engine.trace_counts["train_step"] == 0


def test_trainer_chained_tail_falls_back_single_step(single_run, tmp_path, mesh):
    """chain_steps=3 over 4 steps/epoch: one window + one tail single per
    epoch, still bit-exact, and no per-tail-length chain is compiled."""
    t = make_trainer(tmp_path, mesh, chain_steps=3, **TRAIN_KW)
    t.train()
    assert_trees_equal(single_run.state.params, t.state.params)
    assert t.engine.trace_counts["chained_3"] == 1
    assert t.engine.trace_counts["train_step"] == 1
    assert set(t.engine._chained_fns) == {3}


@pytest.fixture(scope="module")
def nan_plan_runs(tmp_path_factory, mesh):
    """nan_policy='skip' + injected NaN at (epoch 0, step 1), chained vs
    single. The injection window [0,4) of epoch 0 runs single-step (fault
    fallback); epoch 1 chains — parity must survive the mode switches."""
    runs = []
    for chain in (1, 4):
        plan = FaultPlan().add("nan_loss", epoch=0, step=1)
        t = make_trainer(
            tmp_path_factory.mktemp(f"nan{chain}"),
            mesh,
            chain_steps=chain,
            nan_policy="skip",
            fault_plan=plan,
            **TRAIN_KW,
        )
        t.train()
        runs.append(t)
    return runs


def test_trainer_chained_nan_policy_skip_parity(nan_plan_runs):
    single, chained = nan_plan_runs
    assert single.nonfinite_steps == chained.nonfinite_steps == 1
    assert single.fault_plan.count_fired("nan_loss") == 1
    assert chained.fault_plan.count_fired("nan_loss") == 1
    assert_trees_equal(single.state.params, chained.state.params)
    for leaf in jax.tree.leaves(chained.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # the fault-active window ran single-step; the clean epoch chained
    assert chained.engine.trace_counts["train_step"] == 1
    assert chained.engine.trace_counts["chained_4"] == 1


# ---------------------------------------------------------------------------
# Config validation: incompatible knobs fail loudly at construction.


def test_chain_steps_must_divide_log_every(tmp_path, mesh):
    with pytest.raises(ValueError, match="log_every"):
        make_trainer(tmp_path, mesh, chain_steps=4, log_every=6, **TRAIN_KW)


def test_chain_steps_rejects_nonpositive(tmp_path, mesh):
    with pytest.raises(ValueError, match="chain_steps must be >= 1"):
        make_trainer(tmp_path, mesh, chain_steps=0, **TRAIN_KW)


def test_chain_steps_rejects_custom_train_step(tmp_path, mesh):
    class CustomStep(ToyTrainer):
        def train_step(self, state, batch):
            return super().train_step(state, batch)

    with pytest.raises(ValueError, match="overrides train_step"):
        make_trainer(tmp_path, mesh, cls=CustomStep, chain_steps=4, **TRAIN_KW)


def test_preemption_cadence_rounded_to_window_boundary(tmp_path, mesh):
    t = make_trainer(
        tmp_path, mesh, chain_steps=4, preemption_check_every=10, **TRAIN_KW
    )
    assert t.preemption_check_every == 12
