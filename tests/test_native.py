"""Native C++ data-loader runtime tests: decode/resize/normalize parity with
the Python (cv2) path, deterministic augmentation, loader fast-path
integration, and throughput sanity (csrc/dtp_native.cpp)."""

import numpy as np
import pytest

from distributed_training_pytorch_tpu.data import ShardedLoader, native
from distributed_training_pytorch_tpu.data.dataset import NativeImageFolderSource
from distributed_training_pytorch_tpu.data.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    eval_transform,
)

pytestmark = pytest.mark.skipif(not native.available(), reason="native lib unavailable")


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    import cv2

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for label in ("cat", "dog"):
        d = root / label
        d.mkdir()
        for i in range(6):
            img = rng.randint(0, 255, size=(37, 53, 3), dtype=np.uint8)
            cv2.imwrite(str(d / f"{i}.png"), img)
            cv2.imwrite(str(d / f"{i}.jpg"), img, [cv2.IMWRITE_JPEG_QUALITY, 95])
    return root


def test_decode_resize_normalize_matches_python(image_dir):
    """Native decode+bilinear+normalize vs cv2 pipeline: same PNG bytes, same
    resize convention (half-pixel centers) -> near-identical floats."""
    paths = sorted(str(p) for p in (image_dir / "cat").glob("*.png"))
    out = native.decode_resize_normalize(paths, 24, 32, IMAGENET_MEAN, IMAGENET_STD)
    assert out.shape == (len(paths), 24, 32, 3) and out.dtype == np.float32

    import cv2

    t = eval_transform(24, 32)
    for i, p in enumerate(paths):
        img = cv2.imread(p, cv2.IMREAD_COLOR)[:, :, ::-1]
        ref = t(img)
        # Bilinear rounding differs by at most ~1/255 per channel pre-normalize.
        np.testing.assert_allclose(out[i], ref, atol=2.5 / 255 / IMAGENET_STD.min())


def test_decode_jpeg(image_dir):
    paths = sorted(str(p) for p in (image_dir / "dog").glob("*.jpg"))
    out = native.decode_resize_normalize(paths, 16, 16, IMAGENET_MEAN, IMAGENET_STD)
    assert out.shape == (len(paths), 16, 16, 3)
    assert np.isfinite(out).all()


def test_decode_failure_reports_file(tmp_path):
    bad = tmp_path / "bad.png"
    bad.write_bytes(b"not an image")
    with pytest.raises(ValueError, match="bad.png"):
        native.decode_resize_normalize([str(bad)], 8, 8, IMAGENET_MEAN, IMAGENET_STD)


def test_normalize_exact():
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, size=(4, 8, 8, 3), dtype=np.uint8)
    out = native.normalize(imgs, IMAGENET_MEAN, IMAGENET_STD)
    ref = (imgs.astype(np.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_augment_deterministic_and_varied():
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 255, size=(8, 16, 16, 3), dtype=np.uint8)
    idx = np.arange(8, dtype=np.int64)
    kw = dict(pad=2, seed=3, mean=IMAGENET_MEAN, std=IMAGENET_STD)
    a = native.augment_crop_flip(imgs, idx, epoch=5, **kw)
    b = native.augment_crop_flip(imgs, idx, epoch=5, **kw)
    np.testing.assert_array_equal(a, b)
    c = native.augment_crop_flip(imgs, idx, epoch=6, **kw)
    assert not np.array_equal(a, c), "epoch must change the augmentation"
    # Identical input rows with different indices draw different crops.
    same = np.repeat(imgs[:1], 8, axis=0)
    d = native.augment_crop_flip(same, idx, epoch=0, **kw)
    assert any(not np.array_equal(d[0], d[i]) for i in range(1, 8))


def test_augment_zero_pad_no_flip_is_normalize():
    rng = np.random.RandomState(2)
    imgs = rng.randint(0, 255, size=(3, 8, 8, 3), dtype=np.uint8)
    out = native.augment_crop_flip(
        imgs, np.arange(3, dtype=np.int64), pad=0, seed=0, epoch=0,
        mean=IMAGENET_MEAN, std=IMAGENET_STD, hflip=False,
    )
    ref = native.normalize(imgs, IMAGENET_MEAN, IMAGENET_STD)
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_native_image_folder_loader(image_dir):
    src = NativeImageFolderSource(str(image_dir), ["cat", "dog"], 16, 16)
    loader = ShardedLoader(src, 8, shuffle=False, num_workers=2,
                           drop_last=False, pad_final=True)
    batches = list(loader)
    assert len(batches) == 3  # 24 images / 8
    for b in batches:
        assert b["image"].shape == (8, 16, 16, 3)
        assert b["image"].dtype == np.float32
        assert "mask" in b
    # Labels follow scan order: first 12 records are 'cat' (= 0).
    np.testing.assert_array_equal(batches[0]["label"], np.zeros(8, np.int32))


def test_crop_flip_transform_in_loader_matches_direct():
    from distributed_training_pytorch_tpu.data import ArrayDataSource

    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, size=(12, 8, 8, 3), dtype=np.uint8)
    labels = np.arange(12, dtype=np.int32)
    t = native.NativeCropFlipNormalize(IMAGENET_MEAN, IMAGENET_STD, pad=1, seed=7)
    src = ArrayDataSource(transform=t, image=imgs, label=labels)
    loader = ShardedLoader(src, 4, shuffle=False, num_workers=2, transform=src.transform)
    loader.set_epoch(2)
    batches = list(loader)
    assert len(batches) == 3
    direct = t.batch_apply(imgs[:4], np.arange(4), 2)
    np.testing.assert_array_equal(batches[0]["image"], direct)
    np.testing.assert_array_equal(batches[1]["label"], np.arange(4, 8))


def test_corrupt_payloads_raise_not_crash():
    """Truncated/garbage JPEG and PNG payloads exercise the setjmp error
    paths: a per-record ValueError, never a crash or leak-driven abort."""
    from distributed_training_pytorch_tpu.data import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    import io

    import numpy as np
    from PIL import Image

    good = io.BytesIO()
    Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(good, format="PNG")
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)

    # valid magic + garbage body, for both formats
    bad_jpeg = b"\xff\xd8" + b"\x00" * 64
    bad_png = b"\x89PNG\r\n\x1a\n" + b"junkjunkjunk" * 4
    truncated_png = good.getvalue()[:20]

    for bad in (bad_jpeg, bad_png, truncated_png):
        with pytest.raises(ValueError, match="failed to decode"):
            native.decode_resize_normalize_bytes([good.getvalue(), bad], 8, 8, mean, std)
    # and the good payload still decodes fine afterwards (no corrupted state)
    out = native.decode_resize_normalize_bytes([good.getvalue()], 8, 8, mean, std)
    assert out.shape == (1, 8, 8, 3)
