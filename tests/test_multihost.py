"""Real multi-process distributed training: two OS processes rendezvous via
``jax.distributed`` (the torchrun-contract path, parallel/mesh.py
setup_distributed), build one global mesh over 2x4 virtual CPU devices, and
take lockstep data-parallel train steps on host-local batch halves.

This exercises what the in-process 8-device tests cannot: coordinator
rendezvous, ``jax.make_array_from_process_local_data`` with process-local
rows, cross-process collectives in the jitted step, and identical global
metrics on every host (SURVEY.md §2d — the NCCL/torchrun analog surface).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

mesh_lib.setup_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PID_IDX"]),
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())  # 2 hosts x 4 local

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from flax import linen as nn

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, *, train=False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

model = MLP()
def criterion(logits, b):
    loss = cross_entropy_loss(logits, b["label"])
    return loss, {"loss": loss}

mesh = mesh_lib.create_mesh()  # 1-D data mesh over all 8 global devices
engine = TrainEngine(make_supervised_loss(model, criterion), optax.sgd(0.05), mesh)
state = engine.init_state(jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 4))))

# Each process contributes ITS half of the global batch (global-batch
# semantics: 16 rows total, 8 local — trainer/trainer.py:56 analog).
pid = jax.process_index()
rng = np.random.RandomState(42)  # same stream everywhere; slice per process
x = rng.randn(16, 4).astype(np.float32)
y = rng.randint(0, 3, size=(16,)).astype(np.int32)
local = slice(pid * 8, (pid + 1) * 8)
batch = engine.shard_batch({"image": x[local], "label": y[local]})

losses = []
for _ in range(5):
    state, m = engine.train_step(state, batch)
    losses.append(float(m["loss"]))
print(f"RESULT {jax.process_index()} " + " ".join(f"{l:.6f}" for l in losses), flush=True)
mesh_lib.shutdown_distributed()
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
def test_two_process_distributed_train(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    outs = []
    try:
        for pid in range(2):
            env = dict(
                os.environ,
                REPO=repo,
                COORD=f"127.0.0.1:{port}",
                PID_IDX=str(pid),
            )
            env.pop("JAX_PLATFORMS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # A hung rendezvous or early failure must not orphan the peer:
        # it would block in jax.distributed forever, pinning the port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, *vals = line.split()
                results[int(pid)] = [float(v) for v in vals]
    assert set(results) == {0, 1}, outs
    # Global metrics must be identical on both hosts, and training must move.
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    assert results[0][-1] < results[0][0]
