"""Real multi-process distributed training: two OS processes rendezvous via
``jax.distributed`` (the torchrun-contract path, parallel/mesh.py
setup_distributed), build one global mesh over 2x4 virtual CPU devices, and
take lockstep data-parallel train steps on host-local batch halves.

This exercises what the in-process 8-device tests cannot: coordinator
rendezvous, ``jax.make_array_from_process_local_data`` with process-local
rows, cross-process collectives in the jitted step, and identical global
metrics on every host (SURVEY.md §2d — the NCCL/torchrun analog surface).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_training_pytorch_tpu import compat

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

mesh_lib.setup_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PID_IDX"]),
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())  # 2 hosts x 4 local

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from flax import linen as nn

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, *, train=False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

model = MLP()
def criterion(logits, b):
    loss = cross_entropy_loss(logits, b["label"])
    return loss, {"loss": loss}

mesh = mesh_lib.create_mesh()  # 1-D data mesh over all 8 global devices
engine = TrainEngine(make_supervised_loss(model, criterion), optax.sgd(0.05), mesh)
state = engine.init_state(jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 4))))

# Each process contributes ITS half of the global batch (global-batch
# semantics: 16 rows total, 8 local — trainer/trainer.py:56 analog).
pid = jax.process_index()
rng = np.random.RandomState(42)  # same stream everywhere; slice per process
x = rng.randn(16, 4).astype(np.float32)
y = rng.randint(0, 3, size=(16,)).astype(np.int32)
local = slice(pid * 8, (pid + 1) * 8)
batch = engine.shard_batch({"image": x[local], "label": y[local]})

losses = []
for _ in range(5):
    state, m = engine.train_step(state, batch)
    losses.append(float(m["loss"]))
print(f"RESULT {jax.process_index()} " + " ".join(f"{l:.6f}" for l in losses), flush=True)
mesh_lib.shutdown_distributed()
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
@pytest.mark.skipif(
    not compat.HAS_CPU_MULTIPROCESS,
    reason="this jaxlib's CPU backend cannot run multiprocess computations",
)
def test_two_process_distributed_train(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    outs = []
    try:
        for pid in range(2):
            env = dict(
                os.environ,
                REPO=repo,
                COORD=f"127.0.0.1:{port}",
                PID_IDX=str(pid),
            )
            env.pop("JAX_PLATFORMS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # A hung rendezvous or early failure must not orphan the peer:
        # it would block in jax.distributed forever, pinning the port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs, strict=True):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, *vals = line.split()
                results[int(pid)] = [float(v) for v in vals]
    assert set(results) == {0, 1}, outs
    # Global metrics must be identical on both hosts, and training must move.
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    assert results[0][-1] < results[0][0]


_TRAINER_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

mesh_lib.setup_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PID_IDX"]),
)

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, multistep_lr
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from flax import linen as nn

SAVE = os.environ["SAVE_DIR"]
pid = jax.process_index()

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, *, train=False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

def synth(n, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 3, size=(n,)).astype(np.int32)
    x = (rng.randn(n, 4, 4, 3) + y[:, None, None, None]).astype(np.float32)
    return x, y

class TwoProcTrainer(Trainer):
    preempt_after_epoch = None  # set on ONE process; the vote must stop BOTH

    def build_train_dataset(self):
        x, y = synth(48, 0)   # same global arrays on every host; the
        return ArrayDataSource(image=x, label=y)  # loader slices per process

    def build_val_dataset(self):
        x, y = synth(24, 1)
        return ArrayDataSource(image=x, label=y)

    def build_model(self):
        return MLP()

    criterion_uses_mask = True

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {"ce_loss": loss,
                          "accuracy": accuracy(logits, batch["label"], weights=mask)}
        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return multistep_lr(0.05, milestones=[50], steps_per_epoch=3)

    def train_epoch(self, epoch):
        m = super().train_epoch(epoch)
        if self.preempt_after_epoch is not None and epoch == self.preempt_after_epoch:
            self._preempted = True  # simulates SIGTERM landing on this host
        return m

def make(snapshot=None, preempt_on=None, max_epoch=4):
    t = TwoProcTrainer(
        max_epoch=max_epoch,
        batch_size=16,            # global; 8 rows per process
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=2,
        save_folder=SAVE,
        snapshot_path=snapshot,
        logger=Logger("twoproc", os.path.join(SAVE, "logfile.log")),
        progress=False,
        async_checkpoint=False,
        preemption_check_every=1,
    )
    if preempt_on is not None and pid == preempt_on:
        t.preempt_after_epoch = 1
    return t

# Phase 1: train with a simulated preemption signal on process 1 only after
# epoch 1 — the collective vote must stop BOTH processes at the same epoch
# and save a resumable snapshot.
t = make(preempt_on=1)
t.train()
assert t._preempted, "collective preemption vote must reach every host"
assert t.cur_epoch == 1, t.cur_epoch
last = os.path.join(SAVE, "weights", "last")
assert os.path.isdir(last), "preemption must leave a resumable snapshot"

# Phase 2: resume from the snapshot and run to completion (validation each
# save_period, best/last checkpointing through collective Orbax saves).
t2 = make(snapshot=last)
t2.train()
assert not t2._preempted
assert t2.cur_epoch == 3, t2.cur_epoch
m = t2.validate()
p0 = float(jax.tree.leaves(t2.state.params)[0].sum())
print(f"RESULT {pid} {int(t2.state.step)} {m['accuracy']:.6f} {m['ce_loss']:.6f} {p0:.6f}", flush=True)
mesh_lib.shutdown_distributed()
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
@pytest.mark.slow
def test_two_process_full_trainer(tmp_path):
    """Full Trainer.train() across 2 real processes: loader sharding,
    collective validation, collective checkpoint saves, the preemption vote
    stopping BOTH hosts, and snapshot resume — the path run.sh runs on a
    pod (r2 VERDICT item 10)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "trainer_worker.py"
    script.write_text(_TRAINER_WORKER)
    save_dir = tmp_path / "shared"
    save_dir.mkdir()
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, outs = [], []
    try:
        for pid in range(2):
            env = dict(
                os.environ,
                REPO=repo,
                COORD=f"127.0.0.1:{port}",
                PID_IDX=str(pid),
                SAVE_DIR=str(save_dir),
            )
            env.pop("JAX_PLATFORMS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs, strict=True):
        assert p.returncode == 0, out[-4000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, step, *vals = line.split()
                results[int(pid)] = (int(step), [float(v) for v in vals])
    assert set(results) == {0, 1}, outs
    # Same step count, identical global metrics and params on both hosts.
    assert results[0][0] == results[1][0]
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-6)
    # best/last checkpoints exist in the shared folder
    assert (save_dir / "weights" / "last").is_dir()
    assert (save_dir / "weights" / "best").is_dir()


_MP_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=" + os.environ["LOCAL_DEVS"]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

MODE = os.environ["MODE"]
if MODE == "train":
    mesh_lib.setup_distributed(
        coordinator_address=os.environ["COORD"],
        num_processes=2,
        process_id=int(os.environ["PID_IDX"]),
    )
    assert jax.process_count() == 2 and len(jax.devices()) == 8

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.models import ViTTiny
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel.sharding import transformer_tp_rules
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

SAVE = os.environ["SAVE_DIR"]
model = ViTTiny(num_classes=3)

def criterion(logits, b):
    loss = cross_entropy_loss(logits, b["label"])
    return loss, {"loss": loss}

def build(mesh, rules=None, min_size=2**18):
    engine = TrainEngine(
        make_supervised_loss(model, criterion), optax.sgd(0.05, momentum=0.9),
        mesh, sharding_rules=rules, fsdp_min_size=min_size,
    )
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16, 16, 3)))
    )
    return engine, state

rng = np.random.RandomState(42)
X = rng.randn(16, 16, 16, 3).astype(np.float32)
Y = rng.randint(0, 3, size=(16,)).astype(np.int32)

def steps(engine, state, local):
    batch = engine.shard_batch({"image": X[local], "label": Y[local]})
    losses = []
    for _ in range(2):
        state, m = engine.train_step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses

def fingerprint(state):
    # replicated leaf-sums via a (possibly cross-process) jitted reduction
    sums = jax.jit(lambda p: [jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in jax.tree.leaves(p)])(state.params)
    return [float(s) for s in sums[:4]] + [float(sum(float(s) for s in sums))]

if MODE == "train":
    pid = jax.process_index()
    local = slice(pid * 8, (pid + 1) * 8)

    # (a) reference: pure DP over all 8 devices (2 processes)
    eng_dp, st_dp = build(mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}))
    _, losses_dp = steps(eng_dp, st_dp, local)

    # (b) fsdp axis SPANS the process boundary (fsdp=2 outermost over 2x4
    # devices), tensor-parallel within each process
    mesh_ft = mesh_lib.create_mesh({mesh_lib.FSDP_AXIS: 2, mesh_lib.TENSOR_AXIS: 4})
    eng_ft, st_ft = build(mesh_ft, rules=transformer_tp_rules(), min_size=1024)
    st_ft_trained, losses_ft = steps(eng_ft, st_ft, local)

    # (c) pure TP over all 8 devices: the tensor axis itself crosses the
    # process boundary; batch is replicated so each process feeds all rows
    eng_tp, st_tp = build(mesh_lib.create_mesh({mesh_lib.TENSOR_AXIS: 8}),
                          rules=transformer_tp_rules())
    _, losses_tp = steps(eng_tp, st_tp, slice(None))

    # collective sharded save of the cross-process fsdp+tp state
    mgr = CheckpointManager(SAVE, async_save=False)
    mgr.save("last", st_ft_trained, epoch=2)
    mgr.close()
    fp = fingerprint(st_ft_trained)
    vals = losses_dp + losses_ft + losses_tp + fp
    print(f"RESULT {pid} " + " ".join(f"{v:.6f}" for v in vals), flush=True)
    mesh_lib.shutdown_distributed()
else:
    # restore the 2-process sharded checkpoint in ONE process on a smaller
    # mesh — process-count AND topology change in one restore
    mesh = mesh_lib.create_mesh(
        {mesh_lib.FSDP_AXIS: 2, mesh_lib.TENSOR_AXIS: 2}, devices=jax.devices()[:4]
    )
    engine, target = build(mesh, rules=transformer_tp_rules(), min_size=1024)
    mgr = CheckpointManager(SAVE, async_save=False)
    restored, epoch = mgr.restore("last", target)
    mgr.close()
    assert epoch == 2 and int(restored.step) == 2
    fp = fingerprint(restored)
    print("RESULT R " + " ".join(f"{v:.6f}" for v in fp), flush=True)
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
@pytest.mark.slow
def test_cross_process_model_parallel_and_sharded_restore(tmp_path):
    """Model-parallel axes across a REAL process boundary (r4 VERDICT items
    4+5): (a) DP reference, (b) fsdp spanning the 2 processes + in-process TP,
    (c) a tensor axis itself spanning the boundary — all three loss
    trajectories must agree; then the cross-process fsdp+tp-sharded TrainState
    saves collectively and restores into a SINGLE process on a smaller mesh
    with identical params."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "mp_worker.py"
    script.write_text(_MP_WORKER)
    save_dir = tmp_path / "shared"
    save_dir.mkdir()
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, outs = [], []
    base = dict(os.environ, REPO=repo, SAVE_DIR=str(save_dir))
    base.pop("JAX_PLATFORMS", None)
    try:
        for pid in range(2):
            env = dict(
                base, COORD=f"127.0.0.1:{port}", PID_IDX=str(pid),
                MODE="train", LOCAL_DEVS="4",
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs, strict=True):
        assert p.returncode == 0, out[-4000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, *vals = line.split()
                results[pid] = [float(v) for v in vals]
    assert set(results) == {"0", "1"}, outs
    np.testing.assert_allclose(results["0"], results["1"], rtol=1e-6)
    losses_dp, losses_ft, losses_tp = (
        results["0"][0:2], results["0"][2:4], results["0"][4:6]
    )
    # cross-process fsdp+tp and cross-process pure-TP match the DP reference
    np.testing.assert_allclose(losses_ft, losses_dp, rtol=2e-4)
    np.testing.assert_allclose(losses_tp, losses_dp, rtol=2e-4)

    # single-process restore of the 2-process sharded checkpoint
    out = subprocess.run(
        [sys.executable, str(script)],
        env=dict(base, MODE="restore", LOCAL_DEVS="8"),
        capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    fp = None
    for line in out.stdout.splitlines():
        if line.startswith("RESULT R"):
            fp = [float(v) for v in line.split()[2:]]
    assert fp is not None, out.stdout
    np.testing.assert_allclose(fp, results["0"][6:], rtol=1e-5)
