"""Real multi-process distributed training: two OS processes rendezvous via
``jax.distributed`` (the torchrun-contract path, parallel/mesh.py
setup_distributed), build one global mesh over 2x4 virtual CPU devices, and
take lockstep data-parallel train steps on host-local batch halves.

This exercises what the in-process 8-device tests cannot: coordinator
rendezvous, ``jax.make_array_from_process_local_data`` with process-local
rows, cross-process collectives in the jitted step, and identical global
metrics on every host (SURVEY.md §2d — the NCCL/torchrun analog surface).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

mesh_lib.setup_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PID_IDX"]),
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())  # 2 hosts x 4 local

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from flax import linen as nn

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, *, train=False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

model = MLP()
def criterion(logits, b):
    loss = cross_entropy_loss(logits, b["label"])
    return loss, {"loss": loss}

mesh = mesh_lib.create_mesh()  # 1-D data mesh over all 8 global devices
engine = TrainEngine(make_supervised_loss(model, criterion), optax.sgd(0.05), mesh)
state = engine.init_state(jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 4))))

# Each process contributes ITS half of the global batch (global-batch
# semantics: 16 rows total, 8 local — trainer/trainer.py:56 analog).
pid = jax.process_index()
rng = np.random.RandomState(42)  # same stream everywhere; slice per process
x = rng.randn(16, 4).astype(np.float32)
y = rng.randint(0, 3, size=(16,)).astype(np.int32)
local = slice(pid * 8, (pid + 1) * 8)
batch = engine.shard_batch({"image": x[local], "label": y[local]})

losses = []
for _ in range(5):
    state, m = engine.train_step(state, batch)
    losses.append(float(m["loss"]))
print(f"RESULT {jax.process_index()} " + " ".join(f"{l:.6f}" for l in losses), flush=True)
mesh_lib.shutdown_distributed()
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
def test_two_process_distributed_train(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    outs = []
    try:
        for pid in range(2):
            env = dict(
                os.environ,
                REPO=repo,
                COORD=f"127.0.0.1:{port}",
                PID_IDX=str(pid),
            )
            env.pop("JAX_PLATFORMS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        # A hung rendezvous or early failure must not orphan the peer:
        # it would block in jax.distributed forever, pinning the port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, *vals = line.split()
                results[int(pid)] = [float(v) for v in vals]
    assert set(results) == {0, 1}, outs
    # Global metrics must be identical on both hosts, and training must move.
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    assert results[0][-1] < results[0][0]


_TRAINER_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO"])

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

mesh_lib.setup_distributed(
    coordinator_address=os.environ["COORD"],
    num_processes=2,
    process_id=int(os.environ["PID_IDX"]),
)

import jax.numpy as jnp, numpy as np, optax
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, multistep_lr
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.utils import Logger
from flax import linen as nn

SAVE = os.environ["SAVE_DIR"]
pid = jax.process_index()

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, *, train=False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

def synth(n, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 3, size=(n,)).astype(np.int32)
    x = (rng.randn(n, 4, 4, 3) + y[:, None, None, None]).astype(np.float32)
    return x, y

class TwoProcTrainer(Trainer):
    preempt_after_epoch = None  # set on ONE process; the vote must stop BOTH

    def build_train_dataset(self):
        x, y = synth(48, 0)   # same global arrays on every host; the
        return ArrayDataSource(image=x, label=y)  # loader slices per process

    def build_val_dataset(self):
        x, y = synth(24, 1)
        return ArrayDataSource(image=x, label=y)

    def build_model(self):
        return MLP()

    criterion_uses_mask = True

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {"ce_loss": loss,
                          "accuracy": accuracy(logits, batch["label"], weights=mask)}
        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return multistep_lr(0.05, milestones=[50], steps_per_epoch=3)

    def train_epoch(self, epoch):
        m = super().train_epoch(epoch)
        if self.preempt_after_epoch is not None and epoch == self.preempt_after_epoch:
            self._preempted = True  # simulates SIGTERM landing on this host
        return m

def make(snapshot=None, preempt_on=None, max_epoch=4):
    t = TwoProcTrainer(
        max_epoch=max_epoch,
        batch_size=16,            # global; 8 rows per process
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=2,
        save_folder=SAVE,
        snapshot_path=snapshot,
        logger=Logger("twoproc", os.path.join(SAVE, "logfile.log")),
        progress=False,
        async_checkpoint=False,
        preemption_check_every=1,
    )
    if preempt_on is not None and pid == preempt_on:
        t.preempt_after_epoch = 1
    return t

# Phase 1: train with a simulated preemption signal on process 1 only after
# epoch 1 — the collective vote must stop BOTH processes at the same epoch
# and save a resumable snapshot.
t = make(preempt_on=1)
t.train()
assert t._preempted, "collective preemption vote must reach every host"
assert t.cur_epoch == 1, t.cur_epoch
last = os.path.join(SAVE, "weights", "last")
assert os.path.isdir(last), "preemption must leave a resumable snapshot"

# Phase 2: resume from the snapshot and run to completion (validation each
# save_period, best/last checkpointing through collective Orbax saves).
t2 = make(snapshot=last)
t2.train()
assert not t2._preempted
assert t2.cur_epoch == 3, t2.cur_epoch
m = t2.validate()
p0 = float(jax.tree.leaves(t2.state.params)[0].sum())
print(f"RESULT {pid} {int(t2.state.step)} {m['accuracy']:.6f} {m['ce_loss']:.6f} {p0:.6f}", flush=True)
mesh_lib.shutdown_distributed()
"""


@pytest.mark.skipif(os.name != "posix", reason="subprocess workers")
@pytest.mark.slow
def test_two_process_full_trainer(tmp_path):
    """Full Trainer.train() across 2 real processes: loader sharding,
    collective validation, collective checkpoint saves, the preemption vote
    stopping BOTH hosts, and snapshot resume — the path run.sh runs on a
    pod (r2 VERDICT item 10)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "trainer_worker.py"
    script.write_text(_TRAINER_WORKER)
    save_dir = tmp_path / "shared"
    save_dir.mkdir()
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, outs = [], []
    try:
        for pid in range(2):
            env = dict(
                os.environ,
                REPO=repo,
                COORD=f"127.0.0.1:{port}",
                PID_IDX=str(pid),
                SAVE_DIR=str(save_dir),
            )
            env.pop("JAX_PLATFORMS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-4000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, step, *vals = line.split()
                results[int(pid)] = (int(step), [float(v) for v in vals])
    assert set(results) == {0, 1}, outs
    # Same step count, identical global metrics and params on both hosts.
    assert results[0][0] == results[1][0]
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-6)
    # best/last checkpoints exist in the shared folder
    assert (save_dir / "weights" / "last").is_dir()
    assert (save_dir / "weights" / "best").is_dir()
