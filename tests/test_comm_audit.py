"""SPMD communication audit tests (ISSUE 11; docs/static_analysis.md).

Three layers: the inventory parser (replica-group forms, byte volumes,
axis mapping — hand-built lines with hand-computed answers), the analytic
expected-comm model (hand-computed terms on the audit fixture), and the
end-to-end audit on the REAL partitioned programs — including the
acceptance criteria that the dp8/fsdp8/tp2x4 single-step and chained
inventories match hand-computed per-axis byte totals, and that the
injected mis-ruled TP spec fails with an accidental-gather naming the
offending collective and the rule it traces to.
"""

import jax
import pytest

from distributed_training_pytorch_tpu.analysis.comm_audit import (
    _MISRULED_TP_RULES,
    AUDIT_MESH_SPECS,
    COMM_OPS,
    CommInventory,
    audit_comm_spec,
    collective_inventory,
    comm_fields,
    comm_findings,
    expected_comm,
    load_comm_baseline,
    mesh_axes_for_groups,
    parse_replica_groups,
    record_comm_baseline,
    run_comm_audit,
)
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.profiling.categories import categorize

# Hand-computed audit-fixture facts (AuditNet: conv 3->8 3x3 + dense
# 512->10, f32, fsdp_min_size=128 so both kernels shard, biases do not):
CONV_KERNEL = 3 * 3 * 3 * 8 * 4  # 864
CONV_BIAS = 8 * 4  # 32
DENSE_KERNEL = 512 * 10 * 4  # 20480
DENSE_BIAS = 10 * 4  # 40
PARAM_BYTES = CONV_KERNEL + CONV_BIAS + DENSE_KERNEL + DENSE_BIAS  # 21416
LOSS_SCALAR = 4  # the one metrics all-reduce (f32[] loss)
CHAIN = 3


# ---------------------------------------------------------------------------
# Parser primitives
# ---------------------------------------------------------------------------


class TestReplicaGroupParsing:
    def test_iota_plain(self):
        assert parse_replica_groups("replica_groups=[4,2]<=[8]") == [
            (0, 1), (2, 3), (4, 5), (6, 7)
        ]

    def test_iota_one_group(self):
        assert parse_replica_groups("replica_groups=[1,8]<=[8]") == [
            (0, 1, 2, 3, 4, 5, 6, 7)
        ]

    def test_iota_transposed(self):
        # iota(8).reshape(4,2).T -> rows (0,2,4,6)/(1,3,5,7)
        assert parse_replica_groups("replica_groups=[2,4]<=[4,2]T(1,0)") == [
            (0, 2, 4, 6), (1, 3, 5, 7)
        ]

    def test_explicit(self):
        assert parse_replica_groups("replica_groups={{0,2},{1,3}}") == [
            (0, 2), (1, 3)
        ]

    def test_absent(self):
        assert parse_replica_groups("channel_id=3, dimensions={0}") is None


class TestAxisMapping:
    # A data=2/tensor=2 mesh over 4 devices: coords (d, t), id = d*2 + t.
    COORDS = {0: (0, 0), 1: (0, 1), 2: (1, 0), 3: (1, 1)}
    AXES = ("data", "tensor")

    def test_tensor_groups(self):
        assert mesh_axes_for_groups([(0, 1), (2, 3)], self.COORDS, self.AXES) == (
            "tensor",
        )

    def test_data_groups(self):
        assert mesh_axes_for_groups([(0, 2), (1, 3)], self.COORDS, self.AXES) == (
            "data",
        )

    def test_all_axes(self):
        assert mesh_axes_for_groups([(0, 1, 2, 3)], self.COORDS, self.AXES) == (
            "data", "tensor",
        )

    def test_unknown_device_unmapped(self):
        assert mesh_axes_for_groups([(0, 9)], self.COORDS, self.AXES) == ()


class TestInventoryParsing:
    def _mesh(self):
        return mesh_lib.create_mesh({"data": 8})

    def test_all_reduce_volume_and_axis(self):
        text = (
            "  %all-reduce.3 = f32[10,512]{1,0} all-reduce(f32[10,512]{1,0} "
            "%dot.2), channel_id=8, replica_groups=[1,8]<=[8], "
            "use_global_device_ids=true, to_apply=%add.1.clone\n"
        )
        inv = collective_inventory(text, self._mesh())
        assert len(inv.collectives) == 1
        c = inv.collectives[0]
        assert c.op == "all-reduce"
        assert c.bytes == 10 * 512 * 4
        assert c.axes == ("data",)
        assert c.groups == 1 and c.group_size == 8

    def test_all_gather_counts_full_output(self):
        # Gather [3,3,3,1] -> [3,3,3,8]: volume = the FULL gathered tensor.
        text = (
            "  %all-gather = f32[3,3,3,8]{2,1,0,3} all-gather(f32[3,3,3,1]"
            "{2,1,0,3} %bitcast.39), channel_id=1, replica_groups=[1,8]<=[8], "
            "dimensions={3}, use_global_device_ids=true\n"
        )
        inv = collective_inventory(text, self._mesh())
        assert inv.collectives[0].bytes == 3 * 3 * 3 * 8 * 4

    def test_permute_pairs_and_self_pairs(self):
        text = (
            "  %collective-permute = f32[4,4]{1,0} collective-permute("
            "f32[4,4]{1,0} %copy), channel_id=1, "
            "source_target_pairs={{0,0},{1,2},{2,1},{3,3}}\n"
        )
        inv = collective_inventory(text, self._mesh())
        c = inv.collectives[0]
        assert c.op == "collective-permute"
        assert c.bytes == 4 * 4 * 4
        assert c.groups == 2  # the two non-self pairs
        assert c.axes == ("data",)

    def test_operand_reference_to_collective_not_double_counted(self):
        # `%all-gather` as an OPERAND of a later op must not parse as a
        # second collective.
        text = (
            "  %all-gather = f32[8]{0} all-gather(f32[1]{0} %x), "
            "replica_groups=[1,8]<=[8], dimensions={0}\n"
            "  %fusion = f32[8]{0} fusion(f32[8]{0} %all-gather), kind=kLoop\n"
        )
        inv = collective_inventory(text, self._mesh())
        assert len(inv.collectives) == 1

    def test_singleton_groups_skipped(self):
        text = (
            "  %all-reduce = f32[8]{0} all-reduce(f32[8]{0} %r), "
            "replica_groups=[8,1]<=[8], to_apply=%add\n"
        )
        inv = collective_inventory(text, self._mesh())
        assert inv.collectives == []

    def test_every_comm_op_joins_the_profiler_collective_bucket(self):
        # The inventory's category join: ONE categorizer repo-wide.
        for op in COMM_OPS:
            assert categorize(op) == "collective", op

    def test_async_start_form_counted_once_at_full_bytes(self):
        # TPU optimized HLO splits collectives into -start/-done pairs; the
        # -start carries shapes + groups and counts ONCE, at the largest
        # single buffer of its (operand, output) tuple — summing the tuple
        # would double the collective, and missing the spelling entirely
        # would zero the bench inventory exactly on the target platform.
        text = (
            "  %all-gather-start = (f32[64,10]{1,0}, f32[512,10]{1,0}) "
            "all-gather-start(f32[64,10]{1,0} %p), channel_id=1, "
            "replica_groups=[1,8]<=[8], dimensions={0}\n"
            "  %all-gather-done = f32[512,10]{1,0} all-gather-done("
            "(f32[64,10]{1,0}, f32[512,10]{1,0}) %all-gather-start)\n"
        )
        inv = collective_inventory(text, self._mesh())
        assert len(inv.collectives) == 1
        c = inv.collectives[0]
        assert c.op == "all-gather"  # base opcode: by_op/categorize join
        assert c.bytes == 512 * 10 * 4
        assert c.axes == ("data",)


# ---------------------------------------------------------------------------
# The analytic model (hand-computed on the audit fixture)
# ---------------------------------------------------------------------------


def _spec_fixture(spec, rules="auto"):
    from distributed_training_pytorch_tpu.analysis.comm_audit import _spec_engine

    return _spec_engine(spec, rules=rules)


class TestExpectedModel:
    def test_dp8_grad_sync_only(self, devices):
        engine, state, batch = _spec_fixture("dp8")
        model = expected_comm(engine, state, batch)
        assert model.terms["grad_sync"] == PARAM_BYTES
        assert model.terms["fsdp_gather"] == 0
        assert model.terms["tp_activations"] == 0
        assert model.total == PARAM_BYTES

    def test_fsdp8_adds_double_gather_of_sharded_leaves(self, devices):
        engine, state, batch = _spec_fixture("fsdp8")
        model = expected_comm(engine, state, batch)
        assert model.terms["grad_sync"] == PARAM_BYTES
        # Both kernels shard (>= 128 elements); biases stay replicated.
        assert model.terms["fsdp_gather"] == 2 * (CONV_KERNEL + DENSE_KERNEL)

    def test_tp2x4_activation_term(self, devices):
        engine, state, batch = _spec_fixture("tp2x4")
        model = expected_comm(engine, state, batch)
        # rows per replica = 64 / (data=4) = 16; dense kernel dims 512+10.
        assert model.terms["tp_activations"] == 2 * 16 * (512 + 10) * 4
        tensor_leaves = model.tensor_leaves()
        assert [leaf["path"] for leaf in tensor_leaves] == [
            ".params['Dense_0']['kernel']"
        ]
        assert tensor_leaves[0]["rule"] is not None

    def test_chain_length_scales_total(self, devices):
        engine, state, batch = _spec_fixture("dp8")
        single = expected_comm(engine, state, batch)
        window = expected_comm(engine, state, batch, chain_length=CHAIN)
        assert window.total == CHAIN * single.total


class TestFindings:
    def _expected(self, engine_state_batch):
        return expected_comm(*engine_state_batch)

    def test_accidental_gather_fires_only_on_full_param_gather(self, devices):
        expected = self._expected(_spec_fixture("tp2x4"))
        mesh = mesh_lib.mesh_config_from_spec("tp2x4").build(
            devices=jax.devices()[:8]
        )
        small = collective_inventory(
            "  %all-gather = f32[64,10]{1,0} all-gather(f32[64,5]{1,0} %x), "
            "replica_groups=[4,2]<=[8], dimensions={1}\n",
            mesh,
        )
        assert comm_findings(small, expected) == []
        full = collective_inventory(
            "  %all-gather.2 = f32[512,10]{0,1} all-gather(f32[512,5]{0,1} "
            "%m), replica_groups=[4,2]<=[8], dimensions={1}\n",
            mesh,
        )
        findings = comm_findings(full, expected)
        kinds = [f["kind"] for f in findings]
        assert "accidental-gather" in kinds
        f = findings[kinds.index("accidental-gather")]
        assert f["op"] == "%all-gather.2"
        assert f["leaf"] == ".params['Dense_0']['kernel']"
        assert f["rule"] is not None

    def test_per_leaf_threshold_catches_smaller_kernel_gather(self, devices):
        # A full gather of a SMALLER tensor-sharded kernel must fire even
        # when a bigger tensor-sharded leaf exists (per-leaf thresholds,
        # not max-leaf), and the finding attributes to the largest leaf the
        # volume explains.
        from distributed_training_pytorch_tpu.analysis.comm_audit import (
            ExpectedComm,
        )

        mesh = mesh_lib.mesh_config_from_spec("tp2x4").build(
            devices=jax.devices()[:8]
        )
        expected = ExpectedComm(
            terms={"grad_sync": 1e6},  # ample model headroom: isolate (a)
            leaves=[
                {"path": ".params['big']['kernel']", "shape": (512, 40),
                 "dtype": "float32", "bytes": 512 * 40 * 4,
                 "axes": ("tensor",), "rule": "big.*kernel"},
                {"path": ".params['small']['kernel']", "shape": (64, 8),
                 "dtype": "float32", "bytes": 64 * 8 * 4,
                 "axes": ("tensor",), "rule": "small.*kernel"},
            ],
        )
        inv = collective_inventory(
            "  %all-gather.7 = f32[64,8]{1,0} all-gather(f32[64,4]{1,0} %m), "
            "replica_groups=[4,2]<=[8], dimensions={1}\n",
            mesh,
        )
        findings = comm_findings(inv, expected)
        assert [f["kind"] for f in findings] == ["accidental-gather"]
        assert findings[0]["leaf"] == ".params['small']['kernel']"
        assert findings[0]["rule"] == "small.*kernel"

    def test_bias_sized_gathers_do_not_false_positive(self, devices):
        # Tensor-sharded BIAS leaves (ndim < 2) are excluded from the
        # threshold set: activation gathers routinely exceed a bias's full
        # bytes on a clean program (the baseline gate owns that scale).
        from distributed_training_pytorch_tpu.analysis.comm_audit import (
            ExpectedComm,
        )

        mesh = mesh_lib.mesh_config_from_spec("tp2x4").build(
            devices=jax.devices()[:8]
        )
        expected = ExpectedComm(
            terms={"grad_sync": 1e6},  # ample model headroom: isolate (a)
            leaves=[
                {"path": ".params['d']['bias']", "shape": (8,),
                 "dtype": "float32", "bytes": 32,
                 "axes": ("tensor",), "rule": "bias"},
            ],
        )
        inv = collective_inventory(
            "  %all-gather = f32[64,10]{1,0} all-gather(f32[64,5]{1,0} %x), "
            "replica_groups=[4,2]<=[8], dimensions={1}\n",
            mesh,
        )
        assert comm_findings(inv, expected) == []

    def test_gather_on_data_axis_never_accidental(self, devices):
        # The same full-size gather over the DATA axis groups is not the
        # tensor mis-rule signature (wgrad partial gathers ride batch axes).
        expected = self._expected(_spec_fixture("tp2x4"))
        mesh = mesh_lib.mesh_config_from_spec("tp2x4").build(
            devices=jax.devices()[:8]
        )
        inv = collective_inventory(
            "  %all-gather = f32[512,10]{0,1} all-gather(f32[128,10]{0,1} "
            "%m), replica_groups=[2,4]<=[4,2]T(1,0), dimensions={0}\n",
            mesh,
        )
        assert comm_findings(inv, expected) == []

    def test_model_exceeded_fires_past_tolerance(self, devices):
        fixture = _spec_fixture("dp8")
        expected = self._expected(fixture)
        mesh = fixture[0].mesh
        big = int(expected.total * 3) // 4  # one op; x3 total via 3 copies
        lines = "".join(
            f"  %all-reduce.{i} = f32[{big // 4}]{{0}} all-reduce("
            f"f32[{big // 4}]{{0}} %r{i}), replica_groups=[1,8]<=[8], "
            "to_apply=%add\n"
            for i in range(4)
        )
        inv = collective_inventory(lines, mesh)
        findings = comm_findings(inv, expected, tolerance=1.0)
        assert [f["kind"] for f in findings] == ["model-exceeded"]
        assert comm_findings(inv, expected, tolerance=5.0) == []


# ---------------------------------------------------------------------------
# The real programs (acceptance criteria) — one audit per spec, reused.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dp8_report(devices):
    return audit_comm_spec("dp8", chain_steps=CHAIN)


@pytest.fixture(scope="module")
def fsdp8_report(devices):
    return audit_comm_spec("fsdp8", chain_steps=CHAIN)


@pytest.fixture(scope="module")
def tp_report(devices):
    return audit_comm_spec("tp2x4", chain_steps=CHAIN)


@pytest.fixture(scope="module")
def misruled_report(devices):
    report = audit_comm_spec(
        "tp2x4", chain_steps=CHAIN, rules=_MISRULED_TP_RULES, injected=True
    )
    return report


class TestRealPrograms:
    def test_dp8_per_axis_total_is_param_bytes_plus_loss_scalar(self, dp8_report):
        # ISSUE 11 acceptance: the per-axis byte totals match a
        # hand-computed expectation — pure DP syncs exactly one gradient
        # per param leaf plus the scalar loss metric, all on `data`.
        assert dp8_report.ok, dp8_report.describe()
        by_axes = dp8_report.single.by_axes()
        assert by_axes == {("data",): PARAM_BYTES + LOSS_SCALAR}
        assert dp8_report.single.by_op() == {
            "all-reduce": PARAM_BYTES + LOSS_SCALAR
        }

    def test_dp8_chained_scales_exactly(self, dp8_report):
        assert (
            dp8_report.chained.total_bytes
            == CHAIN * dp8_report.single.total_bytes
        )

    def test_fsdp8_gathers_each_sharded_kernel_whole(self, fsdp8_report):
        # ZeRO-3 signature: one full-size all-gather per fsdp-sharded leaf,
        # on the fsdp axis — hand-computed byte values.
        assert fsdp8_report.ok, fsdp8_report.describe()
        gathers = sorted(
            c.bytes
            for c in fsdp8_report.single.collectives
            if c.op == "all-gather" and c.axes == ("fsdp",)
        )
        assert CONV_KERNEL in gathers
        assert DENSE_KERNEL in gathers
        # Grad sync still present at full bytes (all-reduce or equivalent).
        reduces = fsdp8_report.single.by_op()["all-reduce"]
        assert reduces >= PARAM_BYTES

    def test_fsdp8_chained_scales_exactly(self, fsdp8_report):
        assert (
            fsdp8_report.chained.total_bytes
            == CHAIN * fsdp8_report.single.total_bytes
        )

    def test_tp2x4_clean_and_tensor_axis_carries_activation_syncs(self, tp_report):
        assert tp_report.ok, tp_report.describe()
        by_axes = tp_report.single.by_axes()
        # dgrad activation all-reduce [16,512] rides the tensor axis...
        assert by_axes[("tensor",)] >= 16 * 512 * 4
        # ...but NO all-gather on tensor approaches the kernel's full bytes.
        assert all(
            c.bytes < DENSE_KERNEL
            for c in tp_report.single.collectives
            if c.op == "all-gather" and "tensor" in c.axes
        )
        # wgrad sync of the tensor-sharded kernel rides the data axis at
        # SHARD bytes (the model's documented over-estimate direction).
        assert by_axes[("data",)] >= DENSE_KERNEL // 2

    def test_misruled_spec_fails_with_accidental_gather(self, misruled_report):
        # ISSUE 11 acceptance: the mis-ruled TP spec (rule anchored to
        # .params only -> replicated momentum twin) produces a full-param
        # all-gather on the tensor axis and the audit names it.
        assert not misruled_report.ok
        kinds = [f["kind"] for f in misruled_report.findings]
        assert "accidental-gather" in kinds
        f = misruled_report.findings[kinds.index("accidental-gather")]
        assert f["bytes"] == DENSE_KERNEL
        assert "tensor" in f["axes"]
        assert f["leaf"] == ".params['Dense_0']['kernel']"
        assert f["rule"] == _MISRULED_TP_RULES[0][0]
        assert "all-gather" in f["op"]

    def test_misruled_program_really_gathers_the_kernel(self, misruled_report):
        gathers = [
            c
            for c in misruled_report.single.collectives
            if c.op == "all-gather" and "tensor" in c.axes
            and c.bytes == DENSE_KERNEL
        ]
        assert gathers, misruled_report.single.describe()

    def test_inventory_code_path_shared_with_bench(self, dp8_report, devices):
        # bench's comm_fields and the gate audit the SAME inventory: the
        # probe program's fields must reproduce the report's totals.
        engine, state, batch = _spec_fixture("dp8")
        compiled = engine.compile_step_probe(state, batch, donate=True)
        fields = comm_fields(compiled, engine.mesh)
        assert fields["comm_bytes_per_step"] == int(dp8_report.single.total_bytes)
        assert fields["comm"]["all-reduce"] == int(dp8_report.single.total_bytes)
        assert fields["comm_collectives"] == len(dp8_report.single.collectives)


# ---------------------------------------------------------------------------
# Baseline gating (tmp files; the perf-gate ritual on comm bytes)
# ---------------------------------------------------------------------------


class TestBaselineGate:
    def _baseline_from(self, *reports, tolerance=0.25, scale=1.0):
        return {
            "schema": 1,
            "entries": {
                r.spec: {
                    "comm_bytes_per_step": r.single.total_bytes * scale
                }
                for r in reports
            },
            "tolerance": {r.spec: tolerance for r in reports},
        }

    def test_parity_passes_and_regression_fails(self, dp8_report):
        from distributed_training_pytorch_tpu.profiling.gate import check

        baseline = self._baseline_from(dp8_report)
        entry = baseline["entries"]["dp8"]
        ok = check(
            dp8_report.single.total_bytes,
            entry["comm_bytes_per_step"],
            0.25,
            key="dp8",
            metric="comm_bytes_per_step",
        )
        assert ok.passed and not ok.stale
        regressed = check(
            dp8_report.single.total_bytes * 1.5,
            entry["comm_bytes_per_step"],
            0.25,
            key="dp8",
            metric="comm_bytes_per_step",
        )
        assert not regressed.passed

    def test_stale_nudge_when_comm_shrinks(self, dp8_report):
        from distributed_training_pytorch_tpu.profiling.gate import check

        result = check(
            dp8_report.single.total_bytes,
            dp8_report.single.total_bytes * 2.0,
            0.25,
            key="dp8",
            metric="comm_bytes_per_step",
        )
        assert result.passed and result.stale
        assert "re-record" in result.describe()

    def test_record_and_reload_roundtrip(self, tmp_path, devices):
        path = str(tmp_path / "COMM_BASELINE.json")
        report = record_comm_baseline(path, chain_steps=CHAIN)
        baseline = load_comm_baseline(path)
        assert set(baseline["entries"]) == set(AUDIT_MESH_SPECS)
        for spec_report in report.specs:
            entry = baseline["entries"][spec_report.spec]
            assert entry["comm_bytes_per_step"] == round(
                spec_report.single.total_bytes, 1
            )
            assert baseline["tolerance"][spec_report.spec] == 0.25

    def test_committed_baseline_self_parity(self, devices):
        # The shipped COMM_BASELINE.json gates the shipped programs: the
        # full audit (the verify.sh clean pass) must come back green.
        report = run_comm_audit(chain_steps=4, baseline=load_comm_baseline())
        assert report.skipped is None
        assert report.ok, report.describe()
        for spec_report in report.specs:
            assert spec_report.gate is not None
            assert spec_report.gate.passed

    def test_missing_entry_is_a_finding(self, dp8_report, devices):
        report = run_comm_audit(
            chain_steps=CHAIN,
            baseline={"schema": 1, "entries": {}, "tolerance": {}},
        )
        assert not report.ok
        kinds = [f["kind"] for s in report.specs for f in s.findings]
        assert kinds.count("no-baseline") == len(AUDIT_MESH_SPECS)


class TestEmptyInventoryEdge:
    def test_no_comm_expected_and_none_found_is_clean(self, devices):
        inv = CommInventory(collectives=[], label="empty")
        engine, state, batch = _spec_fixture("dp8")
        expected = expected_comm(engine, state, batch)
        # A DP mesh expects grad syncs; an empty inventory is merely "no
        # findings" here (the baseline gate is what catches vanishing comm
        # via its stale/regression rule on totals).
        assert comm_findings(inv, expected) == []
