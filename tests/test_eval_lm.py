"""examples/eval_lm.py: perplexity + sampling against a saved checkpoint."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _load_module():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "examples"))
    try:
        import importlib

        return importlib.import_module("eval_lm")
    finally:
        sys.path.pop(0)


@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    """A saved LMTiny state (random init — eval only needs a restorable
    checkpoint, not a trained one)."""
    import optax

    from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
    from distributed_training_pytorch_tpu.models import LMTiny
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
    from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss

    tmp = tmp_path_factory.mktemp("lmckpt")
    model = LMTiny(vocab_size=256, dtype=jnp.bfloat16, max_len=128)
    mesh = mesh_lib.create_mesh()

    def criterion(logits, b):
        return jnp.zeros(()), {"loss": jnp.zeros(())}

    engine = TrainEngine(make_supervised_loss(model, criterion), optax.sgd(0.0), mesh)
    state = engine.init_state(
        jax.random.key(0), lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))
    )
    mgr = CheckpointManager(tmp / "weights", async_save=False)
    mgr.save("last", state, epoch=1)
    mgr.close()
    return str(tmp / "weights" / "last")


def test_evaluate_reports_uniformish_ppl(tiny_checkpoint, tmp_path):
    mod = _load_module()
    corpus = tmp_path / "c.bin"
    corpus.write_bytes(np.random.RandomState(0).bytes(4096))
    results = mod.evaluate(
        tiny_checkpoint, str(corpus), size="tiny", seq_len=16, batch=8
    )
    # random-init model on random bytes: ppl near the uniform 256
    assert 100 < results["ppl"] < 700, results
    assert results["n_windows"] > 0


def test_evaluate_rejects_too_short_corpus(tiny_checkpoint, tmp_path):
    mod = _load_module()
    corpus = tmp_path / "tiny.bin"
    corpus.write_bytes(b"abc")
    with pytest.raises(ValueError):
        mod.evaluate(tiny_checkpoint, str(corpus), size="tiny", seq_len=16)


def test_sample_produces_prompt_prefixed_bytes(tiny_checkpoint):
    mod = _load_module()
    out = mod.sample(
        tiny_checkpoint, b"hello ", size="tiny", seq_len=16, gen_steps=6, temperature=0.7
    )
    assert set(out) == {"greedy", "t=0.7"}
    for text in out.values():
        assert text.startswith(b"hello ")
        assert len(text) == len(b"hello ") + 6


def test_decode_benchmark_batches(tiny_checkpoint):
    """decode_benchmark times several decode batch sizes through the jitted
    KV-cache generate path and reports consistent aggregate/per-stream rates."""
    ev = _load_module()
    model, params = ev.load_params(tiny_checkpoint, "tiny", 64)
    rows = ev.decode_benchmark(model, params, prompt_len=8, gen_steps=8, batches=(1, 4))
    assert [r["batch"] for r in rows] == [1, 4]
    for r in rows:
        assert r["tok_per_s"] > 0
        assert abs(r["tok_per_s"] - r["batch"] * r["tok_per_s_per_stream"]) < 1e-6
