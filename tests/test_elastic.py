"""Elastic training tests (ISSUE 12): mesh re-planning for N != M device
counts, the typed topology-mismatch seam in the checkpoint manager, and the
Trainer's automatic elastic restore.

The re-plan solver is pure axis math — no devices needed — so the edge cases
(non-power-of-two counts, tensor-axis preservation, grow-past-original, the
N->1 pure-DP degenerate) run as plain unit tests. The cross-process truth
(actually killing a run on 8 forced-host devices and resuming on 4) lives in
``scripts/chaos_soak.py --elastic`` (verify.sh); in-process, the trainer path
is driven by saving a checkpoint whose *recorded* mesh names a different
device count than the 8-device test rig — the same seam a real topology
change exercises, without needing a second process.
"""

import numpy as np
import optax
import pytest
from flax import linen as nn

import jax
import jax.numpy as jnp

from distributed_training_pytorch_tpu.checkpoint import CheckpointManager
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import elastic
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel.elastic import (
    ElasticReplanError,
    TopologyMismatchError,
)
from distributed_training_pytorch_tpu.telemetry import read_events
from distributed_training_pytorch_tpu.trainer import Trainer


# ---------------------------------------------------------------------------
# replan: pure axis-solver edge cases (satellite checklist)


def test_replan_shrink_8_to_4_halves_fsdp_and_doubles_accum():
    plan = elastic.replan(
        {"mesh": {"data": 1, "fsdp": 8}, "specs": {"x": "P('fsdp',)"}},
        4, batch_size=128, accum_steps=1,
    )
    assert plan.new_axes == {"data": 1, "fsdp": 4}
    assert plan.accum_steps == 2  # per-shard microbatch rows stay at 16
    assert plan.old_accum_steps == 1
    assert "shrink" in plan.reason
    assert plan.mesh_config.fsdp == 4 and plan.mesh_config.data == 1


def test_replan_grow_4_to_8_keeps_fsdp_adds_data_no_accum_change():
    plan = elastic.replan({"data": 1, "fsdp": 4}, 8, batch_size=128, accum_steps=1)
    assert plan.new_axes == {"data": 2, "fsdp": 4}
    assert plan.accum_steps == 1  # rows/shard shrink; nothing to bound
    assert "grow" in plan.reason


def test_replan_non_power_of_two_12_to_6():
    plan = elastic.replan({"data": 3, "fsdp": 4}, 6, batch_size=96, accum_steps=1)
    # fsdp takes gcd(4, 6) = 2; data absorbs the rest.
    assert plan.new_axes == {"data": 3, "fsdp": 2}
    # extent 12 -> 6 doubles rows/shard; accum doubles to compensate.
    assert plan.accum_steps == 2


def test_replan_preserves_tensor_axis_both_directions():
    shrink = elastic.replan({"data": 2, "fsdp": 2, "tensor": 2}, 4, batch_size=32)
    assert shrink.new_axes == {"data": 1, "fsdp": 2, "tensor": 2}
    grow = elastic.replan({"data": 2, "fsdp": 2, "tensor": 2}, 16, batch_size=32)
    assert grow.new_axes == {"data": 4, "fsdp": 2, "tensor": 2}
    assert grow.new_devices == 16 and grow.old_devices == 8


def test_replan_grow_past_original_4_to_16_routes_growth_to_data():
    # fsdp never grows past its proven extent (param divisibility was only
    # ever established for fsdp=4); the new devices land on `data`.
    plan = elastic.replan({"fsdp": 4}, 16, batch_size=64, accum_steps=2)
    assert plan.new_axes == {"data": 4, "fsdp": 4}
    assert plan.accum_steps == 1  # grow relaxes accumulation


def test_replan_single_device_degenerate_is_pure_dp():
    plan = elastic.replan({"data": 2, "fsdp": 4}, 1, batch_size=16)
    assert plan.new_axes == {"data": 1}
    assert plan.mesh_config.fsdp == 1 and plan.mesh_config.tensor == 1
    # All sharding collapses; the whole batch is one shard, accum bounds rows.
    assert plan.accum_steps == 8


def test_replan_refuses_unreplannable_tensor_extent():
    with pytest.raises(ElasticReplanError, match="tensor.*never re-solved|never re-solved"):
        elastic.replan({"data": 1, "tensor": 8}, 4)
    with pytest.raises(ElasticReplanError):
        elastic.replan({"data": 2, "tensor": 3}, 4)  # 4 % 3 != 0


def test_replan_refuses_indivisible_batch():
    with pytest.raises(ElasticReplanError, match="not divisible"):
        elastic.replan({"data": 8}, 6, batch_size=16)  # 16 % 6 != 0


def test_replan_refuses_unknown_axes():
    with pytest.raises(ElasticReplanError, match="unknown axes"):
        elastic.replan({"data": 2, "bogus": 4}, 4)


def test_replan_accum_policy_bounds_per_shard_rows():
    # Shrink: rows/shard would double — accum doubles instead.
    assert elastic.replan_accum(128, 8, 4, old_accum=1) == 2
    # Existing accumulation scales with the extent ratio.
    assert elastic.replan_accum(128, 8, 2, old_accum=2) == 8
    # Grow: the smallest factor within the row bound — relaxes accum
    # proportionally (rows/shard stay at the old 8-row budget).
    assert elastic.replan_accum(128, 4, 8, old_accum=4) == 2
    # Identity when nothing changed (for a config that actually tiled).
    assert elastic.replan_accum(128, 8, 8, old_accum=4) == 4


def test_nearest_divisible_accum():
    assert elastic.nearest_divisible_accum(132, 6, 4) == 2  # 22's divisors
    assert elastic.nearest_divisible_accum(128, 4, 3) == 2
    assert elastic.nearest_divisible_accum(128, 4, 4) == 4
    assert elastic.nearest_divisible_accum(16, 5, 1) is None  # extent misfit


def test_validate_topology_names_both_topologies():
    elastic.validate_topology({"mesh": {"data": 8}}, 8)  # match: no raise
    with pytest.raises(TopologyMismatchError, match=r"8-device.*4 devices"):
        elastic.validate_topology(
            {"mesh": {"data": 1, "fsdp": 8}, "specs": {}}, 4
        )


# ---------------------------------------------------------------------------
# mesh_config_from_spec edge cases (the grammar the elastic soak's children
# and the re-plan's MeshConfig output both ride)


def test_mesh_spec_non_power_of_two_and_shorthand():
    cfg = mesh_lib.mesh_config_from_spec("dp12")
    assert cfg.data == 12
    cfg = mesh_lib.mesh_config_from_spec("fsdp3x4")
    assert cfg.fsdp == 3 and cfg.data == 4
    cfg = mesh_lib.mesh_config_from_spec("dp3fsdp2tp2")
    assert (cfg.data, cfg.fsdp, cfg.tensor) == (3, 2, 2)


def test_mesh_spec_rejects_garbage_and_duplicates():
    with pytest.raises(ValueError, match="unparseable"):
        mesh_lib.mesh_config_from_spec("fsdp")
    with pytest.raises(ValueError, match="twice"):
        mesh_lib.mesh_config_from_spec("dp2dp4")


def test_replan_roundtrips_through_mesh_config_build(devices):
    # A re-planned config must actually build on the new device count.
    plan = elastic.replan({"data": 1, "fsdp": 16}, 8, batch_size=16)
    mesh = plan.mesh_config.build(devices)
    assert dict(mesh.shape) == plan.new_axes == {"data": 1, "fsdp": 8}


# ---------------------------------------------------------------------------
# CheckpointManager: the typed topology seam


def _tiny_state(seed=0):
    rng = np.random.RandomState(seed)
    return jax.device_put(
        __import__(
            "distributed_training_pytorch_tpu.train", fromlist=["TrainState"]
        ).TrainState(
            step=jnp.asarray(0, jnp.int32),
            params={"w": jnp.asarray(rng.randn(8, 4), jnp.float32)},
            opt_state={"m": jnp.zeros((8, 4), jnp.float32)},
            model_state={},
            rng=jax.random.key(seed),
        )
    )


FOREIGN_RECORD = {"mesh": {"data": 1, "fsdp": 16}, "specs": {".params['w']": "P('fsdp',)"}}


def test_restore_raises_typed_topology_mismatch(tmp_path):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    # The record claims a 16-device mesh; the rig has 8. (The stored arrays
    # are global either way — only the record disagrees, exactly what a
    # checkpoint from a differently-sized fleet looks like.)
    mgr.save("foreign", _tiny_state(), epoch=2, sharding=FOREIGN_RECORD)
    with pytest.raises(TopologyMismatchError, match="16-device.*8 devices"):
        mgr.restore("foreign", _tiny_state(seed=9))
    with pytest.raises(TopologyMismatchError):
        mgr.restore_latest_valid(_tiny_state(seed=9))


def test_restore_allow_topology_change_restores_values(tmp_path):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    saved = _tiny_state(seed=3)
    mgr.save("foreign", saved, epoch=2, sharding=FOREIGN_RECORD)
    state, epoch = mgr.restore(
        "foreign", _tiny_state(seed=9), allow_topology_change=True
    )
    assert epoch == 2
    np.testing.assert_array_equal(
        np.asarray(state.params["w"]), np.asarray(saved.params["w"])
    )
    # params_only across a topology change must ALSO restore (the as-stored
    # rest read would die inside orbax on the writer's device mesh; the
    # targeted branch carries it).
    state, _ = mgr.restore(
        "foreign", _tiny_state(seed=9), params_only=True,
        allow_topology_change=True,
    )
    np.testing.assert_array_equal(
        np.asarray(state.params["w"]), np.asarray(saved.params["w"])
    )


def test_same_topology_record_restores_unchallenged(tmp_path, devices):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    record = {"mesh": {"data": 1, "fsdp": 8}, "specs": {".params['w']": "P('fsdp',)"}}
    mgr.save("home", _tiny_state(seed=1), epoch=1, sharding=record)
    state, epoch = mgr.restore("home", _tiny_state(seed=9))  # no flag needed
    assert epoch == 1


def test_latest_valid_name(tmp_path):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    assert mgr.latest_valid_name() is None
    mgr.save("older", _tiny_state(), epoch=1)
    import time as _time

    _time.sleep(0.05)  # distinct mtimes order the walk
    mgr.save("newer", _tiny_state(), epoch=2)
    assert mgr.latest_valid_name() == "newer"
    from distributed_training_pytorch_tpu.fault import corrupt_checkpoint

    corrupt_checkpoint(mgr.path("newer"), mode="flip")
    assert mgr.latest_valid_name() == "older"


# ---------------------------------------------------------------------------
# Trainer: the automatic elastic restore (in-process, via a foreign record)


class _DenseNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(8)(x)


class ElasticToyTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        return ArrayDataSource(
            image=rng.randn(64, 8, 8, 1).astype(np.float32),
            label=rng.randint(0, 8, size=(64,)).astype(np.int32),
        )

    def build_model(self):
        return _DenseNet()

    def build_criterion(self):
        def criterion(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return 0.1


def _make_trainer(folder, **kw):
    defaults = dict(
        max_epoch=1,
        batch_size=16,
        save_folder=str(folder),
        num_workers=0,
        progress=False,
        log_every=0,
        fsdp_min_size=16,
    )
    defaults.update(kw)
    return ElasticToyTrainer(**defaults)


@pytest.fixture(scope="module")
def foreign_checkpoint(tmp_path_factory):
    """A checkpoint whose sharding record claims a 16-device fsdp mesh —
    what a run killed on a 16-device fleet leaves for this 8-device rig."""
    folder = tmp_path_factory.mktemp("elastic_src")
    source = _make_trainer(folder)
    source.checkpoints.save(
        "foreign", source.state, epoch=1, sharding=FOREIGN_RECORD
    )
    return source, source.checkpoints.path("foreign")


def test_trainer_elastic_restore_replans_mesh_and_accum(
    tmp_path, foreign_checkpoint
):
    source, ckpt_path = foreign_checkpoint
    resumed = _make_trainer(
        tmp_path / "resume",
        mesh=None,  # the no-user-intervention contract
        snapshot_path=ckpt_path,
        telemetry="on",
    )
    # 16 recorded devices -> 8 backend devices: fsdp=gcd(16, 8)=8, and the
    # accumulation re-solves so per-shard microbatch rows stay at the old
    # bound (batch 16 / (16 x 1) = 1 row -> accum 2 on extent 8).
    assert resumed._elastic_plan is not None
    assert dict(resumed.mesh.shape) == {"data": 1, "fsdp": 8}
    assert resumed.accum_steps == 2 and resumed.engine.accum_steps == 2
    assert resumed.cur_epoch == 1
    # Values restored exactly through the re-planned (sharded) layout.
    for a, b in zip(
        jax.tree.leaves(jax.device_get(resumed.state.params)),
        jax.tree.leaves(jax.device_get(source.state.params)),
        strict=True,
    ):
        np.testing.assert_array_equal(a, b)
    # The restored state actually landed sharded over the re-planned mesh.
    specs = [
        str(leaf.sharding.spec) for leaf in jax.tree.leaves(resumed.state.params)
    ]
    assert any("fsdp" in s for s in specs)
    # The flight record carries the re-plan.
    events = [
        r
        for r in read_events(
            str(tmp_path / "resume" / "telemetry" / "events.jsonl")
        )
        if r["event"] == "elastic_restore"
    ]
    assert len(events) == 1
    rec = events[0]
    assert rec["replanned"] is True
    assert rec["from_mesh"] == {"data": 1, "fsdp": 16}
    assert rec["to_mesh"] == {"data": 1, "fsdp": 8}
    assert rec["accum_steps"] == 2 and rec["old_accum_steps"] == 1


def test_trainer_same_topology_restore_does_not_replan(tmp_path):
    source = _make_trainer(
        tmp_path / "src", mesh=mesh_lib.MeshConfig(data=1, fsdp=8).build()
    )
    source.checkpoints.save("home", source.state, epoch=1)
    resumed = _make_trainer(
        tmp_path / "resume",
        mesh=None,
        snapshot_path=source.checkpoints.path("home"),
    )
    # Same device count: the PR 9 resharding restore (fsdp checkpoint into
    # the pure-DP default mesh), NOT an elastic re-plan.
    assert resumed._elastic_plan is None and not resumed._topology_changed
    assert dict(resumed.mesh.shape) == {"data": 8}
    assert resumed.accum_steps == 1


def test_trainer_explicit_mesh_overrides_replan(tmp_path, foreign_checkpoint):
    _, ckpt_path = foreign_checkpoint
    resumed = _make_trainer(
        tmp_path / "resume",
        mesh=mesh_lib.create_mesh({"data": 8}),
        snapshot_path=ckpt_path,
        telemetry="on",
    )
    assert resumed._topology_changed and resumed._elastic_plan is None
    assert dict(resumed.mesh.shape) == {"data": 8}
    events = [
        r
        for r in read_events(
            str(tmp_path / "resume" / "telemetry" / "events.jsonl")
        )
        if r["event"] == "elastic_restore"
    ]
    assert len(events) == 1 and events[0]["replanned"] is False


def test_trainer_revalidates_batch_after_topology_change(
    tmp_path, foreign_checkpoint
):
    _, ckpt_path = foreign_checkpoint
    # Explicit mesh + an accumulation factor the new extent cannot tile:
    # batch 16 over extent 8 leaves 2 rows/shard — accum_steps=3 cannot
    # divide them. Must fail fast, ctor-style, with a usable suggestion.
    with pytest.raises(ValueError, match="Nearest divisible accum_steps: 2"):
        _make_trainer(
            tmp_path / "resume",
            mesh=mesh_lib.create_mesh({"data": 8}),
            snapshot_path=ckpt_path,
            accum_steps=3,
        )
