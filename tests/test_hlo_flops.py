"""Executed-FLOP recount from optimized HLO (utils/hlo_flops.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_pytorch_tpu.utils.hlo_flops import (
    arithmetic_intensity,
    bytes_accessed,
    executed_matmul_flops,
    itemize_hlo_matmul_flops,
    xla_cost_analysis,
)


def test_dot_flops_counted_exactly():
    a = jnp.zeros((64, 32), jnp.float32)
    b = jnp.zeros((32, 16), jnp.float32)
    compiled = jax.jit(lambda a, b: a @ b).lower(a, b).compile()
    rows = itemize_hlo_matmul_flops(compiled.as_text())
    assert len(rows) == 1
    # 2*M*N*K
    assert rows[0]["flops"] == 2.0 * 64 * 16 * 32


def test_conv_flops_counted_exactly():
    x = jnp.zeros((2, 8, 8, 4), jnp.float32)
    w = jnp.zeros((3, 3, 4, 16), jnp.float32)

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    compiled = jax.jit(conv).lower(x, w).compile()
    rows = [r for r in itemize_hlo_matmul_flops(compiled.as_text()) if r["kind"] == "conv"]
    assert len(rows) == 1
    # 2 * out_elems * (kh*kw*Cin); XLA-convention counts padded taps too.
    assert rows[0]["flops"] == 2.0 * (2 * 8 * 8 * 16) * (3 * 3 * 4)


def test_grouped_conv_not_double_divided():
    """The HLO rhs kernel of a grouped conv already carries C_in/groups as
    its input-feature dim — dividing again undercounts by groups x
    (regression: r4 review finding; depthwise convs collapsed to ~0)."""
    groups = 4
    x = jnp.zeros((1, 8, 8, groups), jnp.float32)
    w = jnp.zeros((3, 3, 1, groups), jnp.float32)  # depthwise

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        )

    compiled = jax.jit(conv).lower(x, w).compile()
    rows = [r for r in itemize_hlo_matmul_flops(compiled.as_text()) if r["kind"] == "conv"]
    assert len(rows) == 1
    assert rows[0]["flops"] == 2.0 * (1 * 8 * 8 * groups) * (3 * 3 * 1)


def test_executed_guard_rejects_unreconciled_counts():
    """executed_matmul_flops returns a float only when the recount lands in
    the cost_analysis reconciliation band."""
    a = jnp.zeros((256, 256), jnp.float32)
    compiled = jax.jit(lambda a: a @ a).lower(a).compile()
    got = executed_matmul_flops(compiled)
    assert got is None or got > 0
    if got is not None:
        cost = xla_cost_analysis(compiled)
        xla = float(cost.get("flops", 0.0))
        if xla:
            assert 0.3 <= got / xla <= 1.1


def test_bytes_accessed_and_arithmetic_intensity():
    """The roofline pair (ISSUE 3 satellite): bytes accessed surfaces XLA's
    HBM-traffic estimate and intensity = flops / bytes. A matmul must read at
    least its operands and write its output; its intensity must reconcile
    with the two cost_analysis entries it is derived from."""
    a = jnp.zeros((256, 128), jnp.float32)
    b = jnp.zeros((128, 64), jnp.float32)
    compiled = jax.jit(lambda a, b: a @ b).lower(a, b).compile()
    ba = bytes_accessed(compiled)
    cost = xla_cost_analysis(compiled)
    if "bytes accessed" not in cost:
        assert ba is None  # backend reports no estimate: None, not garbage
        return
    assert ba == float(cost["bytes accessed"])
    assert ba >= 4 * (256 * 128 + 128 * 64 + 256 * 64)  # operands + output
    ai = arithmetic_intensity(compiled)
    assert ai is not None and ai > 0
    np.testing.assert_allclose(ai, float(cost.get("flops", 0.0)) / ba)
    # numerator override: the analytic-count convention
    assert arithmetic_intensity(compiled, flops=2.0 * ba) == 2.0


def test_arithmetic_intensity_none_without_cost():
    class FakeNoCost:
        def cost_analysis(self):
            return {}

    assert bytes_accessed(FakeNoCost()) is None
    assert arithmetic_intensity(FakeNoCost()) is None


def test_parser_regression_warns_loudly():
    """Zero matched conv/dot instructions in a program whose cost_analysis
    reports real FLOPs = the HLO print format changed — a warning, not a
    silent None misread as the windowed-conv convention case (ADVICE r4)."""
    import warnings

    class FakeCompiled:
        def as_text(self):
            return "HloModule m\n%root = f32[8]{0} weird-new-op(%x)\n"

        def cost_analysis(self):
            return {"flops": 5e12}

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert executed_matmul_flops(FakeCompiled()) is None
    assert any("parser" in str(x.message) for x in w), [str(x.message) for x in w]

    class FakeSmall(FakeCompiled):
        def cost_analysis(self):
            return {"flops": 12.0}  # trivial program: silence is fine

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert executed_matmul_flops(FakeSmall()) is None
    assert not w


def test_partial_parser_break_warns_on_undercount():
    """A below-band nonzero sum (one regex breaking while the other matches)
    is an undercount the windowed-conv case cannot produce — it warns."""
    import warnings

    class FakePartial:
        def as_text(self):
            # one real-looking dot (256x256x256) in a program whose
            # cost_analysis claims far more
            return (
                "HloModule m\n"
                "%a = f32[256,256]{1,0} parameter(0)\n"
                "%d = f32[256,256]{1,0} dot(%a, %a), lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
            )

        def cost_analysis(self):
            return {"flops": 1e12}

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert executed_matmul_flops(FakePartial()) is None
    assert any("UNDER-count" in str(x.message) for x in w)
