"""ISSUE 15 tests: live run monitoring — the shared incremental event
reader, the streaming doctor + liveness contract, debounced alert rules,
the heartbeat pulse, and the in-process status exporter.

Acceptance pillars:

* ONE reader: ``events.EventFollower`` behind both ``load_run_events``
  and the monitor's tail (timeline owns no private parser — AST-enforced),
  torn-final-line tolerance and ``_line`` citations preserved;
* ONE verdict engine: ``doctor.update_signals`` folded incrementally
  produces byte-identical diagnoses to the post-hoc ``extract_signals``
  path on the same log;
* liveness: training / stale_heartbeat / dead / finished from file
  freshness + heartbeat content alone (fake clock), watchdog patrol
  heartbeats carrying ``since_progress_s``;
* alerts: debounced (fire on false->true, re-arm on clear), min-steady
  guard, ``monitor_alert`` records;
* exporter: ``/status`` JSON + ``/metrics`` valid Prometheus text under
  concurrent requests, port-in-use degrades to a warning, teardown
  releases the port, and an ``export_port=`` run is bit-exact
  (params + trace_counts) with the exporter off — the historical-program
  pillar.
"""

import ast
import json
import os
import re
import socket
import threading
import time
import urllib.request

import jax
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.fault.watchdog import StepWatchdog
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.telemetry import (
    EventFollower,
    EventLog,
    Telemetry,
    load_run_events,
)
from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib
from distributed_training_pytorch_tpu.telemetry import events as events_lib
from distributed_training_pytorch_tpu.telemetry import timeline as timeline_lib
from distributed_training_pytorch_tpu.telemetry.exporter import (
    StatusExporter,
    prometheus_text,
)
from distributed_training_pytorch_tpu.telemetry.monitor import (
    AlertConfig,
    RunMonitor,
    worst_exit_code,
)
from distributed_training_pytorch_tpu.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_lines(path, lines):
    with open(path, "w", encoding="utf-8") as f:
        f.write("".join(lines))


def _append(path, text):
    with open(path, "a", encoding="utf-8") as f:
        f.write(text)


def _rec(event, **fields):
    return json.dumps({"event": event, **fields}) + "\n"


# ---------------------------------------------------------------------------
# EventFollower: the ONE incremental reader.


def test_follower_incremental_polls(tmp_path):
    path = str(tmp_path / "e.jsonl")
    f = EventFollower(path)
    assert f.poll() == []  # not written yet: the monitor may attach early
    _write_lines(path, [_rec("run_start", t_mono=1.0)])
    got = f.poll()
    assert [r["event"] for r in got] == ["run_start"]
    assert f.poll() == []  # nothing new
    _append(path, _rec("window", t_mono=2.0) + _rec("epoch_end", t_mono=3.0))
    assert [r["event"] for r in f.poll()] == ["window", "epoch_end"]


def test_follower_withholds_torn_tail_until_complete(tmp_path):
    path = str(tmp_path / "e.jsonl")
    _write_lines(path, [_rec("run_start", t_mono=1.0), '{"event": "win'])
    f = EventFollower(path)
    assert [r["event"] for r in f.poll()] == ["run_start"]
    _append(path, 'dow", "t_mono": 2.0}\n')
    got = f.poll()
    assert [r["event"] for r in got] == ["window"]
    assert got[0]["_line"] == 2  # the completed line, cited correctly


def test_follower_final_parses_unterminated_tail(tmp_path):
    # A killed writer's last COMPLETE record missing only its newline is
    # data on a post-mortem read; a torn fragment warns and skips (the
    # read_events(strict=False) contract).
    path = str(tmp_path / "e.jsonl")
    _write_lines(path, [_rec("run_start", t_mono=1.0),
                        '{"event": "window", "t_mono": 2.0}'])
    f = EventFollower(path)
    assert [r["event"] for r in f.poll()] == ["run_start"]
    assert [r["event"] for r in f.poll(final=True)] == ["window"]
    torn = str(tmp_path / "torn.jsonl")
    _write_lines(torn, [_rec("run_start", t_mono=1.0), '{"to'])
    f2 = EventFollower(torn)
    with pytest.warns(UserWarning, match="malformed"):
        got = f2.poll(final=True)
    assert [r["event"] for r in got] == ["run_start"]


def test_follower_resets_on_truncation(tmp_path):
    path = str(tmp_path / "e.jsonl")
    _write_lines(path, [_rec("run_start", t_mono=1.0), _rec("window", t_mono=2.0)])
    f = EventFollower(path)
    assert len(f.poll()) == 2
    _write_lines(path, [_rec("run_start", t_mono=9.0)])  # fresh attempt, smaller
    got = f.poll()
    assert [r["event"] for r in got] == ["run_start"]
    assert got[0]["t_mono"] == 9.0 and got[0]["_line"] == 1


def test_follower_line_citations_stable_past_blank_and_malformed(tmp_path):
    path = str(tmp_path / "e.jsonl")
    _write_lines(path, [
        _rec("run_start", t_mono=1.0),
        "\n",
        "not json\n",
        _rec("window", t_mono=2.0),
    ])
    with pytest.warns(UserWarning, match="malformed"):
        recs = load_run_events(path)
    assert [(r["event"], r["_line"]) for r in recs] == [
        ("run_start", 1), ("window", 4)]


def test_load_run_events_equals_incremental_accumulation(tmp_path):
    path = str(tmp_path / "e.jsonl")
    lines = [_rec("run_start", t_mono=1.0), _rec("window", t_mono=2.0),
             _rec("run_end", t_mono=3.0)]
    _write_lines(path, lines[:1])
    f = EventFollower(path)
    acc = f.poll()
    _append(path, "".join(lines[1:]))
    acc += f.poll(final=True)
    assert acc == load_run_events(path)


def test_follower_final_tail_not_consumed_on_resurrection(tmp_path):
    """A 'dead' verdict's final poll must not destroy the tail: if the
    writer was only stalled and resumes, the completed line is read
    normally (no lost record, no duplicate, no drifted _line)."""
    path = str(tmp_path / "e.jsonl")
    # complete record missing only its newline: final-yielded, then deduped
    # when the newline lands
    _write_lines(path, [_rec("run_start", t_mono=1.0),
                        '{"event": "window", "t_mono": 2.0}'])
    f = EventFollower(path)
    f.poll()
    assert [r["event"] for r in f.poll(final=True)] == ["window"]
    _append(path, "\n" + _rec("epoch_end", t_mono=3.0))
    got = f.poll()
    assert [(r["event"], r["_line"]) for r in got] == [("epoch_end", 3)]
    # a TORN fragment at final poll: withheld (not consumed), so the
    # resumed writer's continuation completes it into a real record
    torn = str(tmp_path / "torn.jsonl")
    _write_lines(torn, [_rec("run_start", t_mono=1.0), '{"event": "win'])
    f2 = EventFollower(torn)
    f2.poll()
    with pytest.warns(UserWarning, match="malformed"):
        assert f2.poll(final=True) == []
    _append(torn, 'dow", "t_mono": 2.0}\n')
    assert [(r["event"], r["_line"]) for r in f2.poll()] == [("window", 2)]


def test_load_run_events_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="telemetry-off"):
        load_run_events(str(tmp_path / "nope"))


def test_timeline_owns_no_private_parser():
    """Satellite contract: the timeline re-exports the shared reader and
    holds NO parsing of its own — no json.loads, no read_events call, no
    open-for-read of the log (AST-enforced; the PR 6 dedup pattern)."""
    assert timeline_lib.load_run_events is events_lib.load_run_events
    path = os.path.join(
        REPO, "distributed_training_pytorch_tpu", "telemetry", "timeline.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if name in ("loads", "read_events", "EventFollower"):
                offenders.append((name, node.lineno))
    assert not offenders, (
        f"timeline.py grew a private event parser at {offenders} — use "
        "telemetry.events.load_run_events/EventFollower (ISSUE 15)")


# ---------------------------------------------------------------------------
# Doctor: the incremental fold IS the batch path.


_HAND_LOG = [
    {"event": "run_start", "t_mono": 0.0, "_line": 1,
     "goodput_seconds": {"productive_step": 0.0}},
    {"event": "compile", "t_mono": 1.0, "epoch": 2, "executables": 1, "_line": 2},
    {"event": "anomaly", "t_mono": 2.0, "kind": "loss_spike", "value": 9.0,
     "_line": 3},
    {"event": "anomaly", "t_mono": 2.5, "kind": "straggler", "value": 2.0,
     "_line": 4},
    {"event": "window", "t_mono": 3.0, "steps": 4, "step_ms": 10.0,
     "straggler_ratio": 2.2, "_line": 5},
    {"event": "hung_step", "t_mono": 4.0, "timeout_s": 5.0, "_line": 6},
    {"event": "profile_capture", "t_mono": 5.0,
     "categories": {"collective": 0.4, "idle": 0.6}, "_line": 7},
    {"event": "run_end", "t_mono": 9.0, "_line": 8,
     "goodput_seconds": {"productive_step": 5.0, "data_wait": 3.0,
                         "checkpoint": 1.0, "compile": 4.0}},
]


def test_update_signals_matches_extract_signals_byte_identical():
    batch = doctor_lib.diagnose([dict(r) for r in _HAND_LOG])
    sig = doctor_lib.Signals()
    for rec in _HAND_LOG:
        doctor_lib.update_signals(sig, dict(rec))
    streaming = doctor_lib.diagnose(sig)
    assert (
        json.dumps(streaming.to_dict(), sort_keys=True)
        == json.dumps(batch.to_dict(), sort_keys=True)
    )
    # and the evidence (line citations included) folded identically
    assert streaming.signals.evidence == batch.signals.evidence


def test_verdict_vocabulary_includes_liveness_kinds():
    assert "stale_heartbeat" in doctor_lib.VERDICTS
    assert "dead" in doctor_lib.VERDICTS
    # the offline rules never produce them: scalar projections stay 0.0
    scores = doctor_lib.scalar_fields(doctor_lib.Signals(
        goodput_seconds={"productive_step": 5.0}))
    assert scores["stale_heartbeat"] == 0.0 and scores["dead"] == 0.0


# ---------------------------------------------------------------------------
# Monitor liveness (fake clock over synthetic logs).


def _mk_run(tmp_path, lines, name="run"):
    run = tmp_path / name
    (run / "telemetry").mkdir(parents=True)
    _write_lines(str(run / "telemetry" / "events.jsonl"), lines)
    return str(run)


def test_monitor_attaches_before_run_dir_exists(tmp_path):
    """Deploy-the-monitor-first: a RunMonitor constructed before the
    trainer has created the run directory must still resolve the log's
    eventual location (an isdir-based resolution would freeze the bare
    dir path and report 'waiting' forever)."""
    base = time.time()
    run = str(tmp_path / "not_yet")  # does not exist at construction
    mon = RunMonitor(run, AlertConfig(stale_after_s=60.0),
                     clock=lambda: base + 1.0)
    assert mon.poll().status == "waiting"
    os.makedirs(os.path.join(run, "telemetry"))
    _append(os.path.join(run, "telemetry", "events.jsonl"),
            _rec("run_start", t_wall=base, t_mono=0.0))
    assert mon.poll().status == "training"


def test_watchdog_fire_does_not_reset_patrol_progress():
    """A fire re-arms the escalation window (_last_pat) but must NOT
    claim progress: patrol heartbeats after a SIGTERM recovery attempt
    still report the hang, or the monitor would read a wedged run as
    'training' for the whole escalation window."""
    patrols = []
    # max_fires=2 = the trainer's config: the patrol thread survives the
    # first (SIGTERM-recovery) fire and keeps pulsing through the
    # escalation window.
    dog = StepWatchdog(timeout=0.1, on_timeout=lambda: None,
                       poll_interval=0.02, max_fires=2,
                       on_patrol=patrols.append)
    dog.start()
    time.sleep(0.4)  # first fire at ~0.1s; never patted
    dog.stop()
    assert dog.fired == 1
    # post-fire patrol figures keep GROWING past the fire point
    assert max(patrols) > 0.25
    assert dog.progress_elapsed > 0.35


def test_monitor_waiting_then_training(tmp_path):
    run = tmp_path / "run"
    (run / "telemetry").mkdir(parents=True)
    base = time.time()
    mon = RunMonitor(str(run), AlertConfig(stale_after_s=5.0),
                     clock=lambda: base + 1.0)
    st = mon.poll()
    assert st.status == "waiting" and st.exit_code == 3
    _append(str(run / "telemetry" / "events.jsonl"),
            _rec("run_start", t_wall=base, t_mono=0.0))
    st = mon.poll()
    assert st.status == "training" and st.verdict == "healthy"
    assert st.exit_code == 0


def test_monitor_stale_heartbeat_from_watchdog_lag(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        # the patrol thread keeps pulsing while the main thread is stuck:
        # fresh record (t_wall base+10), progress 9s before it
        _rec("heartbeat", t_wall=base + 10.0, t_mono=10.0, source="watchdog",
             since_progress_s=9.0),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=5.0, dead_after_s=60.0),
                     clock=lambda: base + 11.0)
    st = mon.poll()
    assert st.status == "stale_heartbeat" and st.verdict == "stale_heartbeat"
    assert st.exit_code == 1
    assert st.progress_age_s == pytest.approx(10.0, abs=1.0)
    assert any(a["rule"] == "stale_heartbeat" for a in st.alerts)


def test_monitor_loop_heartbeat_is_progress(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        _rec("heartbeat", t_wall=base + 10.0, t_mono=10.0, source="loop",
             epoch=0, step_in_epoch=8, units=8, step_ms=3.0),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=5.0, dead_after_s=60.0),
                     clock=lambda: base + 11.0)
    st = mon.poll()
    assert st.status == "training"
    assert st.headline["units"] == 8 and st.headline["step_ms"] == 3.0


def test_monitor_dead_on_silence_and_drains_torn_tail(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        # a SIGKILL'd writer's torn tail: parsed once the run is declared
        # dead (no more bytes are coming)
        '{"event": "window", "t_wall": %r, "t_mono": 5.0, "steps": 4, '
        '"step_ms": 2.0}' % (base + 5.0),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=5.0, dead_after_s=30.0),
                     clock=lambda: base + 100.0)
    st = mon.poll()
    assert st.status == "dead" and st.verdict == "dead" and st.exit_code == 2
    assert any(a["rule"] == "dead" for a in st.alerts)
    # the tail window record was ingested on the final drain
    assert st.headline.get("step_ms") == 2.0


def test_monitor_finished_is_not_dead(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0,
             goodput_seconds={"productive_step": 0.0}),
        _rec("run_end", t_wall=base + 5.0, t_mono=5.0,
             goodput_seconds={"productive_step": 9.0, "data_wait": 0.1}),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=5.0, dead_after_s=30.0),
                     clock=lambda: base + 10_000.0)
    st = mon.poll()
    assert st.status == "finished" and st.verdict == "healthy"
    assert st.exit_code == 0


def test_monitor_resumed_attempt_reopens_the_run(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        _rec("run_end", t_wall=base + 5.0, t_mono=5.0),
        _rec("run_start", t_wall=base + 8.0, t_mono=0.5),  # append-across-restarts
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=60.0), clock=lambda: base + 9.0)
    assert mon.poll().status == "training"


def test_monitor_resets_state_on_log_truncation(tmp_path):
    """A fresh attempt truncating the log must rebuild the monitor's
    accumulated signals — folding the re-read records onto the old run's
    Signals would double-count and weld two runs' verdicts together."""
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        _rec("anomaly", t_wall=base + 1.0, t_mono=1.0, kind="loss_spike",
             value=9.0),
        _rec("hung_step", t_wall=base + 2.0, t_mono=2.0, timeout_s=5.0),
    ])
    path = os.path.join(run, "telemetry", "events.jsonl")
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0),
                     clock=lambda: base + 3.0)
    st = mon.poll()
    assert st.verdict == "straggler"  # hung_step from attempt 1
    assert "anomaly:loss_spike" in st.active_alerts
    # attempt 2 rewrites the log, smaller: clean run, nothing carried over
    _write_lines(path, [_rec("run_start", t_wall=base + 4.0, t_mono=0.0)])
    st = mon.poll()
    assert st.status == "training" and st.verdict == "healthy"
    assert mon.signals.anomaly_counts == {} and mon.signals.hung_steps == 0
    assert st.active_alerts == () and st.exit_code == 0


def test_monitor_attempt_change_resets_and_rebases(tmp_path):
    """ISSUE 16 die-and-restart-in-place: a controller-restarted run
    APPENDS a new attempt to the same events.jsonl. The in-band attempt
    id must (a) walk the fleet-table state dead -> training -> healthy,
    (b) drop the dead attempt's accumulated signals (no welded hung/
    anomaly counters), and (c) rebase goodput at the restored cumulative
    snapshot the new run_start carries, so fraction verdicts describe
    THIS attempt — not the diseased history the restart just cured."""
    base = time.time()
    clock = {"now": base + 3.0}
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0, attempt=1, epoch=0),
        _rec("hung_step", t_wall=base + 1.0, t_mono=1.0, timeout_s=5.0),
        # attempt 1 accrued 80% data_wait before it died
        _rec("epoch_end", t_wall=base + 2.0, t_mono=2.0, epoch=0,
             goodput_seconds={"productive_step": 1.0, "data_wait": 4.0}),
    ])
    path = os.path.join(run, "telemetry", "events.jsonl")
    mon = RunMonitor(run, AlertConfig(stale_after_s=5.0),
                     clock=lambda: clock["now"])
    st = mon.poll()
    assert st.attempt == 1 and st.verdict == "data_bound"
    assert mon.signals.hung_steps == 1  # the hang is on attempt 1's ledger
    assert "data_bound" in st.active_alerts
    # silence past 3x stale ceiling: the attempt reads dead
    clock["now"] = base + 40.0
    assert mon.poll().status == "dead"
    # the controller respawned: attempt 2 appends, carrying the restored
    # cumulative goodput snapshot (trainer restores BEFORE run_start)
    _append(path, _rec("run_start", t_wall=base + 41.0, t_mono=0.0,
                       attempt=2, epoch=1,
                       goodput_seconds={"productive_step": 1.0,
                                        "data_wait": 4.0}))
    _append(path, _rec("epoch_end", t_wall=base + 44.0, t_mono=3.0, epoch=1,
                       goodput_seconds={"productive_step": 4.0,
                                        "data_wait": 4.1}))
    clock["now"] = base + 45.0
    st = mon.poll()
    assert st.status == "training" and st.attempt == 2
    # no welded counters: attempt 1's hang is gone, verdict healthy on
    # attempt 2's OWN accrual (3.0 productive vs 0.1 data_wait), even
    # though the welded cumulative would still read data_bound
    assert mon.signals.hung_steps == 0
    assert st.verdict == "healthy" and "data_bound" not in st.active_alerts
    assert st.steady_fractions["data_wait"] == pytest.approx(0.1 / 3.1)


def test_monitor_alert_rearms_across_attempt_change(tmp_path):
    """A fresh attempt's recurrence of a disease must ALERT AGAIN: the
    debounce ledger belongs to the attempt, not the run directory. Two
    attempts over the line = two firings of the same rule."""
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0, attempt=1),
        _goodput_line(base, 1.0, productive_step=1.0, data_wait=1.0),
    ])
    path = os.path.join(run, "telemetry", "events.jsonl")
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0),
                     clock=lambda: base + 2.0)
    st = mon.poll()
    assert [a["rule"] for a in st.alerts] == ["data_bound"]
    assert mon.poll().alerts == []  # debounced while it persists
    _append(path, _rec("run_start", t_wall=base + 3.0, t_mono=0.0, attempt=2,
                       goodput_seconds={"productive_step": 1.0,
                                        "data_wait": 1.0}))
    _append(path, _rec("epoch_end", t_wall=base + 5.0, t_mono=2.0, epoch=0,
                       goodput_seconds={"productive_step": 2.0,
                                        "data_wait": 3.0}))
    st = mon.poll()
    assert st.attempt == 2
    assert [a["rule"] for a in st.alerts] == ["data_bound"]  # re-armed


def test_worst_exit_code_aggregation():
    def st(code):
        class S:
            exit_code = code
        return S()

    assert worst_exit_code([st(0), st(0)]) == 0
    assert worst_exit_code([st(0), st(1)]) == 1
    assert worst_exit_code([st(1), st(2), st(3)]) == 2
    assert worst_exit_code([st(0), st(3)]) == 3
    assert worst_exit_code([]) == 3


# ---------------------------------------------------------------------------
# Alert rules: debounce, re-arm, min-steady guard, JSONL records.


def _goodput_line(base, t, **buckets):
    return _rec("epoch_end", t_wall=base + t, t_mono=t, epoch=0,
                goodput_seconds=buckets)


def test_alert_debounce_fires_once_then_rearms(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [_rec("run_start", t_wall=base, t_mono=0.0)])
    path = os.path.join(run, "telemetry", "events.jsonl")
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0),
                     clock=lambda: base + 1.0)
    # over the ceiling -> ONE alert
    _append(path, _goodput_line(base, 1.0, productive_step=1.0, data_wait=1.0))
    st = mon.poll()
    assert [a["rule"] for a in st.alerts] == ["data_bound"]
    assert "data_bound" in st.active_alerts and st.exit_code == 1
    # still over -> silence (debounced)
    _append(path, _goodput_line(base, 2.0, productive_step=1.5, data_wait=1.4))
    assert mon.poll().alerts == []
    # recovered -> cleared, re-armed
    _append(path, _goodput_line(base, 3.0, productive_step=20.0, data_wait=1.5))
    st = mon.poll()
    assert st.alerts == [] and "data_bound" not in st.active_alerts
    # over again -> a SECOND alert (the rule re-armed on clear)
    _append(path, _goodput_line(base, 4.0, productive_step=21.0, data_wait=9.0))
    assert [a["rule"] for a in mon.poll().alerts] == ["data_bound"]


def test_alert_min_steady_guard(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        # 90% data_wait but only 0.1s of steady wall: honest noise
        _goodput_line(base, 1.0, productive_step=0.01, data_wait=0.09),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0, min_steady_s=1.0),
                     clock=lambda: base + 2.0)
    st = mon.poll()
    assert st.alerts == [] and "data_bound" not in st.active_alerts


def test_anomaly_kind_alert_and_verdict_transition(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        _rec("anomaly", t_wall=base + 1.0, t_mono=1.0, kind="loss_spike",
             value=9.0),
        _rec("window", t_wall=base + 2.0, t_mono=2.0, steps=4, step_ms=10.0,
             straggler_ratio=2.0),
    ])
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0),
                     clock=lambda: base + 3.0)
    st = mon.poll()
    rules = {a["rule"] for a in st.alerts}
    assert "anomaly:loss_spike" in rules
    assert "straggler" in rules  # verdict transition: ratio 2.0 > 1.5
    assert st.verdict == "straggler" and st.exit_code == 1
    # both stay active, neither re-fires
    _append(os.path.join(run, "telemetry", "events.jsonl"),
            _rec("window", t_wall=base + 4.0, t_mono=4.0, steps=4,
                 step_ms=10.0, straggler_ratio=2.1))
    assert mon.poll().alerts == []


def test_monitor_alert_records_written(tmp_path):
    base = time.time()
    run = _mk_run(tmp_path, [
        _rec("run_start", t_wall=base, t_mono=0.0),
        _goodput_line(base, 1.0, productive_step=1.0, data_wait=1.0),
    ])
    alerts_path = str(tmp_path / "alerts.jsonl")
    log = EventLog(alerts_path, process_index=0)
    mon = RunMonitor(run, AlertConfig(stale_after_s=600.0), alert_log=log,
                     clock=lambda: base + 2.0)
    mon.poll()
    log.close()
    recs = load_run_events(alerts_path)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["event"] == "monitor_alert" and rec["rule"] == "data_bound"
    assert rec["run_dir"] == run and rec["status"] == "training"
    assert rec["value"] == pytest.approx(0.5) and rec["threshold"] == 0.2


# ---------------------------------------------------------------------------
# Status exporter.


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\n]*\")*\})?"
    r" [-+0-9.eE]+(nan|inf)?$"
)


def _assert_valid_prometheus(text):
    samples = 0
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("#"):
            assert re.match(r"^# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ", line), line
            continue
        assert _PROM_SAMPLE.match(line), f"invalid exposition line: {line!r}"
        samples += 1
    assert samples > 0
    return samples


def test_prometheus_text_renders_scalars_dicts_and_info():
    text = prometheus_text({
        "step_ms": 12.5,
        "epoch": 3,
        "finished": False,
        "verdict": "data_bound",
        "run_dir": "/tmp/x",
        "goodput_fractions": {"productive_step": 0.75, "data_wait": 0.25},
        "anomaly_counts": {"loss_spike": 2},
        "ignored": [1, 2, 3],  # non-numeric leaves are skipped, never a 500
    })
    _assert_valid_prometheus(text)
    assert 'tpu_trainer_goodput_fractions{bucket="data_wait"} 0.25' in text
    assert 'tpu_trainer_anomaly_counts{kind="loss_spike"} 2.0' in text
    assert "tpu_trainer_step_ms 12.5" in text
    assert 'verdict="data_bound"' in text and "tpu_trainer_up 1" in text


def test_status_endpoint_survives_nonfinite_values():
    """A diverged run (loss=NaN) is exactly when /status gets scraped:
    the payload must stay STRICT json (the events._jsonable rule — bare
    NaN tokens are rejected by jq/JSON.parse)."""
    snap = {"loss": float("nan"), "step_ms": float("inf"), "verdict": "healthy"}
    ex = StatusExporter(lambda: snap, 0, host="127.0.0.1")
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{ex.port}/status", timeout=10).read().decode()
    ex.close()
    assert "NaN" not in body and "Infinity" not in body
    parsed = json.loads(body)  # strict: would reject bare NaN
    assert parsed["loss"] == "nan" and parsed["step_ms"] == "inf"


def test_goodput_evidence_row_is_replaced_not_appended():
    """Heartbeats carry a goodput snapshot every pulse: the doctor's
    goodput evidence must hold ONE row (the latest snapshot), not grow by
    one identical row per heartbeat for the length of the run."""
    sig = doctor_lib.Signals()
    for i in range(50):
        doctor_lib.update_signals(sig, {
            "event": "heartbeat", "t_mono": float(i), "_line": i + 1,
            "goodput_seconds": {"productive_step": float(i)},
        })
    assert len(sig.evidence["goodput"]) == 1
    assert sig.evidence["goodput"][0]["line"] == 50  # the latest wins
    assert sig.goodput_seconds == {"productive_step": 49.0}


def test_exporter_serves_concurrent_requests_and_tears_down():
    snap = {"step_ms": 1.5, "verdict": "healthy",
            "goodput_fractions": {"productive_step": 1.0}}
    ex = StatusExporter(lambda: dict(snap), 0, host="127.0.0.1")
    assert ex.enabled and ex.port
    base = f"http://127.0.0.1:{ex.port}"
    errors = []

    def hammer():
        try:
            for _ in range(10):
                body = urllib.request.urlopen(base + "/status", timeout=10).read()
                assert json.loads(body)["step_ms"] == 1.5
                text = urllib.request.urlopen(base + "/metrics", timeout=10).read()
                _assert_valid_prometheus(text.decode())
        except Exception as e:  # noqa: BLE001 — collected for the assert below
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    port = ex.port
    ex.close()
    assert not ex.enabled
    # teardown released the port: a fresh exporter can bind it
    ex2 = StatusExporter(lambda: {}, port, host="127.0.0.1")
    assert ex2.enabled
    ex2.close()


def test_exporter_port_in_use_degrades_to_warning():
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    warned = []
    ex = StatusExporter(lambda: {}, port, host="127.0.0.1", log=warned.append)
    assert not ex.enabled and ex.port is None
    assert warned and "disabled" in warned[0]
    ex.close()  # idempotent on a disabled exporter
    blocker.close()


def test_exporter_unknown_route_404_and_snapshot_failure_500():
    def boom():
        raise RuntimeError("snapshot bug")

    ex = StatusExporter(boom, 0, host="127.0.0.1")
    base = f"http://127.0.0.1:{ex.port}"
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(base + "/metrics", timeout=10)
    assert e.value.code == 500
    ex.close()
    ex2 = StatusExporter(lambda: {}, 0, host="127.0.0.1")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(f"http://127.0.0.1:{ex2.port}/nope", timeout=10)
    assert e.value.code == 404
    ex2.close()


# ---------------------------------------------------------------------------
# Watchdog patrol hook.


def test_watchdog_on_patrol_reports_elapsed_and_swallows_errors():
    seen = []

    def patrol(elapsed):
        seen.append(elapsed)
        raise RuntimeError("must never wedge the watchdog")

    dog = StepWatchdog(timeout=50.0, on_timeout=lambda: None,
                       poll_interval=0.02, on_patrol=patrol)
    dog.start()
    time.sleep(0.15)
    dog.pat()
    time.sleep(0.1)
    dog.stop()
    assert len(seen) >= 3  # patrolled repeatedly despite the exception
    assert max(seen) >= 0.1  # elapsed grew while unpatted
    assert min(seen) >= 0.0


# ---------------------------------------------------------------------------
# Trainer integration: heartbeats + exporter, historical program untouched.


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))


class TinyTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(48,)).astype(np.int32)
        images = (rng.randn(48, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return TinyNet()

    def build_criterion(self):
        def crit(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return crit

    def build_optimizer(self, schedule):
        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


class _Quiet:
    def log(self, *a, **k):
        pass


def make_tiny(tmp_path, **kw):
    defaults = dict(
        max_epoch=2,
        batch_size=8,
        have_validate=False,
        save_folder=str(tmp_path / "run"),
        num_workers=0,
        log_every=2,
        chain_steps=2,
        async_checkpoint=False,
        progress=False,
        logger=_Quiet(),
    )
    defaults.update(kw)
    return TinyTrainer(**defaults)


@pytest.fixture(scope="module")
def hb_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hb_run")
    trainer = make_tiny(tmp, telemetry=Telemetry(heartbeat_every_s=1e-4))
    trainer.train()
    return trainer, load_run_events(trainer.save_folder)


def test_heartbeats_ride_the_log_every_syncs(hb_run):
    trainer, events = hb_run
    hbs = [r for r in events if r["event"] == "heartbeat"]
    assert hbs, "no heartbeat records in a heartbeat-on run"
    assert {h["source"] for h in hbs} == {"loop"}  # no watchdog armed here
    units = [h["units"] for h in hbs]
    assert units == sorted(units)  # progress is monotone
    last = hbs[-1]
    assert set(last["goodput_seconds"]) == set(doctor_lib.BUCKETS)
    assert last["step_ms"] > 0 and last["epoch"] == trainer.max_epoch - 1


def test_heartbeat_off_removes_records(tmp_path):
    trainer = make_tiny(tmp_path, telemetry=Telemetry(heartbeat_every_s=0.0))
    trainer.train()
    events = load_run_events(trainer.save_folder)
    assert not [r for r in events if r["event"] == "heartbeat"]


def test_monitor_matches_doctor_on_real_run(hb_run):
    """ISSUE 15 acceptance: the streaming monitor's fractions equal the
    post-hoc doctor's to 1e-6 on the same log (they are the same floats),
    and the diagnosis dicts are byte-identical."""
    trainer, events = hb_run
    post = doctor_lib.diagnose(events)
    st = RunMonitor(trainer.save_folder).poll()
    assert st.status == "finished"
    doctor_fr = doctor_lib.steady_fractions(post.signals.goodput_seconds or {})
    for bucket, frac in doctor_fr.items():
        assert abs(st.steady_fractions[bucket] - frac) <= 1e-6
    assert (
        json.dumps(st.diagnosis.to_dict(), sort_keys=True)
        == json.dumps(post.to_dict(), sort_keys=True)
    )


def test_timeline_skips_heartbeat_markers(hb_run):
    trainer, events = hb_run
    trace = timeline_lib.build_timeline(events)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "heartbeat" not in names  # liveness plumbing, not narrative
    # ...but their goodput snapshots refined the span chain: it still
    # re-derives the meter's fractions exactly
    derived = timeline_lib.span_bucket_seconds(trace)
    want = trainer.goodput.to_state()
    total_d, total_w = sum(derived.values()), sum(want.values())
    for bucket, w in want.items():
        assert abs(
            derived.get(bucket, 0.0) / max(total_d, 1e-12)
            - w / max(total_w, 1e-12)
        ) <= 1e-6


def test_exporter_on_is_historical_program(tmp_path, hb_run):
    """THE parity pillar (ISSUE 15 acceptance): export_port= only READS
    host-side snapshots — params and trace_counts bit-identical with the
    exporter off."""
    on_trainer, _ = hb_run
    off = make_tiny(
        tmp_path,
        telemetry=Telemetry(heartbeat_every_s=1e-4, export_port=0),
    )
    # scrape mid-run through the real HTTP surface (piggybacked on the
    # status-update hook so the request lands while training is live)
    scrapes = {}
    orig = off._update_status

    def spy(**kw):
        orig(**kw)
        if off.exporter is not None and off.exporter.enabled and not scrapes:
            base = f"http://127.0.0.1:{off.exporter.port}"
            scrapes["status"] = json.loads(
                urllib.request.urlopen(base + "/status", timeout=10).read())
            scrapes["metrics"] = urllib.request.urlopen(
                base + "/metrics", timeout=10).read().decode()

    off._update_status = spy
    off.train()
    assert scrapes, "the exporter never served during the run"
    assert scrapes["status"]["phase"] == "training"
    assert scrapes["status"]["verdict"] == "healthy"
    _assert_valid_prometheus(scrapes["metrics"])
    assert off.exporter is None  # torn down with the run
    assert dict(off.engine.trace_counts) == dict(on_trainer.engine.trace_counts)
    for a, b in zip(
        jax.tree.leaves(off.state.params),
        jax.tree.leaves(on_trainer.state.params),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_exporter_port_taken_never_kills_training(tmp_path):
    blocker = socket.socket()
    blocker.bind(("0.0.0.0", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    trainer = make_tiny(
        tmp_path, telemetry=Telemetry(heartbeat_every_s=0.0, export_port=port)
    )
    trainer.train()  # completes despite the bind failure
    blocker.close()
    assert trainer.exporter is None
    events = load_run_events(trainer.save_folder)
    assert any(r["event"] == "run_end" for r in events)
