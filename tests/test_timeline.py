"""ISSUE 13 tests: unified run timeline, straggler attribution, run doctor,
the data_wait perf-gate ceiling, and the event-schema/doc contracts.

Acceptance pillars:

* the exported timeline is VALID trace-event JSON (stdlib re-parse), every
  lane's spans are monotone and non-overlapping, the async committer gets
  its own track, and the goodput lanes' span durations re-derive the
  meter's bucket seconds exactly;
* straggler sampling observes the run without perturbing it: params and
  ``trace_counts`` bit-identical with ``telemetry=None`` (the historical
  program), and ``Telemetry(straggler=False)`` removes the fields;
* the doctor's verdict rules are deterministic on hand-built run dirs;
* the data_wait gate shares profiling.gate's one rule, with exact
  boundary behavior;
* every event kind the code emits appears in docs/observability.md's
  vocabulary table (doc drift = test failure — the PR 6 AST pattern), and
  every emitted record carries ``schema``/``chips``.
"""

import ast
import json
import math
import os

import jax
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.profiling import gate as gate_lib
from distributed_training_pytorch_tpu.telemetry import (
    SCHEMA_VERSION,
    AnomalyDetector,
    EventLog,
    Telemetry,
    read_events,
)
from distributed_training_pytorch_tpu.telemetry import doctor as doctor_lib
from distributed_training_pytorch_tpu.telemetry import straggler as straggler_lib
from distributed_training_pytorch_tpu.telemetry import timeline as timeline_lib
from distributed_training_pytorch_tpu.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "distributed_training_pytorch_tpu")


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


# ---------------------------------------------------------------------------
# Timeline: hand-built event logs -> trace-event JSON.


def _write_run(tmp_path, records):
    tdir = tmp_path / "telemetry"
    tdir.mkdir(parents=True, exist_ok=True)
    path = tdir / "events.jsonl"
    base = {"t_wall": 0.0, "process": 0, "host": "h", "pid": 7, "chips": "0",
            "schema": SCHEMA_VERSION}
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps({**base, **rec}) + "\n")
    return str(tmp_path)


def _goodput(**kw):
    base = {b: 0.0 for b in doctor_lib.BUCKETS}
    base.update(kw)
    return base


def _lane_spans(trace, tid):
    return sorted(
        (e for e in trace["traceEvents"] if e.get("ph") == "X" and e.get("tid") == tid),
        key=lambda e: e["ts"],
    )


def test_timeline_valid_and_monotone(tmp_path):
    run = _write_run(tmp_path, [
        {"event": "run_start", "t_mono": 10.0,
         "goodput_seconds": _goodput()},
        {"event": "compile", "t_mono": 11.0, "epoch": 0, "executables": 1},
        {"event": "window", "t_mono": 12.0, "epoch": 0, "step_in_epoch": 4,
         "steps": 4, "step_ms": 100.0, "live_bytes": 1000},
        # overlapping claim: this window says it took 3s but only 1s passed
        {"event": "window", "t_mono": 13.0, "epoch": 0, "step_in_epoch": 8,
         "steps": 6, "step_ms": 500.0},
        {"event": "epoch_end", "t_mono": 13.5, "epoch": 0, "wall_s": 3.4,
         "steps": 8, "step_ms": 420.0,
         "goodput_seconds": _goodput(productive_step=2.0, compile=1.0,
                                     data_wait=0.4)},
        {"event": "run_end", "t_mono": 14.0,
         "goodput_seconds": _goodput(productive_step=2.2, compile=1.0,
                                     data_wait=0.5, other=0.3)},
    ])
    trace, path = timeline_lib.export_timeline(run)
    with open(path, encoding="utf-8") as f:
        reparsed = json.load(f)  # strict JSON contract
    assert reparsed["traceEvents"]
    # every non-metadata record carries the trace-event schema
    for ev in reparsed["traceEvents"]:
        assert ev["ph"] in ("X", "i", "C", "M")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and "ts" in ev and "tid" in ev
    # per-lane monotone, non-overlapping spans (the overlapping window
    # claim above must have been trimmed, not emitted overlapping)
    lanes = {}
    for ev in reparsed["traceEvents"]:
        if ev.get("ph") == "X":
            lanes.setdefault((ev["pid"], ev["tid"]), []).append(ev)
    assert lanes
    for lane in lanes.values():
        lane.sort(key=lambda e: e["ts"])
        for a, b in zip(lane, lane[1:], strict=False):
            assert b["ts"] >= a["ts"] + a["dur"] - 1e-6, (a, b)
    # narrative kinds become markers; counters carry the live bytes
    names = {e["name"] for e in reparsed["traceEvents"]}
    assert {"run_start", "run_end", "compile", "live_bytes"} <= names
    # the original dict and the reparse agree
    assert json.dumps(trace, sort_keys=True) == json.dumps(reparsed, sort_keys=True)


def test_timeline_goodput_spans_rederive_fractions(tmp_path):
    final = _goodput(productive_step=3.0, compile=2.0, data_wait=1.0,
                     checkpoint=0.5, checkpoint_async=0.25, other=0.25)
    run = _write_run(tmp_path, [
        {"event": "run_start", "t_mono": 0.0, "goodput_seconds": _goodput()},
        {"event": "epoch_end", "t_mono": 4.0, "epoch": 0, "wall_s": 4.0,
         "steps": 4, "step_ms": 10.0,
         "goodput_seconds": _goodput(productive_step=1.5, compile=2.0,
                                     data_wait=0.25)},
        {"event": "run_end", "t_mono": 7.0, "goodput_seconds": final},
    ])
    trace, _ = timeline_lib.export_timeline(run)
    derived = timeline_lib.span_bucket_seconds(trace)
    for bucket, want in final.items():
        assert math.isclose(derived[bucket], want, abs_tol=1e-9), bucket
    # fractions re-derive exactly as well
    total = sum(derived.values())
    for bucket, want in final.items():
        assert math.isclose(derived[bucket] / total, want / sum(final.values()),
                            abs_tol=1e-12)


def test_timeline_committer_own_track(tmp_path):
    run = _write_run(tmp_path, [
        {"event": "checkpoint_save", "t_mono": 1.0, "name": "last",
         "mode": "async", "snapshot_ms": 5.0, "epoch": 0},
        {"event": "checkpoint_commit", "t_mono": 2.0, "name": "last",
         "commit_ms": 300.0},
        {"event": "checkpoint_save", "t_mono": 3.0, "name": "best",
         "mode": "sync", "save_ms": 80.0, "epoch": 0},
    ])
    trace, _ = timeline_lib.export_timeline(run)
    ckpt = _lane_spans(trace, timeline_lib.TRACKS["checkpoint"])
    committer = _lane_spans(trace, timeline_lib.TRACKS["committer"])
    assert [s["name"] for s in ckpt] == ["snapshot:last", "save:best"]
    # the committer thread is its own track: queued gap + the commit span
    assert [s["name"] for s in committer] == ["queued:last", "commit:last"]
    queued, commit = committer
    assert math.isclose(commit["dur"], 300.0 * 1e3)
    # queued covers snapshot-end -> commit-start on the one t_mono clock
    assert math.isclose(queued["ts"], 1.0 * 1e6)
    assert math.isclose(queued["ts"] + queued["dur"], commit["ts"])
    # and the sync save's full stall is a span, not an instant
    assert math.isclose(ckpt[1]["dur"], 80.0 * 1e3)


def test_timeline_missing_run_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="telemetry-off"):
        timeline_lib.load_run_events(str(tmp_path))


def test_load_run_events_cites_file_lines_past_torn_records(tmp_path):
    """The doctor's evidence cites FILE lines: a torn fragment (hard-kill
    artifact the tolerant reader skips) must not shift every later
    citation off by one."""
    run = _write_run(tmp_path, [
        {"event": "run_start", "t_mono": 0.0},
        {"event": "window", "t_mono": 1.0, "steps": 2, "step_ms": 1.0},
    ])
    path = os.path.join(run, "telemetry", "events.jsonl")
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"torn fragm\n')  # line 3: malformed
        f.write(json.dumps({"event": "run_end", "t_mono": 2.0,
                            "t_wall": 0.0, "process": 0, "host": "h",
                            "pid": 7}) + "\n")  # line 4
    with pytest.warns(UserWarning, match="malformed"):
        events = timeline_lib.load_run_events(run)
    assert [e["_line"] for e in events] == [1, 2, 4]
    assert events[-1]["event"] == "run_end"


# ---------------------------------------------------------------------------
# Straggler sampling + anomaly kind.


def test_sample_arrivals_multichip(mesh):
    x = jax.device_put(
        np.float32(3.0),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    fields = straggler_lib.sample_arrivals({"loss": x})
    assert fields["chips_sampled"] == 8
    assert fields["chip_skew_ms"] >= 0.0
    assert fields["chip_wall_ms_max"] >= fields["chip_wall_ms_min"]
    assert fields["slowest_chip"] in {d.id for d in mesh.devices.flat}
    assert set(fields) == set(straggler_lib.FIELDS)


def test_sample_arrivals_degrades_to_empty():
    # host scalars / single-shard arrays: nothing to attribute
    assert straggler_lib.sample_arrivals({"loss": 3.0}) == {}
    single = jax.device_put(np.float32(1.0), jax.devices()[0])
    assert straggler_lib.sample_arrivals({"loss": single}) == {}
    assert straggler_lib.sample_arrivals({}) == {}


class _FakeShard:
    """Duck-typed shard whose block_until_ready sleeps — the only way to
    simulate a slow chip on a CPU backend."""

    class _Data:
        def __init__(self, delay):
            self._delay = delay

        def block_until_ready(self):
            import time

            time.sleep(self._delay)

    class _Device:
        def __init__(self, i):
            self.id = i

    def __init__(self, device_id, delay):
        self.device = self._Device(device_id)
        self.data = self._Data(delay)


class _FakeArray:
    def __init__(self, delays):
        self.addressable_shards = [_FakeShard(i, d) for i, d in enumerate(delays)]


def test_sample_arrivals_attributes_the_actually_slow_chip():
    """Incremental-delta attribution: the straggler is named wherever it
    sits in sampling order — including FIRST, where cumulative-elapsed
    timing would bill its tail to every later chip (and report near-zero
    skew with the last chip as 'slowest')."""
    fields = straggler_lib.sample_arrivals({"m": _FakeArray([0.05, 0.0, 0.0, 0.0])})
    assert fields["slowest_chip"] == 0
    assert fields["chip_skew_ms"] > 30.0
    fields = straggler_lib.sample_arrivals({"m": _FakeArray([0.0, 0.0, 0.05, 0.0])})
    assert fields["slowest_chip"] == 2
    assert fields["chip_skew_ms"] > 30.0


def test_straggler_ratio():
    assert straggler_lib.ratio(0.0, 10.0) == 1.0
    assert math.isclose(straggler_lib.ratio(10.0, 10.0), 2.0)
    assert straggler_lib.ratio(-5.0, 10.0) == 1.0  # clock noise clamps


def test_anomaly_straggler_floor_baselined():
    det = AnomalyDetector(warmup=2, straggler=1.5)
    # warmup observations never fire and never set the floor
    assert det.observe(0, straggler_ratio=5.0) == []
    assert det.observe(1, straggler_ratio=5.0) == []
    # first post-warmup observation seeds the floor
    assert det.observe(2, straggler_ratio=1.02) == []
    # under factor x floor: quiet; the floor can only move DOWN
    assert det.observe(3, straggler_ratio=1.4) == []
    found = det.observe(4, straggler_ratio=1.8)
    assert [a.kind for a in found] == ["straggler"]
    assert found[0].baseline == pytest.approx(1.02)
    # absent value never fires (single-chip hosts)
    assert det.observe(5, straggler_ratio=None) == []


# ---------------------------------------------------------------------------
# Doctor: deterministic verdicts on hand-built run dirs.


def _diagnose(tmp_path, records):
    run = _write_run(tmp_path, records)
    return doctor_lib.diagnose(timeline_lib.load_run_events(run))


def test_doctor_healthy(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=9.0, compile=3.0,
                                     data_wait=0.2, checkpoint=0.1)},
    ])
    assert d.healthy and d.verdict == "healthy"
    assert d.to_dict()["steady_fractions"]["compile"] == 0.0


def test_doctor_data_bound(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=2.0, compile=5.0,
                                     data_wait=3.0)},
    ])
    assert d.verdict == "data_bound" and not d.healthy
    top = d.verdicts[0]
    assert top.score == pytest.approx((3.0 / 5.0) / 0.20)
    assert any(r.get("metric") == "data_wait_frac_steady" for r in top.evidence)


def test_doctor_checkpoint_stall(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=2.0, checkpoint=2.0)},
    ])
    assert d.verdict == "checkpoint_stall"


def test_doctor_compile_bound_requires_late_compiles(tmp_path):
    # huge compile fraction alone (warmup) is NOT compile_bound...
    d = _diagnose(tmp_path, [
        {"event": "compile", "t_mono": 1.0, "epoch": 0, "executables": 2},
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=1.0, compile=50.0)},
    ])
    assert d.verdict == "healthy"
    # ...a steady-state retrace is
    d = _diagnose(tmp_path, [
        {"event": "compile", "t_mono": 1.0, "epoch": 2, "executables": 1},
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=5.0, compile=50.0)},
    ])
    assert d.verdict == "compile_bound"
    assert any(r.get("line") == 1 for r in d.verdicts[0].evidence)


def test_doctor_straggler_signals(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "anomaly", "t_mono": 1.0, "kind": "step_time_regression",
         "value": 0.5, "baseline": 0.01, "factor": 2.5},
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=5.0)},
    ])
    assert d.verdict == "straggler"
    # the worst window's ratio alone also fires
    d = _diagnose(tmp_path, [
        {"event": "window", "t_mono": 1.0, "steps": 4, "step_ms": 10.0,
         "straggler_ratio": 2.4, "chip_skew_ms": 14.0},
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=5.0)},
    ])
    assert d.verdict == "straggler"
    assert d.verdicts[0].score == pytest.approx(2.4 / 1.5)


def test_doctor_comm_heavy(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "profile_capture", "t_mono": 1.0, "span_us": 100.0,
         "categories": {"collective": 0.5, "conv": 0.3, "idle": 0.2}},
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=5.0)},
    ])
    assert d.verdict == "comm_heavy"
    assert d.verdicts[0].score == pytest.approx(0.5 / 0.25)


def test_doctor_ranking_most_severe_first(tmp_path):
    d = _diagnose(tmp_path, [
        {"event": "run_end", "t_mono": 9.0,
         "goodput_seconds": _goodput(productive_step=1.0, data_wait=8.0,
                                     checkpoint=5.0)},
    ])
    kinds = [v.kind for v in d.verdicts]
    # both fire; data_wait (8/14)/0.2 outranks checkpoint (5/14)/0.2
    assert kinds == ["data_bound", "checkpoint_stall"]
    assert d.verdicts[0].score > d.verdicts[-1].score


def test_doctor_scalar_fields_match_offline_rules():
    sig = doctor_lib.Signals(
        goodput_seconds=_goodput(productive_step=2.0, data_wait=3.0),
        anomaly_counts={"step_time_regression": 2},
    )
    scores = doctor_lib.scalar_fields(sig)
    assert scores["data_bound"] == pytest.approx((3.0 / 5.0) / 0.20)
    assert scores["straggler"] == pytest.approx(2.0)
    assert scores["healthy"] == 0.0
    quiet = doctor_lib.scalar_fields(doctor_lib.Signals(
        goodput_seconds=_goodput(productive_step=5.0)))
    assert quiet["healthy"] == 1.0 and quiet["data_bound"] == 0.0


def test_steady_fractions_exclude_warmup_buckets():
    fr = doctor_lib.steady_fractions(_goodput(
        productive_step=1.0, compile=97.0, restart_rollback=1.0,
        checkpoint_async=1.0, data_wait=1.0))
    assert fr["compile"] == 0.0 and fr["restart_rollback"] == 0.0
    assert fr["productive_step"] == pytest.approx(0.5)
    assert fr["data_wait"] == pytest.approx(0.5)
    assert doctor_lib.steady_fractions({}) == {b: 0.0 for b in doctor_lib.BUCKETS}


# ---------------------------------------------------------------------------
# data_wait gate: the one rule, boundary-exact.


def test_data_wait_gate_boundary():
    # pass exactly at ceiling*(1+tol); fail epsilon above
    at = gate_lib.check(0.125, 0.10, 0.25, key="k", metric="data_wait_frac")
    assert at.passed
    over = gate_lib.check(0.125 + 1e-9, 0.10, 0.25, key="k", metric="data_wait_frac")
    assert not over.passed
    assert "data_wait_frac" in over.describe()


def test_data_wait_gate_metric_selection_and_stale():
    baseline = {"entries": {"k": {"data_wait_frac": 0.10}},
                "tolerance": {"k": 0.25}}
    res = gate_lib.evaluate(baseline, "k", {"data_wait_frac": 0.01})
    assert res.metric == "data_wait_frac" and res.passed
    # sitting far under a ceiling is healthy, never a stale-baseline nudge
    assert res.stale is False
    # step_per_calib still wins when both sides carry it
    both = {"entries": {"k": {"data_wait_frac": 0.10, "step_per_calib": 1.0}},
            "tolerance": {"k": 0.25}}
    res = gate_lib.evaluate(both, "k",
                            {"data_wait_frac": 0.01, "step_per_calib": 1.1})
    assert res.metric == "step_per_calib"


def test_perf_gate_refuses_conflicting_injection_flags():
    """Flag validation happens BEFORE any measurement (the PR 6 rule):
    --data-wait with --inject-slowdown must be an instant argparse error,
    not a post-run KeyError."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_gate.py"),
         "--data-wait", "--inject-slowdown", "3"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 2  # argparse error exit
    assert "--inject-data-wait" in out.stderr


def test_committed_data_wait_baseline_entry():
    """The committed PERF_BASELINE.json carries a usable data-wait ceiling
    (self-parity: the gate could actually gate with it)."""
    baseline = gate_lib.load_baseline()
    entry = baseline["entries"]["data-wait-cpu"]
    assert entry["data_wait_frac"] > 0
    assert "data-wait-cpu" in baseline.get("tolerance", {})
    res = gate_lib.evaluate(baseline, "data-wait-cpu", {"data_wait_frac": 0.01})
    assert res.metric == "data_wait_frac" and res.passed


# ---------------------------------------------------------------------------
# Event schema + vocabulary doc drift (the PR 6 AST-dedup pattern).


def _emitted_event_kinds():
    """AST-scan the package + scripts + bench for ``<events>.emit("kind")``
    call sites (EventLog receivers only: ``events`` / ``_events`` /
    ``event_log`` attributes or a direct ``EventLog(...)`` ctor call —
    analysis/lint.py's unrelated ``self.emit`` never matches)."""
    kinds = {}
    roots = [PKG, os.path.join(REPO, "scripts"), os.path.join(REPO, "bench.py")]
    files = []
    for root in roots:
        if root.endswith(".py"):
            files.append(root)
            continue
        for dirpath, _, names in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            files.extend(os.path.join(dirpath, n) for n in names if n.endswith(".py"))
    for path in files:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"):
                continue
            recv = node.func.value
            is_log = (
                (isinstance(recv, ast.Attribute)
                 and recv.attr in ("events", "_events", "event_log"))
                or (isinstance(recv, ast.Name)
                    and recv.id in ("events", "_events", "event_log"))
                or (isinstance(recv, ast.Call) and (
                    (isinstance(recv.func, ast.Name) and recv.func.id == "EventLog")
                    or (isinstance(recv.func, ast.Attribute)
                        and recv.func.attr == "EventLog")))
            )
            if not is_log or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                kinds.setdefault(first.value, []).append(path)
    return kinds


def test_every_emitted_event_kind_is_documented():
    kinds = _emitted_event_kinds()
    # sanity: the scan actually found the core vocabulary
    assert {"run_start", "window", "checkpoint_save", "anomaly",
            "run_doctor"} <= set(kinds)
    with open(os.path.join(REPO, "docs", "observability.md"), encoding="utf-8") as f:
        table_lines = [ln for ln in f if ln.lstrip().startswith("|")]
    missing = [
        k for k in kinds
        if not any(f"`{k}`" in ln for ln in table_lines)
    ]
    assert not missing, (
        f"event kinds emitted but absent from the docs/observability.md "
        f"vocabulary table: {missing} (emitted at "
        f"{[kinds[k][0] for k in missing]}) — doc drift is a test failure"
    )


def test_every_record_carries_schema_and_chips(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, process_index=0)
    log.emit("run_start", epoch=0)
    log.emit("anomaly", kind="loss_spike")
    log.close()
    records = list(read_events(path))
    assert len(records) == 2
    for rec in records:
        assert rec["schema"] == SCHEMA_VERSION
        assert "chips" in rec and isinstance(rec["chips"], str)


# ---------------------------------------------------------------------------
# Trainer integration: straggler fields on, historical program untouched.


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))


class TinyTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(48,)).astype(np.int32)
        images = (rng.randn(48, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return TinyNet()

    def build_criterion(self):
        def crit(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return crit

    def build_optimizer(self, schedule):
        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


class _Quiet:
    def log(self, *a, **k):
        pass


def make_tiny(tmp_path, mesh, **kw):
    defaults = dict(
        max_epoch=2,
        batch_size=8,
        have_validate=False,
        save_best_for=None,
        save_period=None,
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=2,
        chain_steps=2,
        async_checkpoint=False,
        mesh=mesh,
        progress=False,
        logger=_Quiet(),
    )
    defaults.update(kw)
    return TinyTrainer(**defaults)


@pytest.fixture(scope="module")
def straggler_run(tmp_path_factory, mesh):
    tmp = tmp_path_factory.mktemp("straggler_run")
    trainer = make_tiny(tmp, mesh, telemetry="on")
    trainer.train()
    events = list(read_events(
        os.path.join(trainer.save_folder, "telemetry", "events.jsonl")))
    return trainer, events


def test_window_events_carry_straggler_fields(straggler_run):
    trainer, events = straggler_run
    windows = [e for e in events if e["event"] == "window"]
    assert windows
    for w in windows:
        assert w["chips_sampled"] == 8
        assert w["chip_skew_ms"] >= 0.0
        assert w["straggler_ratio"] >= 1.0
    # epoch_end carries the last window's skew + the goodput snapshot
    epoch_end = [e for e in events if e["event"] == "epoch_end"][-1]
    assert "chip_skew_ms" in epoch_end
    assert set(epoch_end["goodput_seconds"]) == set(doctor_lib.BUCKETS)
    # run_start anchors the timeline's goodput chain
    assert "goodput_seconds" in events[0] and events[0]["event"] == "run_start"


def test_straggler_off_removes_fields(tmp_path, mesh):
    trainer = make_tiny(tmp_path, mesh, telemetry=Telemetry(straggler=False))
    trainer.train()
    events = list(read_events(
        os.path.join(trainer.save_folder, "telemetry", "events.jsonl")))
    for w in (e for e in events if e["event"] == "window"):
        assert "chip_skew_ms" not in w and "straggler_ratio" not in w


def test_straggler_on_is_historical_program(tmp_path, mesh, straggler_run):
    """THE parity pillar: straggler sampling (and the goodput snapshots /
    doctor counters riding the same syncs) observes the run — trace_counts
    and final params bit-identical with telemetry=None."""
    on, _ = straggler_run
    off = make_tiny(tmp_path, mesh, telemetry=None)
    off.train()
    assert dict(off.engine.trace_counts) == dict(on.engine.trace_counts)
    for a, b in zip(jax.tree.leaves(off.state.params),
                    jax.tree.leaves(on.state.params), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_timeline_exports_from_real_run(straggler_run):
    trainer, _ = straggler_run
    trace, path = timeline_lib.export_timeline(trainer.save_folder)
    with open(path, encoding="utf-8") as f:
        reparsed = json.load(f)
    derived = timeline_lib.span_bucket_seconds(reparsed)
    want = trainer.goodput.to_state()
    total_d, total_w = sum(derived.values()), sum(want.values())
    assert total_d > 0
    for bucket, w in want.items():
        assert abs(derived[bucket] / total_d - w / total_w) < 1e-6, bucket
    # steps lane exists and is monotone
    steps = _lane_spans(reparsed, timeline_lib.TRACKS["steps"])
    assert steps
    for a, b in zip(steps, steps[1:], strict=False):
        assert b["ts"] >= a["ts"] + a["dur"] - 1e-6
