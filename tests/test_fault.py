"""Fault-tolerance subsystem tests (fault/ + crash-consistent checkpointing +
verified resume + graceful degradation).

The two acceptance pillars:

* kill training mid-epoch with an injected (real) SIGTERM, resume from the
  auto-saved snapshot, and land BIT-EXACT on the uninterrupted run's params;
* corrupt the newest checkpoint on disk and watch restore fall back to the
  newest *valid* one instead of crashing — with saves atomic throughout
  (a failed save never damages the previously-committed checkpoint).
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.checkpoint import (
    LAST,
    CheckpointError,
    CheckpointManager,
    CorruptCheckpointError,
)
from distributed_training_pytorch_tpu.data import ArrayDataSource, ShardedLoader
from distributed_training_pytorch_tpu.data.records import (
    CorruptRecordError,
    RecordFileSource,
)
from distributed_training_pytorch_tpu.fault import (
    CorruptingSource,
    FaultPlan,
    StepWatchdog,
    corrupt_checkpoint,
)
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.train import NonFiniteLossError, TrainState

from test_trainer import make_trainer, synthetic_images


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


# ---------------------------------------------------------------------------
# CheckpointManager: atomic commits, integrity, retry, newest-valid fallback.
# A bare TrainState avoids the ~20s model-compile cost of the trainer tests.


def _tiny_state(seed=0, step=0):
    rng = np.random.RandomState(seed)
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params={"w": jnp.asarray(rng.randn(4, 3), jnp.float32)},
        opt_state={"m": jnp.zeros((4, 3), jnp.float32)},
        model_state={},
        rng=jax.random.key(seed),
    )


def test_manifest_validate_and_corruption_modes(tmp_path):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, _tiny_state(), epoch=1)
    mgr.validate(LAST)  # fresh commit passes

    corrupt_checkpoint(mgr.path(LAST), mode="flip")
    with pytest.raises(CorruptCheckpointError, match="hash mismatch"):
        mgr.validate(LAST)
    with pytest.raises(CorruptCheckpointError):
        mgr.restore(LAST, _tiny_state(seed=9))

    mgr.save(LAST, _tiny_state(), epoch=1)  # overwrite repairs
    corrupt_checkpoint(mgr.path(LAST), mode="truncate")
    with pytest.raises(CorruptCheckpointError, match="torn write"):
        mgr.validate(LAST)

    mgr.save(LAST, _tiny_state(), epoch=1)
    corrupt_checkpoint(mgr.path(LAST), mode="delete")
    with pytest.raises(CorruptCheckpointError, match="missing file"):
        mgr.validate(LAST)
    mgr.close()


def test_corrupt_latest_falls_back_to_newest_valid(tmp_path):
    """The acceptance scenario: latest checkpoint corrupt -> restore falls
    back to the previous valid one instead of crashing."""
    state1, state2 = _tiny_state(seed=1, step=10), _tiny_state(seed=2, step=20)
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save("checkpoint_epoch_1", state1, epoch=1)
    time.sleep(0.05)  # distinct mtimes for newest-first ordering
    mgr.save(LAST, state2, epoch=2)

    corrupt_checkpoint(mgr.path(LAST), mode="truncate")
    restored, epoch, name = mgr.restore_latest_valid(_tiny_state(seed=9))
    assert name == "checkpoint_epoch_1" and epoch == 1
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.asarray(state1.params["w"])
    )
    mgr.close()

    # nothing valid at all -> a catchable CheckpointError, not a crash
    mgr2 = CheckpointManager(tmp_path / "c2", async_save=False)
    mgr2.save(LAST, state1, epoch=1)
    corrupt_checkpoint(mgr2.path(LAST), mode="flip")
    with pytest.raises(CheckpointError):
        mgr2.restore_latest_valid(_tiny_state(seed=9))
    mgr2.close()


def test_transient_write_failure_retries_then_succeeds(tmp_path):
    plan = FaultPlan().add("checkpoint_write", count=2)
    mgr = CheckpointManager(
        tmp_path / "c",
        async_save=False,
        save_retries=2,
        retry_backoff=0.01,
        fault_plan=plan,
    )
    mgr.save(LAST, _tiny_state(step=5), epoch=3)  # attempts 1+2 fail, 3 lands
    assert plan.count_fired("checkpoint_write") == 2
    mgr.validate(LAST)
    _, epoch = mgr.restore(LAST, _tiny_state(seed=9))
    assert epoch == 3
    mgr.close()


def test_failed_save_is_atomic_old_checkpoint_survives(tmp_path):
    """A save that exhausts its retries must leave the previously committed
    checkpoint fully intact under the final name (atomicity guarantee)."""
    state_good = _tiny_state(seed=1, step=1)
    plan = FaultPlan()
    mgr = CheckpointManager(
        tmp_path / "c",
        async_save=False,
        save_retries=1,
        retry_backoff=0.01,
        fault_plan=plan,
    )
    mgr.save(LAST, state_good, epoch=1)  # clean commit
    plan.add("checkpoint_write", count=10)  # now every attempt fails
    with pytest.raises(CheckpointError, match="failed after 2 attempts"):
        mgr.save(LAST, _tiny_state(seed=2, step=2), epoch=2)
    mgr.validate(LAST)  # old checkpoint still valid under the final name
    restored, epoch = mgr.restore(LAST, _tiny_state(seed=9))
    assert epoch == 1
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.asarray(state_good.params["w"])
    )
    mgr.close()


def test_first_save_write_failure_raises_and_leaves_nothing(tmp_path):
    plan = FaultPlan().add("checkpoint_write", count=10)
    mgr = CheckpointManager(
        tmp_path / "c",
        async_save=False,
        save_retries=1,
        retry_backoff=0.01,
        fault_plan=plan,
    )
    with pytest.raises(CheckpointError):
        mgr.save(LAST, _tiny_state(), epoch=1)
    assert not mgr.exists(LAST)  # no partial checkpoint under the final name
    mgr.close()


def test_crash_mid_swap_recovers_on_next_manager(tmp_path):
    """Crash between the two commit renames leaves only `<name>.old`; the
    next manager construction rolls it back."""
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, _tiny_state(step=7), epoch=4)
    mgr.close()
    final = os.path.join(str(tmp_path / "c"), LAST)
    os.rename(final, final + ".old")  # simulate the crash window

    mgr2 = CheckpointManager(tmp_path / "c", async_save=False)
    assert mgr2.exists(LAST)
    mgr2.validate(LAST)
    _, epoch = mgr2.restore(LAST, _tiny_state(seed=9))
    assert epoch == 4
    mgr2.close()


def test_loop_state_round_trips_through_meta(tmp_path):
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, _tiny_state(), epoch=2, loop_state={"step_in_epoch": 3})
    assert mgr.read_meta(LAST)["loop"] == {"step_in_epoch": 3}
    mgr.close()


# ---------------------------------------------------------------------------
# Data-path degradation: corrupt records skip-and-count.


def _write_shard(tmp_path, n=12):
    import cv2

    from distributed_training_pytorch_tpu.data.records import write_shards

    def records():
        rng = np.random.RandomState(0)
        for i in range(n):
            img = rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
            ok, buf = cv2.imencode(".png", img)
            assert ok
            yield buf.tobytes(), i % 3

    return write_shards(str(tmp_path / "train"), records(), num_shards=1)[0]


def _corrupt_record_length(path, source, index):
    """Overwrite record `index`'s length field with garbage (structural
    corruption: payload would overrun the shard's payload region)."""
    offset = int(source._shard_offsets[0][index])
    with open(path, "rb+") as f:
        f.seek(offset + 8)  # label i64 then length u64
        f.write((2**40).to_bytes(8, "little"))


def test_corrupt_record_raises_typed_error(tmp_path):
    pytest.importorskip("cv2")
    shard = _write_shard(tmp_path)
    src = RecordFileSource(shard)
    _corrupt_record_length(shard, src, 5)
    with pytest.raises(CorruptRecordError, match="overruns"):
        src[5]
    assert src[4]["image"].shape == (8, 8, 3)  # neighbors unaffected


def test_loader_skips_and_counts_corrupt_records(tmp_path):
    pytest.importorskip("cv2")
    shard = _write_shard(tmp_path)
    src = RecordFileSource(shard)
    _corrupt_record_length(shard, src, 5)
    loader = ShardedLoader(
        src,
        4,
        shuffle=False,
        num_workers=0,
        skip_corrupt=True,
        process_index=0,
        process_count=1,
    )
    batches = list(loader)
    assert len(batches) == 3  # every batch produced despite the bad record
    assert src.corrupt_skipped == 1
    # substitution is deterministic: a second epoch pass skips the same way
    batches2 = list(loader)
    np.testing.assert_array_equal(batches[1]["image"], batches2[1]["image"])

    strict = ShardedLoader(
        RecordFileSource(shard), 4, shuffle=False, num_workers=0,
        process_index=0, process_count=1,
    )
    with pytest.raises(CorruptRecordError):
        list(strict)


def test_fast_path_batch_decode_tolerance(tmp_path):
    """Whole-batch (native fast path) decode failures degrade like the
    per-record path: the bad position's (payload, label) pair is substituted
    by the next readable record and counted."""
    pytest.importorskip("cv2")
    from distributed_training_pytorch_tpu.data.native import DecodeError

    shard = _write_shard(tmp_path)
    src = RecordFileSource(shard, skip_corrupt=True)
    rows = np.arange(4)
    payloads, labels = map(list, zip(*(src.read_record(int(i)) for i in rows), strict=True))
    bad_payload = payloads[2]

    def produce(pls):
        if pls[2] == bad_payload:  # "bit-rot": this payload never decodes
            raise DecodeError(2)
        return np.zeros((4, 8, 8, 3), np.uint8)

    out = src._produce_batch_tolerant(rows, payloads, labels, produce)
    assert out.shape == (4, 8, 8, 3)
    assert src.corrupt_skipped == 1
    assert (payloads[2], labels[2]) == src.read_record(3)  # neighbor pair

    strict = RecordFileSource(shard)
    p2, l2 = map(list, zip(*(strict.read_record(int(i)) for i in rows), strict=True))
    with pytest.raises(CorruptRecordError):
        strict._produce_batch_tolerant(rows, p2, l2, produce)


def test_completed_async_staging_promoted_on_recovery(tmp_path):
    """A finished-but-uncommitted write (process died between the async
    write's completion and the next wait()) is promoted on the next manager
    construction, not discarded."""
    mgr = CheckpointManager(tmp_path / "c", async_save=False)
    mgr.save(LAST, _tiny_state(step=3), epoch=2)
    mgr.close()
    final = os.path.join(str(tmp_path / "c"), LAST)
    staging_root = os.path.join(str(tmp_path / "c"), ".staging")
    os.makedirs(staging_root)
    os.rename(final, os.path.join(staging_root, "last.9"))
    os.remove(os.path.join(staging_root, "last.9", "manifest.dtp.json"))

    mgr2 = CheckpointManager(tmp_path / "c", async_save=False)
    assert mgr2.exists(LAST)
    mgr2.validate(LAST)
    _, epoch = mgr2.restore(LAST, _tiny_state(seed=9))
    assert epoch == 2
    mgr2.close()


def test_latest_valid_cold_start(tmp_path, mesh):
    """snapshot_path='latest_valid' on a first launch (nothing saved yet)
    must start fresh, not raise — the restart wrapper is idempotent."""
    trainer = make_trainer(
        tmp_path, mesh, max_epoch=1, have_validate=False, save_best_for=None,
        save_period=None, snapshot_path="latest_valid",
    )
    assert trainer.cur_epoch == 0


def test_injected_corrupt_record_via_fault_plan():
    images, labels = synthetic_images(16, seed=0)
    plan = FaultPlan().add("corrupt_record", step=5)
    src = CorruptingSource(ArrayDataSource(image=images, label=labels), plan)
    loader = ShardedLoader(
        src, 4, shuffle=False, num_workers=0, skip_corrupt=True,
        process_index=0, process_count=1,
    )
    assert len(list(loader)) == 4
    assert loader.corrupt_skipped == 1
    assert plan.count_fired("corrupt_record") == 1


# ---------------------------------------------------------------------------
# Watchdog.


def test_watchdog_fires_on_stall_and_not_on_progress():
    fired = []
    with StepWatchdog(0.08, lambda: fired.append(1), poll_interval=0.02) as dog:
        for _ in range(5):  # regular pats: no fire
            time.sleep(0.03)
            dog.pat()
        assert not fired
        time.sleep(0.2)  # stall: fires exactly once (max_fires=1)
    assert fired == [1]
    assert dog.fired == 1


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        StepWatchdog(0)


# ---------------------------------------------------------------------------
# Trainer integration: kill/resume bit-exactness, NaN policies, hung step.


def test_sigterm_mid_epoch_resume_is_bit_exact(tmp_path, mesh):
    """THE acceptance test: epoch 1 is killed at step 2 by an injected (real)
    SIGTERM; the run resumes from the auto-saved snapshot and finishes with
    params BIT-EXACT to an uninterrupted run's."""
    kw = dict(
        max_epoch=2, have_validate=False, save_best_for=None, save_period=None
    )
    baseline = make_trainer(tmp_path / "a", mesh, **kw)
    baseline.train()

    plan = FaultPlan().add("sigterm", epoch=1, step=2)
    interrupted = make_trainer(tmp_path / "b", mesh, fault_plan=plan, **kw)
    interrupted.train()
    assert interrupted._preempted and interrupted._epoch_interrupted
    assert interrupted.checkpoints.exists(LAST)
    meta = interrupted.checkpoints.read_meta(LAST)
    assert meta["epoch"] == 1 and meta["loop"] == {"step_in_epoch": 2}

    resumed = make_trainer(
        tmp_path / "b",
        mesh,
        snapshot_path=interrupted.checkpoints.path(LAST),
        **kw,
    )
    assert resumed.cur_epoch == 1 and resumed._resume_step_in_epoch == 2
    resumed.train()

    assert int(resumed.state.step) == int(baseline.state.step)
    for a, b in zip(
        jax.tree.leaves(baseline.state.params),
        jax.tree.leaves(resumed.state.params),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(baseline.state.opt_state),
        jax.tree.leaves(resumed.state.opt_state),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sigterm_resume_crosses_window_boundary_chained(tmp_path, mesh):
    """Chained-mode preemption acceptance (ISSUE 2): a chain_steps=4 run is
    killed by an injected (real) SIGTERM at epoch 1, step 2 — inside the
    fault-active window [0,4), which therefore runs single-step, preserving
    exact per-step interruption semantics. The resume then REALIGNS: steps
    2-3 run single-step until the next window boundary, and [4,8) chains —
    finishing bit-exact with an uninterrupted chain_steps=1 run."""
    kw = dict(
        max_epoch=2, batch_size=8, have_validate=False, save_best_for=None,
        save_period=None,
    )
    baseline = make_trainer(tmp_path / "a", mesh, **kw)
    baseline.train()

    plan = FaultPlan().add("sigterm", epoch=1, step=2)
    interrupted = make_trainer(
        tmp_path / "b", mesh, chain_steps=4, fault_plan=plan, **kw
    )
    interrupted.train()
    assert interrupted._preempted and interrupted._epoch_interrupted
    assert interrupted.checkpoints.exists(LAST)
    meta = interrupted.checkpoints.read_meta(LAST)
    assert meta["epoch"] == 1 and meta["loop"] == {"step_in_epoch": 2}
    # epoch 0 had no pending injections: it really chained (2 windows of 4)
    assert interrupted.engine.trace_counts["chained_4"] == 1

    resumed = make_trainer(
        tmp_path / "b",
        mesh,
        chain_steps=4,
        snapshot_path=interrupted.checkpoints.path(LAST),
        **kw,
    )
    assert resumed.cur_epoch == 1 and resumed._resume_step_in_epoch == 2
    resumed.train()

    assert int(resumed.state.step) == int(baseline.state.step)
    for a, b in zip(
        jax.tree.leaves(baseline.state.params),
        jax.tree.leaves(resumed.state.params),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(baseline.state.opt_state),
        jax.tree.leaves(resumed.state.opt_state),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # realignment shape: 2 lead singles (steps 2-3), then ONE chained window
    assert resumed.engine.trace_counts["train_step"] == 1
    assert resumed.engine.trace_counts["chained_4"] == 1


def test_nan_policy_raise(tmp_path, mesh):
    plan = FaultPlan().add("nan_loss", epoch=0, step=1)
    trainer = make_trainer(
        tmp_path, mesh, max_epoch=1, have_validate=False, save_best_for=None,
        save_period=None, nan_policy="raise", fault_plan=plan,
    )
    with pytest.raises(NonFiniteLossError):
        trainer.train()


def test_nan_policy_skip_preserves_params_and_counts(tmp_path, mesh):
    plan = FaultPlan().add("nan_loss", epoch=0, step=1)
    trainer = make_trainer(
        tmp_path, mesh, max_epoch=1, have_validate=False, save_best_for=None,
        save_period=None, nan_policy="skip", fault_plan=plan,
    )
    trainer.train()
    assert trainer.nonfinite_steps == 1
    assert plan.count_fired("nan_loss") == 1
    # the poisoned step was dropped, not absorbed: params stayed finite
    for leaf in jax.tree.leaves(trainer.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_hung_step_watchdog_forces_resumable_save(tmp_path, mesh):
    """A simulated hung step (fault 'hang') trips the step watchdog, which
    SIGTERMs the process; the preemption machinery turns that into a
    resumable mid-epoch save."""
    plan = FaultPlan().add("hang", epoch=0, step=1, payload=0.8)
    trainer = make_trainer(
        tmp_path, mesh, max_epoch=1, have_validate=False, save_best_for=None,
        save_period=None, step_timeout=0.2, fault_plan=plan,
    )
    trainer.train()
    assert trainer._preempted
    assert trainer.checkpoints.exists(LAST)
    meta = trainer.checkpoints.read_meta(LAST)
    assert meta["loop"]["step_in_epoch"] == 1  # step 0 done, step 1 hung
