"""ops/dispatch.py — the single kernel-policy decision point (ISSUE 17).

Covers the knob grammar (``PALLAS`` env parse, tri-state resolve), the
one-time ``kernel_dispatch`` recording contract (dedup, buffer-then-flush
into an event sink), the per-model routing, and the two acceptance
invariants: the OFF path reproduces the historical program bit-exactly
(params AND outputs), and toggling the kernel knob recompiles exactly once
per shape.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_pytorch_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _fresh_dispatch():
    dispatch.reset()
    yield
    dispatch.reset()


# ---------------------------------------------------------------------------
# knob grammar
# ---------------------------------------------------------------------------


def test_pallas_from_env_parse():
    assert dispatch.pallas_from_env({"PALLAS": "1"}) is True
    assert dispatch.pallas_from_env({"PALLAS": "0"}) is False
    assert dispatch.pallas_from_env({}) is None
    assert dispatch.pallas_from_env({"PALLAS": ""}) is None
    assert dispatch.pallas_from_env({}, default=True) is True
    with pytest.raises(ValueError):
        dispatch.pallas_from_env({"PALLAS": "yes"})


def test_resolve_tri_state():
    assert dispatch.resolve(True, False) is True
    assert dispatch.resolve(False, True) is False
    assert dispatch.resolve(None, "legacy") == "legacy"


# ---------------------------------------------------------------------------
# one-time recording + sink
# ---------------------------------------------------------------------------


def test_record_dedups_per_process():
    assert dispatch.record("m", "op", "plain", reason="r") is True
    assert dispatch.record("m", "op", "plain", reason="r") is False
    assert dispatch.record("m", "op", "pallas", reason="r") is True  # new path
    paths = {(r["model"], r["op"], r["path"]) for r in dispatch.records()}
    assert paths == {("m", "op", "plain"), ("m", "op", "pallas")}


def test_decisions_buffer_then_flush_into_the_sink():
    """Decisions made while building the model (before the Trainer installs
    EventLog.emit) must still land in the run's event log."""
    dispatch.record("m", "op", "plain", reason="before-sink", seq_len=7)
    got = []
    dispatch.set_event_sink(lambda event, **f: got.append((event, f)))
    assert [(e, f["reason"]) for e, f in got] == [
        ("kernel_dispatch", "before-sink")]
    assert got[0][1]["seq_len"] == 7
    dispatch.record("m", "op2", "flash", reason="live")
    assert [f["reason"] for _, f in got] == ["before-sink", "live"]
    # dedup state survives sink teardown (one-time per process, not per run)
    dispatch.clear_event_sink()
    assert dispatch.record("m", "op2", "flash", reason="live") is False


# ---------------------------------------------------------------------------
# routing policies
# ---------------------------------------------------------------------------


def test_attention_fn_routing_on_cpu():
    # explicit off: plain, named
    assert dispatch.attention_fn("vit", False) is None
    # auto on a non-TPU backend: plain, named with the backend
    assert dispatch.attention_fn("vit", None) is None
    reasons = {r["reason"] for r in dispatch.records()}
    assert "pallas=False" in reasons
    assert any(r.startswith("auto: backend=") for r in reasons)
    # forced on: a callable that records the flash path per actual length
    fn = dispatch.attention_fn("vit", True)
    assert fn is not None
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32) for _ in range(3))
    out = fn(q, k, v)
    assert out.shape == q.shape
    flash = [r for r in dispatch.records() if r["path"] == "flash"]
    assert flash and flash[0]["reason"] == "pallas=True (forced)"
    assert flash[0]["seq_len"] == 8


def test_attention_fn_names_the_short_sequence_fall_through(monkeypatch):
    """The formerly-silent fall-through: auto mode below FLASH_MIN_SEQ_LEN
    routes to plain — same routing as ever, now with a named record.
    Backend pinned to 'tpu' so auto mode builds the thresholded adapter; the
    short sequence then takes make_attention_fn's plain branch (CPU-safe)."""
    from distributed_training_pytorch_tpu.ops.pallas import FLASH_MIN_SEQ_LEN

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    fn = dispatch.attention_fn("vit", None)
    assert fn is not None
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32) for _ in range(3))
    out = fn(q, k, v)
    assert out.shape == q.shape
    entry = [r for r in dispatch.records() if r.get("seq_len") == 8][0]
    assert entry["path"] == "plain"
    assert entry["reason"] == f"T=8 < FLASH_MIN_SEQ_LEN={FLASH_MIN_SEQ_LEN}"


def test_lm_attention_impl_mapping():
    assert dispatch.lm_attention_impl("auto", True) == "flash"
    assert dispatch.lm_attention_impl("auto", False) == "plain"
    assert dispatch.lm_attention_impl("auto", None) == "auto"
    assert dispatch.lm_attention_impl("ring", None) == "ring"


def test_conv1x1_policy_auto_stays_off_and_is_named():
    assert dispatch.conv1x1_policy("resnet", None) is False
    assert dispatch.conv1x1_policy("resnet", True) is True
    assert dispatch.conv1x1_policy("resnet", False, legacy=True) is False
    assert dispatch.conv1x1_policy("resnet", None, legacy=True) is True
    by_reason = {r["reason"]: r["path"] for r in dispatch.records()}
    assert by_reason["pallas=True"] == "pallas"
    assert by_reason["pallas=False"] == "plain"
    assert by_reason["legacy knob"] == "pallas"
    assert any("opt in" in r or "auto" in r for r in by_reason)


def test_model_builds_record_their_resolutions():
    from distributed_training_pytorch_tpu.models import ConvNeXtTiny, ResNet18Slim

    x = jnp.ones((1, 16, 16, 3), jnp.float32)
    ResNet18Slim(num_classes=4).init(jax.random.key(0), x)
    ConvNeXtTiny(num_classes=4).init(jax.random.key(0), x)
    seen = {(r["model"], r["op"], r["path"]) for r in dispatch.records()}
    assert ("resnet", "conv1x1_bn_act", "plain") in seen
    assert ("convnext", "dense_gelu", "plain") in seen


def test_vgg_records_the_no_coverage_no_op():
    from distributed_training_pytorch_tpu.models import create_model

    create_model("vgg16", 4, pallas=True)
    seen = [r for r in dispatch.records() if r["model"] == "vgg16"]
    assert seen and seen[0]["path"] == "plain"
    assert "no fused-kernel coverage" in seen[0]["reason"]


# ---------------------------------------------------------------------------
# acceptance invariants: OFF is bit-exact; toggling recompiles once per shape
# ---------------------------------------------------------------------------


def _bit_equal_trees(a, b):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for la, lb in zip(flat_a, flat_b, strict=True):
        assert la.dtype == lb.dtype and la.shape == lb.shape
        assert np.array_equal(np.asarray(la), np.asarray(lb)), "bit drift"


@pytest.mark.parametrize("factory", ["resnet", "convnext", "vit"])
def test_pallas_off_reproduces_the_historical_program_bit_exactly(factory):
    """pallas=False and the unset default produce bit-identical params AND
    outputs — PALLAS=0 is the historical program, not a near miss."""
    from distributed_training_pytorch_tpu.models import (
        ConvNeXtTiny,
        ResNet18Slim,
        ViTTiny,
    )

    make = {"resnet": ResNet18Slim, "convnext": ConvNeXtTiny, "vit": ViTTiny}[factory]
    x = jnp.linspace(0, 1, 1 * 16 * 16 * 3, dtype=jnp.float32).reshape(1, 16, 16, 3)
    legacy = make(num_classes=4)
    off = make(num_classes=4, pallas=False)
    v_legacy = legacy.init(jax.random.key(0), x)
    v_off = off.init(jax.random.key(0), x)
    _bit_equal_trees(v_legacy, v_off)
    out_legacy = legacy.apply(v_legacy, x)
    out_off = off.apply(v_off, x)
    assert np.array_equal(np.asarray(out_legacy), np.asarray(out_off))


def test_convnext_pallas_param_tree_is_knob_invariant():
    """Flipping the ConvNeXt kernel knob changes the program, never the
    param tree: bit-identical init (PallasDenseAct pins nn.Dense's names,
    shapes, and initializers), near-identical forward."""
    from distributed_training_pytorch_tpu.models import ConvNeXtTiny

    x = jnp.linspace(-1, 1, 2 * 16 * 16 * 3, dtype=jnp.float32).reshape(2, 16, 16, 3)
    plain = ConvNeXtTiny(num_classes=4, pallas=False)
    fused = ConvNeXtTiny(num_classes=4, pallas=True)
    v_plain = plain.init(jax.random.key(0), x)
    v_fused = fused.init(jax.random.key(0), x)
    _bit_equal_trees(v_plain, v_fused)  # same tree -> checkpoints interchange
    np.testing.assert_allclose(
        np.asarray(fused.apply(v_plain, x)),
        np.asarray(plain.apply(v_plain, x)),
        atol=2e-5,
    )


def test_toggling_the_kernel_knob_recompiles_exactly_once_per_shape():
    """trace_counts contract: each knob setting is one program — repeated
    calls at a shape never retrace, a new shape traces exactly once more."""
    from distributed_training_pytorch_tpu.models import ConvNeXtTiny

    x1 = jnp.ones((1, 16, 16, 3), jnp.float32)
    x2 = jnp.ones((2, 16, 16, 3), jnp.float32)
    variables = ConvNeXtTiny(num_classes=4, pallas=False).init(jax.random.key(0), x1)
    for knob in (False, True):
        model = ConvNeXtTiny(num_classes=4, pallas=knob)
        count = [0]

        def fn(v, x, model=model, count=count):
            count[0] += 1
            return model.apply(v, x)

        jfn = jax.jit(fn)
        jfn(variables, x1), jfn(variables, x1)
        assert count[0] == 1, f"pallas={knob}: retrace at a seen shape"
        jfn(variables, x2), jfn(variables, x2)
        assert count[0] == 2, f"pallas={knob}: new shape must trace once"
