import jax.numpy as jnp
import numpy as np
from sklearn.metrics import top_k_accuracy_score

from distributed_training_pytorch_tpu.ops import (
    cross_entropy_loss,
    multistep_lr,
    top_k_accuracy,
    warmup_cosine_lr,
)
from distributed_training_pytorch_tpu.ops.losses import (
    softmax_cross_entropy_with_integer_labels,
)


def test_cross_entropy_matches_manual():
    logits = jnp.asarray([[2.0, 0.0, -1.0], [0.0, 0.0, 0.0]])
    labels = jnp.asarray([0, 2])
    per_ex = softmax_cross_entropy_with_integer_labels(logits, labels)
    expected0 = -np.log(np.exp(2.0) / (np.exp(2.0) + 1 + np.exp(-1.0)))
    np.testing.assert_allclose(np.asarray(per_ex), [expected0, np.log(3.0)], rtol=1e-6)
    np.testing.assert_allclose(
        float(cross_entropy_loss(logits, labels)), (expected0 + np.log(3.0)) / 2, rtol=1e-6
    )


def test_label_smoothing_increases_loss_on_confident_preds():
    logits = jnp.asarray([[10.0, 0.0, 0.0]])
    labels = jnp.asarray([0])
    plain = float(cross_entropy_loss(logits, labels))
    smoothed = float(cross_entropy_loss(logits, labels, label_smoothing=0.1))
    assert smoothed > plain


def test_top_k_accuracy_matches_sklearn():
    rng = np.random.RandomState(0)
    scores = rng.randn(64, 5)
    labels = rng.randint(0, 5, size=64)
    for k in (1, 2, 3):
        ours = float(top_k_accuracy(jnp.asarray(scores), jnp.asarray(labels), k=k))
        ref = top_k_accuracy_score(labels, scores, k=k, labels=np.arange(5))
        np.testing.assert_allclose(ours, ref, rtol=1e-6)


def test_multistep_lr_matches_reference_schedule():
    # example_trainer.py:66 — MultiStepLR milestones [50,100,200], gamma 0.1
    sched = multistep_lr(0.1, [50, 100, 200], 0.1, steps_per_epoch=10)
    assert np.isclose(float(sched(0)), 0.1)
    assert np.isclose(float(sched(499)), 0.1)
    assert np.isclose(float(sched(500)), 0.01)
    assert np.isclose(float(sched(1000)), 0.001)
    assert np.isclose(float(sched(2000)), 1e-4)


def test_warmup_cosine_endpoints():
    sched = warmup_cosine_lr(1.0, total_epochs=10, steps_per_epoch=10, warmup_epochs=2)
    assert float(sched(0)) < 1e-6
    assert np.isclose(float(sched(20)), 1.0, atol=1e-3)
    assert float(sched(100)) < 1e-3


def test_tied_cross_entropy_matches_naive():
    """Chunked tied-head CE == naive full-logits CE, values and grads."""
    import jax
    import jax.numpy as jnp

    from distributed_training_pytorch_tpu.ops.losses import (
        softmax_cross_entropy_with_integer_labels,
        tied_cross_entropy,
    )

    rng = np.random.RandomState(0)
    n, d, v = 12, 8, 37  # vocab not a multiple of the chunk size
    hidden = jnp.asarray(rng.randn(3, 4, d), jnp.float32)
    emb = jnp.asarray(rng.randn(v, d) * 0.3, jnp.float32)
    targets = jnp.asarray(rng.randint(0, v, size=(3, 4)), jnp.int32)

    def naive(hidden, emb):
        logits = jnp.einsum("btd,vd->btv", hidden, emb)
        return softmax_cross_entropy_with_integer_labels(logits, targets)

    for chunk in (8, 16, 64):
        out = tied_cross_entropy(hidden, emb, targets, chunk_size=chunk)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive(hidden, emb)), atol=1e-5
        )

    g_fused = jax.grad(lambda h, e: tied_cross_entropy(h, e, targets, chunk_size=8).sum(),
                       argnums=(0, 1))(hidden, emb)
    g_naive = jax.grad(lambda h, e: naive(h, e).sum(), argnums=(0, 1))(hidden, emb)
    for a, b in zip(g_fused, g_naive, strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)
