"""Trainer orchestration tests: the nine-hook surface, epoch loop, periodic
validation with best/last checkpointing, and snapshot resume (SURVEY.md §4's
'overfit a synthetic 3-class set' integration test).

Structure note: one module-scoped trained ToyTrainer (``trained``) backs the
read-only assertions — every extra Trainer construction costs ~15-40s of CPU
compile/checkpoint time, so tests share the run unless they need their own
config (resume, periodic-without-validation, preprocess hook).
"""

import numpy as np
import optax
import pytest

from distributed_training_pytorch_tpu.checkpoint import BEST, LAST
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, multistep_lr
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.trainer import Trainer


def synthetic_images(n, num_classes=3, size=32, seed=0):
    """Class-separable random images (mean shifted per class)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(n,)).astype(np.int32)
    images = rng.randn(n, size, size, 3).astype(np.float32)
    images += labels[:, None, None, None].astype(np.float32) * 1.5
    return images, labels


class ToyTrainer(Trainer):
    """All nine hooks implemented — the ExampleTrainer analog for tests."""

    def build_train_dataset(self):
        images, labels = synthetic_images(64, seed=0)
        return ArrayDataSource(image=images, label=labels)

    def build_val_dataset(self):
        images, labels = synthetic_images(24, seed=1)
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return VGG16(
            num_classes=3,
            stage_features=(4, 8),
            stage_layers=(1, 1),
            classifier_widths=(16,),  # 4096-wide default heads cost ~40s/test in CPU compile+saves
        )

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return multistep_lr(0.01, milestones=[50], steps_per_epoch=4)


class RecordingToyTrainer(ToyTrainer):
    """Keeps per-epoch train metrics so one run serves many assertions."""

    epoch_metrics: list

    def train_epoch(self, epoch):
        metrics = super().train_epoch(epoch)
        self.epoch_metrics.append(metrics)
        return metrics


class _CaptureLogger:
    def __init__(self):
        self.lines = []

    def log(self, message, log_type="info"):
        self.lines.append(f"{log_type.upper()}: {message}")


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def make_trainer(tmp_path, mesh, cls=ToyTrainer, **kw):
    defaults = dict(
        max_epoch=3,
        batch_size=16,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=1,
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=0,
        async_checkpoint=False,
        mesh=mesh,
        progress=False,
    )
    defaults.update(kw)
    return cls(**defaults)


@pytest.fixture(scope="module")
def trained(tmp_path_factory, mesh):
    """One full 3-epoch training run with validation + best/last saves."""
    tmp_path = tmp_path_factory.mktemp("trained")
    logger = _CaptureLogger()
    trainer = make_trainer(tmp_path, mesh, cls=RecordingToyTrainer, logger=logger)
    trainer.epoch_metrics = []
    trainer.train()
    return trainer, logger


def test_full_training_run(trained):
    trainer, logger = trained
    out = "\n".join(logger.lines)
    assert int(trainer.state.step) == 3 * 4  # 64 records / batch 16 = 4 steps/epoch
    assert trainer.checkpoints.exists(BEST)
    assert trainer.checkpoints.exists(LAST)
    assert "VALIDATE RESULTS" in out
    assert "The BEST model" in out
    assert "THE NEXT LEARNING RATE VALUE IS" in out
    assert "Finished!" in out
    # Global (not local) loss reporting.
    assert "TOTAL GLOBAL TRAINING LOSS" in out


def test_loss_decreases(trained):
    trainer, _ = trained
    metrics = trainer.epoch_metrics
    assert len(metrics) == 3
    assert metrics[-1]["ce_loss"] < metrics[0]["ce_loss"]


def test_best_only_improves(trained):
    trainer, _ = trained
    assert trainer.checkpoints.best_value is not None


def test_validation_is_mask_exact(trained):
    """24 val records with global batch 16 -> second batch is half padding;
    accuracy must weight real rows only (impossible to exceed 1.0)."""
    trainer, _ = trained
    metrics = trainer.validate()
    assert 0.0 <= metrics["accuracy"] <= 1.0
    assert np.isfinite(metrics["ce_loss"])


def test_resume_from_snapshot(trained, tmp_path, mesh):
    trainer, _ = trained
    saved_step = int(trainer.state.step)
    last_path = trainer.checkpoints.path(LAST)

    resumed = make_trainer(tmp_path, mesh, max_epoch=4, snapshot_path=last_path)
    assert resumed.cur_epoch == 3, "resume epoch must come from the snapshot"
    assert int(resumed.state.step) == saved_step
    resumed.train()  # continues epoch 3 only
    assert int(resumed.state.step) == 4 * 4


def test_periodic_checkpoint_without_validation(tmp_path, mesh):
    trainer = make_trainer(
        tmp_path, mesh, have_validate=False, save_best_for=None, save_period=2, max_epoch=3
    )
    trainer.train()
    # Epochs 0 and 2 save checkpoint_epoch_{epoch+1} (trainer/trainer.py:166).
    assert trainer.checkpoints.exists("checkpoint_epoch_1")
    assert trainer.checkpoints.exists("checkpoint_epoch_3")
    assert not trainer.checkpoints.exists(LAST)
    assert not trainer.checkpoints.exists(BEST)


def test_preprocess_batch_hook(tmp_path, mesh):
    class Scaled(ToyTrainer):
        def preprocess_batch(self, batch):
            batch = dict(batch)
            batch["image"] = batch["image"] * 0.0
            return batch

    scaled = make_trainer(
        tmp_path,
        mesh,
        cls=Scaled,
        max_epoch=1,
        have_validate=False,
        save_best_for=None,
        save_period=10,
    )
    m = scaled.train_epoch(0)
    # Zeroed images -> logits identical across classes at init... loss ~ log(3).
    assert abs(m["ce_loss"] - np.log(3)) < 0.7


def test_missing_hook_raises(tmp_path, mesh):
    class Incomplete(Trainer):
        pass

    with pytest.raises(NotImplementedError):
        Incomplete(max_epoch=1, batch_size=8, save_folder=str(tmp_path), mesh=mesh)


def test_preemption_saves_resumable_snapshot(tmp_path, mesh):
    """SIGTERM (cloud eviction warning) -> the loop saves LAST and returns;
    the snapshot resumes at the interrupted epoch (SURVEY §5.3 upgrade)."""
    import os
    import signal as signal_mod

    trainer = make_trainer(
        tmp_path, mesh, max_epoch=3, have_validate=False, save_best_for=None, save_period=None
    )
    # The handler installs at train() start; install first so the raw SIGTERM
    # below flips the trainer flag instead of killing pytest.
    trainer._install_sigterm()
    os.kill(os.getpid(), signal_mod.SIGTERM)  # handler flips the flag only
    trainer.train()
    assert trainer._preempted
    assert trainer.checkpoints.exists(LAST)
    resumed = make_trainer(
        tmp_path,
        mesh,
        max_epoch=3,
        have_validate=False,
        save_best_for=None,
        save_period=None,
        snapshot_path=trainer.checkpoints.path(LAST),
    )
    assert resumed.cur_epoch == 0  # epoch 0 was interrupted -> retrain it


def test_tensorboard_writer_emits_events(tmp_path, mesh):
    """tensorboard_dir writes BOTH train/ and val/ scalars (SURVEY §5.5)."""
    pytest.importorskip("tensorboardX")
    tb_dir = tmp_path / "tb"
    trainer = make_trainer(tmp_path, mesh, max_epoch=1, tensorboard_dir=str(tb_dir))
    trainer.train()
    events = list(tb_dir.glob("events.out.tfevents.*"))
    assert events, "no event file written"
    payload = b"".join(p.read_bytes() for p in events)
    # Tags are embedded as plain strings in the event protos.
    assert b"train/ce_loss" in payload
    assert b"val/accuracy" in payload


def test_build_loss_fn_hook_override(tmp_path, mesh):
    """The advanced loss hook replaces the model+criterion composition (the
    fused-CE path in examples/train_lm.py relies on this contract)."""
    calls = []

    class CustomLoss(ToyTrainer):
        def build_loss_fn(self):
            model = self.model

            def loss_fn(params, model_state, batch, rng, train):
                calls.append(train)
                logits = model.apply(
                    {"params": params}, batch["image"], train=train,
                    **({"rngs": {"dropout": rng}} if train else {}),
                )
                loss = cross_entropy_loss(logits, batch["label"])
                return loss, ({"custom_loss": loss}, model_state)

            return loss_fn

    trainer = make_trainer(
        tmp_path, mesh, cls=CustomLoss, max_epoch=1,
        have_validate=False, save_best_for=None, save_period=None,
    )
    metrics = trainer.train_epoch(0)
    assert calls, "custom loss_fn never traced"
    assert "custom_loss" in metrics and np.isfinite(metrics["custom_loss"])


def test_last_save_period_gates_epoch_saves(tmp_path, devices):
    """last_save_period=N saves `last` every N epochs (plus the final epoch)
    instead of the reference's every-epoch default — the knob for slow
    checkpoint paths. The saved resume label still points at the next epoch."""
    import os

    t = ToyTrainer(
        max_epoch=5,
        batch_size=16,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=100,
        last_save_period=2,
        save_folder=str(tmp_path),
        progress=False,
        # Sync saves: this test asserts the request CADENCE by spying on
        # manager.save — under async checkpointing a queued `last` is
        # legitimately superseded by a newer one before its commit starts
        # (newest-wins; test_resilience.py covers that coalescing).
        async_checkpoint=False,
    )
    saves = []
    orig = t.checkpoints.save

    def spy(name, state, epoch, **kw):
        saves.append((name, epoch))
        return orig(name, state, epoch, **kw)

    t.checkpoints.save = spy
    t.train()
    last_saves = [e for n, e in saves if n == LAST]
    # epochs are 1-indexed in the save label: every 2nd + the final (5)
    assert last_saves == [2, 4, 5], saves
