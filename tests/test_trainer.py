"""Trainer orchestration tests: the nine-hook surface, epoch loop, periodic
validation with best/last checkpointing, and snapshot resume (SURVEY.md §4's
'overfit a synthetic 3-class set' integration test)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_pytorch_tpu.checkpoint import BEST, LAST
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.models import VGG16
from distributed_training_pytorch_tpu.ops import accuracy, cross_entropy_loss, multistep_lr
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.trainer import Trainer


def synthetic_images(n, num_classes=3, size=32, seed=0):
    """Class-separable random images (mean shifted per class)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=(n,)).astype(np.int32)
    images = rng.randn(n, size, size, 3).astype(np.float32)
    images += labels[:, None, None, None].astype(np.float32) * 1.5
    return images, labels


class ToyTrainer(Trainer):
    """All nine hooks implemented — the ExampleTrainer analog for tests."""

    def build_train_dataset(self):
        images, labels = synthetic_images(64, seed=0)
        return ArrayDataSource(image=images, label=labels)

    def build_val_dataset(self):
        images, labels = synthetic_images(24, seed=1)
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return VGG16(num_classes=3, stage_features=(4, 8), stage_layers=(1, 1))

    def build_criterion(self):
        def criterion(logits, batch):
            mask = batch.get("mask")
            loss = cross_entropy_loss(logits, batch["label"], weights=mask)
            return loss, {
                "ce_loss": loss,
                "accuracy": accuracy(logits, batch["label"], weights=mask),
            }

        return criterion

    def build_optimizer(self, schedule):
        return optax.sgd(schedule, momentum=0.9)

    def build_scheduler(self):
        return multistep_lr(0.01, milestones=[50], steps_per_epoch=4)


@pytest.fixture
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def make_trainer(tmp_path, mesh, **kw):
    defaults = dict(
        max_epoch=3,
        batch_size=16,
        have_validate=True,
        save_best_for=("accuracy", "geq"),
        save_period=1,
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=0,
        async_checkpoint=False,
        mesh=mesh,
    )
    defaults.update(kw)
    return ToyTrainer(**defaults)


def test_full_training_run(tmp_path, mesh, capsys):
    trainer = make_trainer(tmp_path, mesh)
    trainer.train()
    out = capsys.readouterr().out
    # Loss decreased from epoch 1 to epoch 3 (overfit on separable data).
    assert int(trainer.state.step) == 3 * 4  # 64 records / batch 16 = 4 steps/epoch
    assert trainer.checkpoints.exists(BEST)
    assert trainer.checkpoints.exists(LAST)
    assert "VALIDATE RESULTS" in out
    assert "The BEST model" in out
    assert "THE NEXT LEARNING RATE VALUE IS" in out
    assert "Finished!" in out
    # Global (not local) loss reporting.
    assert "TOTAL GLOBAL TRAINING LOSS" in out


def test_loss_decreases(tmp_path, mesh):
    trainer = make_trainer(tmp_path, mesh, max_epoch=5, have_validate=False, save_period=10)
    first = trainer.train_epoch(0)
    for e in range(1, 5):
        trainer.train_dataloader.set_epoch(e)
        last = trainer.train_epoch(e)
    assert last["ce_loss"] < first["ce_loss"]


def test_resume_from_snapshot(tmp_path, mesh):
    trainer = make_trainer(tmp_path, mesh, max_epoch=2)
    trainer.train()
    saved_step = int(trainer.state.step)
    last_path = trainer.checkpoints.path(LAST)

    resumed = make_trainer(tmp_path, mesh, max_epoch=4, snapshot_path=last_path)
    assert resumed.cur_epoch == 2, "resume epoch must come from the snapshot"
    assert int(resumed.state.step) == saved_step
    resumed.train()  # continues epochs 2..3
    assert int(resumed.state.step) == 4 * 4


def test_periodic_checkpoint_without_validation(tmp_path, mesh):
    trainer = make_trainer(
        tmp_path, mesh, have_validate=False, save_best_for=None, save_period=2, max_epoch=3
    )
    trainer.train()
    # Epochs 0 and 2 save checkpoint_epoch_{epoch+1} (trainer/trainer.py:166).
    assert trainer.checkpoints.exists("checkpoint_epoch_1")
    assert trainer.checkpoints.exists("checkpoint_epoch_3")
    assert not trainer.checkpoints.exists(LAST)
    assert not trainer.checkpoints.exists(BEST)


def test_validation_is_mask_exact(tmp_path, mesh):
    """24 val records with global batch 16 -> second batch is half padding;
    accuracy must weight real rows only (impossible to exceed 1.0)."""
    trainer = make_trainer(tmp_path, mesh)
    metrics = trainer.validate()
    assert 0.0 <= metrics["accuracy"] <= 1.0
    assert np.isfinite(metrics["ce_loss"])


def test_best_only_improves(tmp_path, mesh):
    trainer = make_trainer(tmp_path, mesh, max_epoch=1)
    trainer.train()
    best_after = trainer.checkpoints.best_value
    assert best_after is not None


def test_preprocess_batch_hook(tmp_path, mesh):
    class Scaled(ToyTrainer):
        def preprocess_batch(self, batch):
            batch = dict(batch)
            batch["image"] = batch["image"] * 0.0
            return batch

    trainer = make_trainer(tmp_path, mesh)
    scaled = Scaled(
        max_epoch=1,
        batch_size=16,
        have_validate=False,
        save_period=10,
        save_folder=str(tmp_path / "r2"),
        num_workers=0,
        log_every=0,
        async_checkpoint=False,
        mesh=mesh,
    )
    m = scaled.train_epoch(0)
    # Zeroed images -> logits identical across classes at init... loss ~ log(3).
    assert abs(m["ce_loss"] - np.log(3)) < 0.7


def test_missing_hook_raises(tmp_path, mesh):
    class Incomplete(Trainer):
        pass

    with pytest.raises(NotImplementedError):
        Incomplete(max_epoch=1, batch_size=8, save_folder=str(tmp_path), mesh=mesh)
