"""Static-analysis subsystem tests (ISSUE 7; docs/static_analysis.md).

Three layers under test: jaxlint's AST rules (each tripped exactly once by
a fixture snippet, with a clean twin that must NOT trip), the waiver
protocol, and the HLO audit (donation aliasing, precision leaks, host
callbacks) — including the acceptance criterion that the shipped engine's
REAL single-step and chained programs donate 100% of param + optimizer-
state input bytes, and the self-parity contract that the shipped codebase
passes the full lint gate with zero unwaived findings.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from distributed_training_pytorch_tpu.analysis import (
    audit_donation,
    audit_host_callbacks,
    audit_precision_leaks,
    build_audit_engine,
    lint_paths,
    lint_source,
    parse_input_output_aliases,
    run_generic,
    run_hlo_audit,
    scan_waivers,
)
from distributed_training_pytorch_tpu.analysis.hlo_audit import (
    count_entry_parameters,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "distributed_training_pytorch_tpu")


def rules_of(result):
    return [f.rule for f in result.unwaived]


# ---------------------------------------------------------------------------
# jaxlint rules: one fixture trips each rule exactly once; a clean twin
# stays silent.
# ---------------------------------------------------------------------------


class TestHostSyncRule:
    def test_float_on_traced_value_trips_once(self):
        src = (
            "import jax\n"
            "def step(state, batch):\n"
            "    loss = batch.sum()\n"
            "    return state, float(loss)\n"
            "stepped = jax.jit(step, donate_argnums=(0,))\n"
        )
        assert rules_of(lint_source(src)) == ["host-sync-in-step"]

    def test_item_and_asarray_each_trip(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def step(state, batch):\n"
            "    return state, (batch.sum().item(), np.asarray(batch))\n"
            "stepped = jax.jit(step, donate_argnums=(0,))\n"
        )
        assert rules_of(lint_source(src)) == ["host-sync-in-step"] * 2

    def test_clean_twin_device_resident_metrics(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def step(state, batch):\n"
            "    return state, {'loss': jnp.mean(batch)}\n"
            "stepped = jax.jit(step, donate_argnums=(0,))\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_static_casts_allowed(self):
        # float()/int() of self-config and shape metadata are trace-time
        # Python, not device syncs.
        src = (
            "import jax\n"
            "class E:\n"
            "    def build(self):\n"
            "        return jax.jit(self._impl, donate_argnums=(0,))\n"
            "    def _impl(self, state, batch):\n"
            "        scale = 1.0 / float(self.accum)\n"
            "        n = int(batch.shape[0])\n"
            "        return state, batch.sum() * scale * n\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_host_code_float_not_flagged(self):
        src = (
            "def log_point(metrics):\n"
            "    return {k: float(v) for k, v in metrics.items()}\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_transitive_callee_is_compiled(self):
        # A helper called from the jitted fn is part of the compiled region.
        src = (
            "import jax\n"
            "def helper(x):\n"
            "    return float(x)\n"
            "def step(state, batch):\n"
            "    return state, helper(batch.sum())\n"
            "stepped = jax.jit(step, donate_argnums=(0,))\n"
        )
        assert rules_of(lint_source(src)) == ["host-sync-in-step"]


class TestWallClockRule:
    def test_time_time_in_scan_body_trips_once(self):
        src = (
            "import jax, time\n"
            "def sweep(xs):\n"
            "    def body(carry, x):\n"
            "        return carry + x, time.time()\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
            "swept = jax.jit(sweep)\n"
        )
        assert rules_of(lint_source(src)) == ["wall-clock-in-step"]

    def test_clean_twin_host_timing(self):
        src = (
            "import time\n"
            "def train_epoch():\n"
            "    t0 = time.perf_counter()\n"
            "    return time.perf_counter() - t0\n"
        )
        assert rules_of(lint_source(src)) == []


class TestRankGateRule:
    UNGATED = (
        "def dump(path, lines):\n"
        "    with open(path, 'w') as f:\n"
        "        f.writelines(lines)\n"
    )

    def test_ungated_write_trips_once(self):
        assert rules_of(lint_source(self.UNGATED)) == [
            "file-write-without-rank-gate"
        ]

    def test_gated_twin_clean(self):
        src = (
            "import jax\n"
            "def dump(path, lines):\n"
            "    if jax.process_index() != 0:\n"
            "        return\n"
            "    with open(path, 'w') as f:\n"
            "        f.writelines(lines)\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_enabled_class_convention_clean(self):
        # The EventLog pattern: the class establishes self.enabled from a
        # process-index compare; methods write under that contract.
        src = (
            "import jax\n"
            "class Log:\n"
            "    def __init__(self, path):\n"
            "        proc = jax.process_index()\n"
            "        self.enabled = path is not None and proc == 0\n"
            "        self._path = path\n"
            "    def _open(self):\n"
            "        return open(self._path, 'a')\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_read_mode_never_flagged(self):
        src = "def load(p):\n    return open(p).read()\n"
        assert rules_of(lint_source(src)) == []


class TestCrossThreadRule:
    def test_unlocked_mutation_trips_once(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        self.count += 1\n"
        )
        assert rules_of(lint_source(src)) == ["cross-thread-mutation-without-lock"]

    def test_locked_twin_clean(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "        self._lock = threading.Lock()\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_transitive_class_callee_checked(self):
        src = (
            "import threading\n"
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self.done = False\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        self._finish()\n"
            "    def _finish(self):\n"
            "        self.done = True\n"
        )
        assert rules_of(lint_source(src)) == ["cross-thread-mutation-without-lock"]

    def test_threadless_class_clean(self):
        src = (
            "class Plain:\n"
            "    def bump(self):\n"
            "        self.count = 1\n"
        )
        assert rules_of(lint_source(src)) == []


class TestBareExceptRule:
    def test_bare_except_trips_once(self):
        src = "try:\n    x = 1\nexcept:\n    pass\n"
        assert rules_of(lint_source(src)) == ["bare-except"]

    def test_except_exception_clean(self):
        src = "try:\n    x = 1\nexcept Exception:\n    pass\n"
        assert rules_of(lint_source(src)) == []

    def test_bare_except_with_reraise_clean(self):
        src = "try:\n    x = 1\nexcept:\n    raise\n"
        assert rules_of(lint_source(src)) == []


class TestZipStrictRule:
    def test_tree_leaf_zip_without_strict_trips_once(self):
        src = (
            "import jax\n"
            "def pair(a, b):\n"
            "    return list(zip(jax.tree.leaves(a), jax.tree.leaves(b)))\n"
        )
        assert rules_of(lint_source(src)) == ["zip-no-strict"]

    def test_leaves_named_iterables_trip(self):
        # The PR 9 bug shape: pre-flattened leaf lists, zipped lazily.
        src = (
            "def pair(leaves_a, leaves_b):\n"
            "    return list(zip(leaves_a, leaves_b))\n"
        )
        assert rules_of(lint_source(src)) == ["zip-no-strict"]

    def test_strict_true_twin_clean(self):
        src = (
            "import jax\n"
            "def pair(a, b):\n"
            "    return list(zip(jax.tree.leaves(a), jax.tree.leaves(b), "
            "strict=True))\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_strict_false_documents_truncation(self):
        src = (
            "import jax\n"
            "def pair(a, b):\n"
            "    return list(zip(jax.tree.leaves(a), jax.tree.leaves(b), "
            "strict=False))\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_non_tree_zip_is_generic_layers_business(self):
        src = (
            "def pair(xs, ys):\n"
            "    return list(zip(xs, ys))\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_starred_transpose_clean(self):
        src = (
            "import jax\n"
            "def t(rows):\n"
            "    return list(zip(*(jax.tree.leaves(r) for r in rows)))\n"
        )
        assert rules_of(lint_source(src)) == []


class TestMissingDonateRule:
    def test_state_jit_without_donate_trips_once(self):
        src = (
            "import jax\n"
            "def step(state, batch):\n"
            "    return state\n"
            "stepped = jax.jit(step)\n"
        )
        assert rules_of(lint_source(src)) == ["missing-donate-on-jit"]

    def test_donated_twin_clean(self):
        src = (
            "import jax\n"
            "def step(state, batch):\n"
            "    return state\n"
            "stepped = jax.jit(step, donate_argnums=(0,))\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_stateless_jit_clean(self):
        src = (
            "import jax\n"
            "def apply(params, x):\n"
            "    return x\n"
            "applied = jax.jit(apply)\n"
        )
        assert rules_of(lint_source(src)) == []

    def test_decorator_form_trips_once(self):
        src = (
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnums=(1,))\n"
            "def step(state, n):\n"
            "    return state\n"
        )
        assert rules_of(lint_source(src)) == ["missing-donate-on-jit"]


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------


class TestWaivers:
    VIOLATION = (
        "def dump(path):\n"
        "    with open(path, 'w') as f:  "
        "# jaxlint: disable=file-write-without-rank-gate -- {reason}\n"
        "        f.write('x')\n"
    )

    def test_reasoned_waiver_suppresses(self):
        res = lint_source(self.VIOLATION.format(reason="single-process CLI"))
        assert res.unwaived == []
        assert len(res.waived) == 1
        assert res.waived[0].waiver_reason == "single-process CLI"
        assert res.unused_waivers == []

    def test_waiver_without_reason_does_not_waive(self):
        src = (
            "def dump(path):\n"
            "    with open(path, 'w') as f:  "
            "# jaxlint: disable=file-write-without-rank-gate\n"
            "        f.write('x')\n"
        )
        res = lint_source(src)
        assert sorted(rules_of(res)) == [
            "file-write-without-rank-gate",
            "waiver-missing-reason",
        ]

    def test_waiver_for_other_rule_does_not_apply(self):
        src = (
            "def dump(path):\n"
            "    with open(path, 'w') as f:  "
            "# jaxlint: disable=bare-except -- wrong rule\n"
            "        f.write('x')\n"
        )
        res = lint_source(src)
        assert rules_of(res) == ["file-write-without-rank-gate"]
        assert len(res.unused_waivers) == 1

    def test_scan_waivers_parses_multi_rule(self):
        waivers = scan_waivers(
            "x = 1  # jaxlint: disable=bare-except,host-sync-in-step -- why\n"
        )
        assert waivers[1].rules == ("bare-except", "host-sync-in-step")
        assert waivers[1].reason == "why"


# ---------------------------------------------------------------------------
# HLO audit primitives
# ---------------------------------------------------------------------------


def _compile(fn, args, **jit_kwargs):
    return jax.jit(fn, **jit_kwargs).lower(*args).compile()


class TestDonationAudit:
    STATE = {
        "w": jax.ShapeDtypeStruct((128, 64), jnp.float32),
        "m": jax.ShapeDtypeStruct((128, 64), jnp.float32),
    }
    BATCH = jax.ShapeDtypeStruct((8, 64), jnp.float32)

    @staticmethod
    def _fn(state, batch):
        return (
            {"w": state["w"] + batch.sum(), "m": state["m"] * 0.9},
            batch.mean(),
        )

    def test_donated_program_fully_aliased(self):
        compiled = _compile(self._fn, (self.STATE, self.BATCH), donate_argnums=(0,))
        report = audit_donation(
            compiled, (self.STATE, self.BATCH), must_donate=lambda p: "[0]" in p
        )
        assert report.ok
        assert report.donated_fraction == 1.0
        assert report.audited_bytes == 2 * 128 * 64 * 4

    def test_undonated_program_reports_exact_bytes(self):
        compiled = _compile(self._fn, (self.STATE, self.BATCH))
        assert parse_input_output_aliases(compiled.as_text()) == set()
        report = audit_donation(
            compiled, (self.STATE, self.BATCH), must_donate=lambda p: "[0]" in p
        )
        assert not report.ok
        assert report.undonated_bytes == 2 * 128 * 64 * 4
        assert "UNDONATED" in report.describe()

    def test_entry_parameter_count_matches_leaves(self):
        compiled = _compile(self._fn, (self.STATE, self.BATCH), donate_argnums=(0,))
        assert count_entry_parameters(compiled.as_text()) == 3

    def test_leaf_mapping_mismatch_refuses(self):
        compiled = _compile(self._fn, (self.STATE, self.BATCH), donate_argnums=(0,))
        with pytest.raises(ValueError, match="cannot map"):
            audit_donation(compiled, (self.STATE, self.BATCH, self.BATCH))


class TestPrecisionAudit:
    def test_bf16_program_clean(self):
        w = jax.ShapeDtypeStruct((64, 64), jnp.bfloat16)
        x = jax.ShapeDtypeStruct((8, 64), jnp.bfloat16)
        lowered = jax.jit(lambda w, x: jnp.dot(x, w)).lower(w, x)
        report = audit_precision_leaks(lowered.as_text(), policy="bf16")
        assert report.ok and report.mxu_ops == 1

    def test_f32_dot_is_a_leak(self):
        w = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        x = jax.ShapeDtypeStruct((8, 64), jnp.float32)
        lowered = jax.jit(lambda w, x: jnp.dot(x, w)).lower(w, x)
        report = audit_precision_leaks(lowered.as_text(), policy="bf16")
        assert not report.ok
        assert report.leaks[0]["category"] == "matmul"
        assert report.leaks[0]["result_type"].endswith("f32")

    def test_zero_mxu_ops_is_not_a_pass(self):
        # A parse/workload regression must not pass vacuously.
        report = audit_precision_leaks("module @empty {}", policy="bf16")
        assert not report.ok
        assert "vacuous" in report.describe()


class TestCallbackAudit:
    def test_clean_program(self):
        x = jax.ShapeDtypeStruct((8,), jnp.float32)
        compiled = _compile(lambda x: x * 2.0, (x,))
        assert audit_host_callbacks(compiled.as_text()).ok

    def test_callback_markers_detected(self):
        text = (
            'ENTRY %main { %t = token[] after-all()\n'
            '%i = (f32[8], token[]) infeed(token[] %t)\n'
            '%c = f32[8] custom-call(), custom_call_target='
            '"xla_python_cpu_callback" }'
        )
        report = audit_host_callbacks(text)
        assert not report.ok
        assert "infeed" in report.hits
        assert any("callback" in h for h in report.hits)


# ---------------------------------------------------------------------------
# The shipped engine programs (acceptance criterion) + self-parity
# ---------------------------------------------------------------------------


class TestEngineDonationParity:
    def test_single_and_chained_programs_donate_all_state_bytes(self):
        # ISSUE 7 acceptance: 100% of param + optimizer-state input bytes
        # aliased in BOTH the single-step and chained (chain_steps>1)
        # compiled programs.
        report = run_hlo_audit(chain_steps=3)
        assert report.single.ok and report.single.donated_fraction == 1.0
        assert report.chained.ok and report.chained.donated_fraction == 1.0
        assert report.single.audited_bytes > 0
        # params AND opt_state both actually audited (not vacuously).
        roles = {e["role"] for e in report.single.entries if e["must_donate"]}
        assert roles == {"params", "opt_state"}
        assert report.precision.ok
        assert report.callbacks.ok
        assert report.ok

    def test_injected_violation_fails(self):
        report = run_hlo_audit(chain_steps=3, inject_violation=True)
        assert not report.ok
        assert not report.single.ok and not report.chained.ok
        assert report.single.undonated_bytes == report.single.audited_bytes
        # ISSUE 10 satellite: the injected-violation self-test covers the
        # SHARDED path too — an undonated SPMD program must fail its audit.
        assert report.sharded
        assert not report.sharded_single.ok and not report.sharded_chained.ok

    def test_sharded_programs_donate_all_state_bytes(self):
        # ISSUE 10 satellite: 100% param+opt-state donation and no
        # precision leaks must hold under SPMD partitioning (the 8-device
        # conftest platform always runs the sharded audit), and the audited
        # state must be GENUINELY sharded — fsdp and tensor specs both
        # present — or the pass would be vacuous.
        from distributed_training_pytorch_tpu.analysis.hlo_audit import (
            _AUDIT_FSDP_MIN_SIZE,
            _AUDIT_SHARDING_RULES,
            _audit_mesh,
            build_audit_engine,
        )

        report = run_hlo_audit(chain_steps=3)
        assert report.sharded
        assert report.sharded_single.ok
        assert report.sharded_single.donated_fraction == 1.0
        assert report.sharded_chained.ok
        assert report.sharded_chained.donated_fraction == 1.0
        assert report.sharded_precision.ok
        engine, state, _ = build_audit_engine(
            mesh=_audit_mesh(),
            sharding_rules=_AUDIT_SHARDING_RULES,
            fsdp_min_size=_AUDIT_FSDP_MIN_SIZE,
        )
        specs = [
            str(s.spec)
            for s in jax.tree.leaves(
                engine.state_sharding_tree(state),
                is_leaf=lambda x: hasattr(x, "spec"),
            )
        ]
        assert any("fsdp" in s for s in specs), specs
        assert any("tensor" in s for s in specs), specs

    def test_chained_probe_matches_real_dispatch_program(self):
        # The audit's chained probe (no trace-count side effects) and the
        # REAL dispatch program (engine._chained_step_fn) are two
        # constructions of the same window: pin their lowered HLO equal so
        # a change to one cannot silently leave the audit verifying a
        # program the trainer no longer runs.
        length = 3
        engine, state, batch = build_audit_engine()
        window = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct((length,) + tuple(x.shape), x.dtype),
            batch,
        )
        probe_text = engine.lower_step_probe(
            state, window, donate=True, chain_length=length
        ).as_text()
        real_fn = engine._chained_step_fn(length, state)
        with engine._ambient_mesh():
            real_text = real_fn.lower(state, window).as_text()
        assert probe_text == real_text

    def test_donate_state_false_engine_audits_undonated(self):
        # The probe mirrors the dispatch path's donation flag: an engine
        # built with donate_state=False runs undonated programs, and the
        # audit must see (and fail on) exactly that program.
        import optax

        from distributed_training_pytorch_tpu.train import TrainEngine

        engine, state, batch = build_audit_engine()
        plain = TrainEngine(
            engine.loss_fn, optax.sgd(0.05, momentum=0.9), engine.mesh,
            donate_state=False,
        )
        compiled = plain.compile_step_probe(state, batch, donate=True)
        report = audit_donation(compiled, (state, batch))
        assert not report.ok
        assert report.undonated_bytes == report.audited_bytes

    def test_probe_memoized_and_keyed_by_donate(self):
        engine, state, batch = build_audit_engine()
        a = engine.compile_step_probe(state, batch, donate=True)
        b = engine.compile_step_probe(state, batch, donate=True)
        c = engine.compile_step_probe(state, batch)  # undonated default
        assert a is b
        assert a is not c
        assert parse_input_output_aliases(c.as_text()) == set()


class TestSelfParity:
    def test_package_passes_jaxlint(self):
        res = lint_paths([PACKAGE])
        assert res.unwaived == [], "\n".join(f.describe() for f in res.unwaived)
        # Every waiver in the shipped tree is used and carries a reason.
        assert res.unused_waivers == []
        assert all(f.waiver_reason for f in res.waived)

    def test_repo_passes_generic_layer(self):
        paths = [PACKAGE] + [
            os.path.join(REPO, p)
            for p in ("scripts", "tests", "examples", "bench.py")
        ]
        report = run_generic([p for p in paths if os.path.exists(p)])
        assert report.ok, "\n".join(f.describe() for f in report.findings)


class TestStaticAuditCLI:
    def _run(self, *flags):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "static_audit.py"),
             *flags],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=REPO, timeout=300,
        )

    def test_source_passes_exit_zero_and_emit_event(self, tmp_path):
        events = tmp_path / "events.jsonl"
        proc = self._run("--skip-hlo", "--skip-comm", "--events", str(events))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        from distributed_training_pytorch_tpu.telemetry import read_events

        records = [e for e in read_events(str(events))
                   if e["event"] == "static_audit"]
        assert len(records) == 1
        assert records[0]["passed"] is True
        assert records[0]["lint_findings"] == 0
        assert records[0]["lint_waived"] >= 1

    def test_injected_lint_violation_fails(self):
        proc = self._run("--skip-hlo", "--skip-comm", "--inject-violation", "lint")
        assert proc.returncode == 2, proc.stdout + proc.stderr
        # every rule tripped at least once in the synthetic module
        from distributed_training_pytorch_tpu.analysis import RULES

        for rule in RULES:
            if rule == "waiver-missing-reason":
                continue
            assert rule in proc.stdout, f"{rule} not tripped:\n{proc.stdout}"

    def test_unused_waiver_reported_and_still_exits_zero(self, tmp_path):
        # ISSUE 11 satellite: the CLI's unused-waiver reporting path. A
        # waiver whose finding is gone is a NOTE (delete-the-comment nudge),
        # never a failure — via --lint-path, the CLI's lint-a-known-tree
        # seam (the shipped package can't carry one: self-parity forbids it).
        mod = tmp_path / "stale.py"
        mod.write_text(
            "x = 1  # jaxlint: disable=bare-except -- fixed long ago\n"
        )
        proc = self._run("--skip-hlo", "--skip-comm", "--lint-path", str(tmp_path))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "NOTE unused waiver" in proc.stdout
        assert f"{mod}:1" in proc.stdout
        assert "bare-except" in proc.stdout

    def test_waived_finding_printed_with_reason(self, tmp_path):
        mod = tmp_path / "waived.py"
        mod.write_text(
            "def dump(path):\n"
            "    with open(path, 'w') as f:  "
            "# jaxlint: disable=file-write-without-rank-gate -- test CLI\n"
            "        f.write('x')\n"
        )
        proc = self._run("--skip-hlo", "--skip-comm", "--lint-path", str(tmp_path))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "[waived: test CLI]" in proc.stdout

    def test_comm_injection_flag_conflicts_refused_fast(self):
        # perf_gate discipline: flag conflicts fail BEFORE any work.
        proc = self._run("--inject-violation", "comm", "--skip-comm")
        assert proc.returncode == 2
        assert "requires the comm pass" in proc.stderr
        proc = self._run("--inject-violation", "hlo", "--skip-hlo")
        assert proc.returncode == 2
        assert "requires the HLO pass" in proc.stderr
        proc = self._run("--update-comm-baseline", "--inject-violation", "lint")
        assert proc.returncode == 2
        assert "must not record" in proc.stderr
