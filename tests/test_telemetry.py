"""Telemetry subsystem tests (ISSUE 4): event log, goodput accounting,
on-device train-health stats, MFU fields, anomaly detectors, and the
trainer integration's acceptance pillars:

* on-device stats add ZERO extra host syncs and ZERO retraces —
  ``TrainEngine.trace_counts`` identical with telemetry on/off — and never
  perturb the update arithmetic (params bit-exact with a stats-off run);
* chained windows stay bit-exact with single-step runs with stats enabled
  (the PR 2 invariant extended);
* goodput bucket fractions sum to 1, and the cumulative counters survive a
  SIGTERM-kill -> resume cycle bit-identically (the test_fault pattern).

Cost note: trainer tests use a tiny Dense net (seconds of CPU compile, the
test_precision MiniTrainer pattern), never the toy VGG.
"""

import json
import math
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributed_training_pytorch_tpu.checkpoint import LAST
from distributed_training_pytorch_tpu.data import ArrayDataSource
from distributed_training_pytorch_tpu.fault import FaultPlan
from distributed_training_pytorch_tpu.ops import cross_entropy_loss
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.telemetry import (
    AnomalyDetector,
    AnomalyError,
    BUCKETS,
    EventLog,
    GoodputMeter,
    Telemetry,
    device_peak_flops,
    mfu_value,
    read_events,
    resolve_telemetry,
    window_report,
)
from distributed_training_pytorch_tpu.trainer import Trainer
from distributed_training_pytorch_tpu.train import TrainEngine, make_supervised_loss
from distributed_training_pytorch_tpu.utils.tensorboard import MetricsWriter

from test_engine import TinyMLP, criterion, synthetic_batch


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.create_mesh({mesh_lib.DATA_AXIS: 8}, devices=devices)


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb, strict=True):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# GoodputMeter: exhaustive partition + checkpoint round trip.


def test_goodput_partition_sums_to_one():
    m = GoodputMeter()
    m.start()
    for bucket in ("data_wait", "compile", "productive_step", "checkpoint"):
        m.tick(bucket)
    m.stop()  # trailing interval -> other
    fractions = m.fractions()
    assert set(fractions) == set(BUCKETS)
    assert math.isclose(sum(fractions.values()), 1.0, abs_tol=1e-9)
    assert m.total() == sum(m.buckets.values())


def test_goodput_first_tick_starts_clock_without_attribution():
    m = GoodputMeter()
    assert m.tick("data_wait") == 0.0  # starting tick attributes nothing
    assert m.total() == 0.0
    assert m.tick("productive_step") >= 0.0  # second tick attributes


def test_goodput_rejects_unknown_bucket():
    m = GoodputMeter()
    with pytest.raises(KeyError, match="unknown goodput bucket"):
        m.tick("not_a_bucket")
    with pytest.raises(KeyError, match="unknown goodput bucket"):
        m.account("typo", 1.0)


def test_goodput_state_round_trips_bit_identically_through_json():
    m = GoodputMeter()
    m.account("productive_step", 1.2345678901234567)
    m.account("compile", 0.1)
    m.account("other", 3.3333333333333335e-3)
    state = m.to_state()
    # The checkpoint path: meta json write -> read (json round-trips floats
    # exactly in Python).
    restored = GoodputMeter(json.loads(json.dumps(state)))
    for bucket in BUCKETS:
        assert restored.buckets[bucket] == m.buckets[bucket]  # bit-identical


def test_goodput_unknown_saved_bucket_folds_into_other():
    m = GoodputMeter({"productive_step": 1.0, "renamed_legacy_bucket": 2.0})
    assert m.buckets["productive_step"] == 1.0
    assert m.buckets["other"] == 2.0
    assert math.isclose(sum(m.fractions().values()), 1.0, abs_tol=1e-9)


# ---------------------------------------------------------------------------
# EventLog: JSONL schema, ordering, no-op contract.


def test_event_log_jsonl_well_formed(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.emit("run_start", epoch=0, devices=8)
    log.emit("window", step_ms=1.5, mfu=np.float32(0.42))  # numpy scalar coerces
    log.emit("run_end", weird=object())  # non-serializable -> repr, never raises
    log.close()
    events = list(read_events(path))
    assert [e["event"] for e in events] == ["run_start", "window", "run_end"]
    for e in events:
        for field in ("event", "t_wall", "t_mono", "process", "host", "pid"):
            assert field in e
    mono = [e["t_mono"] for e in events]
    assert mono == sorted(mono)
    assert events[1]["mfu"] == pytest.approx(0.42)
    assert isinstance(events[2]["weird"], str)


def test_event_log_nonfinite_values_stay_strict_json(tmp_path):
    """json.dumps would emit bare NaN/Infinity (invalid strict JSON, rejected
    by jq / JSON.parse); non-finite payload values are preserved as strings."""
    path = str(tmp_path / "e.jsonl")
    log = EventLog(path)
    log.emit("anomaly", value=float("nan"), norm=np.float32("inf"))
    log.close()
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    event = next(iter(read_events(path)))
    assert event["value"] == "nan" and event["norm"] == "inf"


def test_event_log_appends_across_reopen(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.emit("run_start")
    log.close()
    log.emit("run_start")  # a re-entered train() lazily reopens in append mode
    log.close()
    assert [e["event"] for e in read_events(path)] == ["run_start", "run_start"]


def test_event_log_disabled_paths(tmp_path):
    assert EventLog(None).emit("x") is None  # no path
    off = EventLog(str(tmp_path / "e.jsonl"), process_index=1)  # not rank 0
    assert not off.enabled and off.emit("x") is None
    assert not os.path.exists(tmp_path / "e.jsonl")


def test_read_events_rejects_malformed(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"event": "ok"}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        list(read_events(str(p)))
    # strict=False (post-crash audit): skip-with-warning, keep the stream
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        events = list(read_events(str(p), strict=False))
    assert [e["event"] for e in events] == ["ok"]
    assert any("malformed" in str(w.message) for w in caught)


def test_event_log_repairs_torn_last_line(tmp_path):
    """A hard kill mid-write leaves a partial line; the resumed run's reopen
    must newline-terminate it so records never merge."""
    path = str(tmp_path / "e.jsonl")
    log = EventLog(path)
    log.emit("run_start")
    log.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "torn-by-sigk')  # no trailing newline
    resumed = EventLog(path)
    resumed.emit("run_start")
    resumed.close()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        events = list(read_events(path, strict=False))
    assert [e["event"] for e in events] == ["run_start", "run_start"]


# ---------------------------------------------------------------------------
# AnomalyDetector: spikes, warmup, non-finite handling.


def test_anomaly_loss_spike_after_warmup():
    det = AnomalyDetector(warmup=3, loss_spike=3.0)
    for step in range(5):
        assert det.observe(step, loss=1.0) == []
    found = det.observe(5, loss=10.0)
    assert [a.kind for a in found] == ["loss_spike"]
    assert found[0].value == 10.0 and found[0].baseline == pytest.approx(1.0)
    assert det.total_fired == 1


def test_anomaly_warmup_suppresses_early_spikes():
    det = AnomalyDetector(warmup=5, loss_spike=3.0)
    # A wild but early value must not fire (init transients are normal).
    assert det.observe(0, loss=1.0) == []
    assert det.observe(1, loss=50.0) == []


def test_anomaly_grad_explosion_and_step_time_regression():
    det = AnomalyDetector(warmup=2, grad_explosion=10.0, step_time_regression=2.5)
    for step in range(4):
        assert det.observe(step, grad_norm=0.5, step_time=0.1) == []
    found = det.observe(4, grad_norm=50.0, step_time=1.0)
    assert sorted(a.kind for a in found) == ["grad_explosion", "step_time_regression"]


def test_anomaly_nonfinite_fires_and_never_poisons_baseline():
    det = AnomalyDetector(warmup=2, loss_spike=3.0)
    for step in range(3):
        det.observe(step, loss=1.0)
    assert [a.kind for a in det.observe(3, loss=float("nan"))] == ["loss_spike"]
    # baseline survived the NaN: a normal value right after does not fire
    assert det.observe(4, loss=1.0) == []


def test_anomaly_nonfinite_fires_even_with_disabled_factor():
    """factor=None disables the EWMA threshold, NOT non-finite detection."""
    det = AnomalyDetector(loss_spike=None)
    assert det.observe(0, loss=1.0) == []
    assert [a.kind for a in det.observe(1, loss=float("inf"))] == ["loss_spike"]


def test_anomaly_rejects_bad_action():
    with pytest.raises(ValueError, match="action"):
        AnomalyDetector(action="explode")


# ---------------------------------------------------------------------------
# MFU fields.


def test_mfu_value_and_degenerate_cases():
    assert mfu_value(5e11, 1.0, 1e12) == pytest.approx(0.5)
    assert mfu_value(0.0, 1.0, 1e12) is None
    assert mfu_value(1e12, 0.0, 1e12) is None
    assert mfu_value(1e12, 1.0, 0.0) is None


def test_device_peak_flops_table(devices):
    assert device_peak_flops(devices[0]) == 1e12  # cpu nominal
    fake_v5e = type("D", (), {"device_kind": "TPU v5 lite"})()
    assert device_peak_flops(fake_v5e) == 197e12


def test_window_report_fields():
    r = window_report(10, 1.0, flops_per_step=2e11, peak_flops=1e12)
    assert r["steps"] == 10
    assert r["step_ms"] == pytest.approx(100.0)
    assert r["mfu"] == pytest.approx(2.0)  # synthetic numbers, exact ratio
    assert "mfu" not in window_report(10, 1.0, flops_per_step=None, peak_flops=1e12)


def test_resolve_telemetry_specs():
    assert resolve_telemetry(None) is None
    assert resolve_telemetry(False) is None
    assert resolve_telemetry("off") is None
    assert isinstance(resolve_telemetry(True), Telemetry)
    assert isinstance(resolve_telemetry("on"), Telemetry)
    t = Telemetry(stats=False)
    assert resolve_telemetry(t) is t
    with pytest.raises(ValueError):
        resolve_telemetry("sideways")
    with pytest.raises(TypeError):
        resolve_telemetry(42)


# ---------------------------------------------------------------------------
# MetricsWriter satellite: one-shot coercion + non-finite tolerance.


class _FakeBackend:
    def __init__(self):
        self.scalars = []
        self.flushes = 0

    def add_scalar(self, tag, value, step):
        assert isinstance(value, float) and isinstance(step, int)
        self.scalars.append((tag, value, step))

    def flush(self):
        self.flushes += 1


def test_metrics_writer_coerces_scalars_and_tolerates_nonfinite():
    writer = MetricsWriter(None)
    writer._writer = _FakeBackend()  # bypass tensorboardX presence
    writer.write(
        np.int64(7),
        {
            "plain": 1.5,
            "numpy": np.float32(2.5),
            "zero_d": np.asarray(3.5),
            "jax": jnp.asarray(4.5),
            "nan": float("nan"),          # tolerated: skipped, no crash
            "inf": np.float32("inf"),     # tolerated: skipped, no crash
            "vector": np.zeros(3),        # non-scalar: skipped
            "string": "not a number",     # non-numeric: skipped
        },
        prefix="t",
    )
    backend = writer._writer
    assert [(t, v) for t, v, _ in backend.scalars] == [
        ("t/plain", 1.5),
        ("t/numpy", 2.5),
        ("t/zero_d", 3.5),
        ("t/jax", 4.5),
    ]
    assert all(s == 7 for _, _, s in backend.scalars)
    assert backend.flushes == 1


# ---------------------------------------------------------------------------
# Engine: on-device stats — presence, bit-exactness, zero retraces.


def make_engine(stats=False, nan_guard=False):
    mesh = mesh_lib.create_mesh()
    model = TinyMLP()
    engine = TrainEngine(
        make_supervised_loss(model, criterion),
        optax.sgd(0.05, momentum=0.9),
        mesh,
        stats=stats,
        nan_guard=nan_guard,
    )
    state = engine.init_state(
        jax.random.key(0), lambda rng: model.init(rng, jnp.zeros((1, 4, 4, 3)))
    )
    return engine, state


def test_stats_metrics_present_and_sane(devices):
    engine, state = make_engine(stats=True)
    state, m = engine.train_step(state, engine.shard_batch(synthetic_batch(16, seed=0)))
    m = jax.device_get(m)
    assert float(m["grad_norm"]) > 0
    assert float(m["param_norm"]) > 0
    assert float(m["update_ratio"]) > 0
    assert float(m["nonfinite"]) == 0.0


def test_stats_flag_nonfinite_on_poisoned_batch(devices):
    engine, state = make_engine(stats=True)
    batch = synthetic_batch(16, seed=1)
    batch = dict(batch, image=np.full_like(batch["image"], np.nan))
    state, m = engine.train_step(state, engine.shard_batch(batch))
    assert float(m["nonfinite"]) == 1.0
    assert not np.isfinite(float(m["grad_norm"]))


def test_stats_do_not_perturb_training(devices):
    """The norms read the dataflow without feeding back into it: params and
    opt_state stay BIT-EXACT with a stats-off run on the same stream."""
    eng_off, state_off = make_engine(stats=False)
    eng_on, state_on = make_engine(stats=True)
    for i in range(3):
        b = synthetic_batch(16, seed=10 + i)
        state_off, _ = eng_off.train_step(state_off, eng_off.shard_batch(b))
        state_on, _ = eng_on.train_step(state_on, eng_on.shard_batch(b))
    assert_trees_equal(state_off.params, state_on.params)
    assert_trees_equal(state_off.opt_state, state_on.opt_state)


def test_stats_chained_bit_exact_with_single_step(devices):
    """PR 2's acceptance invariant extended: chained windows with stats
    enabled == sequential single steps with stats enabled — params AND every
    per-step stat metric (stacked scan outputs) bit-exact."""
    host = [synthetic_batch(16, seed=20 + i) for i in range(4)]
    eng_a, state_a = make_engine(stats=True)
    eng_b, state_b = make_engine(stats=True)
    seq = []
    for hb in host:
        state_a, m = eng_a.train_step(state_a, eng_a.shard_batch(hb))
        seq.append(jax.device_get(m))
    stacked_host = jax.tree.map(lambda *xs: np.stack(xs), *host)
    gb = mesh_lib.global_chain_array_from_host_local(stacked_host, eng_b.mesh)
    state_b, stacked = eng_b.train_steps_chained(state_b, gb, 4)
    assert_trees_equal(state_a.params, state_b.params)
    assert_trees_equal(state_a.opt_state, state_b.opt_state)
    stacked = jax.device_get(stacked)
    for key in ("grad_norm", "param_norm", "update_ratio", "nonfinite", "loss"):
        for i, m in enumerate(seq):
            np.testing.assert_array_equal(
                np.asarray(m[key]), np.asarray(stacked[key][i]), err_msg=key
            )


def test_stats_compose_with_nan_guard(devices):
    """Guard + stats: ONE nonfinite key (the guard's exact per-leaf
    predicate), stats norms alongside, the poisoned update still dropped."""
    engine, state = make_engine(stats=True, nan_guard=True)
    batch = synthetic_batch(16, seed=2)
    poisoned = dict(batch, image=np.full_like(batch["image"], np.nan))
    state, m = engine.train_step(state, engine.shard_batch(poisoned))
    assert float(m["nonfinite"]) == 1.0
    for leaf in jax.tree.leaves(state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_step_cost_analysis_probe_leaves_trace_counts_alone(devices):
    engine, state = make_engine()
    batch = engine.shard_batch(synthetic_batch(16, seed=3))
    state, _ = engine.train_step(state, batch)
    before = dict(engine.trace_counts)
    cost = engine.step_cost_analysis(state, batch)
    assert float(cost.get("flops", 0.0)) > 0
    assert dict(engine.trace_counts) == before
    # abstract avals work too (what the trainer's probe passes)
    abstract_batch = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch
    )
    cost2 = engine.step_cost_analysis(state, abstract_batch)
    assert cost2.get("flops") == cost.get("flops")
    assert dict(engine.trace_counts) == before


def test_compile_step_probe_memoized_per_shape(devices):
    """Telemetry's MFU probe and profiling's roofline join share one probe
    compile: same abstract shapes must return the cached executable, a new
    batch shape must compile fresh."""
    engine, state = make_engine()
    batch = engine.shard_batch(synthetic_batch(16, seed=4))
    first = engine.compile_step_probe(state, batch)
    abstract_batch = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch
    )
    assert engine.compile_step_probe(state, abstract_batch) is first
    assert engine.compile_step_probe(state, batch) is first
    other = engine.shard_batch(synthetic_batch(32, seed=4))
    assert engine.compile_step_probe(state, other) is not first


# ---------------------------------------------------------------------------
# Trainer integration: a tiny Dense trainer (compile cost: seconds).


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(3)(x)


class TinyTrainer(Trainer):
    def build_train_dataset(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 3, size=(48,)).astype(np.int32)
        images = (rng.randn(48, 4, 4, 3) + labels[:, None, None, None]).astype(
            np.float32
        )
        return ArrayDataSource(image=images, label=labels)

    def build_model(self):
        return TinyNet()

    def build_criterion(self):
        def crit(logits, batch):
            loss = cross_entropy_loss(logits, batch["label"])
            return loss, {"loss": loss}

        return crit

    def build_optimizer(self, schedule):
        return optax.sgd(schedule)

    def build_scheduler(self):
        return 0.05


class _Quiet:
    def log(self, *a, **k):
        pass


def make_tiny(tmp_path, mesh, **kw):
    defaults = dict(
        max_epoch=2,
        batch_size=8,
        have_validate=False,
        save_best_for=None,
        save_period=None,
        save_folder=str(tmp_path / "runs"),
        num_workers=0,
        log_every=2,
        chain_steps=2,
        async_checkpoint=False,
        mesh=mesh,
        progress=False,
        logger=_Quiet(),
    )
    defaults.update(kw)
    return TinyTrainer(**defaults)


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory, mesh):
    """One chained telemetry-on run backing the read-only assertions."""
    tmp = tmp_path_factory.mktemp("telemetry_run")
    trainer = make_tiny(tmp, mesh, telemetry="on")
    trainer.train()
    events = list(
        read_events(os.path.join(trainer.save_folder, "telemetry", "events.jsonl"))
    )
    return trainer, events


def test_trainer_event_log_narrative(telemetry_run):
    trainer, events = telemetry_run
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    for required in ("window", "compile", "epoch_end"):
        assert required in kinds, kinds
    mono = [e["t_mono"] for e in events]
    assert mono == sorted(mono)
    run_end = events[-1]
    assert run_end["preempted"] is False
    assert math.isclose(
        sum(run_end["goodput_fractions"].values()), 1.0, abs_tol=1e-6
    )


def test_trainer_goodput_fractions_sum_to_one(telemetry_run):
    trainer, _ = telemetry_run
    fractions = trainer.goodput.fractions()
    assert math.isclose(sum(fractions.values()), 1.0, abs_tol=1e-9)
    assert trainer.goodput.buckets["compile"] > 0
    assert trainer.goodput.buckets["productive_step"] > 0
    assert trainer.goodput.buckets["data_wait"] > 0


def test_trainer_mfu_probe_ran_once(telemetry_run):
    trainer, events = telemetry_run
    assert trainer._flops_per_step and trainer._flops_per_step > 0
    probes = [e for e in events if e["event"] == "compile" and e.get("kind") == "mfu_probe"]
    assert len(probes) == 1
    # probed MFU reaches the per-window reports of later epochs
    windows_with_mfu = [e for e in events if e["event"] == "window" and "mfu" in e]
    assert windows_with_mfu


def test_trainer_epoch_metrics_carry_health_stats(telemetry_run):
    trainer, events = telemetry_run
    epoch_end = [e for e in events if e["event"] == "epoch_end"][-1]
    for key in ("grad_norm", "step_ms"):
        assert key in epoch_end and np.isfinite(epoch_end[key])
    assert epoch_end["nonfinite"] == 0.0


def test_trainer_telemetry_zero_retrace_and_bit_exact(tmp_path, mesh, telemetry_run):
    """THE acceptance test: trace_counts (and so per-shape compiles and the
    per-step dispatch structure) identical with telemetry on/off, and final
    params bit-exact — telemetry observes the run, it does not alter it."""
    on, _ = telemetry_run
    off = make_tiny(tmp_path, mesh, telemetry=None)
    off.train()
    assert dict(off.engine.trace_counts) == dict(on.engine.trace_counts)
    assert_trees_equal(off.state.params, on.state.params)
    assert_trees_equal(off.state.opt_state, on.state.opt_state)
    # off = the historical program: no events file, no meter
    assert off.goodput is None and not off.events.enabled
    assert not os.path.exists(os.path.join(off.save_folder, "telemetry"))


def test_goodput_counters_survive_sigterm_resume_bit_identically(tmp_path, mesh):
    """Kill/resume acceptance (test_fault pattern): an injected real SIGTERM
    interrupts epoch 1; the preemption save embeds the goodput counters in
    checkpoint meta; the resumed trainer re-seeds them BIT-IDENTICALLY and
    books the restore as restart_rollback."""
    kw = dict(telemetry="on", chain_steps=1, log_every=0)
    plan = FaultPlan().add("sigterm", epoch=1, step=2)
    interrupted = make_tiny(tmp_path, mesh, fault_plan=plan, **kw)
    interrupted.train()
    assert interrupted._preempted and interrupted.checkpoints.exists(LAST)
    meta = interrupted.checkpoints.read_meta(LAST)
    saved = meta["telemetry"]["goodput"]
    assert set(saved) == set(BUCKETS)

    resumed = make_tiny(
        tmp_path, mesh, snapshot_path=interrupted.checkpoints.path(LAST), **kw
    )
    for bucket, value in saved.items():
        if bucket == "restart_rollback":
            # the restore itself is rollback overhead, booked on top
            assert resumed.goodput.buckets[bucket] > value
        else:
            assert resumed.goodput.buckets[bucket] == value  # bit-identical
    resumed.train()
    # counters only grew; the partition property held through the carry
    assert resumed.goodput.total() > sum(saved.values())
    assert math.isclose(sum(resumed.goodput.fractions().values()), 1.0, abs_tol=1e-9)
    # the run's flight record shows the whole story
    events = [
        e["event"]
        for e in read_events(
            os.path.join(resumed.save_folder, "telemetry", "events.jsonl")
        )
    ]
    for required in ("fault_injection", "preemption", "checkpoint_save",
                     "checkpoint_restore"):
        assert required in events, events


def test_anomaly_raise_action_aborts_training(tmp_path, mesh):
    """anomaly='raise' + a mid-run NaN loss (no nan guard): the log_every
    sync sees the raw per-step loss (epoch means exclude flagged steps) and
    the detector turns the non-finite value into AnomalyError."""
    plan = FaultPlan().add("nan_loss", epoch=1, step=1)
    trainer = make_tiny(
        tmp_path,
        mesh,
        fault_plan=plan,
        chain_steps=1,
        log_every=2,
        telemetry=Telemetry(anomaly=AnomalyDetector(action="raise", warmup=0)),
    )
    with pytest.raises(AnomalyError, match="loss_spike"):
        trainer.train()
